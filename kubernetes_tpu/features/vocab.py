"""Exact interning vocabularies.

The reference does string matching everywhere (label selectors
``pkg/labels``, taints/tolerations ``pkg/api/helpers.go``, host ports,
volume conflict keys).  On TPU those become set-membership tensor ops, which
requires mapping strings to dense integer ids.  We use *exact* incremental
interning (not hashing) so collisions can never break decision parity —
vocabularies live host-side, are append-only, and device tensors are sized to
a padded capacity that grows geometrically (a capacity change recompiles the
kernels, which XLA caches per shape).

Token kinds share one id space per vocabulary:
  label vocab:   "kv:<key>=<value>" and "key:<key>"
  taint vocab:   "<key>=<value>:<effect>"
  port vocab:    decimal port number
  volume vocab:  conflict key e.g. "gce:<pdName>"
  image vocab:   image name
  topo-key vocab / topo-value vocab: topology domains
"""

from __future__ import annotations


def _next_pow2(n: int) -> int:
    p = 8
    while p < n:
        p *= 2
    return p


class Vocab:
    """Append-only exact string->id interning table."""

    __slots__ = ("_ids", "_tokens", "generation")

    def __init__(self) -> None:
        self._ids: dict[str, int] = {}
        self._tokens: list[str] = []
        self.generation = 0  # bumped on growth; lets tensor caches invalidate

    def __len__(self) -> int:
        return len(self._tokens)

    def id(self, token: str) -> int:
        """Intern (assigning a fresh id if unseen)."""
        i = self._ids.get(token)
        if i is None:
            i = len(self._tokens)
            self._ids[token] = i
            self._tokens.append(token)
            self.generation += 1
        return i

    def get(self, token: str) -> int:
        """Lookup without interning; -1 if absent."""
        return self._ids.get(token, -1)

    def token(self, i: int) -> str:
        return self._tokens[i]

    def tokens(self) -> list[str]:
        return list(self._tokens)

    @property
    def capacity(self) -> int:
        """Padded device-tensor width for this vocabulary."""
        return _next_pow2(max(len(self._tokens), 1))


class LabelVocab(Vocab):
    """Label vocabulary with kv-pair and key-presence entries sharing one id
    space, mirroring the two things ``labels.Requirement.Matches`` can test."""

    def kv_id(self, key: str, value: str) -> int:
        return self.id(f"kv:{key}={value}")

    def key_id(self, key: str) -> int:
        return self.id(f"key:{key}")

    def kv_get(self, key: str, value: str) -> int:
        return self.get(f"kv:{key}={value}")

    def key_get(self, key: str) -> int:
        return self.get(f"key:{key}")
