"""Volume-count, volume-zone, and service-affinity compilation.

Host-side tensor builders for the predicates/priorities that resolve cluster
objects (PVs, PVCs, services) rather than node features:

* ``MaxEBSVolumeCount`` / ``MaxGCEPDVolumeCount``
  (MaxPDVolumeCountChecker, predicates.go:155-316): per-family unique-volume
  id sets become interned bool matrices; the device check is
  ``existing + new - overlap <= max`` with overlap as a [P,W] @ [W,N]
  contraction.
* ``NoVolumeZoneConflict`` (VolumeZoneChecker, predicates.go:318-418):
  bound PVs' zone/region labels against node labels, deduplicated into
  per-group [G, N] masks.
* ``ServiceAffinity`` (predicates.go:623-719) and
  ``ServiceAntiAffinityPriority`` (selector_spreading.go:178-253):
  first-matching-service peer lookups deduplicated into per-group node
  masks / score rows.

Everything here is numpy on small [G, N] / [*, W] shapes; the [P, N] hot
path stays on device.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Protocol, Sequence

import numpy as np

from kubernetes_tpu.api import types as api

# A missing PVC/PV counts as one un-dedupable volume (predicates.go:195-226);
# an unbound PVC is a hard error failing every node (predicates.go:212-214),
# modeled as an impossibly large new-volume count.
INFEASIBLE_EXTRA = 1 << 20

# Content-sized table axes are pow2-bucketed (features.padcap): a live
# daemon mints volume ids / service signatures freely, and every new
# count would otherwise re-specialize the compiled scan (a fresh XLA
# compile on the scheduling clock).  Padded rows are inert — no pod
# indexes them.
from kubernetes_tpu.features.padcap import (pow2 as _pow2,  # noqa: E402
                                            stack_pad as _stack_pad)


class VolumeListers(Protocol):
    def get_pv(self, name: str) -> Optional[api.PersistentVolume]: ...
    def get_pvc(self, namespace: str,
                name: str) -> Optional[api.PersistentVolumeClaim]: ...
    def first_service(self, pod: api.Pod) -> Optional[api.Service]: ...


class VolSvcTensors(NamedTuple):
    """Device-ready tables (numpy; solver converts)."""

    # MaxPD families: EBS and GCE PD unique-volume membership.
    pd_pod_ebs: np.ndarray    # [P, We] bool
    pd_node_ebs: np.ndarray   # [N, We] bool
    pd_extra_ebs: np.ndarray  # [P] int32 — un-dedupable new volumes
    pd_node_extra_ebs: np.ndarray  # [N] int32 — existing un-dedupable
    pd_node_err_ebs: np.ndarray    # [N] bool — existing unbound PVC
    pd_pod_gce: np.ndarray    # [P, Wg] bool
    pd_node_gce: np.ndarray   # [N, Wg] bool
    pd_extra_gce: np.ndarray  # [P] int32
    pd_node_extra_gce: np.ndarray  # [N] int32
    pd_node_err_gce: np.ndarray    # [N] bool
    # NoVolumeZoneConflict groups.
    vz_group: np.ndarray      # [P] int32
    vz_mask: np.ndarray       # [G, N] bool
    # ServiceAffinity groups.
    sa_group: np.ndarray      # [P] int32
    sa_mask: np.ndarray       # [Gs, N] bool
    # ServiceAntiAffinity (selector_spreading.go:193-253) carried state:
    # the solver's scan carries per-(label, group) per-domain peer counts so
    # every in-batch placement moves the live score — the same visibility
    # the reference's one-at-a-time loop gets through its pod lister.
    saa_group: np.ndarray     # [P] int32 — pod's (ns, first-svc-sel) group
    saa_src: np.ndarray       # [P, Gy] bool — groups a placed pod joins
    saa_dom: np.ndarray       # [L, N] int32 — node's label-value domain id
    saa_labeled: np.ndarray   # [L, N] bool — has label & schedulable
    saa_cnt: np.ndarray       # [L, Gy, D] f32 — batch-start domain counts
    saa_num: np.ndarray       # [Gy] f32 — batch-start peer totals
    # CheckNodeLabelPresence / NodeLabelPriority policy-arg rows
    # (predicates.go:586-621, priorities.go:160-197) — pod-independent.
    nl_pred_row: np.ndarray   # [N] bool
    nl_prio_rows: np.ndarray  # [Lnl, N] bool


def _pd_ids(pod: api.Pod, family: str,
            listers: Optional[VolumeListers]) -> tuple[set[str], int]:
    """filterVolumes (predicates.go:188-241) for one family: unique volume
    ids + count of un-dedupable extras (missing PVC/PV), INFEASIBLE_EXTRA on
    an unbound PVC."""
    ids: set[str] = set()
    extra = 0
    for v in pod.volumes:
        if family == "ebs" and v.aws_ebs_id:
            ids.add(v.aws_ebs_id)
        elif family == "gce" and v.gce_pd_name:
            ids.add(v.gce_pd_name)
        elif v.pvc_claim_name:
            pvc = listers.get_pvc(pod.namespace, v.pvc_claim_name) \
                if listers is not None else None
            if pvc is None:
                extra += 1  # missing PVC: assume it matches (random id)
                continue
            if not pvc.volume_name:
                return ids, INFEASIBLE_EXTRA  # unbound: hard error
            pv = listers.get_pv(pvc.volume_name)
            if pv is None:
                extra += 1  # missing PV: assume it matches
                continue
            if family == "ebs" and pv.aws_ebs_id:
                ids.add(pv.aws_ebs_id)
            elif family == "gce" and pv.gce_pd_name:
                ids.add(pv.gce_pd_name)
    return ids, extra


def _compile_pd_family(pods: Sequence[api.Pod],
                       volume_pods: Sequence[tuple[api.Pod, int]],
                       n_nodes: int, family: str,
                       listers: Optional[VolumeListers]
                       ) -> tuple[np.ndarray, np.ndarray, np.ndarray,
                                  np.ndarray, np.ndarray]:
    """Returns (pod_ids [P,W], node_ids [N,W], pod_extra [P],
    node_extra [N], node_err [N]).  Existing pods' missing-PVC/PV volumes
    count toward the node total (predicates.go:265-268 runs filterVolumes
    on existing pods too); an existing pod's unbound PVC is a hard error
    failing the node for any volume-carrying candidate."""
    vocab: dict[str, int] = {}
    pod_ids: list[set[str]] = []
    extra = np.zeros(len(pods), np.int32)
    for i, pod in enumerate(pods):
        if not pod.volumes:
            pod_ids.append(set())
            continue
        ids, ex = _pd_ids(pod, family, listers)
        pod_ids.append(ids)
        extra[i] = ex
        for vid in ids:
            vocab.setdefault(vid, len(vocab))
    node_ids: list[tuple[int, set[str]]] = []
    node_extra = np.zeros(n_nodes, np.int32)
    node_err = np.zeros(n_nodes, bool)
    for epod, nidx in volume_pods:
        if nidx < 0 or nidx >= n_nodes:
            continue
        ids, ex = _pd_ids(epod, family, listers)
        if ex >= INFEASIBLE_EXTRA:
            node_err[nidx] = True
        else:
            node_extra[nidx] += ex
        if ids:
            node_ids.append((nidx, ids))
            for vid in ids:
                vocab.setdefault(vid, len(vocab))
    w = _pow2(len(vocab))
    pod_m = np.zeros((len(pods), w), bool)
    node_m = np.zeros((n_nodes, w), bool)
    for i, ids in enumerate(pod_ids):
        for vid in ids:
            pod_m[i, vocab[vid]] = True
    for nidx, ids in node_ids:
        for vid in ids:
            node_m[nidx, vocab[vid]] = True
    return pod_m, node_m, extra, node_extra, node_err


def _vz_constraints(pod: api.Pod, listers: Optional[VolumeListers]
                    ) -> Optional[list[tuple[str, str]]]:
    """Pod's bound-PV zone/region constraints; None = resolution error
    (missing/unbound PVC or missing PV fails nodes with zone labels,
    predicates.go:369-418)."""
    out: list[tuple[str, str]] = []
    for v in pod.volumes:
        if not v.pvc_claim_name:
            continue
        pvc = listers.get_pvc(pod.namespace, v.pvc_claim_name) \
            if listers is not None else None
        if pvc is None or not pvc.volume_name:
            return None
        pv = listers.get_pv(pvc.volume_name)
        if pv is None:
            return None
        for k in (api.ZONE_LABEL, api.REGION_LABEL):
            if k in pv.labels:
                out.append((k, pv.labels[k]))
    return out


def _compile_volume_zone(pods: Sequence[api.Pod],
                         nodes: Sequence[api.Node],
                         listers: Optional[VolumeListers]
                         ) -> tuple[np.ndarray, np.ndarray]:
    n = len(nodes)
    # Nodes without zone/region labels always pass (predicates.go:362-368).
    has_constraint = np.array(
        [api.ZONE_LABEL in nd.labels or api.REGION_LABEL in nd.labels
         for nd in nodes], bool)
    groups: dict = {}
    rows: list[np.ndarray] = []
    group = np.zeros(len(pods), np.int32)
    for i, pod in enumerate(pods):
        if not pod.volumes or not any(v.pvc_claim_name for v in pod.volumes):
            sig = ("pass",)
        else:
            cons = _vz_constraints(pod, listers)
            sig = ("err",) if cons is None else tuple(sorted(set(cons)))
        g = groups.get(sig)
        if g is None:
            g = len(rows)
            groups[sig] = g
            if sig == ("pass",):
                rows.append(np.ones(n, bool))
            elif sig == ("err",):
                rows.append(~has_constraint)
            else:
                ok = np.ones(n, bool)
                for k, v in sig:
                    node_v = np.array([nd.labels.get(k, "") for nd in nodes])
                    ok &= node_v == v
                rows.append(ok | ~has_constraint)
        group[i] = g
    mask = _stack_pad(rows, n, True)
    return group, mask


def _compile_service_affinity(pods: Sequence[api.Pod],
                              nodes: Sequence[api.Node],
                              labels_cfg: tuple[str, ...],
                              listers: Optional[VolumeListers],
                              first_peer) -> tuple[np.ndarray, np.ndarray]:
    """CheckServiceAffinity (predicates.go:649-719): implicit node selector
    on the configured labels, inherited from the first peer pod's node for
    labels the pod's nodeSelector doesn't pin."""
    n = len(nodes)
    groups: dict = {}
    rows: list[np.ndarray] = []
    group = np.zeros(len(pods), np.int32)
    for i, pod in enumerate(pods):
        affinity_labels: dict[str, str] = {}
        missing = False
        for lb in labels_cfg:
            if lb in pod.node_selector:
                affinity_labels[lb] = pod.node_selector[lb]
            else:
                missing = True
        err = False
        if missing and listers is not None and first_peer is not None:
            svc = listers.first_service(pod)
            if svc is not None:
                peer_node_name = first_peer(pod.namespace, svc.selector)
                if peer_node_name is not None:
                    nd = next((x for x in nodes
                               if x.name == peer_node_name), None)
                    if nd is None:
                        err = True  # GetNodeInfo error fails all nodes
                    else:
                        for lb in labels_cfg:
                            if lb not in affinity_labels and lb in nd.labels:
                                affinity_labels[lb] = nd.labels[lb]
        sig = ("err",) if err else tuple(sorted(affinity_labels.items()))
        g = groups.get(sig)
        if g is None:
            g = len(rows)
            groups[sig] = g
            if sig == ("err",):
                rows.append(np.zeros(n, bool))
            else:
                ok = np.ones(n, bool)
                for k, v in sig:
                    node_v = np.array([nd.labels.get(k) or "" for nd in nodes])
                    ok &= node_v == v
                rows.append(ok)
        group[i] = g
    mask = _stack_pad(rows, n, True)
    return group, mask


def _compile_service_anti_affinity(pods: Sequence[api.Pod],
                                   nodes: Sequence[api.Node],
                                   schedulable: np.ndarray,
                                   labels_cfg: tuple[str, ...],
                                   listers: Optional[VolumeListers],
                                   service_peers
                                   ) -> tuple[np.ndarray, np.ndarray,
                                              np.ndarray, np.ndarray,
                                              np.ndarray, np.ndarray]:
    """CalculateAntiAffinityPriority (selector_spreading.go:193-253):
    int(10 * (numServicePods - countsOnLabelValue) / numServicePods) on
    ready nodes carrying the label, 0 elsewhere, 10 when no service pods.

    Emits carried state rather than baked scores: (group [P], src [P,Gy],
    dom [L,N], labeled [L,N], cnt [L,Gy,D], num [Gy]).  The solver scores
    from (cnt, num) and updates both per in-batch placement; `src[i, g]`
    marks every group whose namespace+selector pod i joins when placed
    (a pod counts toward EVERY matching service's spread, not just the
    first service it reads its own score from)."""
    n = len(nodes)
    L = max(len(labels_cfg), 1)
    name_to_idx = {nd.name: j for j, nd in enumerate(nodes)}
    # Per-label node domains: distinct label values interned per label.
    dom = np.zeros((L, n), np.int32)
    labeled = np.zeros((L, n), bool)
    n_doms = 1
    for li, lb in enumerate(labels_cfg):
        values: dict[str, int] = {}
        for j, nd in enumerate(nodes):
            v = nd.labels.get(lb)
            if v is None:
                continue
            labeled[li, j] = bool(schedulable[j])
            d = values.get(v)
            if d is None:
                d = len(values)
                values[v] = d
            dom[li, j] = d
        n_doms = max(n_doms, len(values))
    D = _pow2(n_doms)

    groups: dict = {}
    sigs: list = []          # group -> (ns, selector dict or None)
    peer_lists: list = []    # group -> peer node-name list
    group = np.zeros(len(pods), np.int32)
    for i, pod in enumerate(pods):
        svc = listers.first_service(pod) if listers is not None else None
        sig = (pod.namespace, tuple(sorted(svc.selector.items()))
               if svc is not None else None)
        g = groups.get(sig)
        if g is None:
            g = len(sigs)
            groups[sig] = g
            sigs.append((pod.namespace,
                         dict(svc.selector) if svc is not None else None))
            peer_lists.append(service_peers(pod.namespace, svc.selector)
                              if svc is not None else [])
        group[i] = g
    gcount = _pow2(len(sigs))
    cnt = np.zeros((L, gcount, D), np.float32)
    num = np.zeros(gcount, np.float32)
    for g, peer_nodes in enumerate(peer_lists):
        num[g] = len(peer_nodes)
        for pn in peer_nodes:
            j = name_to_idx.get(pn)
            if j is None:
                continue
            for li in range(L):
                if labeled[li, j]:
                    cnt[li, g, dom[li, j]] += 1.0
    src = np.zeros((len(pods), gcount), bool)
    for i, pod in enumerate(pods):
        for g, (ns, sel) in enumerate(sigs):
            if sel is not None and pod.namespace == ns and \
                    all(pod.labels.get(k) == v for k, v in sel.items()):
                src[i, g] = True
    return group, src, dom, labeled, cnt, num


def empty_volsvc(p: int, n: int) -> VolSvcTensors:
    """Neutral all-pass tables (no volumes, no service policy args)."""
    return VolSvcTensors(
        pd_pod_ebs=np.zeros((p, 1), bool), pd_node_ebs=np.zeros((n, 1), bool),
        pd_extra_ebs=np.zeros(p, np.int32),
        pd_node_extra_ebs=np.zeros(n, np.int32),
        pd_node_err_ebs=np.zeros(n, bool),
        pd_pod_gce=np.zeros((p, 1), bool), pd_node_gce=np.zeros((n, 1), bool),
        pd_extra_gce=np.zeros(p, np.int32),
        pd_node_extra_gce=np.zeros(n, np.int32),
        pd_node_err_gce=np.zeros(n, bool),
        vz_group=np.zeros(p, np.int32), vz_mask=np.ones((1, n), bool),
        sa_group=np.zeros(p, np.int32), sa_mask=np.ones((1, n), bool),
        saa_group=np.zeros(p, np.int32), saa_src=np.zeros((p, 1), bool),
        saa_dom=np.zeros((1, n), np.int32),
        saa_labeled=np.zeros((1, n), bool),
        saa_cnt=np.zeros((1, 1, 1), np.float32),
        saa_num=np.zeros(1, np.float32),
        nl_pred_row=np.ones(n, bool), nl_prio_rows=np.zeros((1, n), bool))


def compile_volsvc(pods: Sequence[api.Pod],
                   nodes: Sequence[api.Node],
                   schedulable: np.ndarray,
                   volume_pods: Sequence[tuple[api.Pod, int]] = (),
                   listers: Optional[VolumeListers] = None,
                   service_affinity_labels: tuple[str, ...] = (),
                   service_anti_affinity_labels: tuple[str, ...] = (),
                   node_label_args: Optional[tuple[tuple[str, ...], bool]] = None,
                   node_label_prio_args: Sequence[tuple[str, bool]] = (),
                   service_peers=None, first_peer=None) -> VolSvcTensors:
    """Build all volume/service tables for a batch.

    ``service_peers(ns, selector)`` -> list of node names hosting matching
    assigned pods; ``first_peer(ns, selector)`` -> first such node name or
    None.  Both come from the scheduler cache.
    """
    n = len(nodes)
    p = len(pods)
    any_vols = any(pod.volumes for pod in pods)
    if any_vols or volume_pods:
        pe, ne, xe, nxe, nee = _compile_pd_family(
            pods, volume_pods, n, "ebs", listers)
        pg, ng, xg, nxg, neg = _compile_pd_family(
            pods, volume_pods, n, "gce", listers)
    else:
        pe = np.zeros((p, 1), bool)
        ne = np.zeros((n, 1), bool)
        xe = np.zeros(p, np.int32)
        nxe = np.zeros(n, np.int32)
        nee = np.zeros(n, bool)
        pg, ng, xg = pe.copy(), ne.copy(), xe.copy()
        nxg, neg = nxe.copy(), nee.copy()

    if any_vols:
        vz_group, vz_mask = _compile_volume_zone(pods, nodes, listers)
    else:
        vz_group = np.zeros(p, np.int32)
        vz_mask = np.ones((1, n), bool)

    if service_affinity_labels:
        sa_group, sa_mask = _compile_service_affinity(
            pods, nodes, service_affinity_labels, listers, first_peer)
    else:
        sa_group = np.zeros(p, np.int32)
        sa_mask = np.ones((1, n), bool)

    if service_anti_affinity_labels:
        (saa_group, saa_src, saa_dom, saa_labeled, saa_cnt,
         saa_num) = _compile_service_anti_affinity(
            pods, nodes, schedulable, service_anti_affinity_labels, listers,
            service_peers)
    else:
        saa_group = np.zeros(p, np.int32)
        saa_src = np.zeros((p, 1), bool)
        saa_dom = np.zeros((1, n), np.int32)
        saa_labeled = np.zeros((1, n), bool)
        saa_cnt = np.zeros((1, 1, 1), np.float32)
        saa_num = np.zeros(1, np.float32)

    # CheckNodeLabelPresence: with presence=True every listed label must be
    # on the node; with False none may be (predicates.go:599-621).
    nl_pred_row = np.ones(n, bool)
    if node_label_args is not None:
        nl_labels, nl_presence = node_label_args
        for lb in nl_labels:
            has = np.array([lb in nd.labels for nd in nodes], bool)
            nl_pred_row &= has if nl_presence else ~has
    nl_prio_rows = np.zeros((max(len(node_label_prio_args), 1), n), bool)
    for li, (lb, pres) in enumerate(node_label_prio_args):
        has = np.array([lb in nd.labels for nd in nodes], bool)
        nl_prio_rows[li] = has if pres else ~has

    return VolSvcTensors(
        pd_pod_ebs=pe, pd_node_ebs=ne, pd_extra_ebs=xe,
        pd_node_extra_ebs=nxe, pd_node_err_ebs=nee,
        pd_pod_gce=pg, pd_node_gce=ng, pd_extra_gce=xg,
        pd_node_extra_gce=nxg, pd_node_err_gce=neg,
        vz_group=vz_group, vz_mask=vz_mask,
        sa_group=sa_group, sa_mask=sa_mask,
        saa_group=saa_group, saa_src=saa_src, saa_dom=saa_dom,
        saa_labeled=saa_labeled, saa_cnt=saa_cnt, saa_num=saa_num,
        nl_pred_row=nl_pred_row, nl_prio_rows=nl_prio_rows)
