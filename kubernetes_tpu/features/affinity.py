"""Inter-pod (anti-)affinity compilation: terms -> sig tables -> tensors.

The reference evaluates inter-pod affinity as nested loops over
(candidate pod x existing pods x terms x nodes) — the quadratic heart of
``predicates.go:825-1068`` and ``interpod_affinity.go:117-260``.  The TPU
recast groups every term by its *signature* — (resolved namespace set,
selector, topology key[, weight]) — and precomputes one [S, N] row table per
signature family, so the whole per-(pod,node) evaluation becomes three
[P,S] @ [S,N] contractions on the MXU (see ops/interpod.py).

Three signature families:

``match`` sigs (M) — "does an existing pod match this (ns, selector)?",
    used by the candidate's OWN terms: required affinity (reach must be
    nonzero), required anti-affinity (reach must be zero), and preferred
    ±weight (reach count scales the score).  Reach of sig s =
    per-node count of matching existing pods' topology domains.

``decl`` sigs (D) — anti-affinity terms DECLARED by existing pods
    (satisfiesExistingPodsAntiAffinity, predicates.go:1000-1035): candidate
    matching the sig may not land in the topology of any declaring pod.

``sym`` sigs (Y) — the priority's symmetric soft part
    (interpod_affinity.go:164-196): terms declared by existing pods
    (required affinity x hardPodAffinityWeight, preferred affinity +w,
    preferred anti-affinity -w) score candidate pods that match them.

Topology: ``node_dom[K, N]`` holds a compact domain id per (key, node), -1
when the node lacks the label; key index -1 in a sig means the term had an
empty topologyKey, which the reference resolves as "any default failure
domain" (topologies.go:66-76).  The first ``n_default`` rows are the default
failure-domain keys.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import NamedTuple, Optional, Sequence

import numpy as np

from kubernetes_tpu.api import types as api
from kubernetes_tpu.features import compiler as fc
from kubernetes_tpu.features.padcap import pad1 as _pad1, pow2 as _pow2

# Resolved namespace marker: () after resolution means "all namespaces".
_ALL_NS = ()


def _resolve_ns(term: api.PodAffinityTerm, owner: api.Pod) -> tuple[str, ...]:
    """getNamespacesFromPodAffinityTerm (topologies.go:31-38)."""
    if term.namespaces is None:
        return (owner.namespace,)
    return tuple(sorted(set(term.namespaces)))


def _sel_sig(sel: Optional[api.LabelSelector]):
    """Hashable selector identity.  None (nil selector) matches nothing
    (LabelSelectorAsSelector -> Nothing)."""
    if sel is None:
        return None
    return (tuple(sorted(sel.match_labels)),
            tuple(sorted((e.key, e.operator, tuple(sorted(e.values)))
                         for e in sel.match_expressions)))


@dataclass(frozen=True)
class Sig:
    """One deduplicated term signature."""

    namespaces: tuple[str, ...]  # () = all namespaces
    selector: object             # _sel_sig output (None = matches nothing)
    key: str                     # topology key ("" = default domains)
    weight: int = 0              # sym sigs only (signed)


class AffinityTensors(NamedTuple):
    """Device-ready affinity tables for one batch.  All S dims are >= 1
    (padded with inert rows) so shapes are stable when no affinity exists."""

    node_dom: np.ndarray     # [K, N] int32 domain ids, -1 absent
    n_default: np.ndarray    # [] int32 — first rows of node_dom = default keys
    # -- match sigs (candidate's own terms) --
    match_key: np.ndarray    # [Sm] int32 key row, -1 = any-default
    match_cnt: np.ndarray    # [Sm, N] f32 — matching existing pods per domain-reach
    match_total: np.ndarray  # [Sm] f32 — matching existing pods anywhere
    match_src: np.ndarray    # [P, Sm] bool — batch pod matches sig (placement source)
    aff_need: np.ndarray     # [P, Sm] bool — required affinity
    aff_self: np.ndarray     # [P, Sm] bool — self-match escape (predicates.go:1038-1048)
    anti_need: np.ndarray    # [P, Sm] bool — required anti-affinity
    pref_w: np.ndarray       # [P, Sm] f32 — signed preferred weight sum
    # -- decl sigs (existing pods' hard anti-affinity) --
    decl_key: np.ndarray     # [Sd] int32
    decl_reach: np.ndarray   # [Sd, N] bool — forbidden topology of declaring pods
    decl_match: np.ndarray   # [P, Sd] bool — candidate is repelled by sig
    decl_src: np.ndarray     # [P, Sd] bool — batch pod declares sig
    # -- sym sigs (existing pods' scored terms) --
    sym_key: np.ndarray      # [Ss] int32
    sym_w: np.ndarray        # [Ss] f32 signed weight
    sym_cnt: np.ndarray      # [Ss, N] f32 — declaring term instances per domain-reach
    sym_match: np.ndarray    # [P, Ss] bool — candidate matches sig
    sym_src: np.ndarray      # [P, Ss] bool — batch pod declares term with sig
    has_any: bool            # static: skip all kernels when False


def _pod_matches_sig(sig: Sig, ns: str, labels: dict[str, str]) -> bool:
    if sig.namespaces != _ALL_NS and ns not in sig.namespaces:
        return False
    if sig.selector is None:
        return False
    ml, mexpr = sig.selector
    for k, v in ml:
        if labels.get(k) != v:
            return False
    for k, op, vals in mexpr:
        has = k in labels
        if op == "In":
            if not has or labels[k] not in vals:
                return False
        elif op == "NotIn":
            if has and labels[k] in vals:
                return False
        elif op == "Exists":
            if not has:
                return False
        elif op == "DoesNotExist":
            if has:
                return False
        else:
            return False
    return True


def _sig_match_existing(sig: Sig, ep: fc.ExistingPodTensors,
                        space: fc.FeatureSpace) -> np.ndarray:
    """[M] bool — existing pods matching sig (ns + selector), vectorized over
    the existing-pod label multi-hot."""
    m = ep.labels.shape[0]
    cand = ep.alive & (ep.node_idx >= 0)
    if sig.namespaces != _ALL_NS:
        ns_ids = [space.namespaces.get(n) for n in sig.namespaces]
        ns_ids = [i for i in ns_ids if i >= 0]
        if not ns_ids:
            return np.zeros(m, bool)
        cand &= np.isin(ep.ns_id, ns_ids)
    if sig.selector is None:
        return np.zeros(m, bool)
    ml, mexpr = sig.selector
    mask = cand
    for k, v in ml:
        kv = space.pod_labels.kv_get(k, v)
        mask = mask & (ep.labels[:, kv] if kv >= 0 else False)
    for k, op, vals in mexpr:
        kid = space.pod_labels.key_get(k)
        has = ep.labels[:, kid] if kid >= 0 else np.zeros(m, bool)
        ids = [space.pod_labels.kv_get(k, v) for v in vals]
        ids = [i for i in ids if i >= 0]
        inset = ep.labels[:, ids].any(1) if ids else np.zeros(m, bool)
        if op == "In":
            mask = mask & inset
        elif op == "NotIn":
            mask = mask & ~inset
        elif op == "Exists":
            mask = mask & has
        elif op == "DoesNotExist":
            mask = mask & ~has
        else:
            return np.zeros(m, bool)
    return np.asarray(mask, bool)


class _DomainTable:
    """node_dom builder: interned topology keys -> per-node domain ids."""

    def __init__(self, nodes: Sequence[api.Node], n: int):
        self.nodes = nodes
        self.n = n
        self.keys: list[str] = list(api.DEFAULT_FAILURE_DOMAINS)
        self.key_to_row: dict[str, int] = {k: i for i, k in enumerate(self.keys)}
        self.n_default = len(self.keys)

    def row(self, key: str) -> int:
        """Row index for a non-empty topology key ('' handled by caller as -1)."""
        r = self.key_to_row.get(key)
        if r is None:
            r = len(self.keys)
            self.keys.append(key)
            self.key_to_row[key] = r
        return r

    def build(self) -> np.ndarray:
        n = self.n
        dom = np.full((len(self.keys), n), -1, np.int32)
        for ki, key in enumerate(self.keys):
            vals: dict[str, int] = {}
            for i, node in enumerate(self.nodes):
                v = node.labels.get(key)
                if v:  # len(labels[key]) > 0 (topologies.go:58)
                    dom[ki, i] = vals.setdefault(v, len(vals))
        return dom

    def same_topo_row(self, dom: np.ndarray, key_row: int,
                      node_idx: int) -> np.ndarray:
        """[N] bool — nodes sharing topology with node_idx under key_row
        (-1 = any default key), NodesHaveSameTopologyKey semantics."""
        if key_row >= 0:
            d = dom[key_row]
            return (d == d[node_idx]) & (d >= 0)
        out = np.zeros(dom.shape[1], bool)
        for r in range(self.n_default):
            d = dom[r]
            out |= (d == d[node_idx]) & (d >= 0)
        return out


@dataclass
class _SigTable:
    sig_to_idx: dict[Sig, int] = field(default_factory=dict)
    sigs: list[Sig] = field(default_factory=list)

    def idx(self, sig: Sig) -> int:
        i = self.sig_to_idx.get(sig)
        if i is None:
            i = len(self.sigs)
            self.sig_to_idx[sig] = i
            self.sigs.append(sig)
        return i


def _pod_terms(pod: api.Pod):
    """(required_affinity, required_anti, preferred_affinity_weighted,
    preferred_anti_weighted) — getPodAffinityTerms/getPodAntiAffinityTerms
    (predicates.go:881-906) + the priority's preferred lists."""
    aff = pod.affinity()
    req_a: tuple = ()
    req_aa: tuple = ()
    pref_a: tuple = ()
    pref_aa: tuple = ()
    if aff is not None:
        if aff.pod_affinity is not None:
            req_a = aff.pod_affinity.required
            pref_a = aff.pod_affinity.preferred
        if aff.pod_anti_affinity is not None:
            req_aa = aff.pod_anti_affinity.required
            pref_aa = aff.pod_anti_affinity.preferred
    return req_a, req_aa, pref_a, pref_aa


def pod_has_affinity(pod: api.Pod) -> bool:
    """PodsWithAffinity membership (node_info.go): any affinity annotation."""
    return pod.affinity() is not None


def compile_affinity(pods: Sequence[api.Pod],
                     affinity_pods: Sequence[tuple[api.Pod, int]],
                     ep: Optional[fc.ExistingPodTensors],
                     nodes: Optional[Sequence[api.Node]],
                     n_nodes: int,
                     space: fc.FeatureSpace,
                     hard_pod_affinity_weight: int = 1,
                     reps: Optional[Sequence[api.Pod]] = None,
                     tpl_idx: Optional[np.ndarray] = None) -> AffinityTensors:
    """Build the batch's affinity tables.

    ``affinity_pods``: (existing pod, node index) for every assigned pod with
    an affinity annotation (the cache's PodsWithAffinity analogue).
    ``ep``: existing-pod label tensors for vectorized own-term matching.
    ``nodes`` may be None (no label access): every topology domain is then
    empty, matching nodes without the label.
    ``reps``/``tpl_idx``: template dedup from compile_batch — per-pod
    incidence rows are built once per spec-identical template and gathered
    back to the full pod axis.
    """
    if reps is not None and tpl_idx is not None:
        cand = reps
    else:
        cand = pods
        tpl_idx = None
    p = len(cand)
    n = n_nodes
    dt = _DomainTable(nodes or [], n)

    m_tab, d_tab, y_tab = _SigTable(), _SigTable(), _SigTable()

    # -- candidate pods' own terms -> match sigs ------------------------
    pod_m: list[list[tuple[int, str]]] = []  # per pod: (sig idx, kind)
    pod_pref: list[list[tuple[int, int]]] = []  # per pod: (sig idx, ±weight)
    any_affinity = False
    for pod in cand:
        req_a, req_aa, pref_a, pref_aa = _pod_terms(pod)
        entries: list[tuple[int, str]] = []
        prefs: list[tuple[int, int]] = []
        for t in req_a:
            sig = Sig(_resolve_ns(t, pod), _sel_sig(t.label_selector),
                      t.topology_key)
            entries.append((m_tab.idx(sig), "aff"))
        for t in req_aa:
            sig = Sig(_resolve_ns(t, pod), _sel_sig(t.label_selector),
                      t.topology_key)
            entries.append((m_tab.idx(sig), "anti"))
        for wt in pref_a:
            if wt.weight == 0:
                continue
            t = wt.pod_affinity_term
            sig = Sig(_resolve_ns(t, pod), _sel_sig(t.label_selector),
                      t.topology_key)
            prefs.append((m_tab.idx(sig), wt.weight))
        for wt in pref_aa:
            if wt.weight == 0:
                continue
            t = wt.pod_affinity_term
            sig = Sig(_resolve_ns(t, pod), _sel_sig(t.label_selector),
                      t.topology_key)
            prefs.append((m_tab.idx(sig), -wt.weight))
        if entries or prefs:
            any_affinity = True
        pod_m.append(entries)
        pod_pref.append(prefs)

    # -- existing pods' terms -> decl + sym sigs ------------------------
    decl_sources: dict[int, list[int]] = {}  # decl sig -> [node_idx]
    sym_sources: dict[int, list[int]] = {}   # sym sig -> [node_idx] per instance
    for epod, nidx in affinity_pods:
        if nidx < 0 or nidx >= n:
            continue
        req_a, req_aa, pref_a, pref_aa = _pod_terms(epod)
        for t in req_aa:
            sig = Sig(_resolve_ns(t, epod), _sel_sig(t.label_selector),
                      t.topology_key)
            decl_sources.setdefault(d_tab.idx(sig), []).append(nidx)
            any_affinity = True
        if hard_pod_affinity_weight > 0:
            for t in req_a:
                sig = Sig(_resolve_ns(t, epod), _sel_sig(t.label_selector),
                          t.topology_key, weight=hard_pod_affinity_weight)
                sym_sources.setdefault(y_tab.idx(sig), []).append(nidx)
                any_affinity = True
        for wt in pref_a:
            if wt.weight == 0:
                continue
            t = wt.pod_affinity_term
            sig = Sig(_resolve_ns(t, epod), _sel_sig(t.label_selector),
                      t.topology_key, weight=wt.weight)
            sym_sources.setdefault(y_tab.idx(sig), []).append(nidx)
            any_affinity = True
        for wt in pref_aa:
            if wt.weight == 0:
                continue
            t = wt.pod_affinity_term
            sig = Sig(_resolve_ns(t, epod), _sel_sig(t.label_selector),
                      t.topology_key, weight=-wt.weight)
            sym_sources.setdefault(y_tab.idx(sig), []).append(nidx)
            any_affinity = True

    # Batch pods that DECLARE terms (for in-batch sequential visibility):
    # placing pod j extends decl reach / sym counts / match counts.
    # Register their sigs too so the scan state has rows for them.
    pod_decl: list[list[int]] = []
    pod_sym: list[list[int]] = []
    for pod in cand:
        req_a, req_aa, pref_a, pref_aa = _pod_terms(pod)
        dsigs: list[int] = []
        ysigs: list[int] = []
        for t in req_aa:
            sig = Sig(_resolve_ns(t, pod), _sel_sig(t.label_selector),
                      t.topology_key)
            dsigs.append(d_tab.idx(sig))
        if hard_pod_affinity_weight > 0:
            for t in req_a:
                sig = Sig(_resolve_ns(t, pod), _sel_sig(t.label_selector),
                          t.topology_key, weight=hard_pod_affinity_weight)
                ysigs.append(y_tab.idx(sig))
        for wt in pref_a:
            if wt.weight == 0:
                continue
            t = wt.pod_affinity_term
            ysigs.append(y_tab.idx(Sig(_resolve_ns(t, pod),
                                       _sel_sig(t.label_selector),
                                       t.topology_key, weight=wt.weight)))
        for wt in pref_aa:
            if wt.weight == 0:
                continue
            t = wt.pod_affinity_term
            ysigs.append(y_tab.idx(Sig(_resolve_ns(t, pod),
                                       _sel_sig(t.label_selector),
                                       t.topology_key, weight=-wt.weight)))
        pod_decl.append(dsigs)
        pod_sym.append(ysigs)

    # Assign key rows now that all sigs are known.
    def key_row(sig: Sig) -> int:
        return -1 if sig.key == "" else dt.row(sig.key)

    m_rows = [key_row(s) for s in m_tab.sigs]
    d_rows = [key_row(s) for s in d_tab.sigs]
    y_rows = [key_row(s) for s in y_tab.sigs]
    node_dom = dt.build()

    # Sig-axis sizes are pow2-bucketed (padcap's discipline): live batches
    # mint signatures freely, and every new count would otherwise be a
    # fresh compiled scan shape (measured ~5-7 s recompiles per drain at
    # density rates).  Padded rows are all-zero/inert — no pod references
    # them.
    sm, sd, sy = _pow2(len(m_tab.sigs)), _pow2(len(d_tab.sigs)), \
        _pow2(len(y_tab.sigs))

    # -- match sig state from existing pods -----------------------------
    match_cnt = np.zeros((sm, n), np.float32)
    match_total = np.zeros(sm, np.float32)
    if ep is not None:
        for si, sig in enumerate(m_tab.sigs):
            me = _sig_match_existing(sig, ep, space)
            if not me.any():
                continue
            nidxs = ep.node_idx[me]
            match_total[si] = float(len(nidxs))
            krow = m_rows[si]
            for ni in nidxs:
                match_cnt[si] += dt.same_topo_row(node_dom, krow, int(ni))

    decl_reach = np.zeros((sd, n), bool)
    for si, nidxs in decl_sources.items():
        krow = d_rows[si]
        for ni in set(nidxs):
            decl_reach[si] |= dt.same_topo_row(node_dom, krow, ni)

    sym_cnt = np.zeros((sy, n), np.float32)
    for si, nidxs in sym_sources.items():
        krow = y_rows[si]
        for ni in nidxs:  # one instance per declaring term occurrence
            sym_cnt[si] += dt.same_topo_row(node_dom, krow, ni)

    # -- per-pod incidence matrices --------------------------------------
    aff_need = np.zeros((p, sm), bool)
    aff_self = np.zeros((p, sm), bool)
    anti_need = np.zeros((p, sm), bool)
    pref_w = np.zeros((p, sm), np.float32)
    match_src = np.zeros((p, sm), bool)
    decl_match = np.zeros((p, sd), bool)
    decl_src = np.zeros((p, sd), bool)
    sym_match = np.zeros((p, sy), bool)
    sym_src = np.zeros((p, sy), bool)

    # Candidate-vs-sig matching memoized by (namespace, labels) template:
    # pods stamped from one controller share labels, so each template is
    # matched against each sig family once.
    tmpl_cache: dict = {}
    for i, pod in enumerate(cand):
        for si, kind in pod_m[i]:
            if kind == "aff":
                aff_need[i, si] = True
            else:
                anti_need[i, si] = True
        for si, w in pod_pref[i]:
            pref_w[i, si] += w
        for si in pod_decl[i]:
            decl_src[i, si] = True
        for si in pod_sym[i]:
            sym_src[i, si] = True
        tkey = (pod.namespace, tuple(sorted(pod.labels.items())))
        rows = tmpl_cache.get(tkey)
        if rows is None:
            rows = (
                np.array([_pod_matches_sig(s, pod.namespace, pod.labels)
                          for s in m_tab.sigs] or [False], bool),
                np.array([_pod_matches_sig(s, pod.namespace, pod.labels)
                          for s in d_tab.sigs] or [False], bool),
                np.array([_pod_matches_sig(s, pod.namespace, pod.labels)
                          for s in y_tab.sigs] or [False], bool))
            tmpl_cache[tkey] = rows
        match_src[i, :len(rows[0])] = rows[0][:sm]
        decl_match[i, :len(rows[1])] = rows[1][:sd]
        sym_match[i, :len(rows[2])] = rows[2][:sy]
        # Self-match escape hatch (predicates.go:1038-1048).
        for si, kind in pod_m[i]:
            if kind == "aff" and match_src[i, si]:
                aff_self[i, si] = True

    if tpl_idx is not None:
        # Expand template rows back to the full pod axis.
        aff_need, aff_self, anti_need, pref_w, match_src = (
            a[tpl_idx] for a in (aff_need, aff_self, anti_need, pref_w,
                                 match_src))
        decl_match, decl_src = decl_match[tpl_idx], decl_src[tpl_idx]
        sym_match, sym_src = sym_match[tpl_idx], sym_src[tpl_idx]

    return AffinityTensors(
        node_dom=node_dom,
        n_default=np.int32(dt.n_default),
        match_key=_pad1(m_rows, sm, -1, np.int32),
        match_cnt=match_cnt, match_total=match_total, match_src=match_src,
        aff_need=aff_need, aff_self=aff_self, anti_need=anti_need,
        pref_w=pref_w,
        decl_key=_pad1(d_rows, sd, -1, np.int32),
        decl_reach=decl_reach, decl_match=decl_match, decl_src=decl_src,
        sym_key=_pad1(y_rows, sy, -1, np.int32),
        sym_w=_pad1([s.weight for s in y_tab.sigs], sy, 0, np.float32),
        sym_cnt=sym_cnt, sym_match=sym_match, sym_src=sym_src,
        has_any=any_affinity)
