"""Monotonic compile capacities for content-sized table axes.

The solver's scan is specialized on the shapes of its signature/group
tables (inter-pod affinity sigs, PD volume widths, volume-zone and
service-affinity groups, selector/spread/avoid groups).  Those counts vary
freely with live batch content, and every new count is a fresh XLA
compile — measured as multi-second stalls on the scheduling clock at
density rates.  The vocabulary spaces (features.vocab) already solve this
for string features by growing capacity monotonically in buckets; this
module applies the same discipline to the table axes: each axis is padded
up to the largest pow2 size this scheduler instance has ever seen, so a
long-running daemon converges on one compiled program per (chunk, cluster)
shape.

Padded rows/columns are inert by construction: no pod index references
them, mask rows pad with "no constraint" (True), count/score rows with
zero, key rows with -1.
"""

from __future__ import annotations

import dataclasses

import numpy as np

# axis name -> list of (container, field, axis, fill).  Container "" = the
# PodBatch itself, "aff"/"volsvc" its nested tables.  Every field listed
# for one axis name shares that axis size by construction.
AXES: dict[str, list[tuple[str, str, int, object]]] = {
    "aff_sm": [("aff", "match_key", 0, -1), ("aff", "match_cnt", 0, 0.0),
               ("aff", "match_total", 0, 0.0), ("aff", "match_src", 1, False),
               ("aff", "aff_need", 1, False), ("aff", "aff_self", 1, False),
               ("aff", "anti_need", 1, False), ("aff", "pref_w", 1, 0.0)],
    "aff_sd": [("aff", "decl_key", 0, -1), ("aff", "decl_reach", 0, False),
               ("aff", "decl_match", 1, False), ("aff", "decl_src", 1, False)],
    "aff_sy": [("aff", "sym_key", 0, -1), ("aff", "sym_w", 0, 0.0),
               ("aff", "sym_cnt", 0, 0.0), ("aff", "sym_match", 1, False),
               ("aff", "sym_src", 1, False)],
    "vs_we": [("volsvc", "pd_pod_ebs", 1, False),
              ("volsvc", "pd_node_ebs", 1, False)],
    "vs_wg": [("volsvc", "pd_pod_gce", 1, False),
              ("volsvc", "pd_node_gce", 1, False)],
    "vs_vz": [("volsvc", "vz_mask", 0, True)],
    "vs_sa": [("volsvc", "sa_mask", 0, True)],
    "vs_saa_g": [("volsvc", "saa_src", 1, False),
                 ("volsvc", "saa_cnt", 1, 0.0),
                 ("volsvc", "saa_num", 0, 0.0)],
    "vs_saa_d": [("volsvc", "saa_cnt", 2, 0.0)],
    "b_sel": [("", "sel_required", 0, True),
              ("", "sel_pref_counts", 0, 0)],
    "b_spread": [("", "spread_node_counts", 0, 0.0),
                 ("", "spread_zone_counts", 0, 0.0),
                 ("", "spread_has_zones", 0, False),
                 ("", "spread_incr", 1, False)],
    "b_avoid": [("", "avoid_rows", 0, False)],
    "b_nztmpl": [("", "nz_templates", 0, 0)],
}

# Axes where an EMPTY table is a semantic sentinel (feature disabled for
# this batch — the fused scan's over-cap fallback), not a size-0 count:
# padding it up would fabricate live rows.
SKIP_EMPTY_AXES = frozenset({"b_nztmpl"})


def pow2(x: int) -> int:
    """Next power of two ≥ max(x, 1) — the bucket size for every
    content-sized axis (bounds distinct compiled shapes at log2)."""
    return 1 << (max(x, 1) - 1).bit_length()


def pad_rows_pow2(a: np.ndarray, fill=0) -> np.ndarray:
    """Pad dim 0 to its pow2 bucket with `fill` rows."""
    return _pad_axis(a, 0, pow2(a.shape[0]), fill)


def stack_pad(rows: list, n: int, fill, dtype=bool) -> np.ndarray:
    """Stack [*, n] rows padded to a pow2 row count with `fill` rows."""
    g = pow2(len(rows))
    out = np.full((g, n), fill, dtype)
    if rows:
        out[:len(rows)] = np.stack(rows)
    return out


def pad1(vals, size: int, fill, dtype) -> np.ndarray:
    """A 1-D array of `size` filled with `fill` beyond len(vals)."""
    out = np.full(size, fill, dtype)
    vals = np.asarray(vals, dtype)[:size]
    out[:len(vals)] = vals
    return out


def _pad_axis(a: np.ndarray, axis: int, size: int, fill) -> np.ndarray:
    if a.shape[axis] >= size:
        return a
    shape = list(a.shape)
    shape[axis] = size
    out = np.full(shape, fill, a.dtype)
    sl = tuple(slice(0, s) for s in a.shape)
    out[sl] = a
    return out


def apply_caps(batch, caps: dict[str, int]):
    """Pad `batch`'s content-sized axes up to the monotonic caps, growing
    the caps to cover this batch.  Returns a (possibly replaced) batch;
    untouched arrays are shared, not copied."""
    batch_updates: dict = {}
    aff_updates: dict = {}
    vs_updates: dict = {}
    for axis_name, fields in AXES.items():
        container0, field0, axis0, _ = fields[0]
        src0 = batch if container0 == "" else getattr(batch, container0)
        current = getattr(src0, field0).shape[axis0]
        if current == 0 and axis_name in SKIP_EMPTY_AXES:
            continue
        cap = max(caps.get(axis_name, 1), current)
        caps[axis_name] = cap
        if cap == current:
            continue
        for container, field, axis, fill in fields:
            src = batch if container == "" else getattr(batch, container)
            updates = (batch_updates if container == "" else
                       aff_updates if container == "aff" else vs_updates)
            # A field listed under two axes (saa_cnt: group AND domain)
            # must pad its already-padded copy, not the original.
            arr = updates.get(field, getattr(src, field))
            updates[field] = _pad_axis(arr, axis, cap, fill)
    if aff_updates:
        batch_updates["aff"] = batch.aff._replace(**aff_updates)
    if vs_updates:
        batch_updates["volsvc"] = batch.volsvc._replace(**vs_updates)
    if batch_updates:
        batch = dataclasses.replace(batch, **batch_updates)
    return batch
