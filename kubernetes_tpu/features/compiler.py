"""Feature compiler: Pod/Node objects -> dense device tensors.

This is the tensor-native replacement for the reference's ``schedulercache``
(``plugin/pkg/scheduler/schedulercache/node_info.go``): where ``NodeInfo``
pre-aggregates requested/allocatable resources and per-node pod lists for one
node, we build the whole cluster as stacked arrays so every predicate and
priority evaluates for all (pod, node) pairs at once on the MXU/VPU.

Unit conventions (chosen so exact Go int64 arithmetic fits in int32 on TPU):
  cpu     : millicores            (reference: int64 millicores)
  memory  : MiB — requests ceil'd, allocatable floor'd (reference: bytes).
            Real-world requests are MiB-aligned (incl. the 200*1024*1024-byte
            non-zero default, non_zero.go:47), so quantization is exact in
            practice; the parity harness measures any residual divergence.
  gpu     : count
  pods    : count
  image   : KiB (floor)

Resource vectors are [*, 4] int32 in order (milli_cpu, memory_mib, gpu, pods).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from kubernetes_tpu.api import types as api
from kubernetes_tpu.features.vocab import LabelVocab, Vocab

RES_CPU, RES_MEM, RES_GPU, RES_PODS = 0, 1, 2, 3

_MIB = 1024 * 1024


def _mib_ceil(b: int) -> int:
    return -((-b) // _MIB)


def _mib_floor(b: int) -> int:
    return b // _MIB


@dataclass
class FeatureSpace:
    """All interning vocabularies; the single source of id assignment."""

    labels: LabelVocab = field(default_factory=LabelVocab)       # node labels
    # Pod labels get their own vocabulary: selector matching against
    # existing pods only ever reads POD labels, and node vocabularies carry
    # per-node uniques (hostname) that would blow the [pods, V] matrix up
    # by orders of magnitude.
    pod_labels: LabelVocab = field(default_factory=LabelVocab)
    taints: Vocab = field(default_factory=Vocab)       # "key=value:effect"
    ports: Vocab = field(default_factory=Vocab)        # "tcp:port" etc
    volumes: Vocab = field(default_factory=Vocab)      # conflict keys
    images: Vocab = field(default_factory=Vocab)       # image name
    namespaces: Vocab = field(default_factory=Vocab)
    topo_keys: Vocab = field(default_factory=Vocab)    # topology label keys
    topo_vals: Vocab = field(default_factory=Vocab)    # "key=value" domains

    def __post_init__(self) -> None:
        # Default failure domains are always interned so topology columns
        # exist from the start (pkg/api/types.go:3053-3063).
        for k in api.DEFAULT_FAILURE_DOMAINS:
            self.topo_keys.id(k)

    # -- volume conflict tokens (predicates.go:100-144) --------------------
    @staticmethod
    def volume_tokens(v: api.Volume) -> list[tuple[str, bool]]:
        """Conflict tokens for a volume as (token, read_only) pairs.

        EBS conflicts regardless of read-only (predicates.go:116-120), so its
        token is always read_only=False.  RBD "shares at least one monitor"
        (haveSame, predicates.go:126-133) is made exact by emitting one token
        per monitor.
        """
        out: list[tuple[str, bool]] = []
        if v.gce_pd_name:
            out.append((f"gce:{v.gce_pd_name}", v.gce_read_only))
        if v.aws_ebs_id:
            out.append((f"ebs:{v.aws_ebs_id}", False))
        if v.rbd_key:
            mons, pool, image = (v.rbd_key.split("#") + ["", ""])[:3]
            for mon in mons.split(","):
                if mon:
                    out.append((f"rbd:{mon}#{pool}#{image}", v.rbd_read_only))
        return out


@dataclass
class NodeTensors:
    """Static per-node features [N, ...] (rebuilt when nodes change)."""

    names: list[str]
    name_to_idx: dict[str, int]
    alloc: np.ndarray          # [N, 4] int32
    labels: np.ndarray         # [N, V] bool — kv + key-presence membership
    taints_nosched: np.ndarray  # [N, T] bool  (effect != PreferNoSchedule)
    taints_prefer: np.ndarray   # [N, T] bool  (effect == PreferNoSchedule)
    mem_pressure: np.ndarray   # [N] bool
    disk_pressure: np.ndarray  # [N] bool
    schedulable: np.ndarray    # [N] bool — getNodeConditionPredicate
    image_kib: np.ndarray      # [N, I] int32
    topo_val: np.ndarray       # [N, K] int32 — domain id per topo key, -1 absent

    @property
    def n(self) -> int:
        return len(self.names)


@dataclass
class NodeAggregates:
    """Per-node aggregates over the pods assigned to each node — the tensor
    analogue of NodeInfo.{requestedResource, nonzeroRequest, pods}
    (node_info.go:32-61).  Maintained incrementally by the scheduler cache."""

    requested: np.ndarray      # [N, 4] int32 (cpu, mem_mib, gpu, pod count)
    nonzero: np.ndarray        # [N, 2] int32 (cpu, mem_mib)
    ports_used: np.ndarray     # [N, P] bool
    vol_any: np.ndarray        # [N, W] bool — volume token mounted by any pod
    vol_rw: np.ndarray         # [N, W] bool — mounted by a non-read-only pod...
    vol_rw_count: np.ndarray   # [N, W] int16 rw mount counts (for removal)
    vol_any_count: np.ndarray  # [N, W] int16


@dataclass
class ExistingPodTensors:
    """Existing (assigned, non-terminated) pods as tensors — for selector
    spreading and inter-pod affinity, which must match *other pods'* labels.
    [M, ...] with a capacity that grows geometrically."""

    labels: np.ndarray         # [M, V] bool
    ns_id: np.ndarray          # [M] int32
    node_idx: np.ndarray       # [M] int32 (-1 = slot free)
    alive: np.ndarray          # [M] bool
    deleted: np.ndarray        # [M] bool (DeletionTimestamp set)
    keys: list[Optional[str]]  # slot -> pod key
    key_to_slot: dict[str, int]
    free_slots: list[int]      # O(1) slot allocation (popped LIFO)


def compile_nodes(nodes: Sequence[api.Node], space: FeatureSpace) -> NodeTensors:
    """Build static node tensors, interning all label/taint/image tokens.
    Row encoding is shared with the incremental churn path
    (update_node_row/append_node_row) via _intern_node/_write_node_row, so
    rebuilt rows and incrementally-updated rows cannot diverge."""
    n = len(nodes)
    # Intern first so capacities are final before allocation.
    for node in nodes:
        _intern_node(node, space)

    V, T, I, K = (space.labels.capacity, space.taints.capacity,
                  space.images.capacity, space.topo_keys.capacity)
    nt = NodeTensors(
        names=[nd.name for nd in nodes],
        name_to_idx={nd.name: i for i, nd in enumerate(nodes)},
        alloc=np.zeros((n, 4), np.int32),
        labels=np.zeros((n, V), bool),
        taints_nosched=np.zeros((n, T), bool),
        taints_prefer=np.zeros((n, T), bool),
        mem_pressure=np.zeros(n, bool),
        disk_pressure=np.zeros(n, bool),
        schedulable=np.zeros(n, bool),
        image_kib=np.zeros((n, I), np.int32),
        topo_val=np.full((n, K), -1, np.int32))
    for i, node in enumerate(nodes):
        _write_node_row(nt, i, node, space)
    return nt


def _intern_node(node: api.Node, space: FeatureSpace) -> None:
    for k, v in node.labels.items():
        space.labels.kv_id(k, v)
        space.labels.key_id(k)
    for t in node.taints():
        space.taints.id(f"{t.key}={t.value}:{t.effect}")
    for img in node.images:
        for name in img.names:
            space.images.id(name)
    for key in space.topo_keys.tokens():
        if key in node.labels:
            space.topo_vals.id(f"{key}={node.labels[key]}")


def _grow_node_columns(nt: NodeTensors, space: FeatureSpace) -> None:
    nt.labels = _grow_cols(nt.labels, space.labels.capacity)
    nt.taints_nosched = _grow_cols(nt.taints_nosched, space.taints.capacity)
    nt.taints_prefer = _grow_cols(nt.taints_prefer, space.taints.capacity)
    nt.image_kib = _grow_cols(nt.image_kib, space.images.capacity)
    nt.topo_val = _grow_cols(nt.topo_val, space.topo_keys.capacity, fill=-1)


def _write_node_row(nt: NodeTensors, i: int, node: api.Node,
                    space: FeatureSpace) -> None:
    nt.alloc[i] = (node.allocatable_milli_cpu,
                   _mib_floor(node.allocatable_memory),
                   node.allocatable_gpu, node.allocatable_pods)
    nt.labels[i, :] = False
    for k, v in node.labels.items():
        nt.labels[i, space.labels.kv_id(k, v)] = True
        nt.labels[i, space.labels.key_id(k)] = True
    nt.taints_nosched[i, :] = False
    nt.taints_prefer[i, :] = False
    for t in node.taints():
        tid = space.taints.id(f"{t.key}={t.value}:{t.effect}")
        if t.effect == api.TAINT_EFFECT_PREFER_NO_SCHEDULE:
            nt.taints_prefer[i, tid] = True
        else:
            nt.taints_nosched[i, tid] = True
    nt.mem_pressure[i] = node.condition(api.NODE_MEMORY_PRESSURE) == "True"
    nt.disk_pressure[i] = node.condition(api.NODE_DISK_PRESSURE) == "True"
    nt.schedulable[i] = node.is_ready()
    nt.image_kib[i, :] = 0
    for img in node.images:
        kib = img.size_bytes // 1024
        for name in img.names:
            nt.image_kib[i, space.images.id(name)] = kib
    nt.topo_val[i, :] = -1
    for ki, key in enumerate(space.topo_keys.tokens()):
        if key in node.labels:
            nt.topo_val[i, ki] = space.topo_vals.id(
                f"{key}={node.labels[key]}")


def update_node_row(nt: NodeTensors, idx: int, node: api.Node,
                    space: FeatureSpace) -> None:
    """Incremental node UPDATE: rewrite one row of the static node tensors
    in place (growing vocab columns when the node introduced new tokens) —
    the churn path the node controller exercises with Ready flips
    (nodecontroller.go:70-160) must not recompile 5k rows."""
    _intern_node(node, space)
    _grow_node_columns(nt, space)
    _write_node_row(nt, idx, node, space)


def append_node_row(nt: NodeTensors, node: api.Node,
                    space: FeatureSpace) -> int:
    """Incremental node ADD: append one row to every [N, ...] tensor."""
    _intern_node(node, space)
    _grow_node_columns(nt, space)
    i = len(nt.names)
    nt.alloc = np.concatenate([nt.alloc, np.zeros((1, 4), np.int32)])
    nt.labels = np.concatenate(
        [nt.labels, np.zeros((1, nt.labels.shape[1]), bool)])
    nt.taints_nosched = np.concatenate(
        [nt.taints_nosched,
         np.zeros((1, nt.taints_nosched.shape[1]), bool)])
    nt.taints_prefer = np.concatenate(
        [nt.taints_prefer, np.zeros((1, nt.taints_prefer.shape[1]), bool)])
    nt.mem_pressure = np.concatenate([nt.mem_pressure, np.zeros(1, bool)])
    nt.disk_pressure = np.concatenate([nt.disk_pressure, np.zeros(1, bool)])
    nt.schedulable = np.concatenate([nt.schedulable, np.zeros(1, bool)])
    nt.image_kib = np.concatenate(
        [nt.image_kib, np.zeros((1, nt.image_kib.shape[1]), np.int32)])
    nt.topo_val = np.concatenate(
        [nt.topo_val, np.full((1, nt.topo_val.shape[1]), -1, np.int32)])
    nt.names.append(node.name)
    nt.name_to_idx[node.name] = i
    _write_node_row(nt, i, node, space)
    return i


def append_aggregate_row(agg: NodeAggregates) -> None:
    """Zero aggregates for a newly appended node row."""
    agg.requested = np.concatenate(
        [agg.requested, np.zeros((1, 4), np.int32)])
    agg.nonzero = np.concatenate([agg.nonzero, np.zeros((1, 2), np.int32)])
    for field_name in ("ports_used", "vol_any", "vol_rw"):
        a = getattr(agg, field_name)
        setattr(agg, field_name,
                np.concatenate([a, np.zeros((1, a.shape[1]), bool)]))
    for field_name in ("vol_rw_count", "vol_any_count"):
        a = getattr(agg, field_name)
        setattr(agg, field_name,
                np.concatenate([a, np.zeros((1, a.shape[1]), np.int16)]))


def pod_resource_row(pod: api.Pod) -> np.ndarray:
    """[4] int32 (cpu, mem_mib ceil, gpu, 1) — getResourceRequest.

    Cached on the pod: quantity-string parsing dominates at 30k-pod batches
    and pod specs are immutable once submitted (the reference's
    predicateMetadata makes the same assumption, predicates.go:71-98)."""
    row = getattr(pod, "_res_row", None)
    if row is None:
        r = pod.resource_request()
        row = np.array([r.milli_cpu, _mib_ceil(r.memory), r.nvidia_gpu, 1],
                       np.int32)
        pod._res_row = row
    return row


def pod_nonzero_row(pod: api.Pod) -> np.ndarray:
    row = getattr(pod, "_nz_row", None)
    if row is None:
        cpu, mem = pod.non_zero_request()
        row = np.array([cpu, _mib_ceil(mem)], np.int32)
        pod._nz_row = row
    return row


def empty_aggregates(n: int, space: FeatureSpace) -> NodeAggregates:
    P, W = space.ports.capacity, space.volumes.capacity
    return NodeAggregates(
        requested=np.zeros((n, 4), np.int32),
        nonzero=np.zeros((n, 2), np.int32),
        ports_used=np.zeros((n, P), bool),
        vol_any=np.zeros((n, W), bool),
        vol_rw=np.zeros((n, W), bool),
        vol_rw_count=np.zeros((n, W), np.int16),
        vol_any_count=np.zeros((n, W), np.int16))


def _pod_port_ids(pod: api.Pod, space: FeatureSpace) -> list[int]:
    return [space.ports.id(str(p)) for p in pod.used_host_ports()]


def _pod_volume_ids(pod: api.Pod, space: FeatureSpace) -> list[tuple[int, bool]]:
    out = []
    for v in pod.volumes:
        for token, ro in FeatureSpace.volume_tokens(v):
            out.append((space.volumes.id(token), ro))
    return out


def add_pod_to_aggregates(agg: NodeAggregates, node_idx: int, pod: api.Pod,
                          space: FeatureSpace) -> NodeAggregates:
    """NodeInfo.addPod (node_info.go:171-196), tensorized. May grow the port
    and volume columns if the pod interned new tokens."""
    agg = _grow_aggregate_columns(agg, space)
    agg.requested[node_idx] += pod_resource_row(pod)
    agg.nonzero[node_idx] += pod_nonzero_row(pod)
    for pid in _pod_port_ids(pod, space):
        agg = _grow_aggregate_columns(agg, space)
        agg.ports_used[node_idx, pid] = True
    for vid, ro in _pod_volume_ids(pod, space):
        agg = _grow_aggregate_columns(agg, space)
        agg.vol_any_count[node_idx, vid] += 1
        if not ro:
            agg.vol_rw_count[node_idx, vid] += 1
        agg.vol_any[node_idx, vid] = agg.vol_any_count[node_idx, vid] > 0
        agg.vol_rw[node_idx, vid] = agg.vol_rw_count[node_idx, vid] > 0
    return agg


def add_pods_to_aggregates_bulk(agg: NodeAggregates,
                                node_idxs: Sequence[int],
                                pods: Sequence[api.Pod],
                                space: FeatureSpace) -> NodeAggregates:
    """Bulk NodeInfo.addPod for a solved batch: one vectorized update instead
    of per-pod row ops.  Equivalent to repeated add_pod_to_aggregates
    (tested by tests/test_cache_bulk.py)."""
    # Intern first so column growth happens once.
    for pod in pods:
        for port in pod.used_host_ports():
            space.ports.id(str(port))
        for v in pod.volumes:
            for token, _ in FeatureSpace.volume_tokens(v):
                space.volumes.id(token)
    agg = _grow_aggregate_columns(agg, space)
    idxs = np.asarray(node_idxs, np.int64)
    req = np.stack([pod_resource_row(p) for p in pods])
    nz = np.stack([pod_nonzero_row(p) for p in pods])
    np.add.at(agg.requested, idxs, req)
    np.add.at(agg.nonzero, idxs, nz)
    for idx, pod in zip(node_idxs, pods):
        if pod.used_host_ports():
            for pid in _pod_port_ids(pod, space):
                agg.ports_used[idx, pid] = True
        if pod.volumes:
            for vid, ro in _pod_volume_ids(pod, space):
                agg.vol_any_count[idx, vid] += 1
                if not ro:
                    agg.vol_rw_count[idx, vid] += 1
                agg.vol_any[idx, vid] = agg.vol_any_count[idx, vid] > 0
                agg.vol_rw[idx, vid] = agg.vol_rw_count[idx, vid] > 0
    return agg


def remove_pod_from_aggregates(agg: NodeAggregates, node_idx: int, pod: api.Pod,
                               space: FeatureSpace,
                               node_pods: Sequence[api.Pod]) -> NodeAggregates:
    """NodeInfo.removePod (node_info.go:199-227).  ``node_pods`` is the node's
    remaining pod set, needed to recompute the port bitmap exactly (ports are
    a set union, not a counter, in the reference)."""
    agg.requested[node_idx] -= pod_resource_row(pod)
    agg.nonzero[node_idx] -= pod_nonzero_row(pod)
    for vid, ro in _pod_volume_ids(pod, space):
        agg.vol_any_count[node_idx, vid] -= 1
        if not ro:
            agg.vol_rw_count[node_idx, vid] -= 1
        agg.vol_any[node_idx, vid] = agg.vol_any_count[node_idx, vid] > 0
        agg.vol_rw[node_idx, vid] = agg.vol_rw_count[node_idx, vid] > 0
    agg.ports_used[node_idx] = False
    for p in node_pods:
        if p.key != pod.key:
            for pid in _pod_port_ids(p, space):
                agg = _grow_aggregate_columns(agg, space)
                agg.ports_used[node_idx, pid] = True
    return agg


def _grow_cols(a: np.ndarray, width: int, fill=0) -> np.ndarray:
    if a.shape[1] >= width:
        return a
    out = np.full((a.shape[0], width), fill, a.dtype)
    out[:, : a.shape[1]] = a
    return out


def _grow_aggregate_columns(agg: NodeAggregates, space: FeatureSpace) -> NodeAggregates:
    agg.ports_used = _grow_cols(agg.ports_used, space.ports.capacity)
    for f in ("vol_any", "vol_rw", "vol_rw_count", "vol_any_count"):
        setattr(agg, f, _grow_cols(getattr(agg, f), space.volumes.capacity))
    return agg


# ---------------------------------------------------------------------------
# Existing-pod tensors (spreading / inter-pod affinity inputs)
# ---------------------------------------------------------------------------

def empty_existing_pods(space: FeatureSpace, cap: int = 256) -> ExistingPodTensors:
    V = space.pod_labels.capacity
    return ExistingPodTensors(
        labels=np.zeros((cap, V), bool),
        ns_id=np.zeros(cap, np.int32),
        node_idx=np.full(cap, -1, np.int32),
        alive=np.zeros(cap, bool),
        deleted=np.zeros(cap, bool),
        keys=[None] * cap,
        key_to_slot={},
        free_slots=list(range(cap - 1, -1, -1)))


def existing_pods_add(ep: ExistingPodTensors, pod: api.Pod, node_idx: int,
                      space: FeatureSpace) -> ExistingPodTensors:
    for k, v in pod.labels.items():
        space.pod_labels.kv_id(k, v)
        space.pod_labels.key_id(k)
    ep.labels = _grow_cols(ep.labels, space.pod_labels.capacity)
    slot = ep.key_to_slot.get(pod.key)
    if slot is None:
        if not ep.free_slots:
            m = len(ep.keys)
            ep.labels = np.concatenate([ep.labels, np.zeros_like(ep.labels)], 0)
            ep.ns_id = np.concatenate([ep.ns_id, np.zeros(m, np.int32)])
            ep.node_idx = np.concatenate([ep.node_idx, np.full(m, -1, np.int32)])
            ep.alive = np.concatenate([ep.alive, np.zeros(m, bool)])
            ep.deleted = np.concatenate([ep.deleted, np.zeros(m, bool)])
            ep.keys += [None] * m
            ep.free_slots.extend(range(2 * m - 1, m - 1, -1))
        slot = ep.free_slots.pop()
        ep.key_to_slot[pod.key] = slot
        ep.keys[slot] = pod.key
    ep.labels[slot] = False
    for k, v in pod.labels.items():
        ep.labels[slot, space.pod_labels.kv_id(k, v)] = True
        ep.labels[slot, space.pod_labels.key_id(k)] = True
    ep.ns_id[slot] = space.namespaces.id(pod.namespace)
    ep.node_idx[slot] = node_idx
    ep.alive[slot] = True
    ep.deleted[slot] = pod.deletion_timestamp is not None
    return ep


def existing_pods_add_bulk(ep: ExistingPodTensors, pods: Sequence[api.Pod],
                           node_idxs: Sequence[int],
                           space: FeatureSpace) -> ExistingPodTensors:
    """Bulk existing_pods_add: one growth pass + vectorized row writes.
    Label-column ids are memoized per pod template (controller-stamped pods
    share labels)."""
    col_memo: dict = {}

    def label_cols(pod: api.Pod) -> list[int]:
        mk = getattr(pod, "_tpl_key", None) \
            or (pod.namespace, tuple(sorted(pod.labels.items())))
        cl = col_memo.get(mk)
        if cl is None:
            cl = []
            for k, v in pod.labels.items():
                cl.append(space.pod_labels.kv_id(k, v))
                cl.append(space.pod_labels.key_id(k))
            col_memo[mk] = cl
        return cl

    for pod in pods:
        if pod.labels:
            label_cols(pod)  # intern before growth
    ep.labels = _grow_cols(ep.labels, space.pod_labels.capacity)
    need = sum(1 for p in pods if p.key not in ep.key_to_slot)
    while len(ep.free_slots) < need:
        m = len(ep.keys)
        ep.labels = np.concatenate([ep.labels, np.zeros_like(ep.labels)], 0)
        ep.ns_id = np.concatenate([ep.ns_id, np.zeros(m, np.int32)])
        ep.node_idx = np.concatenate([ep.node_idx, np.full(m, -1, np.int32)])
        ep.alive = np.concatenate([ep.alive, np.zeros(m, bool)])
        ep.deleted = np.concatenate([ep.deleted, np.zeros(m, bool)])
        ep.keys += [None] * m
        ep.free_slots.extend(range(2 * m - 1, m - 1, -1))
    slots = np.empty(len(pods), np.int64)
    for i, pod in enumerate(pods):
        slot = ep.key_to_slot.get(pod.key)
        if slot is None:
            slot = ep.free_slots.pop()
            ep.key_to_slot[pod.key] = slot
            ep.keys[slot] = pod.key
        slots[i] = slot
    ep.labels[slots] = False
    rows, cols = [], []
    for i, pod in enumerate(pods):
        if pod.labels:
            cl = label_cols(pod)
            cols.extend(cl)
            rows.extend([slots[i]] * len(cl))
    if rows:
        ep.labels[rows, cols] = True
    ep.ns_id[slots] = [space.namespaces.id(p.namespace) for p in pods]
    ep.node_idx[slots] = np.asarray(node_idxs, np.int64)
    ep.alive[slots] = True
    ep.deleted[slots] = [p.deletion_timestamp is not None for p in pods]
    return ep


def existing_pods_remove(ep: ExistingPodTensors, pod_key: str) -> ExistingPodTensors:
    slot = ep.key_to_slot.pop(pod_key, None)
    if slot is not None:
        ep.alive[slot] = False
        ep.node_idx[slot] = -1
        ep.keys[slot] = None
        ep.free_slots.append(slot)
    return ep
