"""Pod batch compilation: a pending queue -> dense [P, ...] tensors plus
deduplicated selector-group tables.

Pods from the same controller share identical node selectors / affinity /
service membership, so per-pod selector evaluation is deduplicated into G
small "groups"; the per-group [G, N] tables are computed once per batch and
gathered per pod on device.  This is the batched analogue of the reference's
per-pod ``predicateMetadata`` precompute (predicates.go:70-98).

Group tables are built host-side in vectorized numpy over the node label
multi-hot matrix; the [P, N] hot path stays on TPU.  For the sequential
device solver, spreading state is carried as (per-node counts [S,N],
per-zone counts [S,Z]) together with an in-batch increment matrix [P,S]
saying which groups' counts grow when pod ``i`` lands.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional, Sequence

import numpy as np

from kubernetes_tpu.api import types as api
from kubernetes_tpu.features import compiler as fc
from kubernetes_tpu.features.affinity import AffinityTensors, compile_affinity
from kubernetes_tpu.features.padcap import (pad_rows_pow2 as _pad_rows_pow2,
                                            pow2 as _pow2)
from kubernetes_tpu.features.volumes import (VolSvcTensors, compile_volsvc,
                                             empty_volsvc)


@dataclass
class PodBatch:
    """Dense per-pod features for one scheduling batch."""

    pods: list[api.Pod]
    request: np.ndarray        # [P, 4] int32
    zero_request: np.ndarray   # [P] bool — cpu==mem==gpu==0 (predicates.go:463)
    nonzero: np.ndarray        # [P, 2] int32
    best_effort: np.ndarray    # [P] bool
    host_idx: np.ndarray       # [P] int32: -1 no constraint, -2 unknown node name
    ports: np.ndarray          # [P, PortCap] bool
    vol_ro: np.ndarray         # [P, VolCap] bool — read-only conflict tokens
    vol_rw: np.ndarray         # [P, VolCap] bool — writable conflict tokens
    tol_nosched: np.ndarray    # [P, TaintCap] bool — vocab taints tolerated
    tol_prefer: np.ndarray     # [P, TaintCap] bool — PreferNoSchedule tolerated
    has_tolerations: np.ndarray  # [P] bool — pod declares any toleration
    images: np.ndarray         # [P, ImgCap] int32 — per-container multiplicity
    sel_group: np.ndarray      # [P] int32 into selector group tables
    sel_required: np.ndarray   # [G, N] bool — nodeSelector+required affinity
    sel_pref_counts: np.ndarray  # [G, N] int32 — preferred-term weight sums
    spread_group: np.ndarray   # [P] int32 into spread tables
    spread_node_counts: np.ndarray  # [S, N] f32 — matching pods per node
    spread_zone_counts: np.ndarray  # [S, Z] f32 — matching pods per zone
    spread_has_zones: np.ndarray    # [S] bool — haveZones for the group
    spread_incr: np.ndarray    # [P, S] bool — placing pod i increments group s
    node_zone_id: np.ndarray   # [N] int32 — compact zone id, -1 = no zone
    avoid_group: np.ndarray    # [P] int32 — controller-signature group
    avoid_rows: np.ndarray     # [G, N] bool — NodePreferAvoidPods hit
    nz_tmpl_idx: np.ndarray    # [P] int32 into nz_templates
    nz_templates: np.ndarray   # [T, 2] int32 distinct nonzero rows
    #                            (T=0: above cap, in-scan score path)
    aff: AffinityTensors       # inter-pod (anti-)affinity sig tables
    volsvc: VolSvcTensors      # volume counts/zones + service (anti-)affinity

    @property
    def p(self) -> int:
        return len(self.pods)


def _term_mask(term: api.NodeSelectorTerm, nt: fc.NodeTensors,
               space: fc.FeatureSpace,
               nodes: Optional[Sequence[api.Node]]) -> np.ndarray:
    """[N] bool — one NodeSelectorTerm (AND of exprs), per labels.Requirement
    semantics (pkg/labels/selector.go).  Empty/invalid exprs match nothing
    (predicates.go:520-525, :495)."""
    n = nt.labels.shape[0]
    if not term.match_expressions:
        return np.zeros(n, bool)
    mask = np.ones(n, bool)
    for e in term.match_expressions:
        if e.operator == api.NS_OP_IN:
            ids = [space.labels.kv_get(e.key, v) for v in e.values]
            ids = [i for i in ids if i >= 0]
            sat = nt.labels[:, ids].any(1) if ids else np.zeros(n, bool)
        elif e.operator == api.NS_OP_NOT_IN:
            ids = [space.labels.kv_get(e.key, v) for v in e.values]
            ids = [i for i in ids if i >= 0]
            sat = ~nt.labels[:, ids].any(1) if ids else np.ones(n, bool)
        elif e.operator == api.NS_OP_EXISTS:
            kid = space.labels.key_get(e.key)
            sat = nt.labels[:, kid] if kid >= 0 else np.zeros(n, bool)
        elif e.operator == api.NS_OP_DOES_NOT_EXIST:
            kid = space.labels.key_get(e.key)
            sat = ~nt.labels[:, kid] if kid >= 0 else np.ones(n, bool)
        elif e.operator in (api.NS_OP_GT, api.NS_OP_LT) and nodes is not None:
            # Numeric compare on the raw label value (rare; host loop).
            sat = np.zeros(n, bool)
            if len(e.values) != 1:
                return np.zeros(n, bool)
            try:
                rhs = int(e.values[0])
            except ValueError:
                return np.zeros(n, bool)  # invalid selector matches nothing
            for i, node in enumerate(nodes):
                val = node.labels.get(e.key)
                if val is not None:
                    try:
                        sat[i] = (int(val) > rhs) if e.operator == api.NS_OP_GT \
                            else (int(val) < rhs)
                    except ValueError:
                        pass
        else:
            return np.zeros(n, bool)  # unknown operator: selector parse error
        mask &= sat
    return mask


def _selector_set_mask(sel: dict[str, str], nt: fc.NodeTensors,
                       space: fc.FeatureSpace) -> np.ndarray:
    """[N] bool — labels.SelectorFromSet(map): AND over key=value pairs."""
    n = nt.labels.shape[0]
    mask = np.ones(n, bool)
    for k, v in sel.items():
        kv = space.labels.kv_get(k, v)
        mask &= nt.labels[:, kv] if kv >= 0 else np.zeros(n, bool)
    return mask


def required_node_mask(pod: api.Pod, nt: fc.NodeTensors, space: fc.FeatureSpace,
                       nodes: Optional[Sequence[api.Node]] = None) -> np.ndarray:
    """[N] bool — podMatchesNodeLabels (predicates.go:504-554):
    spec.nodeSelector AND required node affinity."""
    mask = _selector_set_mask(pod.node_selector, nt, space)
    aff = pod.affinity()
    if aff is not None and aff.node_affinity is not None \
            and aff.node_affinity.required is not None:
        terms = aff.node_affinity.required.node_selector_terms
        tmask = np.zeros(nt.labels.shape[0], bool)  # empty terms match nothing
        for t in terms:
            tmask |= _term_mask(t, nt, space, nodes)
        mask &= tmask
    return mask


def preferred_count_row(pod: api.Pod, nt: fc.NodeTensors, space: fc.FeatureSpace,
                        nodes: Optional[Sequence[api.Node]] = None) -> np.ndarray:
    """[N] int32 — sum of preferred-term weights matching each node
    (node_affinity.go:32-65).  Zero-weight terms skipped."""
    n = nt.labels.shape[0]
    counts = np.zeros(n, np.int32)
    aff = pod.affinity()
    if aff is not None and aff.node_affinity is not None:
        for term in aff.node_affinity.preferred:
            if term.weight == 0:
                continue
            counts += term.weight * _term_mask(term.preference, nt, space, nodes)
    return counts


def _label_selector_match_mask(sel: api.LabelSelector, labels_mh: np.ndarray,
                               space: fc.FeatureSpace) -> np.ndarray:
    """[M] bool — LabelSelector vs each existing pod's label multi-hot (pod-label vocab)."""
    m = labels_mh.shape[0]
    mask = np.ones(m, bool)
    for k, v in sel.match_labels:
        kv = space.pod_labels.kv_get(k, v)
        mask &= labels_mh[:, kv] if kv >= 0 else np.zeros(m, bool)
    for e in sel.match_expressions:
        if e.operator == "In":
            ids = [space.pod_labels.kv_get(e.key, v) for v in e.values]
            ids = [i for i in ids if i >= 0]
            mask &= labels_mh[:, ids].any(1) if ids else np.zeros(m, bool)
        elif e.operator == "NotIn":
            ids = [space.pod_labels.kv_get(e.key, v) for v in e.values]
            ids = [i for i in ids if i >= 0]
            if ids:
                mask &= ~labels_mh[:, ids].any(1)
        elif e.operator == "Exists":
            kid = space.pod_labels.key_get(e.key)
            mask &= labels_mh[:, kid] if kid >= 0 else np.zeros(m, bool)
        elif e.operator == "DoesNotExist":
            kid = space.pod_labels.key_get(e.key)
            if kid >= 0:
                mask &= ~labels_mh[:, kid]
        else:
            return np.zeros(m, bool)
    return mask


def _selector_matches_pod_labels(sel, labels: dict[str, str]) -> bool:
    if isinstance(sel, dict):
        return bool(sel) and all(labels.get(k) == v for k, v in sel.items())
    if isinstance(sel, api.LabelSelector):
        return sel.matches(labels)
    return False


def pod_template_key(pod: api.Pod) -> tuple:
    """Equivalence-class key: every field compile_batch/compile_affinity
    reads, except the pod's identity (name/uid).  Controller-stamped pods
    share one key, so per-pod feature rows compile once per template — the
    batched analogue of the reference's per-pod predicateMetadata memo
    (predicates.go:71-98) extended across pods, exploiting that a
    controller's pods are spec-identical.  Cached on the pod (specs are
    immutable once submitted)."""
    k = getattr(pod, "_tpl_key", None)
    if k is not None:
        return k
    ann = pod.annotations
    lab = pod.labels
    nsel = pod.node_selector
    k = (
        pod.namespace, pod.node_name, pod.deletion_timestamp is not None,
        tuple(sorted(lab.items())) if len(lab) > 1 else tuple(lab.items()),
        tuple(sorted(nsel.items())) if len(nsel) > 1 else tuple(nsel.items()),
        (ann.get(api.AFFINITY_ANNOTATION_KEY, ""),
         ann.get(api.TOLERATIONS_ANNOTATION_KEY, "")) if ann else ("", ""),
        tuple((c.image,
               tuple(sorted((k_, str(v)) for k_, v in c.requests.items())),
               tuple(sorted(c.limits)),
               tuple(p.host_port for p in c.ports if p.host_port))
              for c in pod.containers),
        tuple((v.gce_pd_name, v.gce_read_only, v.aws_ebs_id, v.aws_read_only,
               v.rbd_key, v.rbd_read_only, v.iscsi_key, v.iscsi_read_only,
               v.nfs_key, v.nfs_read_only, v.pvc_claim_name)
              for v in pod.volumes) if pod.volumes else (),
    )
    pod._tpl_key = k
    return k


# Lister signature: pod -> list of selector objects (dict for services/RCs,
# LabelSelector for ReplicaSets) matching it.
SpreadSelectors = Callable[[api.Pod], list]
# Lister: pod -> list of controller UIDs as ("ReplicationController"|"ReplicaSet", uid).
ControllerRefs = Callable[[api.Pod], list]


def _node_zone_ids(nt: fc.NodeTensors, space: fc.FeatureSpace) -> np.ndarray:
    """Compact per-batch zone ids from GetZoneKey (region+zone labels)."""
    n = nt.n
    zone_col = space.topo_keys.get(api.ZONE_LABEL)
    region_col = space.topo_keys.get(api.REGION_LABEL)
    zv = nt.topo_val[:, zone_col] if zone_col >= 0 else np.full(n, -1)
    rv = nt.topo_val[:, region_col] if region_col >= 0 else np.full(n, -1)
    has = (zv >= 0) | (rv >= 0)
    packed = (rv.astype(np.int64) + 1) * (len(space.topo_vals) + 2) + zv + 1
    packed = np.where(has, packed, -1)
    ids = np.full(n, -1, np.int32)
    if has.any():
        _, inv = np.unique(packed[has], return_inverse=True)
        ids[has] = inv.astype(np.int32)
    return ids


_DEFAULT_NZ_ROW: Optional[np.ndarray] = None


def _default_nz_row() -> np.ndarray:
    """[2] int32 — the nonzero row of a request-less pod, computed once
    through ``fc.pod_nonzero_row`` (the exact encoder pad/inert pods
    use) so the always-present template row can never diverge from what
    a pad pod actually contributes."""
    global _DEFAULT_NZ_ROW
    if _DEFAULT_NZ_ROW is None:
        _DEFAULT_NZ_ROW = fc.pod_nonzero_row(
            api.Pod(name="__nz-default", namespace="__nz__"))
    return _DEFAULT_NZ_ROW


def compile_batch(pods: Sequence[api.Pod], nt: fc.NodeTensors,
                  space: fc.FeatureSpace,
                  ep: Optional[fc.ExistingPodTensors] = None,
                  nodes: Optional[Sequence[api.Node]] = None,
                  spread_selectors: Optional[SpreadSelectors] = None,
                  controller_refs: Optional[ControllerRefs] = None,
                  affinity_pods: Sequence[tuple[api.Pod, int]] = (),
                  hard_pod_affinity_weight: int = 1,
                  volsvc: Optional[VolSvcTensors] = None) -> PodBatch:
    """Compile a pending-pod batch against the current node tensors.

    ``volsvc``: precompiled volume/service tables (compile_volsvc); a
    neutral all-pass table is built when omitted."""
    p = len(pods)
    n = nt.n

    # Group the batch into spec-identical templates; all per-pod rows are
    # compiled once per template and gathered back to [P, ...] at the end.
    tpl_of: dict[tuple, int] = {}
    reps: list[api.Pod] = []
    tpl_idx = np.empty(p, np.int64)
    for i, pod in enumerate(pods):
        k = pod_template_key(pod)
        ti = tpl_of.get(k)
        if ti is None:
            ti = len(reps)
            tpl_of[k] = ti
            reps.append(pod)
        tpl_idx[i] = ti
    t = len(reps)

    # Intern everything first so capacities are final.
    for pod in reps:
        for port in pod.used_host_ports():
            space.ports.id(str(port))
        for v in pod.volumes:
            for token, _ in fc.FeatureSpace.volume_tokens(v):
                space.volumes.id(token)
        for c in pod.containers:
            if c.image:
                space.images.id(c.image)

    request = np.zeros((t, 4), np.int32)
    nonzero = np.zeros((t, 2), np.int32)
    zero_req = np.zeros(t, bool)
    best_effort = np.zeros(t, bool)
    host_idx = np.full(t, -1, np.int32)
    ports = np.zeros((t, space.ports.capacity), bool)
    vol_ro = np.zeros((t, space.volumes.capacity), bool)
    vol_rw = np.zeros((t, space.volumes.capacity), bool)
    tol_ns = np.zeros((t, space.taints.capacity), bool)
    tol_pref = np.zeros((t, space.taints.capacity), bool)
    has_tols = np.zeros(t, bool)
    images = np.zeros((t, space.images.capacity), np.int32)
    avoid_group = np.zeros(t, np.int32)
    avoid_rows_map: dict = {(): 0}
    avoid_rows: list[np.ndarray] = [np.zeros(n, bool)]

    # Parse the taint vocabulary once; every pod's tolerations are matched
    # against it host-side, turning device-side toleration checks into a
    # single untolerated-taints contraction.
    vocab_taints = []
    for tok in space.taints.tokens():
        kv, _, effect = tok.rpartition(":")
        key, _, value = kv.partition("=")
        vocab_taints.append(api.Taint(key=key, value=value, effect=effect))

    # Node avoid-annotation entries, parsed once: node -> set of
    # (kind, uid) controller signatures (GetAvoidPodsFromNodeAnnotations).
    node_avoids: list[set] = []
    if controller_refs is not None and nodes is not None:
        import json as _json
        for node in nodes:
            entries = set()
            raw = node.annotations.get(api.PREFER_AVOID_PODS_ANNOTATION_KEY, "")
            if raw:
                try:
                    d = _json.loads(raw)
                    for e in d.get("preferAvoidPods") or ():
                        pc = (e.get("podSignature") or {}).get("podController") or {}
                        entries.add((pc.get("kind", ""), pc.get("uid", "")))
                except (ValueError, AttributeError):
                    pass
            node_avoids.append(entries)

    sel_sig_to_group: dict = {}
    sel_rows: list[np.ndarray] = []
    pref_rows: list[np.ndarray] = []
    sel_group = np.zeros(t, np.int32)
    # Lister lookups memoized by (namespace, labels): controller-stamped
    # pods share both, and the listers answer from labels alone.
    _sel_memo: dict = {}
    _ref_memo: dict = {}

    node_zone_id = _node_zone_ids(nt, space)
    num_zones = int(node_zone_id.max()) + 1 if (node_zone_id >= 0).any() else 0
    # haveZones iff some READY node carries zone info (the reference's
    # countsByZone only sees the ready node list, selector_spreading.go:121).
    any_zones = bool(((node_zone_id >= 0) & nt.schedulable).any())

    spread_sig_to_group: dict = {}
    spread_groups_meta: list[tuple[str, list]] = []  # (namespace, selectors)
    spread_node_rows: list[np.ndarray] = []
    spread_zone_rows: list[np.ndarray] = []
    spread_has_zone: list[bool] = []
    spread_group = np.zeros(t, np.int32)

    for i, pod in enumerate(reps):
        request[i] = fc.pod_resource_row(pod)
        nonzero[i] = fc.pod_nonzero_row(pod)
        zero_req[i] = not (request[i, 0] or request[i, 1] or request[i, 2])
        best_effort[i] = pod.is_best_effort()
        if pod.node_name:
            host_idx[i] = nt.name_to_idx.get(pod.node_name, -2)
        for port in pod.used_host_ports():
            ports[i, space.ports.id(str(port))] = True
        for v in pod.volumes:
            for token, ro in fc.FeatureSpace.volume_tokens(v):
                (vol_ro if ro else vol_rw)[i, space.volumes.id(token)] = True
        tols = pod.tolerations()
        has_tols[i] = len(tols) > 0
        pref_tols = [t for t in tols if not t.effect
                     or t.effect == api.TAINT_EFFECT_PREFER_NO_SCHEDULE]
        for ti, taint in enumerate(vocab_taints):
            tol_ns[i, ti] = taint.tolerated_by(tols)
            tol_pref[i, ti] = taint.tolerated_by(pref_tols)
        for c in pod.containers:
            if c.image:
                images[i, space.images.id(c.image)] += 1

        # NodePreferAvoidPods: mark nodes whose annotation lists one of the
        # pod's controllers (priorities.go:326-398), deduped by controller
        # signature so the [P, N] plane is a gather of few [N] rows.
        if controller_refs is not None and nodes is not None:
            lkey = (pod.namespace, tuple(sorted(pod.labels.items())))
            refs = _ref_memo.get(lkey)
            if refs is None:
                refs = _ref_memo[lkey] = tuple(controller_refs(pod))
            g = avoid_rows_map.get(refs)
            if g is None:
                row = np.zeros(n, bool)
                for ni, avoids in enumerate(node_avoids):
                    if any(r in avoids for r in refs):
                        row[ni] = True
                g = avoid_rows_map[refs] = len(avoid_rows)
                avoid_rows.append(row)
            avoid_group[i] = g

        # Selector group (nodeSelector + node affinity).
        aff = pod.affinity()
        na = aff.node_affinity if aff else None
        sig = (tuple(sorted(pod.node_selector.items())), na)
        g = sel_sig_to_group.get(sig)
        if g is None:
            g = len(sel_rows)
            sel_sig_to_group[sig] = g
            sel_rows.append(required_node_mask(pod, nt, space, nodes))
            pref_rows.append(preferred_count_row(pod, nt, space, nodes))
        sel_group[i] = g

        # Spread group (services/RCs/RSs selecting this pod), if listers given.
        # Pad rows (the stream drain's inert "__pad__" fill) must not mint
        # a group: their distinct namespace would otherwise change S only
        # on drains that happen to need padding — a new compiled shape for
        # identical real content.
        if spread_selectors is not None and ep is not None \
                and pod.namespace != "__pad__":
            lkey = (pod.namespace, tuple(sorted(pod.labels.items())))
            sels = _sel_memo.get(lkey)
            if sels is None:
                sels = _sel_memo[lkey] = spread_selectors(pod)
            ssig = (pod.namespace, tuple(sorted(repr(s) for s in sels)))
            sg = spread_sig_to_group.get(ssig)
            if sg is None:
                sg = len(spread_node_rows)
                spread_sig_to_group[ssig] = sg
                spread_groups_meta.append((pod.namespace, sels))
                ncounts, zcounts = _spread_counts(
                    pod.namespace, sels, ep, space, n, node_zone_id, num_zones,
                    nt.schedulable)
                spread_node_rows.append(ncounts)
                spread_zone_rows.append(zcounts)
                spread_has_zone.append(any_zones and len(sels) > 0)
            spread_group[i] = sg

    # Content-sized group axes are padded to powers of two (padcap's
    # bucketing discipline): live batches vary these counts freely (every
    # new selector signature, spread group, or avoid signature would
    # otherwise be a fresh compiled shape).  Padding rows are never
    # referenced by any pod index: sel pad rows are all-ones ("no
    # constraint"), the rest zeros.
    G = _pow2(len(sel_rows))
    sel_required = np.ones((G, n), bool)
    if sel_rows:
        sel_required[:len(sel_rows)] = np.stack(sel_rows)
    sel_pref = np.zeros((G, n), np.int32)
    if pref_rows:
        sel_pref[:len(pref_rows)] = np.stack(pref_rows)
    S = _pow2(len(spread_node_rows))
    Z = max(num_zones, 1)
    sp_n = np.zeros((S, n), np.float32)
    if spread_node_rows:
        sp_n[:len(spread_node_rows)] = np.stack(spread_node_rows)
    sp_z = np.zeros((S, Z), np.float32)
    if spread_zone_rows:
        sp_z[:len(spread_zone_rows)] = np.stack(spread_zone_rows)
    sp_hz = np.zeros(S, bool)
    if spread_has_zone:
        sp_hz[:len(spread_has_zone)] = spread_has_zone

    # In-batch increments: once pod i is placed it becomes an "existing pod"
    # for every later pod in the batch (the reference sees it via the assumed-
    # pod cache, cache.go:107).
    spread_incr = np.zeros((t, S), bool)
    if spread_groups_meta:
        for i, pod in enumerate(reps):
            if pod.deletion_timestamp is not None:
                continue
            for s, (ns, sels) in enumerate(spread_groups_meta):
                if ns == pod.namespace and any(
                        _selector_matches_pod_labels(sel, pod.labels)
                        for sel in sels):
                    spread_incr[i, s] = True

    # Stamp the parsed/compiled per-pod caches from each pod's template rep
    # so the assume path (cache.assume_pods -> aggregate updates) never
    # re-parses quantities or affinity JSON for controller-stamped pods.
    for pod, ti in zip(pods, tpl_idx.tolist()):
        rep = reps[ti]
        if rep is not pod:
            pod._res_row = rep._res_row
            pod._nz_row = rep._nz_row
            pod._affinity = rep._affinity
            pod._affinity_parsed = True

    aff = compile_affinity(pods, affinity_pods, ep, nodes, n, space,
                           hard_pod_affinity_weight,
                           reps=reps, tpl_idx=tpl_idx)
    if volsvc is None:
        if nodes is not None:
            volsvc = compile_volsvc(pods, nodes, nt.schedulable)
        else:
            volsvc = empty_volsvc(p, n)

    # Nonzero-request templates for the fused scan's template-factored
    # score planes (engine/solver.py _fused_scan): the distinct nonzero
    # rows, pow2-row-padded (padcap's "b_nztmpl" axis keeps the bucket
    # monotonic across batches).  Above the cap the table compiles away
    # (shape 0) and the scan keeps its in-step score path.
    from kubernetes_tpu.engine.solver import DYN_TEMPLATE_CAP
    # The default nonzero row (a request-less pod's non_zero_request) is
    # ALWAYS in the table: chunk/gang pad pods carry exactly it, and a
    # live padded batch must not grow the template table past what the
    # prewarm batches (which are never padded) traced — that cap bump
    # minted an unwarmed scan shape on the wire clock.  Derived through
    # the SAME row encoder the pad pods go through (not re-derived
    # constants), so the two can never diverge.
    nz_uniq, nz_inv = np.unique(
        np.concatenate([nonzero, _default_nz_row()[None]]), axis=0,
        return_inverse=True)
    if 0 < len(nz_uniq) <= DYN_TEMPLATE_CAP:
        # Row floor of 8 bounds tiny-batch wobble to one shape.
        rows = max(_pow2(len(nz_uniq)), 8)
        nz_templates = np.zeros((rows, 2), np.int32)
        nz_templates[:len(nz_uniq)] = nz_uniq
        nz_tmpl_idx = nz_inv[:-1].astype(np.int32)[tpl_idx]
    else:
        nz_templates = np.zeros((0, 2), np.int32)
        nz_tmpl_idx = np.zeros(p, np.int32)

    return PodBatch(
        pods=list(pods), request=request[tpl_idx],
        zero_request=zero_req[tpl_idx], nonzero=nonzero[tpl_idx],
        best_effort=best_effort[tpl_idx], host_idx=host_idx[tpl_idx],
        ports=ports[tpl_idx],
        vol_ro=vol_ro[tpl_idx], vol_rw=vol_rw[tpl_idx],
        tol_nosched=tol_ns[tpl_idx], tol_prefer=tol_pref[tpl_idx],
        has_tolerations=has_tols[tpl_idx],
        images=images[tpl_idx], sel_group=sel_group[tpl_idx],
        sel_required=sel_required, sel_pref_counts=sel_pref,
        spread_group=spread_group[tpl_idx],
        spread_node_counts=sp_n, spread_zone_counts=sp_z,
        spread_has_zones=sp_hz, spread_incr=spread_incr[tpl_idx],
        node_zone_id=node_zone_id, avoid_group=avoid_group[tpl_idx],
        avoid_rows=_pad_rows_pow2(np.stack(avoid_rows)),
        nz_tmpl_idx=nz_tmpl_idx, nz_templates=nz_templates,
        aff=aff, volsvc=volsvc)


def _spread_counts(namespace: str, selectors: list,
                   ep: fc.ExistingPodTensors, space: fc.FeatureSpace,
                   n: int, node_zone_id: np.ndarray, num_zones: int,
                   schedulable: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """SelectorSpread count phase (selector_spreading.go:89-135): count
    existing same-namespace, non-deleted pods matching ANY selector, per node
    and per zone.  Only ready nodes are iterated by the reference, so
    non-schedulable nodes' pods never enter the node or zone counts."""
    Z = max(num_zones, 1)
    if not selectors:
        return np.zeros(n, np.float32), np.zeros(Z, np.float32)
    ns = space.namespaces.get(namespace)
    cand = ep.alive & ~ep.deleted & (ep.ns_id == ns) & (ep.node_idx >= 0)
    match = np.zeros(len(cand), bool)
    for sel in selectors:
        if isinstance(sel, dict):
            if not sel:
                continue  # empty map selector selects nothing
            m = np.ones(len(cand), bool)
            for k, v in sel.items():
                kv = space.pod_labels.kv_get(k, v)
                m &= ep.labels[:, kv] if kv >= 0 else False
            match |= m
        elif isinstance(sel, api.LabelSelector):
            match |= _label_selector_match_mask(sel, ep.labels, space)
    match &= cand
    node_counts = np.bincount(ep.node_idx[match], minlength=n).astype(np.float32)[:n]
    node_counts = np.where(schedulable, node_counts, 0.0).astype(np.float32)
    zone_counts = np.zeros(Z, np.float32)
    if num_zones > 0:
        zmask = node_zone_id >= 0
        zone_counts[:num_zones] = np.bincount(
            node_zone_id[zmask], weights=node_counts[zmask],
            minlength=num_zones)[:num_zones]
    return node_counts, zone_counts
