"""kubernetes_tpu — a TPU-native scheduling framework.

A ground-up re-design of the Kubernetes scheduler (reference:
``plugin/pkg/scheduler`` in aa47206150/kubernetes, v1.4.0-alpha era) for TPU:
instead of a serial per-pod fit-and-score loop, the cluster's node cache is a
dense ``(nodes x features)`` tensor resident on device, hard predicates are
boolean mask kernels ``[pods, nodes]``, priorities are score planes reduced by
a single weighted contraction, and an entire pending queue is placed as one
batched assignment problem under ``jax.jit`` / ``pjit`` over a device mesh.

Wire compatibility is preserved at the framework boundary: the scheduler
extender HTTP protocol (reference ``plugin/pkg/scheduler/api/types.go:133-163``)
and scheduler policy JSON (``api/types.go:27-35``) are spoken unchanged, so a
stock Go control plane can delegate Filter/Prioritize to this engine.
"""

__version__ = "0.1.0"

from kubernetes_tpu.api import types as api_types  # noqa: F401
