"""The standalone scheduler daemon binary.

``python -m kubernetes_tpu.scheduler --api-server http://... `` is the
analogue of plugin/cmd/kube-scheduler (app/server.go:71-183): flag surface
(options/options.go:55-77), policy-file load (server.go:165-183), an HTTP
mux serving /healthz /metrics /configz (server.go:93-109), and an optional
leader-election-wrapped run on an Endpoints annotation lease
(server.go:142-159).  Without --api-server it runs against a fresh
in-process MemStore + HTTP apiserver (--serve-apiserver), the all-in-one
dev mode.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from kubernetes_tpu.api import types as api
from kubernetes_tpu.utils import threadreg
from kubernetes_tpu.api.policy import (cluster_autoscaler_provider,
                                       default_provider, policy_from_json)
from kubernetes_tpu.scheduler.factory import ConfigFactory
from kubernetes_tpu.utils.leaderelection import (APIResourceLock,
                                                 LeaderElector)
from kubernetes_tpu.utils.logging import configure, get_logger

log = get_logger("scheduler")

DEFAULT_PORT = 10251  # options/options.go:49 SchedulerDefaultPort


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="kube-scheduler (kubernetes_tpu)",
        description="TPU-batched scheduler daemon; watches an apiserver and "
                    "binds pods (plugin/cmd/kube-scheduler analogue)")
    p.add_argument("--api-server", default="",
                   help="apiserver base URL; empty runs an in-process "
                        "MemStore control plane")
    p.add_argument("--serve-apiserver", type=int, default=0, metavar="PORT",
                   help="with no --api-server: also expose the in-process "
                        "store over HTTP on this port (0 = off)")
    p.add_argument("--port", type=int, default=DEFAULT_PORT,
                   help="healthz/metrics/configz port (0 = ephemeral)")
    p.add_argument("--algorithm-provider", default="DefaultProvider",
                   choices=["DefaultProvider", "ClusterAutoscalerProvider"])
    p.add_argument("--policy-config-file", default="",
                   help="scheduler policy JSON (overrides the provider)")
    p.add_argument("--scheduler-name", default=api.DEFAULT_SCHEDULER_NAME)
    p.add_argument("--kube-api-qps", type=float, default=50.0)
    p.add_argument("--kube-api-burst", type=int, default=100)
    p.add_argument("--kube-api-token", default="",
                   help="bearer token for an authenticated apiserver")
    from kubernetes_tpu.client.http import TLSConfig
    TLSConfig.add_flags(p)
    p.add_argument("--hard-pod-affinity-symmetric-weight", type=int,
                   default=None)
    p.add_argument("--leader-elect", action="store_true", default=False)
    p.add_argument("--leader-elect-lease-duration", type=float, default=15.0)
    p.add_argument("--leader-elect-renew-deadline", type=float, default=10.0)
    p.add_argument("--leader-elect-retry-period", type=float, default=2.0)
    p.add_argument("--v", type=int, default=None,
                   help="log verbosity (glog-style; also KT_LOG_V)")
    p.add_argument("--profile-dir", default="",
                   help="write jax.profiler device traces of every solve "
                        "here (also KT_PROFILE_DIR; view with XProf)")
    p.add_argument("--config", default="",
                   help="KubeSchedulerConfiguration JSON file "
                        "(componentconfig/types.go:426-457); explicit "
                        "flags override file values")
    p.add_argument("--feature-gates", default="",
                   help="comma-separated Name=true|false pairs "
                        "(BatchBindings, StreamingDrain, JointSolver)")
    return p


def apply_component_config(p: argparse.ArgumentParser, argv):
    """--config provides flag DEFAULTS, explicit flags override (the
    reference's scheme-defaults-then-flags order).  Returns parsed opts
    with the validated config folded in."""
    pre, _ = p.parse_known_args(argv)
    if pre.config:
        from kubernetes_tpu.api.componentconfig import (
            KubeSchedulerConfiguration)
        with open(pre.config) as f:
            cfg = KubeSchedulerConfiguration.from_json(f.read())
        errors = cfg.validate()
        if errors:
            raise SystemExit("invalid --config: " + "; ".join(errors))
        p.set_defaults(
            port=cfg.port,
            algorithm_provider=cfg.algorithm_provider,
            policy_config_file=cfg.policy_config_file,
            scheduler_name=cfg.scheduler_name,
            kube_api_qps=cfg.kube_api_qps,
            kube_api_burst=cfg.kube_api_burst,
            hard_pod_affinity_symmetric_weight=(
                cfg.hard_pod_affinity_symmetric_weight),
            feature_gates=cfg.feature_gates,
            enable_profiling=cfg.enable_profiling,
            leader_elect=cfg.leader_election.leader_elect,
            leader_elect_lease_duration=cfg.leader_election.lease_duration,
            leader_elect_renew_deadline=cfg.leader_election.renew_deadline,
            leader_elect_retry_period=cfg.leader_election.retry_period)
    opts = p.parse_args(argv)
    if not hasattr(opts, "enable_profiling"):
        opts.enable_profiling = True  # reference scheduler default
    return opts


def load_policy(opts):
    """createConfig (server.go:165-183): policy file beats provider; file
    policies are validated (CreateFromConfig -> validation.ValidatePolicy)."""
    if opts.policy_config_file:
        from kubernetes_tpu.api.validation import validate_policy
        with open(opts.policy_config_file) as f:
            policy = policy_from_json(f.read())
        validate_policy(policy)
    elif opts.algorithm_provider == "ClusterAutoscalerProvider":
        policy = cluster_autoscaler_provider()
    else:
        policy = default_provider()
    if opts.hard_pod_affinity_symmetric_weight is not None:
        policy.hard_pod_affinity_symmetric_weight = \
            opts.hard_pod_affinity_symmetric_weight
    return policy


def _decisions_route(daemon, query: str) -> tuple[int, bytes, str]:
    """/debug/scheduler/decisions: the flight recorder's batch ring;
    ``?pod=ns/name`` explains one pod's latest decision (chosen node, or
    per-predicate failure counts and top-scoring candidates);
    ``?tenant=name`` filters batch summaries to one tenant's rows (the
    multi-tenant service's per-tenant decision history)."""
    from urllib.parse import parse_qs
    recorder = daemon.config.flight_recorder
    if recorder is None:
        return 404, b"flight recorder disabled", "text/plain"
    q = parse_qs(query)
    pod = q.get("pod", [""])[0]
    if pod:
        decision = recorder.explain(pod)
        if decision is None:
            return (404,
                    json.dumps({"pod": pod,
                                "error": "no recorded decision"}).encode(),
                    "application/json")
        return 200, json.dumps(decision).encode(), "application/json"
    try:
        limit = int(q.get("limit", ["0"])[0] or "0")
    except ValueError:
        return (400, b'{"error": "limit must be an integer"}',
                "application/json")
    tenant = q.get("tenant", [""])[0]
    return (200, json.dumps(recorder.snapshot(
        limit=limit, tenant=tenant)).encode(), "application/json")


def _status_mux(factory: ConfigFactory, configz: dict, port: int
                ) -> ThreadingHTTPServer:
    """The daemon's own HTTP surface (server.go:93-109)."""
    from kubernetes_tpu.utils import telemetry
    # Self-scrape ring: the daemon-scoped metric set (queue depth, batch
    # size, attempts) rides the ring next to the default registry so the
    # dashboard's queue/stage/SLO sparklines have their sources.
    telemetry.ensure_started(
        factory.daemon.config.metrics.all_metrics())
    # kt-prof rides the same lifecycle: sampling starts with the mux so
    # the profile covers the daemon's whole life (KT_PROF=0 = no-op).
    from kubernetes_tpu.utils import profiler
    profiler.ensure_started()

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def _send(self, code: int, body: bytes,
                  ctype: str = "text/plain") -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            path, _, query = self.path.partition("?")
            if path == "/healthz":
                self._send(200, b"ok")
            elif path == "/metrics":
                if "format=openmetrics" in query:
                    from kubernetes_tpu.utils.debugmux import \
                        OPENMETRICS_CTYPE
                    self._send(
                        200,
                        factory.daemon.config.metrics
                        .expose_openmetrics().encode(),
                        OPENMETRICS_CTYPE)
                else:
                    self._send(
                        200,
                        factory.daemon.config.metrics.expose().encode())
            elif path == "/configz":
                self._send(200, json.dumps(configz).encode(),
                           "application/json")
            elif path.startswith("/debug/pprof"):
                # The goroutine-dump analogue (app/server.go:96-100): all
                # live thread stacks.  EnableProfiling=false removes the
                # handlers, as the reference's mux does (server.go:96).
                if not configz.get("enableProfiling", True):
                    self._send(404, b"profiling disabled")
                    return
                from kubernetes_tpu.utils.profiling import thread_stacks
                self._send(200, thread_stacks().encode())
            elif path == "/debug/profile":
                # kt-prof continuous CPU profile (speedscope JSON, or
                # collapsed stacks via ?format=collapsed).  KT_PROF=0 is
                # a client-visible state: 404, never 500.
                from kubernetes_tpu.utils import profiler
                resolved = profiler.render(query)
                if resolved is None:
                    self._send(404, b"profiling disabled (KT_PROF=0)")
                else:
                    body, ctype = resolved
                    self._send(200, body, ctype)
            elif path == "/debug/traces":
                # The span ring as Chrome trace-event JSON: load in
                # Perfetto and the queue_wait -> snapshot -> compile ->
                # transfer -> solve -> readback -> assume -> bind pipeline
                # is visible per batch.
                from kubernetes_tpu.utils import trace
                self._send(200, trace.to_chrome_trace().encode(),
                           "application/json")
            elif path == "/debug/scheduler/decisions":
                self._send(*_decisions_route(factory.daemon, query))
            elif path == "/debug/timeseries":
                from kubernetes_tpu.utils import telemetry
                self._send(200, telemetry.timeseries_json().encode(),
                           "application/json")
            elif path == "/debug/dashboard":
                from kubernetes_tpu.utils import telemetry
                self._send(200, telemetry.dashboard_html().encode(),
                           "text/html; charset=utf-8")
            elif path == "/debug/vars":
                from kubernetes_tpu.utils.metrics import (
                    CACHE_INVARIANT_VIOLATIONS, POST_PREWARM_COMPILES)
                cache = factory.algorithm.cache
                queue = factory.daemon.queue
                self._send(200, json.dumps({
                    "queueDepth": len(queue),
                    "queueHighWatermark": queue.high_watermark,
                    "queuePeakDepth": queue.peak_depth,
                    # The degradation ladder's operator surface: 1 while
                    # the daemon sheds load (largest-bucket drains, gang
                    # holds bypassed).
                    "degraded": queue.degraded(),
                    # The serving surface: the formation deadline in
                    # force, the former's adaptive target bucket, and
                    # the warm-start audit's per-signature cache stats.
                    "batchDeadlineMs": round(
                        factory.daemon.pipeline.former.deadline_s * 1e3,
                        1),
                    "batchFormerTarget":
                        factory.daemon.pipeline.former.target,
                    "prewarmCacheStats":
                        factory.daemon.prewarm_cache_stats,
                    # The SLO plane: live burn rates + budget left
                    # (scheduler/slo.py) and the device-side watchdog.
                    "slo": factory.slo.report(),
                    # The device-fault plane: engine mode (device/host),
                    # last classified fault, bisect cap, gate rejects
                    # (engine/guard.py).
                    "engine": factory.algorithm.guard.report(),
                    "postPrewarmCompiles": POST_PREWARM_COMPILES.value,
                    "invariantViolations":
                        CACHE_INVARIANT_VIOLATIONS.value,
                    "lastRecovery": getattr(factory, "last_recovery",
                                            None),
                    # Active-active HA (scheduler/shards.py): this
                    # incarnation's id, the shards it holds, and the
                    # recent shard-takeover reconciles; null when
                    # running single-scheduler (KT_HA_SHARDS=0).
                    "ha": (factory.shards.report()
                           if getattr(factory, "shards", None)
                           is not None else None),
                    # The multi-tenant solver service (tenancy/): per-
                    # tenant mode/weights/trips/fault attribution; null
                    # when KT_TENANTS is unset.
                    "tenancy": (factory.tenancy.report()
                                if getattr(factory, "tenancy", None)
                                is not None else None),
                    "shardRecoveries": getattr(
                        factory, "shard_recoveries", [])[-8:],
                    # Client-side backpressure against a shedding
                    # apiserver (utils/flowcontrol.py): the AIMD bind
                    # window + retry-budget saturation; null when the
                    # store is in-process (no wire, nothing to shed).
                    "overload": (factory.store.flow_report()
                                 if hasattr(factory.store, "flow_report")
                                 else None),
                    "cachedPods": cache.pod_count(),
                    "cachedNodes": len(cache.nodes()),
                    "cacheStats": cache.stats,
                    "generation": cache.generation,
                }).encode(), "application/json")
            elif path == "/tenancy":
                if getattr(factory, "tenancy", None) is None:
                    self._send(404, b"tenancy disabled")
                else:
                    self._send(200,
                               json.dumps(factory.tenancy.report())
                               .encode(), "application/json")
            else:
                self._send(404, b"not found")

        def do_POST(self):
            # The solver-service boundary over the daemon's existing
            # HTTP surface: with KT_TENANTS set, other control planes
            # POST /solve {tenant, pods:[...]} and get placements from
            # THIS daemon's device (tenancy/service.solve_route).
            path = self.path.partition("?")[0]
            if path != "/solve":
                self._send(404, b"not found")
                return
            if getattr(factory, "tenancy", None) is None:
                self._send(404, b"tenancy disabled")
                return
            try:
                clen = int(self.headers.get("Content-Length", "0") or 0)
            except ValueError:
                clen = 0
            body = self.rfile.read(clen) if clen else b""
            from kubernetes_tpu.tenancy.service import solve_route
            self._send(*solve_route(factory.tenancy, body))

    server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    threadreg.spawn(server.serve_forever, name="scheduler-status-http")
    return server


def main(argv=None) -> int:
    opts = apply_component_config(build_parser(), argv)
    configure(v=opts.v)
    if opts.profile_dir:
        from kubernetes_tpu.utils.profiling import set_profile_dir
        set_profile_dir(opts.profile_dir)
    from kubernetes_tpu.utils import featuregate
    try:
        gates = featuregate.FeatureGate.parse(opts.feature_gates)
    except ValueError as err:
        raise SystemExit(f"--feature-gates: {err}")
    featuregate.set_default(gates)
    policy = load_policy(opts)
    configz = {
        "apiServer": opts.api_server or "(in-process)",
        "algorithmProvider": opts.algorithm_provider,
        "policyConfigFile": opts.policy_config_file,
        "schedulerName": opts.scheduler_name,
        "kubeAPIQPS": opts.kube_api_qps,
        "kubeAPIBurst": opts.kube_api_burst,
        "leaderElect": opts.leader_elect,
        "featureGates": gates.as_dict(),
        "enableProfiling": getattr(opts, "enable_profiling", True),
        "predicates": [s.name for s in policy.predicates],
        "priorities": [[s.name, s.weight] for s in policy.priorities],
    }

    if opts.api_server:
        from kubernetes_tpu.client.http import APIClient, TLSConfig
        source = APIClient(opts.api_server, qps=opts.kube_api_qps,
                           burst=opts.kube_api_burst,
                           token=opts.kube_api_token,
                           tls=TLSConfig.from_opts(opts))
    else:
        from kubernetes_tpu.apiserver.memstore import MemStore
        source = MemStore()
        if opts.serve_apiserver:
            from kubernetes_tpu.apiserver.server import serve
            serve(source, port=opts.serve_apiserver)
            log.info("in-process apiserver on :%d", opts.serve_apiserver)

    # source is a ready APIClient (credentials + TLS) or a MemStore;
    # qps/burst still feed the factory's event-sink rate bucket.
    factory = ConfigFactory(source, policy=policy,
                            scheduler_name=opts.scheduler_name,
                            qps=opts.kube_api_qps,
                            burst=opts.kube_api_burst)
    mux = _status_mux(factory, configz, opts.port)
    log.info("status http on :%d (healthz, metrics, configz)",
             mux.server_address[1])

    stop = threading.Event()

    def shutdown(*_):
        stop.set()

    signal.signal(signal.SIGTERM, shutdown)
    signal.signal(signal.SIGINT, shutdown)

    if opts.leader_elect:
        identity = f"{socket.gethostname()}-{os.getpid()}"
        lock = APIResourceLock(factory.store) if opts.api_server else None
        if lock is None:
            log.warning("--leader-elect without --api-server: using an "
                        "in-process lock (single candidate)")
            from kubernetes_tpu.utils.leaderelection import InMemoryLock
            lock = InMemoryLock()
        elector = LeaderElector(
            lock=lock, identity=identity,
            lease_duration=opts.leader_elect_lease_duration,
            renew_deadline=opts.leader_elect_renew_deadline,
            retry_period=opts.leader_elect_retry_period,
            on_started_leading=lambda: (log.info("leading as %s", identity),
                                        factory.run()),
            on_stopped_leading=lambda: (log.warning("lost lease; exiting"),
                                        stop.set()))
        elector.run()
        log.info("leader election: candidate %s", identity)
    else:
        factory.run()
        log.info("scheduler loop running (no leader election)")

    stop.wait()
    factory.stop()
    mux.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
