"""Continuous rebalancing: the joint solver as an always-on defragmenter.

The joint solve beats greedy placement by ~13 % but only fires on a
pending-pod avalanche; a cluster that merely survives churn still decays
— biased churn (small pods deleted, large ones created) leaves every
node a little bit full, and gangs and large pods strand Pending with no
defense.  This controller is the background duty that reverses the
decay:

1. **Settle** — retire in-flight migrations (rebound pods get their
   intent annotation cleared and arm the verifier's ``defrag``
   reconciliation kind), and credit previously-blocked pods now bound
   (``scheduler_defrag_unblocked_total`` — the soak's ``defrag_gain``
   numerator).
2. **Probe** — dry-solve the pending set.  With a SolverService
   attached the probe rides ``submit_background`` — a low-priority
   tenant that only takes the engine when no live submit is pending, so
   defrag solves never steal device time from live drains; without one
   the host-side feasibility walk below stands in (same blocked-set
   answer, no device).  Pods the solve cannot place are the BLOCKED set.
3. **Plan** — a pure host-side rebalance over apiserver truth: per
   blocked pod, the node needing the fewest movable victims evicted
   such that (a) the blocked pod then fits and (b) every victim re-fits
   on some other node's simulated free space.  Gang-aware twice over:
   gang-member victims are never evicted (migrating one strands its
   gang), and a blocked gang is planned all-or-nothing.
4. **Gate** — the plan is trimmed to ``KT_DEFRAG_MAX_MIGRATIONS``, then
   vetoed wholesale if projected gain per migration falls below
   ``KT_DEFRAG_MIN_GAIN`` or in-flight migrations would exceed
   ``KT_DEFRAG_BUDGET`` (both recorded ``vetoed_budget``); every victim
   is additionally vetoed by the PDB status the DisruptionController
   publishes (``vetoed_pdb`` — a victim whose PDB has no headroom is
   simply not movable).
5. **Execute** — each migration is a crash-safe two-phase record:
   first the intent annotation (``DEFRAG_MIGRATION_ANNOTATION_KEY`` =
   ``{"from": node, "round": n}``) lands under CAS, then the evict-to-
   pending (spec.nodeName cleared under CAS via the binder's
   ``unbind``).  The unassigned reflector's set-transition then requeues
   the pod through the completely ordinary enqueue -> solve -> bind
   path.  A SIGKILL between the phases leaves either a bound pod with a
   stale intent (startup reconcile clears it) or an unbound pod with an
   intent (startup reconcile requeues it and clears the intent) — never
   a stranded pod; see ``scheduler/recovery.py``.

Every decision — executed, vetoed-by-budget, vetoed-by-PDB, CAS-lost,
completed, crash-recovered — is metered
(``scheduler_defrag_migrations_total{result=}``) and flight-recorded
(``FlightRecorder.record_defrag``), so ``kubectl explain pod`` answers
"why did the rebalancer move my pod".

Host-side only by design: no jax import (the kt-lint device fence), no
cache mutation beyond the eviction's ``remove_pod`` (the same call the
preemption path makes).  Knobs are read once at construction.
"""

from __future__ import annotations

import json
import threading
from typing import Callable, Optional

from kubernetes_tpu.api import types as api
from kubernetes_tpu.apiserver.memstore import MemStore
from kubernetes_tpu.client import cas_update
from kubernetes_tpu.controller.replication import _matches
from kubernetes_tpu.utils import knobs, locktrace, metrics, threadreg
from kubernetes_tpu.utils.logging import get_logger

log = get_logger("defrag")

# Resource dimensions a plan is simulated over: (milli_cpu, memory,
# pod slots) — the exact triple MemStore._pod_requests / _node_alloc
# budget binds against, so the plan and the server's capacity check can
# never disagree about whether a move fits.
DIMS = 3


def _node_capacity(obj: dict) -> Optional[list[int]]:
    """status.allocatable of a node JSON as [milli_cpu, memory, pods],
    or None for a node the rebalancer must leave alone (not ready)."""
    node = api.node_from_json(obj)
    if not node.is_ready():
        return None
    return [node.allocatable_milli_cpu, node.allocatable_memory,
            node.allocatable_pods]


def _fits(req: tuple, free: list) -> bool:
    return all(req[i] <= free[i] for i in range(DIMS))


class DefragController:
    """The background rebalancing loop.  ``daemon`` is the scheduler
    (cache + queue + binder + recorder), ``store`` the apiserver source
    (MemStore or APIClient), ``probe`` an optional dry-solve callable
    (pods -> placements | None-when-busy; the factory wires the
    SolverService's low-priority lane), ``verifier`` the cache
    invariant checker whose ``defrag`` reconciliation kind each settled
    migration arms."""

    def __init__(self, daemon, store, probe: Optional[Callable] = None,
                 verifier=None):
        self.daemon = daemon
        self.store = store
        self.probe = probe
        self.verifier = verifier
        self.period_s = knobs.get_float("KT_DEFRAG_PERIOD_S")
        self.max_migrations = knobs.get_int("KT_DEFRAG_MAX_MIGRATIONS")
        self.min_gain = knobs.get_float("KT_DEFRAG_MIN_GAIN")
        self.budget = knobs.get_int("KT_DEFRAG_BUDGET")
        self._lock = locktrace.make_lock("scheduler.DefragController")
        self._stop = threading.Event()
        # In-flight two-phase migrations: pod key -> source node.  An
        # entry lives from the executed evict until the settle pass sees
        # the pod rebound (or deleted).
        self._inflight: dict[str, str] = {}
        # Blocked-set memory for gain attribution: a key seen blocked by
        # a probe and later observed bound was unblocked by the moves.
        self._blocked_prev: set[str] = set()
        self._round = 0
        self.stats = {"rounds": 0, "probes": 0, "probe_skipped": 0,
                      "blocked_peak": 0, "migrations_executed": 0,
                      "migrations_completed": 0, "vetoed_budget": 0,
                      "vetoed_pdb": 0, "cas_conflict": 0, "unblocked": 0,
                      "max_batch": 0}

    # -- plumbing ---------------------------------------------------------

    def _flight(self, pod_key: str, decision: str, from_node: str = "",
                to_node: str = "", target: str = "") -> None:
        fr = self.daemon.config.flight_recorder
        if fr is not None:
            fr.record_defrag(pod_key, decision, from_node=from_node,
                             to_node=to_node, target=target)

    def _clear_intent(self, obj: dict) -> bool:
        """Drop the migration-intent annotation under CAS.  A lost CAS
        is left for the next settle pass (or the startup reconciler)."""
        meta = obj.setdefault("metadata", {})
        ann = dict(meta.get("annotations") or {})
        if ann.pop(api.DEFRAG_MIGRATION_ANNOTATION_KEY, None) is None:
            return False
        meta["annotations"] = ann
        try:
            cas_update(self.store, "pods", obj)
        except Exception:  # noqa: BLE001 — retried next settle
            return False
        return True

    # -- 1. settle --------------------------------------------------------

    def _settle(self, by_key: dict[str, dict]) -> None:
        """Retire in-flight migrations against one truth snapshot and
        credit unblocked pods."""
        with self._lock:
            inflight = dict(self._inflight)
            blocked_prev = set(self._blocked_prev)
        for key, from_node in inflight.items():
            obj = by_key.get(key)
            if obj is None:
                # Deleted mid-migration (churn): nothing left to rebind.
                with self._lock:
                    self._inflight.pop(key, None)
                continue
            node = (obj.get("spec") or {}).get("nodeName") or ""
            if not node:
                # Still pending: the live drain owns it — but nudge it
                # back onto the queue anyway.  The enqueue is idempotent
                # (keyed), and it guarantees a migrant can never strand
                # on a lost or reordered watch delivery: the settle
                # cadence re-offers it until it lands somewhere.
                try:
                    self.daemon.enqueue(api.pod_from_json(obj))
                except Exception:  # noqa: BLE001 — next settle retries
                    pass
                continue
            self._clear_intent(obj)
            self._flight(key, "completed", from_node=from_node,
                         to_node=node)
            self.stats["migrations_completed"] += 1
            if self.verifier is not None:
                self.verifier.note_defrag([key])
            with self._lock:
                self._inflight.pop(key, None)
        unblocked = {k for k in blocked_prev
                     if ((by_key.get(k) or {}).get("spec") or {})
                     .get("nodeName")}
        gone = {k for k in blocked_prev if k not in by_key}
        if unblocked:
            metrics.DEFRAG_UNBLOCKED.inc(len(unblocked))
            self.stats["unblocked"] += len(unblocked)
        with self._lock:
            self._blocked_prev -= unblocked | gone
            metrics.DEFRAG_INFLIGHT.set(len(self._inflight))

    # -- 2. probe ---------------------------------------------------------

    def _blocked_set(self, pend_pods: list,
                     free: dict[str, list]) -> Optional[list]:
        """Pods the dry solve cannot place, or None when the engine
        stayed busy (skip the round — live drains have priority)."""
        if self.probe is not None:
            self.stats["probes"] += 1
            placements = self.probe(pend_pods)
            if placements is None:
                self.stats["probe_skipped"] += 1
                return None
            return [p for p, dest in zip(pend_pods, placements)
                    if dest is None]
        # Host fallback (no SolverService lane): a pod that fits whole
        # on no node's current free space is blocked.  Conservative —
        # it cannot see multi-pod interactions the joint solve can, but
        # it never claims a schedulable pod is blocked.
        out = []
        for p in pend_pods:
            req = MemStore._pod_requests(api.pod_to_json(p))
            if not any(_fits(req, f) for f in free.values()):
                out.append(p)
        return out

    # -- 3/4. plan + gates -----------------------------------------------

    def _pdb_guard(self) -> Callable[[dict], bool]:
        """A per-round veto closure over the PDB status the
        DisruptionController publishes: ``veto(pod_json)`` is True when
        evicting the pod would break any matching budget.  Headroom
        (currentHealthy - desiredHealthy) is consumed per allowed
        eviction, so one batch can never spend a PDB twice; a PDB with
        no published status vetoes conservatively."""
        try:
            pdbs, _ = self.store.list("poddisruptionbudgets")
        except Exception:  # noqa: BLE001 — no PDB state, nothing vetoes
            pdbs = []
        entries = []
        for pdb in pdbs:
            meta = pdb.get("metadata") or {}
            status = pdb.get("status") or {}
            if status.get("disruptionAllowed"):
                left = max(int(status.get("currentHealthy", 0)) -
                           int(status.get("desiredHealthy", 0)), 0)
            else:
                left = 0
            entries.append({"ns": meta.get("namespace", "default"),
                            "sel": (pdb.get("spec") or {})
                            .get("selector") or {}, "left": left})

        def veto(pod_obj: dict) -> bool:
            ns = (pod_obj.get("metadata") or {}).get("namespace",
                                                     "default")
            matching = [e for e in entries
                        if e["ns"] == ns and _matches(e["sel"], pod_obj)]
            if not matching:
                return False
            if any(e["left"] <= 0 for e in matching):
                return True
            for e in matching:
                e["left"] -= 1
            return False
        return veto

    def _plan(self, blocked: list, free: dict[str, list],
              bound_by_node: dict[str, list], pdb_veto) -> list[dict]:
        """Greedy rebalance plan: per blocked pod (gangs as a unit,
        largest first), the node whose deficit the fewest movable
        victims cover, each victim re-fitting on simulated free space
        elsewhere.  Returns subplans
        ``{"for": pod_key, "node": n, "victims": [(key, from_node)]}``;
        records ``vetoed_pdb`` for victims a budget made immovable."""
        taken: set[str] = set()       # victims already claimed
        pdb_vetoed: set[str] = set()  # recorded once per round
        with self._lock:
            unmovable = set(self._inflight)
        plans: list[dict] = []

        def movable(vkey: str, vobj: dict) -> bool:
            if vkey in taken or vkey in unmovable:
                return False
            ann = (vobj.get("metadata") or {}).get("annotations") or {}
            if ann.get(api.GANG_ANNOTATION_KEY):
                return False  # never strand a gang by moving one member
            if api.DEFRAG_MIGRATION_ANNOTATION_KEY in ann:
                return False  # already mid-migration
            if pdb_veto(vobj):
                if vkey not in pdb_vetoed:
                    pdb_vetoed.add(vkey)
                    self.stats["vetoed_pdb"] += 1
                    metrics.DEFRAG_MIGRATIONS.labels(
                        result="vetoed_pdb").inc()
                    self._flight(vkey, "vetoed_pdb")
                return False
            return True

        def plan_one(pod) -> Optional[dict]:
            """One blocked pod's cheapest subplan, committed into the
            simulated free space; None when no node can be cleared."""
            req = MemStore._pod_requests(api.pod_to_json(pod))
            best = None  # (n_victims, node, victims, relocations)
            for node, f in free.items():
                if _fits(req, f):
                    # Schedulable after earlier subplans (or plain
                    # churn): the live drain will place it — no moves.
                    free[node] = [f[i] - req[i] for i in range(DIMS)]
                    return {"for": pod.key, "node": node, "victims": []}
                deficit = [max(req[i] - f[i], 0) for i in range(DIMS)]
                victims: list[tuple[str, str]] = []
                relocations: list[tuple[str, tuple, str]] = []
                sim = {n: list(v) for n, v in free.items()}
                cands = sorted(
                    (c for c in bound_by_node.get(node, ())
                     if movable(c[0], c[1])),
                    key=lambda c: c[2][0], reverse=True)
                for vkey, vobj, vreq in cands:
                    if all(d <= 0 for d in deficit):
                        break
                    # The victim must re-fit somewhere else, in sim.
                    dest = next((n for n, sf in sim.items()
                                 if n != node and _fits(vreq, sf)), None)
                    if dest is None:
                        continue
                    for i in range(DIMS):
                        sim[dest][i] -= vreq[i]
                        deficit[i] = max(deficit[i] - vreq[i], 0)
                    victims.append((vkey, node))
                    relocations.append((vkey, vreq, dest))
                if any(d > 0 for d in deficit) or not victims:
                    continue
                if best is None or len(victims) < best[0]:
                    best = (len(victims), node, victims, relocations)
            if best is None:
                return None
            _, node, victims, relocations = best
            # Commit into the shared sim: victims leave their node, land
            # on their relocation target, the blocked pod takes the gap.
            for vkey, vreq, dest in relocations:
                for i in range(DIMS):
                    free[node][i] += vreq[i]
                    free[dest][i] -= vreq[i]
                taken.add(vkey)
            for i in range(DIMS):
                free[node][i] -= req[i]
            bound_by_node[node] = [c for c in bound_by_node.get(node, ())
                                   if c[0] not in taken]
            return {"for": pod.key, "node": node, "victims": victims}

        # Gangs group together and plan all-or-nothing (a half-unblocked
        # gang still cannot start); singles plan largest-request first.
        groups: dict[str, list] = {}
        singles: list = []
        for pod in blocked:
            (groups.setdefault(pod.gang, []) if pod.gang
             else singles).append(pod)
        singles.sort(key=lambda p: MemStore._pod_requests(
            api.pod_to_json(p))[0], reverse=True)
        for pod in singles:
            sub = plan_one(pod)
            if sub is not None:
                plans.append(sub)
        for gang, members in groups.items():
            snap_free = {n: list(v) for n, v in free.items()}
            snap_taken = set(taken)
            subs = []
            for pod in members:
                sub = plan_one(pod)
                if sub is None:
                    break
                subs.append(sub)
            if len(subs) == len(members):
                plans.extend(subs)
            else:
                # Roll the gang's partial moves back out of the sim.
                free.clear()
                free.update(snap_free)
                taken.clear()
                taken.update(snap_taken)
        return plans

    # -- 5. execute -------------------------------------------------------

    def _execute(self, plans: list[dict]) -> int:
        """Run the gated batch: per victim, stamp the intent (phase 1,
        CAS), evict to pending (phase 2, CAS via the binder's unbind),
        drop the cache attachment.  Any lost CAS skips that victim."""
        cache = self.daemon.config.algorithm.cache
        unbind = getattr(self.daemon.config.binder, "unbind", None)
        executed = 0
        for sub in plans:
            for vkey, vnode in sub["victims"]:
                obj = self.store.get("pods", vkey)
                if obj is None or not ((obj.get("spec") or {})
                                       .get("nodeName") or ""):
                    continue  # deleted or already pending: no move left
                ann = (obj.setdefault("metadata", {})
                       .setdefault("annotations", {}))
                ann[api.DEFRAG_MIGRATION_ANNOTATION_KEY] = json.dumps(
                    {"from": vnode, "round": self._round})
                try:
                    obj = cas_update(self.store, "pods", obj)
                except Exception:  # noqa: BLE001 — racing writer won
                    self.stats["cas_conflict"] += 1
                    metrics.DEFRAG_MIGRATIONS.labels(
                        result="cas_conflict").inc()
                    self._flight(vkey, "cas_conflict", from_node=vnode)
                    continue
                pod = api.pod_from_json(obj)
                try:
                    if unbind is not None:
                        unbind(pod)
                    else:
                        obj.setdefault("spec", {})["nodeName"] = ""
                        cas_update(self.store, "pods", obj)
                except Exception:  # noqa: BLE001 — evict lost its CAS
                    self.stats["cas_conflict"] += 1
                    metrics.DEFRAG_MIGRATIONS.labels(
                        result="cas_conflict").inc()
                    self._flight(vkey, "cas_conflict", from_node=vnode)
                    cur = self.store.get("pods", vkey)
                    if cur is not None:
                        self._clear_intent(cur)  # back out phase 1
                    continue
                cached = cache.get_pod(vkey)
                if cached is not None:
                    cache.remove_pod(cached)
                with self._lock:
                    self._inflight[vkey] = vnode
                executed += 1
                self.stats["migrations_executed"] += 1
                metrics.DEFRAG_MIGRATIONS.labels(result="executed").inc()
                self._flight(vkey, "executed", from_node=vnode,
                             target=sub["for"])
                self.daemon.config.recorder.eventf(
                    vkey, "Normal", "DefragMigration",
                    f"Evicted from {vnode} by the defragmenter to "
                    f"unblock {sub['for']}")
        with self._lock:
            metrics.DEFRAG_INFLIGHT.set(len(self._inflight))
        self.stats["max_batch"] = max(self.stats["max_batch"], executed)
        if executed:
            # Requeue each subplan's anchor NOW, in-process.  The anchor
            # is typically parked in the backoff heap (it failed to fit
            # for many cycles), so without this the evicted victim's
            # watch event re-solves the victim ALONE — and the most-free
            # node is the one it just vacated: a ping-pong.  An eager
            # enqueue puts the anchor at the head of the race for the
            # freed space.
            for sub in plans:
                obj = self.store.get("pods", sub["for"])
                if obj is None or ((obj.get("spec") or {})
                                   .get("nodeName") or ""):
                    continue
                try:
                    self.daemon.enqueue(api.pod_from_json(obj))
                except Exception:  # noqa: BLE001 — watch path still runs
                    pass
        return executed

    # -- a round ----------------------------------------------------------

    def run_once(self) -> dict:
        """One settle -> probe -> plan -> gate -> execute round.  Returns
        the round report (tests and /debug consumers read it)."""
        self._round += 1
        self.stats["rounds"] += 1
        metrics.DEFRAG_ROUNDS.inc()
        report = {"round": self._round, "blocked": 0, "planned": 0,
                  "migrations": 0, "executed": 0, "veto": ""}
        items, _rv = self.store.list("pods")
        by_key = {api.key_from_json(o): o for o in items}
        self._settle(by_key)
        sched = self.daemon.config.scheduler_name
        pend_pods = []
        with self._lock:
            inflight = set(self._inflight)
        for key, obj in by_key.items():
            if key in inflight or api.is_terminated_json(obj):
                continue
            if (obj.get("spec") or {}).get("nodeName"):
                continue
            pod = api.pod_from_json(obj)
            if sched is None or pod.scheduler_name == sched:
                pend_pods.append(pod)
        if not pend_pods:
            return report
        nodes, _ = self.store.list("nodes")
        free: dict[str, list] = {}
        for n in nodes:
            cap = _node_capacity(n)
            if cap is not None:
                free[api.key_from_json(n)] = cap
        bound_by_node: dict[str, list] = {}
        for key, obj in by_key.items():
            if api.is_terminated_json(obj):
                continue
            node = (obj.get("spec") or {}).get("nodeName") or ""
            if not node or node not in free:
                continue
            req = MemStore._pod_requests(obj)
            f = free[node]
            for i in range(DIMS):
                f[i] -= req[i]
            bound_by_node.setdefault(node, []).append((key, obj, req))
        blocked = self._blocked_set(
            pend_pods, {n: list(v) for n, v in free.items()})
        if blocked is None:
            report["veto"] = "engine_busy"
            return report
        report["blocked"] = len(blocked)
        with self._lock:
            self._blocked_prev |= {p.key for p in blocked}
        self.stats["blocked_peak"] = max(self.stats["blocked_peak"],
                                         len(blocked))
        if not blocked:
            return report
        plans = self._plan(blocked, free, bound_by_node,
                           self._pdb_guard())
        plans = [p for p in plans if p["victims"]]
        report["planned"] = len(plans)
        if not plans:
            return report
        # Trim whole subplans to the per-round migration cap — never a
        # partial eviction set that frees space for nobody.
        trimmed: list[dict] = []
        n_migrations = 0
        for sub in plans:
            if n_migrations + len(sub["victims"]) > self.max_migrations:
                continue
            trimmed.append(sub)
            n_migrations += len(sub["victims"])
        plans = trimmed
        report["migrations"] = n_migrations
        if not plans:
            report["veto"] = "vetoed_budget"
            return report
        for key, reason, count in self._gate(plans, n_migrations):
            self.stats["vetoed_budget"] += count
            metrics.DEFRAG_MIGRATIONS.labels(result=reason).inc(count)
            self._flight(key, reason)
            report["veto"] = reason
        if report["veto"]:
            return report
        for sub in plans:
            self._flight(sub["for"], "proposed", to_node=sub["node"])
        report["executed"] = self._execute(plans)
        if report["executed"]:
            log.info("defrag round %d: %d blocked, %d migration(s) "
                     "executed for %d subplan(s)", self._round,
                     len(blocked), report["executed"], len(plans))
        return report

    def _gate(self, plans: list[dict], n_migrations: int) -> list[tuple]:
        """The cost-model gates over a trimmed plan.  Returns veto
        records ``(anchor_key, reason, migration_count)`` — empty means
        the batch executes."""
        anchor = plans[0]["for"]
        with self._lock:
            in_flight = len(self._inflight)
        if in_flight + n_migrations > self.budget:
            return [(anchor, "vetoed_budget", n_migrations)]
        gain = len(plans)  # blocked pods this batch unblocks
        if n_migrations > 0 and gain / n_migrations < self.min_gain:
            return [(anchor, "vetoed_budget", n_migrations)]
        return []

    # -- lifecycle --------------------------------------------------------

    def report(self) -> dict:
        """Stats + live in-flight view (the soak artifact's source)."""
        with self._lock:
            out = dict(self.stats)
            out["inflight"] = len(self._inflight)
        return out

    def run(self, period: Optional[float] = None) -> threading.Thread:
        if period is None:
            period = self.period_s

        def loop():
            while not self._stop.wait(period):
                try:
                    self.run_once()
                except Exception:  # noqa: BLE001 — the rebalancer must
                    log.exception(  # never take the daemon down with it
                        "defrag round crashed; continuing")
        return threadreg.spawn(loop, name="defrag")

    def stop(self) -> None:
        self._stop.set()
