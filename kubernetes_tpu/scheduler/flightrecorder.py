"""Decision flight recorder: a bounded ring of the last N batch decisions.

"Why is my pod unschedulable?" is unanswerable from a running daemon when
the only artifacts are latency histograms — the decision itself (which node
won, which predicates failed where) is gone the moment the drain returns.
The recorder keeps one compact record per drained batch: the placement map
(pod -> node or None), per-pod failure detail (message + per-predicate
failure counts, the ``FitError.failed_predicates`` aggregation), and the
batch's trace id so a decision links to its spans at ``/debug/traces``.

Served at ``/debug/scheduler/decisions`` (batch summaries; ``?pod=ns/name``
explains one pod) and queryable via ``kubectl ... explain pod NAME``.

Recording cost on the hot path is one dict build per batch (the placement
lists the drain already produced); failure *detail* is computed only for
failed pods, capped, and only by the daemon path (the engine's
``explain_failures``)."""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque

from kubernetes_tpu.utils import knobs, locktrace

# Ring capacity in BATCHES (a batch may be one pod or thirty thousand).
DEFAULT_CAPACITY = 64
# The ring's on-disk form under KT_FLIGHT_DIR: dumped on graceful
# shutdown, reloaded on startup, so `kubectl explain pod` keeps answering
# across a scheduler bounce (the soak's restart scenario).
FLIGHT_FILE = "flight_ring.json"
# Failure-detail entries kept per batch (explain_failures caps its device
# work the same way).
MAX_FAILURES_PER_BATCH = 256
# Top-k score entries surfaced per explained pod.
TOP_K = 5


class BatchRecord:
    __slots__ = ("batch_id", "trace_id", "ts", "duration_s", "size",
                 "placed", "placements", "failures", "tenants")

    def __init__(self, batch_id: int, trace_id: str, ts: float,
                 duration_s: float, placements: dict,
                 failures: dict, tenants: dict | None = None):
        self.batch_id = batch_id
        self.trace_id = trace_id
        self.ts = ts
        self.duration_s = duration_s
        self.size = len(placements)
        self.placed = sum(1 for v in placements.values() if v is not None)
        self.placements = placements      # pod key -> node name | None
        self.failures = failures          # pod key -> detail dict
        self.tenants = tenants            # tenant -> row count (tenancy on)

    def summary(self) -> dict:
        out = {"batch_id": self.batch_id, "trace_id": self.trace_id,
               "ts": self.ts, "duration_s": round(self.duration_s, 6),
               "size": self.size, "placed": self.placed,
               "failed": self.size - self.placed}
        if self.tenants:
            out["tenants"] = self.tenants
        return out


class FlightRecorder:
    """Thread-safe ring of batch decisions + a side channel for post-batch
    failures (bind conflicts arrive from the async bind fan-out after the
    batch record was written; they amend it in place)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 flight_dir: str | None = None):
        """``flight_dir`` (default: the KT_FLIGHT_DIR env var) names a
        directory whose persisted ring, if any, is reloaded — batch ids
        continue past the reloaded maximum so restart records never
        collide with pre-restart ones."""
        self._ring: deque[BatchRecord] = deque(maxlen=max(1, capacity))
        self._lock = locktrace.make_lock("scheduler.FlightRecorder")
        self._seq = itertools.count(1)
        if flight_dir is None:
            flight_dir = knobs.get("KT_FLIGHT_DIR")
        if flight_dir:
            try:
                self.load(flight_dir)
            except Exception:  # noqa: BLE001 — a torn, wrong-shaped, or
                pass           # absent dump must never block startup

    # -- recording --------------------------------------------------------

    def record_batch(self, pods, placements, trace_id: str = "",
                     duration_s: float = 0.0,
                     failure_detail: dict | None = None,
                     tenants: dict | None = None) -> int:
        """One drained batch: parallel (pods, placements) lists as produced
        by ``schedule_batch``; ``failure_detail`` maps pod key ->
        {"failed_predicates": {...}, ...} for the pods the engine
        explained.  ``tenants`` (tenant -> row count, tenancy rigs only)
        tags the record so ``/debug/scheduler/decisions?tenant=`` can
        filter one tenant's decision history.  Returns the batch id."""
        placement_map = {pod.key: dest
                         for pod, dest in zip(pods, placements)}
        failures: dict = {}
        detail = failure_detail or {}
        failed_keys = [pod.key for pod, dest in zip(pods, placements)
                       if dest is None]
        with self._lock:
            # Backoff loops re-drain the same unschedulable pod every few
            # seconds: a single-pod failed batch whose pod's newest record
            # is the same single-pod failure refreshes that record in
            # place instead of churning real batches out of the ring.
            if len(placement_map) == 1 and len(failed_keys) == 1:
                key = failed_keys[0]
                for rec in reversed(self._ring):
                    if key in rec.placements:
                        if rec.size == 1 and rec.placements[key] is None:
                            rec.ts = time.time()
                            if detail.get(key):
                                rec.failures[key] = detail[key]
                            return rec.batch_id
                        break
            for pod, dest in zip(pods, placements):
                if dest is not None:
                    continue
                if len(failures) >= MAX_FAILURES_PER_BATCH:
                    break
                failures[pod.key] = detail.get(pod.key) or {
                    "message":
                    f"pod ({pod.name}) failed to fit in any node"}
            batch_id = next(self._seq)
            rec = BatchRecord(batch_id, trace_id, time.time(),
                              duration_s, placement_map, failures,
                              tenants=tenants)
            self._ring.append(rec)
        return batch_id

    def record_failure(self, pod_key: str, reason: str, message: str,
                       failed_predicates: dict | None = None) -> None:
        """Amend (or create) the failure entry for a pod — the
        ``_handle_failure`` hook: fit errors, bind conflicts, and drain
        crashes all pass through it.  If the pod belongs to a recorded
        batch, the batch's entry is updated; otherwise a one-pod record is
        appended (the single-pod ``schedule_one`` path)."""
        entry = {"reason": reason, "message": message}
        if failed_predicates:
            entry["failed_predicates"] = dict(failed_predicates)
        with self._lock:
            for rec in reversed(self._ring):
                if pod_key in rec.placements:
                    # Keep the engine's richer detail (predicate counts,
                    # top-scoring nodes) when this amend doesn't carry it.
                    old = rec.failures.get(pod_key)
                    if old:
                        entry = {**old, **entry}
                    if len(rec.failures) < MAX_FAILURES_PER_BATCH or \
                            pod_key in rec.failures:
                        rec.failures[pod_key] = entry
                    if rec.placements.get(pod_key) is not None:
                        # A bind failure demoted a placed pod.
                        rec.placements[pod_key] = None
                        rec.placed -= 1
                    return
            rec = BatchRecord(next(self._seq), "", time.time(), 0.0,
                              {pod_key: None}, {pod_key: entry})
            self._ring.append(rec)

    def record_preemption(self, pod_key: str, node: str,
                          victims: list[str]) -> None:
        """A preemption decision promoted this pod from unschedulable to
        placed-with-evictions: amend its newest record with the nominated
        node and victim set (the reference's status.nominatedNodeName,
        surfaced by ``kubectl explain``)."""
        detail = {"nominated_node": node,
                  "preempted_victims": list(victims)}
        with self._lock:
            for rec in reversed(self._ring):
                if pod_key not in rec.placements:
                    continue
                if rec.placements.get(pod_key) is None:
                    rec.placements[pod_key] = node
                    rec.placed += 1
                old = rec.failures.get(pod_key)
                rec.failures[pod_key] = {**old, **detail} if old else detail
                return
            rec = BatchRecord(next(self._seq), "", time.time(), 0.0,
                              {pod_key: node}, {pod_key: detail})
            self._ring.append(rec)

    def record_defrag(self, pod_key: str, decision: str,
                      from_node: str = "", to_node: str = "",
                      target: str = "") -> None:
        """A defragmentation decision touched this pod
        (scheduler/defrag.py): ``decision`` is one of proposed /
        executed / completed / vetoed_budget / vetoed_pdb /
        cas_conflict / crash-recovered; ``from_node``/``to_node`` frame
        the migration, ``target`` names the blocked pod the move serves.
        Amends the pod's newest record so ``kubectl explain pod``
        answers "why did the rebalancer move me"."""
        detail: dict = {"defrag": decision}
        if from_node:
            detail["migration_from"] = from_node
        if to_node:
            detail["migration_to"] = to_node
        if target:
            detail["migration_for"] = target
        with self._lock:
            for rec in reversed(self._ring):
                if pod_key not in rec.placements:
                    continue
                old = rec.failures.get(pod_key)
                rec.failures[pod_key] = {**old, **detail} if old \
                    else detail
                return
            rec = BatchRecord(next(self._seq), "", time.time(), 0.0,
                              {pod_key: to_node or None},
                              {pod_key: detail})
            self._ring.append(rec)

    # -- persistence across restarts (KT_FLIGHT_DIR) ----------------------

    def save(self, flight_dir: str) -> str:
        """Dump the ring to ``flight_dir/flight_ring.json`` (atomic
        rename, so a crash mid-dump leaves the previous dump intact).
        Called by Scheduler.stop(); returns the written path."""
        os.makedirs(flight_dir, exist_ok=True)
        with self._lock:
            records = [{"batch_id": r.batch_id, "trace_id": r.trace_id,
                        "ts": r.ts, "duration_s": r.duration_s,
                        "placements": r.placements,
                        "failures": r.failures,
                        "tenants": r.tenants}
                       for r in self._ring]
        path = os.path.join(flight_dir, FLIGHT_FILE)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"capacity": self._ring.maxlen,
                       "records": records}, f, separators=(",", ":"))
        os.replace(tmp, path)
        return path

    def load(self, flight_dir: str) -> int:
        """Reload a persisted ring (newest records win if the dump holds
        more than capacity); the id sequence resumes past the reloaded
        maximum.  Returns the number of records restored."""
        path = os.path.join(flight_dir, FLIGHT_FILE)
        if not os.path.exists(path):
            return 0
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        max_id = 0
        with self._lock:
            for rec in data.get("records", []):
                self._ring.append(BatchRecord(
                    int(rec["batch_id"]), rec.get("trace_id", ""),
                    float(rec.get("ts", 0.0)),
                    float(rec.get("duration_s", 0.0)),
                    dict(rec.get("placements") or {}),
                    dict(rec.get("failures") or {}),
                    tenants=rec.get("tenants") or None))
                max_id = max(max_id, int(rec["batch_id"]))
            self._seq = itertools.count(max_id + 1)
            return len(data.get("records", []))

    # -- querying ---------------------------------------------------------

    def explain(self, pod_key: str) -> dict | None:
        """The most recent decision for a pod, or None if it aged out.
        Predicate-count detail is backfilled from an older record when
        the newest one lacks it — the engine's explain pass runs under a
        cooldown, so a requeued pod's latest failure often carries only
        the message while an earlier record carries the counts."""
        with self._lock:
            out = None
            for rec in reversed(self._ring):
                if pod_key not in rec.placements:
                    continue
                if out is None:
                    dest = rec.placements[pod_key]
                    out = {"pod": pod_key, "batch_id": rec.batch_id,
                           "trace_id": rec.trace_id, "ts": rec.ts,
                           "result": "scheduled" if dest is not None
                           else "unschedulable",
                           "node": dest}
                    detail = rec.failures.get(pod_key)
                    if detail:
                        out.update(detail)
                    if dest is not None or \
                            "failed_predicates" in out:
                        return out
                    continue
                older = rec.failures.get(pod_key) or {}
                if "failed_predicates" in older:
                    for k, v in older.items():
                        out.setdefault(k, v)
                    return out
            return out

    def snapshot(self, limit: int = 0, tenant: str = "") -> dict:
        """Batch summaries, newest first (the /debug endpoint body).
        ``tenant`` filters to batches carrying that tenant's rows (the
        per-tenant flight-recorder view; untagged records — tenancy
        off — never match a tenant filter)."""
        with self._lock:
            recs = list(self._ring)
        recs.reverse()
        if tenant:
            recs = [r for r in recs if r.tenants and tenant in r.tenants]
        if limit > 0:
            recs = recs[:limit]
        return {"capacity": self._ring.maxlen,
                "batches": [r.summary() for r in recs]}
