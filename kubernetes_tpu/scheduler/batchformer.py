"""Deadline-driven batch formation: the serving path's "wait vs solve"
decision, in exactly one place.

The batched drain is only viable as a latency-SLO system if an
individual pod's submit->bind time stays bounded while batches form.
LLM serving systems solve the same tension with continuous/deadline
micro-batching — solve whatever arrived within T rather than waiting
for a batch to fill — and this module is that discipline for the
scheduling queue:

* ``KT_BATCH_DEADLINE_MS`` is the formation budget: once the first pod
  of a batch has been popped, the former tops the batch up from the
  arrival stream for at most that long.  0 (the default) disables
  lingering entirely — a drain solves whatever the pop returned, the
  pre-serving behavior.
* The former exits EARLY on either of two signals: the batch reached
  its adaptive TARGET bucket (a warm bucket's worth arrived — solve
  now), or the arrival stream went IDLE for ``IDLE_WINDOW_S`` (once the
  stream is silent, further lingering is pure latency that cannot grow
  the batch — a lone arrival hands off ~60 ms after it lands, not a
  full deadline later).  A live trickle keeps landing pods inside the
  idle window, so it coalesces toward the deadline; a finished burst
  stops lingering almost immediately.
* The target adapts between the pre-warmed ladder's floor bucket and
  the stream chunk: deadline exits with a small batch shrink it toward
  the floor (trickle — stop waiting for a burst that is not coming),
  filling it grows it toward the chunk (burst — one bigger solve beats
  N floor-bucket solves).  The target is always a pre-warmed ladder
  bucket, so batch formation can never steer a drain onto a shape the
  startup prewarm did not trace.
* DEGRADATION WINS: past the queue's high watermark the former skips
  the deadline entirely and returns one largest-warmed-bucket chunk
  (``pop_some``) immediately — a storm needs shedding, not lingering.
* Held gangs are invisible to the former (the queue releases a gang
  only when complete or overdue), so a deadline firing mid-hold can
  never split a gang across two batches.

``KT_COALESCE`` (seconds — the retired arrival-coalescing linger knob)
is kept as a deprecated alias: it maps onto the deadline so old rig
configs keep their meaning, but the linger loop it used to drive is
gone — the former is the only place that decides "wait vs solve".

Each formed batch records ``scheduler_batch_formation_latency_
microseconds`` and bumps ``scheduler_batch_deadline_misses_total`` when
hand-off overran the deadline (plus a 25% grace — the GIL, a gang
flush, or a slow arrival race ate the budget).  Per-pod admission
timestamps (stamped at enqueue, surviving requeues) ride the pod object
to the commit worker, which closes the loop with
``scheduler_e2e_decision_latency_microseconds`` at bind ack.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from kubernetes_tpu.utils import knobs
from kubernetes_tpu.utils import metrics as metrics_mod
from kubernetes_tpu.utils.logging import get_logger

log = get_logger("batchformer")

# Poll period while lingering inside the deadline, and how long a
# silent arrival stream must stay silent before it counts as "went
# idle".  60 ms ≈ three inter-arrival gaps of a 50 pods/s trickle: a
# live trickle almost always lands another pod inside the window (the
# batch keeps coalescing toward the deadline), while a finished burst
# or a lone arrival stops lingering ~60 ms after its last pod — once
# the stream is idle, more waiting is pure latency that cannot grow
# the batch.
POLL_S = 0.005
IDLE_WINDOW_S = 0.06

# A hand-off later than deadline * (1 + grace) counts as a deadline miss.
MISS_GRACE = 0.25


def _env_deadline_s() -> float:
    """Resolve the formation deadline from the environment, once per
    former (the daemon-lifetime discipline every other knob follows).
    ``KT_BATCH_DEADLINE_MS`` wins; ``KT_COALESCE`` (seconds) is the
    deprecated alias for rigs predating the former."""
    raw = knobs.get("KT_BATCH_DEADLINE_MS")
    if raw:
        try:
            return max(float(raw), 0.0) / 1e3
        except ValueError:
            log.warning("bad KT_BATCH_DEADLINE_MS=%r; deadline off", raw)
            return 0.0
    legacy = knobs.get("KT_COALESCE")
    if legacy:
        try:
            val = max(float(legacy), 0.0)
        except ValueError:
            return 0.0
        if val:
            log.warning("KT_COALESCE is deprecated; treating %ss as "
                        "KT_BATCH_DEADLINE_MS=%d", legacy, int(val * 1e3))
        return val
    return 0.0


def prune_first_seen_fair(registry: dict, bound: int,
                          group_of: Optional[Callable[[str], str]] = None
                          ) -> dict:
    """Shrink a first-seen registry to ``bound`` entries PER-GROUP-FAIR.

    The registry backs the e2e decision-latency SLO: losing a pod's
    stamp silently resets its clock.  Global oldest-first pruning has a
    multi-tenant failure mode — one tenant's flood of fresh stamps makes
    every OTHER tenant's (older, still-live) stamps the global-oldest,
    so the noisy tenant evicts the quiet tenants' clocks.  This prune is
    fair instead: entries are dropped oldest-first WITHIN whichever
    group currently holds the most entries, so shedding always lands on
    the flooder and a quiet group's stamps survive untouched.

    ``group_of`` maps a registry key to its fairness group (default: the
    key's namespace — the tenant proxy, and the right boundary even
    without tenancy configured)."""
    if len(registry) <= bound:
        return registry
    if group_of is None:
        def group_of(key: str) -> str:
            return key.partition("/")[0]
    import heapq
    groups: dict[str, list] = {}
    for key, ts in registry.items():
        groups.setdefault(group_of(key), []).append((ts, key))
    for items in groups.values():
        # Newest first, so shedding pops the group's OLDEST from the end.
        items.sort(reverse=True)
    heap = [(-len(items), name) for name, items in groups.items()]
    heapq.heapify(heap)
    excess = len(registry) - bound
    out = dict(registry)
    while excess > 0 and heap:
        neg, name = heapq.heappop(heap)
        items = groups[name]
        if not items:
            continue
        _, key = items.pop()
        out.pop(key, None)
        excess -= 1
        if items:
            heapq.heappush(heap, (-len(items), name))
    return out


def stamp_first_seen(pod) -> None:
    """Stamp the pod OBJECT's queue-admission time (idempotent).  The
    daemon's authoritative record is its key-indexed first-seen
    registry (watch redeliveries arrive as fresh objects, which an
    object-only stamp would let reset the SLO clock); this helper
    serves rigs driving a bare queue."""
    if getattr(pod, "_kt_first_seen", None) is None:
        pod._kt_first_seen = time.perf_counter()


def first_seen(pod) -> Optional[float]:
    return getattr(pod, "_kt_first_seen", None)


@dataclass
class FormedBatch:
    """One formed drain batch plus its formation telemetry."""

    pods: list
    degraded: bool = False
    # When formation began waiting (the queue_wait stage's backdate).
    t_wait: float = 0.0
    # First-pod-popped -> hand-off (0 for an empty/immediate batch).
    formation_s: float = 0.0
    deadline_missed: bool = False
    # The adaptive target bucket in force when this batch formed.
    target: int = 0


@dataclass
class BatchFormer:
    """Forms drain batches from a scheduling FIFO under a deadline.

    ``queue`` is the daemon's FIFO; ``ladder_fn`` returns the pre-warmed
    bucket ladder (``Scheduler.effective_ladder``) and ``chunk_fn`` the
    stream chunk size — the target's floor and ceiling; ``cap_fn``
    returns the degraded-mode drain cap."""

    queue: object
    ladder_fn: Callable[[], list] = lambda: []
    chunk_fn: Callable[[], int] = lambda: 0
    cap_fn: Callable[[], int] = lambda: 0
    deadline_s: float = field(default_factory=_env_deadline_s)
    # Adaptive target bucket; None until the first ladder read.
    _target: Optional[int] = None

    def _buckets(self) -> list[int]:
        """The target's menu: the warmed ladder, capped at the stream
        chunk (a bigger target than one chunk buys nothing — the stream
        path chunks it right back down)."""
        ladder = sorted(set(self.ladder_fn() or []))
        chunk = self.chunk_fn() or 0
        if chunk:
            ladder = [b for b in ladder if b <= chunk] or [chunk]
        return ladder or [1]

    @property
    def target(self) -> int:
        buckets = self._buckets()
        if self._target is None or self._target not in buckets:
            self._target = buckets[0]
        return self._target

    def _adapt(self, formed: int, hit_deadline: bool) -> None:
        """Shrink toward the floor under trickle, grow toward the chunk
        under burst — one bucket step per drain, so one anomalous batch
        cannot whiplash the target."""
        buckets = self._buckets()
        i = buckets.index(self.target)
        if formed >= self.target and i + 1 < len(buckets):
            self._target = buckets[i + 1]
        elif hit_deadline and formed < self.target and i > 0:
            self._target = buckets[i - 1]

    def form(self, wait_first: bool = True,
             timeout: Optional[float] = None) -> FormedBatch:
        """Pop + top-up one drain batch.  Blocking (up to ``timeout``)
        only for the FIRST pod; the deadline clock starts when it
        lands."""
        t_wait = time.perf_counter()
        if self.queue.degraded():
            # Load shedding: one largest-warmed-bucket chunk, no linger
            # — degradation always wins over the deadline.
            metrics_mod.DEGRADED_DRAINS.inc()
            pods = self.queue.pop_some(self.cap_fn(),
                                       wait_first=wait_first,
                                       timeout=timeout)
            formation_s = time.perf_counter() - t_wait
            if pods:
                # Degraded formation is still a formation: the histogram
                # must count every drain or formation-count == drain-count
                # breaks exactly when the daemon is shedding load.
                metrics_mod.BATCH_FORMATION_LATENCY.observe(
                    formation_s * 1e6)
            return FormedBatch(pods, degraded=True, t_wait=t_wait,
                               formation_s=formation_s)
        pods = self.queue.pop_all(wait_first=wait_first, timeout=timeout)
        if not pods:
            return FormedBatch([], t_wait=t_wait)
        deadline_s = self.deadline_s
        chunk = self.chunk_fn() or 0
        cap = chunk if chunk else (1 << 62)
        t0 = time.perf_counter()
        hit_deadline = False
        if deadline_s > 0 and len(pods) < cap:
            target = self.target
            deadline_at = t0 + deadline_s
            idle_since = None
            while len(pods) < cap:
                now = time.perf_counter()
                remaining = deadline_at - now
                if remaining <= 0:
                    hit_deadline = True
                    break
                if len(pods) >= target:
                    break  # a warm bucket's worth arrived: solve now
                if idle_since is not None and \
                        now - idle_since >= IDLE_WINDOW_S:
                    # The stream went quiet: lingering further is pure
                    # latency — it cannot grow the batch.
                    break
                time.sleep(min(POLL_S, remaining))
                more = self.queue.pop_all(wait_first=False)
                if more:
                    pods.extend(more)
                    idle_since = None
                elif idle_since is None:
                    idle_since = time.perf_counter()
                if self.queue.degraded():
                    break  # a storm crossed the watermark mid-linger
            self._adapt(len(pods), hit_deadline)
            # One object per key per batch: the linger's second pop can
            # re-return a pod that was requeued (bind-conflict backoff)
            # or watch-redelivered between pops — and a duplicated key
            # poisons the commit path (the bulk assume skips the second
            # copy, and the skip-filter then drops BOTH, stranding the
            # pod assumed-but-never-bound).  Keep the FIRST object.
            seen_keys: set = set()
            uniq = [pod for pod in pods
                    if not (pod.key in seen_keys
                            or seen_keys.add(pod.key))]
            if len(uniq) != len(pods):
                pods = uniq
        formation_s = time.perf_counter() - t0
        metrics_mod.BATCH_FORMATION_LATENCY.observe(formation_s * 1e6)
        missed = deadline_s > 0 and \
            formation_s > deadline_s * (1.0 + MISS_GRACE)
        if missed:
            metrics_mod.BATCH_DEADLINE_MISSES.inc()
        return FormedBatch(pods, t_wait=t_wait, formation_s=formation_s,
                           deadline_missed=missed, target=self.target)
