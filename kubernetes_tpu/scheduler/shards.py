"""Active-active HA: sharded scheduler incarnations over one shared state.

One scheduler daemon is a single point of stall: a SIGKILL parks every
pending pod until the recovery reconciler (scheduler/recovery.py) brings
a fresh incarnation up.  Production runs SEVERAL incarnations against the
same apiserver, Omega-style — shared state, optimistic concurrency — and
this module is the partition layer that keeps the steady state conflict-
free while the bind CAS stays the safety net:

* the namespace keyspace is split into ``n_shards`` SHARDS by a hash
  that is deterministic ACROSS PROCESSES (crc32 — ``hash()`` is salted
  per interpreter and two incarnations disagreeing on the shard map
  would both schedule, or neither);
* each shard is one renewable LEASE — an ``APIResourceLock`` on its own
  apiserver object (``kube-scheduler-shard-<i>``), CAS'd exactly like
  the controller-manager's election lock, with per-shard
  ``LeaderElector`` record/expiry semantics reused wholesale;
* an incarnation schedules ONLY pods whose namespace hashes into a
  shard it holds; everything else is dropped at the queue feed and
  picked up by that shard's owner;
* when an incarnation dies, its leases expire within ``lease_duration``
  and the survivors steal them — each acquisition fires
  ``on_acquired(shard)``, whose factory callback runs the shard-scoped
  takeover reconcile (relist, forget stale assumes, requeue the
  orphans) before the survivor drains the shard;
* during the handoff window two incarnations can briefly cover one
  shard (the old holder's in-flight drain + the thief).  That is SAFE,
  not merely tolerated: the apiserver binds ``spec.nodeName`` by CAS,
  so one bind lands and the loser 409s into the ordinary
  forget-and-requeue path (counted as
  ``scheduler_cross_shard_bind_conflicts_total``).

Acquisition is POLITE: before trying a free shard, an incarnation backs
off proportionally to the shards it already holds, so a lightly-loaded
peer wins the race and the shard map stays roughly balanced without any
central assignment.  Politeness only delays, never blocks — a lone
survivor still ends up holding everything.

Politeness alone cannot help a LATE JOINER: every lease is held and
renewed, so a freshly started incarnation (or one recovering after a
crash) would starve.  Incarnations therefore heartbeat a shared
PRESENCE object (``kube-scheduler-incarnations``, annotation-CAS like
the locks), and a holder that sees a stably-live peer stuck below its
fair share RELEASES one surplus shard (gracefully — the record is
zeroed, politeness hands it to the hungry peer).  Liveness is judged by
OBSERVED CHANGE, never by comparing foreign timestamps to the local
clock: a peer is live while its heartbeat value keeps changing, exactly
the cross-process-safe rule the lease expiry itself uses — and a dead
peer's stale presence therefore never triggers a release, which keeps
the takeover window churn-free.
"""

from __future__ import annotations

import json
import random
import threading
import time
import zlib
from typing import Callable, Optional

from kubernetes_tpu.utils import locktrace, metrics, threadreg
from kubernetes_tpu.utils.leaderelection import (APIResourceLock,
                                                 LeaderElector)
from kubernetes_tpu.utils.logging import get_logger

log = get_logger("shards")

SHARD_LOCK_PREFIX = "kube-scheduler-shard"

# Soak/e2e rigs compress these; production defaults keep lease traffic
# far below the apiserver's noise floor while bounding takeover at a
# few seconds.
DEFAULT_LEASE_DURATION = 3.0
DEFAULT_RENEW_DEADLINE = 2.0
DEFAULT_RETRY_PERIOD = 0.5


def shard_of(namespace: str, n_shards: int) -> int:
    """The cross-process-deterministic shard of a namespace (crc32, NOT
    the salted builtin ``hash``)."""
    if n_shards <= 1:
        return 0
    return zlib.crc32(namespace.encode("utf-8")) % n_shards


def shard_lock_name(shard: int) -> str:
    return f"{SHARD_LOCK_PREFIX}-{shard}"


class ShardManager:
    """Per-incarnation shard-lease loop: one ``LeaderElector`` per shard
    over one client, driven by a single tick thread (per-shard threads
    would be N blocking acquire loops fighting for the GIL).

    ``on_acquired(shard, handoff)`` / ``on_lost(shard)`` fire on a
    dedicated callback thread, so a slow takeover reconcile (a full pod
    relist) can never stall the renew loop into missing its own
    deadlines — exactly the failure mode that would cascade one slow
    apiserver call into a full shard-map reshuffle."""

    def __init__(self, client, incarnation: str, n_shards: int,
                 lease_duration: float = DEFAULT_LEASE_DURATION,
                 renew_deadline: float = DEFAULT_RENEW_DEADLINE,
                 retry_period: float = DEFAULT_RETRY_PERIOD,
                 jitter: float = 0.2,
                 on_acquired: Optional[Callable[[int, bool], None]] = None,
                 on_lost: Optional[Callable[[int], None]] = None,
                 now: Callable[[], float] = time.monotonic,
                 lock_factory: Optional[Callable[[int], object]] = None):
        if n_shards < 1:
            raise ValueError(f"n_shards must be >= 1, got {n_shards}")
        self.incarnation = incarnation
        self.n_shards = n_shards
        self.retry_period = retry_period
        self.renew_deadline = renew_deadline
        self.jitter = jitter
        self.on_acquired = on_acquired
        self.on_lost = on_lost
        self.now = now
        if lock_factory is None:
            def lock_factory(shard: int):
                name = shard_lock_name(shard) if shard >= 0 \
                    else "kube-scheduler-incarnations"
                return APIResourceLock(client, name=name)
        # The presence object (lock_factory(-1)): identity -> heartbeat
        # counter, CAS'd like the leases; rebalancing reads it to see
        # peers that hold nothing and would otherwise be invisible.
        self._presence_lock = lock_factory(-1)
        self._hb_counter = 0
        self._hb_at = -1e18
        # identity -> (last value, local time the value last CHANGED,
        # local time first seen) — observed-change liveness.
        self._peers: dict[str, tuple[int, float, float]] = {}
        self._electors = [
            LeaderElector(lock=lock_factory(i), identity=incarnation,
                          lease_duration=lease_duration,
                          renew_deadline=renew_deadline,
                          retry_period=retry_period, jitter=jitter,
                          now=now)
            for i in range(n_shards)]
        self._owned: set[int] = set()
        # shard -> local acquisition time: rebalancing never releases a
        # freshly-taken shard (a takeover must not bounce straight back
        # out).
        self._acquired_at: dict[int, float] = {}
        self.lease_duration = lease_duration
        self._mu = locktrace.make_lock("scheduler.ShardManager")
        # Per-shard renew-success stamp: a holder that cannot CAS for
        # renew_deadline gives the shard up LOCALLY (stops scheduling it)
        # even before the lease expires for everyone else — the reference
        # elector's renew-deadline semantics, per shard.
        self._renewed_at: dict[int, float] = {}
        # Per-shard foreign-lease probe stamp (one GET per renew
        # deadline while someone else holds it).
        self._probed_at: dict[int, float] = {}
        # Politeness gate: no acquisition attempts before this stamp;
        # pushed out by retry_period * len(owned) on every acquisition.
        self._acquire_after = 0.0
        # Rebalance dampener: at most one surplus release per lease
        # period, so a transient liveness misread cannot shed the map.
        self._rebalanced_at = -1e18
        self.handoffs = 0
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._callbacks: list[tuple] = []
        self._cb_cv = threading.Condition()
        metrics.INCARNATION_INFO.labels(incarnation=incarnation).set(1)
        self._publish()

    # -- ownership queries (the queue feed's hot path) --------------------

    def owned(self) -> frozenset[int]:
        with self._mu:
            return frozenset(self._owned)

    def owns_shard(self, shard: int) -> bool:
        with self._mu:
            return shard in self._owned

    def owns_namespace(self, namespace: str) -> bool:
        return self.owns_shard(shard_of(namespace, self.n_shards))

    def owns_pod(self, pod) -> bool:
        return self.owns_namespace(pod.namespace)

    def acquired_at(self, shard: int) -> Optional[float]:
        """The clock reading (``now()`` base, ``time.monotonic`` by
        default) at which this incarnation last acquired ``shard``'s
        lease; None when it never has.  The takeover reconcile uses it
        as the stale-assume cutoff: an assume minted before the
        acquisition is a leftover of an earlier spell, one minted since
        is the live drain loop at work."""
        return self._acquired_at.get(shard)

    def report(self) -> dict:
        with self._mu:
            return {"incarnation": self.incarnation,
                    "nShards": self.n_shards,
                    "shardsOwned": sorted(self._owned),
                    "leaseHandoffs": self.handoffs}

    def _publish(self) -> None:
        metrics.SHARDS_OWNED.labels(incarnation=self.incarnation).set(
            len(self._owned))

    # -- the tick loop -----------------------------------------------------

    def run(self) -> "ShardManager":
        t = threadreg.spawn(
            self._loop, name=f"shard-manager-{self.incarnation}")
        cb = threadreg.spawn(
            self._callback_loop,
            name=f"shard-callbacks-{self.incarnation}")
        self._threads = [t, cb]
        return self

    @property
    def threads(self) -> list[threading.Thread]:
        """The manager's worker threads (tick + callbacks) for the
        embedding daemon's liveness tracking; empty before run()."""
        return list(self._threads)

    def stop(self, release: bool = True) -> None:
        """Graceful stop; ``release=False`` is the SIGKILL simulation —
        the leases are simply abandoned and expire on their own, exactly
        what a kill -9 leaves behind for the survivors to steal."""
        self._stop.set()
        with self._cb_cv:
            self._cb_cv.notify_all()
        # Join the tick loop BEFORE zeroing any lease: a tick already
        # in flight when the stop flag went up could otherwise observe
        # a just-released record as a dead foreign lease and CAS this
        # dying incarnation straight back in as holder — leaving the
        # lease live after exit, so peers wait out the full
        # lease_duration instead of taking over within a retry period.
        for t in self._threads[:1]:
            if t.is_alive() and t is not threading.current_thread():
                t.join(timeout=5.0)
        if release:
            for shard in sorted(self.owned()):
                self._release(shard)
        with self._mu:
            lost = sorted(self._owned)
            self._owned.clear()
            self._publish()
        if release and self.on_lost is not None:
            for shard in lost:
                try:
                    self.on_lost(shard)
                except Exception:  # noqa: BLE001 — teardown best-effort
                    log.exception("on_lost(%d) crashed during stop", shard)

    def abandon(self) -> None:
        self.stop(release=False)

    def _release(self, shard: int) -> None:
        """Zero out the lease record so peers can take over immediately
        instead of waiting out lease_duration (leaderelection.go's
        ReleaseOnCancel).  The holder check parses the freshly-fetched
        record, NOT the elector's cached observation: if a peer stole
        the lease since we last looked, a stale-observation check would
        pass and we would zero the PEER's live lease (the CAS version
        from the same get still guards the write, but the check must
        match the data the version belongs to)."""
        from kubernetes_tpu.utils.leaderelection import \
            LeaderElectionRecord
        el = self._electors[shard]
        try:
            raw, version = el.lock.get()
            if raw:
                rec = LeaderElectionRecord.from_json(raw)
                if rec.holder_identity == self.incarnation:
                    rec.renew_time = rec.acquire_time = 0.0
                    rec.lease_duration_seconds = 0.0
                    el.lock.update(rec.to_json(), version)
        except Exception:  # noqa: BLE001 — release is best-effort
            pass

    def _loop(self) -> None:
        while not self._stop.wait(self._tick_sleep()):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — HandleCrash analogue
                log.exception("shard tick crashed; continuing")

    def _tick_sleep(self) -> float:
        # Jitter the tick itself: the electors' per-sleep jitter never
        # runs here (tick() calls try_acquire_or_renew directly, not
        # LeaderElector.run), so without this N incarnations configured
        # with identical retry periods would phase-lock into
        # simultaneous CAS herds against the lease objects.
        if self.jitter <= 0.0:
            return self.retry_period
        return self.retry_period * (1.0 + self.jitter * random.random())

    def _try_lease(self, shard: int, el) -> bool:
        """``try_acquire_or_renew`` with the lease I/O fault isolated to
        THIS shard: one lease object's apiserver error (timeout, 5xx, a
        chaos rule aimed at that path) must not abort the tick for every
        later shard — nor skip the heartbeat and rebalance behind them.
        Returning False feeds the ordinary renew-deadline machinery, so
        a shard whose lease I/O stays broken is still given up on time."""
        try:
            return el.try_acquire_or_renew()
        except Exception:  # noqa: BLE001 — lease I/O; next tick retries
            log.warning("shard %d lease CAS round failed; next tick "
                        "retries", shard, exc_info=True)
            return False

    def tick(self) -> None:
        """One pass over every shard: renew what we hold, politely try
        what looks free.  Factored out of the loop so clock-injected
        tests can drive it deterministically."""
        now = self.now()
        # Renew on a cadence (a third of the deadline: three CAS
        # attempts before the deadline can pass), not every tick — N
        # held shards at a fast tick would otherwise be N×20 CAS/s of
        # pure lease traffic.
        renew_period = self.renew_deadline / 3.0
        for shard, el in enumerate(self._electors):
            held = self.owns_shard(shard)
            if held:
                last = self._renewed_at.get(shard, 0.0)
                if now - last < renew_period:
                    continue
                if self._try_lease(shard, el):
                    self._renewed_at[shard] = now
                elif not el.is_leader() or \
                        now - self._renewed_at.get(shard, now) >= \
                        self.renew_deadline:
                    # Someone stole the lease (the failed CAS round
                    # observed a foreign record), or we couldn't renew
                    # within the deadline (apiserver gone): stop
                    # scheduling this shard NOW rather than discovering
                    # it at bind time.
                    self._transition(shard, owned=False)
            else:
                holder = el.observed_holder()
                # Politeness: the more we hold, the longer we let
                # lighter peers win the race for a free lease.  EXCEPT
                # for an expired FOREIGN lease — a dead peer's orphan
                # is a takeover, and every second of politeness there
                # is a second of that shard's pods going unscheduled
                # (the CAS settles any survivor-vs-survivor race; a
                # lease we released ourselves keeps the gate, so a
                # rebalance hand-off cannot boomerang).
                urgent = bool(holder) and holder != self.incarnation \
                    and el.lease_dead()
                if not urgent and now < self._acquire_after:
                    continue
                remaining = el.lease_remaining()
                if remaining > 0.0 and \
                        el.observed_holder() != self.incarnation:
                    # Live foreign lease: probe (one GET) on a cadence,
                    # not every tick.  Far from expiry one observation
                    # per renew deadline suffices (and is what notices a
                    # gracefully RELEASED lease early); inside the last
                    # renew-deadline window tighten to the retry period,
                    # so a SIGKILLed holder's shard is stolen ~one tick
                    # after its lease dies instead of up to a full renew
                    # deadline later — the probe tax only ramps when a
                    # takeover is plausibly imminent.
                    probe_period = self.retry_period \
                        if remaining <= self.renew_deadline \
                        else self.renew_deadline
                    if now - self._probed_at.get(shard, -1e18) < \
                            probe_period:
                        continue
                self._probed_at[shard] = now
                prev_holder = el.observed_holder()
                if self._try_lease(shard, el):
                    self._renewed_at[shard] = now
                    self._acquired_at[shard] = now
                    handoff = bool(prev_holder) and \
                        prev_holder != self.incarnation
                    self._transition(shard, owned=True, handoff=handoff)
                    with self._mu:
                        held_n = len(self._owned)
                    self._acquire_after = now + \
                        self.retry_period * held_n
        self._heartbeat(now)
        self._rebalance(now)

    # -- presence + rebalancing -------------------------------------------

    def _heartbeat(self, now: float) -> None:
        """Bump our counter in the shared presence object and fold the
        read-back table into the observed-change liveness view."""
        if now - self._hb_at < self.renew_deadline / 3.0:
            return
        self._hb_at = now
        try:
            raw, version = self._presence_lock.get()
            table = json.loads(raw) if raw else {}
            if not isinstance(table, dict):
                table = {}
        except Exception:  # noqa: BLE001 — presence is advisory
            return
        for ident, val in table.items():
            if ident == self.incarnation:
                continue
            prev = self._peers.get(ident)
            if prev is None:
                self._peers[ident] = (val, now, now)
            elif prev[0] != val:
                self._peers[ident] = (val, now, prev[2])
        # Garbage-collect long-dead identities while we hold the
        # freshest read: the default incarnation id is minted per
        # process start, so a crash-looping fleet adds a new entry on
        # every boot — and the table is re-read and re-CAS'd IN FULL
        # every heartbeat by every incarnation, so without pruning the
        # payload (and the local peer view) grows for the deployment's
        # lifetime.  Dead is judged by OUR clock observing THEIR
        # counter stop changing — the same foreign-timestamp-free rule
        # liveness uses — at 10 lease durations, far beyond the 2 the
        # liveness window tolerates, so a slow peer is never collected
        # (and a wrongly collected one re-inserts itself at its next
        # heartbeat anyway).
        prune_after = 10.0 * self.lease_duration
        for ident in [i for i, (_v, changed, _first)
                      in self._peers.items()
                      if now - changed >= prune_after]:
            table.pop(ident, None)
            del self._peers[ident]
        self._hb_counter += 1
        table[self.incarnation] = self._hb_counter
        # Best-effort CAS: a lost race just means the next cadence
        # writes a fresher counter.
        self._presence_lock.update(
            json.dumps(table, sort_keys=True), version)

    def _live_peers(self, now: float) -> set[str]:
        """Identities whose heartbeat value changed within two lease
        durations — by OUR clock observing THEIR changes, so no foreign
        timestamp is ever compared to a local clock."""
        window = 2.0 * self.lease_duration
        return {ident for ident, (_v, changed, _first)
                in self._peers.items() if now - changed < window}

    def _rebalance(self, now: float) -> None:
        """Release one surplus shard when a STABLY-live peer sits below
        its fair share (the late-joiner/recovery path politeness cannot
        serve: every lease is held and renewed, so without this a fresh
        incarnation would starve forever).  A dead peer's presence
        entry stops changing and thus never triggers a release — the
        takeover window stays churn-free."""
        if now - self._rebalanced_at < self.lease_duration:
            return
        held = sorted(self.owned())
        live = self._live_peers(now)
        live.add(self.incarnation)
        fair = -(-self.n_shards // len(live))  # ceil
        if len(held) <= fair:
            return
        # Shard -> holder, from our own electors' observations.
        holder_counts: dict[str, int] = {}
        for el in self._electors:
            h = el.observed_holder()
            if h and not el.lease_dead():
                holder_counts[h] = holder_counts.get(h, 0) + 1
        stable = 2.0 * self.lease_duration
        hungry = [p for p in live
                  if p != self.incarnation
                  and holder_counts.get(p, 0) < fair
                  and now - self._peers[p][2] >= stable]
        if not hungry:
            return
        # Never bounce a freshly-taken shard; release the newest
        # eligible one (oldest shards keep their warmed-up backlog
        # affinity).
        eligible = [s for s in held
                    if now - self._acquired_at.get(s, now) >= stable]
        if not eligible:
            return
        victim = eligible[-1]
        self._rebalanced_at = now
        self._release(victim)
        self._transition(victim, owned=False)
        self._acquire_after = max(self._acquire_after,
                                  now + self.lease_duration)
        log.info("incarnation %s released shard %d to rebalance "
                 "(fair %d, hungry %s)", self.incarnation, victim,
                 fair, hungry)

    def _transition(self, shard: int, owned: bool,
                    handoff: bool = False) -> None:
        with self._mu:
            if owned:
                self._owned.add(shard)
                if handoff:
                    self.handoffs += 1
                    metrics.SHARD_LEASE_HANDOFFS.labels(
                        incarnation=self.incarnation).inc()
            else:
                self._owned.discard(shard)
            self._publish()
        log.info("incarnation %s %s shard %d (now owns %s)",
                 self.incarnation,
                 "acquired" + (" [handoff]" if handoff else "")
                 if owned else "lost", shard, sorted(self._owned))
        cb = self.on_acquired if owned else self.on_lost
        if cb is None:
            return
        with self._cb_cv:
            self._callbacks.append(
                (cb, (shard, handoff) if owned else (shard,)))
            self._cb_cv.notify()

    def _callback_loop(self) -> None:
        while True:
            with self._cb_cv:
                while not self._callbacks and not self._stop.is_set():
                    self._cb_cv.wait(timeout=0.5)
                if not self._callbacks:
                    if self._stop.is_set():
                        return
                    continue
                cb, args = self._callbacks.pop(0)
            try:
                cb(*args)
            except Exception:  # noqa: BLE001 — a crashing takeover
                # reconcile must not kill the callback thread; the
                # reflector stream still converges the shard eventually.
                log.exception("shard callback %s%s crashed", cb, args)

    def drain_callbacks(self, timeout: float = 5.0) -> bool:
        """Wait until every queued ownership callback has run (tests and
        the takeover-settle measurement)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._cb_cv:
                if not self._callbacks:
                    return True
            time.sleep(0.01)
        return False
