"""Pod queue: the scheduler's FIFO (pkg/client/cache/fifo.go), grown into
a priority queue with gang-aware grouping (engine/workloads/).

Same contract the reference's scheduler relies on: items keyed by pod key;
Add/Update replace in place without changing queue position; Delete removes;
Pop blocks until an item is available; re-adding a popped key re-queues it
at the back of its priority class.  ``pop_all`` drains everything at once —
the batched entry point the TPU solver feeds on.

Two workload-model extensions:

* PRIORITY ORDERING: pops return the highest ``effective_priority``
  first, FIFO within a priority class (the reference's scheduling-queue
  behavior once PodPriority landed).  Priority-less pods (the default 0)
  keep the exact old FIFO order.

* GANG HOLD: a pod carrying ``scheduling.kt.io/gang`` with a declared
  ``gang-size`` > 1 is held aside until that many members are present,
  then all members are released CONTIGUOUSLY at the gang's max member
  priority — a drain therefore sees the whole gang at once, which is what
  makes the solver's all-or-nothing reduction atomic.  Holds expire after
  ``gang_linger_s`` (members released anyway, marked by the annotation
  contract as an incomplete gang the solver will reject) so a gang whose
  member binds got split by faults can still converge instead of
  deadlocking in the hold.

BOUNDED DEGRADATION: past ``high_watermark`` pending items
(``KT_QUEUE_HIGH_WATERMARK``, 0 = unbounded) the queue reports
``degraded()`` and the daemon sheds load gracefully — drains switch to
largest-warmed-bucket-first chunks (``pop_some``) so a storm never
builds one unbounded batch, and NEW gang members bypass the hold (the
solver's all-or-nothing reduction still protects atomicity; what the
bypass drops is only the release-together latency optimization).  A
storm therefore produces slower decisions, never unbounded per-drain
memory growth.
"""

from __future__ import annotations

import heapq
import time
from typing import Optional

import threading

from kubernetes_tpu.api import types as api
from kubernetes_tpu.utils import knobs

# Degradation threshold default: far above any healthy backlog (the 30k
# density burst fits with headroom) but low enough that a runaway storm
# trips shedding before per-drain allocations hurt.
DEFAULT_HIGH_WATERMARK = 65536


class FIFO:
    # Incomplete gangs release anyway after this long in the hold (see
    # module docstring); the chaos suite compresses it.
    gang_linger_s: float = 5.0

    def __init__(self, high_watermark: Optional[int] = None) -> None:
        self._lock = threading.Condition()
        self._items: dict[str, api.Pod] = {}
        # Load-shedding threshold, read once at construction (the daemon's
        # whole-lifetime discipline, like the stream floor): 0 disables.
        if high_watermark is None:
            high_watermark = knobs.get_int("KT_QUEUE_HIGH_WATERMARK")
        self.high_watermark = high_watermark
        # Churn observability: deepest backlog ever seen (soak artifact).
        self.peak_depth = 0
        # Heap of (-priority, seq, key); stale keys skipped at pop (lazy
        # delete, like the old deque).  Equal priorities pop in seq
        # (FIFO) order.
        self._heap: list[tuple[int, int, str]] = []
        self._seq = 0
        # Gang hold: name -> {key: pod}; deadlines: name -> monotonic
        # release-anyway time.
        self._gang_hold: dict[str, dict[str, api.Pod]] = {}
        self._gang_deadline: dict[str, float] = {}
        self._closed = False

    def _push(self, pod: api.Pod, priority: Optional[int] = None) -> None:
        key = pod.key
        if key not in self._items:
            self._seq += 1
            prio = pod.effective_priority if priority is None else priority
            heapq.heappush(self._heap, (-prio, self._seq, key))
        self._items[key] = pod
        depth = len(self._items) + sum(
            len(h) for h in self._gang_hold.values())
        if depth > self.peak_depth:
            self.peak_depth = depth

    def _degraded_locked(self) -> bool:
        return bool(self.high_watermark) and \
            len(self._items) + sum(len(h)
                                   for h in self._gang_hold.values()) \
            >= self.high_watermark

    def degraded(self) -> bool:
        """True while the backlog sits at/past the high watermark — the
        daemon's signal to shed load (largest-bucket drains, gang holds
        bypassed) and the ``scheduler_queue_degraded`` gauge's truth."""
        with self._lock:
            return self._degraded_locked()

    def add(self, pod: api.Pod) -> None:
        with self._lock:
            key = pod.key
            gname, gsize = pod.gang, pod.gang_size
            if gname and gsize > 1 and self._degraded_locked():
                # Degraded: bypass the hold — holding thousands of gangs
                # during a storm defers work the drain could be shedding,
                # and an incomplete gang is still admitted atomically (or
                # rejected whole) by the solver's reduction.
                gname = ""
            if gname and gsize > 1 and key not in self._items:
                hold = self._gang_hold.setdefault(gname, {})
                if not hold:
                    self._gang_deadline[gname] = \
                        time.monotonic() + self.gang_linger_s
                hold[key] = pod
                if len(hold) < gsize:
                    # Wake every blocked popper even though nothing is
                    # poppable yet: a timeout=None popper computed its
                    # wait BEFORE this hold's deadline existed and must
                    # re-clip to it, or the linger flush never fires.
                    self._lock.notify_all()
                    return
                # A whole gang lands at once: one notify() would wake a
                # single schedule_one worker for gsize items.
                self._release_gang(gname)
                self._lock.notify_all()
            else:
                self._push(pod)
                self._lock.notify()

    def _release_gang(self, name: str) -> None:
        """Push every held member contiguously at the gang's max member
        priority (caller holds the lock)."""
        members = self._gang_hold.pop(name, {})
        self._gang_deadline.pop(name, None)
        if not members:
            return
        prio = max(p.effective_priority for p in members.values())
        for pod in members.values():
            self._push(pod, priority=prio)

    def _flush_overdue_gangs(self) -> None:
        now = time.monotonic()
        for name in [n for n, dl in self._gang_deadline.items()
                     if dl <= now]:
            self._release_gang(name)

    def update(self, pod: api.Pod) -> None:
        with self._lock:
            key = pod.key
            for hold in self._gang_hold.values():
                if key in hold:
                    hold[key] = pod
                    return
            if key in self._items:
                self._items[key] = pod
                return
        self.add(pod)

    def delete(self, pod_key: str) -> None:
        with self._lock:
            self._items.pop(pod_key, None)
            for name, hold in list(self._gang_hold.items()):
                if hold.pop(pod_key, None) is not None and not hold:
                    self._gang_hold.pop(name, None)
                    self._gang_deadline.pop(name, None)
            # Lazy removal: stale heap keys are skipped at pop time.

    def delete_matching(self, pred) -> int:
        """Remove every queued/held pod whose OBJECT matches ``pred`` —
        the shard-handoff drop: an incarnation that lost a shard's lease
        sheds that shard's pods in one pass instead of popping (and
        half-scheduling) them.  Returns the number removed."""
        removed = 0
        with self._lock:
            for key in [k for k, p in self._items.items() if pred(p)]:
                self._items.pop(key, None)
                removed += 1
            for name, hold in list(self._gang_hold.items()):
                for key in [k for k, p in hold.items() if pred(p)]:
                    hold.pop(key, None)
                    removed += 1
                if not hold:
                    self._gang_hold.pop(name, None)
                    self._gang_deadline.pop(name, None)
        return removed

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._lock.notify_all()

    def __len__(self) -> int:
        with self._lock:
            return len(self._items) + sum(
                len(h) for h in self._gang_hold.values())

    def __contains__(self, pod_key: str) -> bool:
        with self._lock:
            return pod_key in self._items or any(
                pod_key in h for h in self._gang_hold.values())

    def held_gangs(self) -> dict[str, int]:
        """Gang name -> held member count (observability)."""
        with self._lock:
            return {n: len(h) for n, h in self._gang_hold.items()}

    def pop(self, timeout: Optional[float] = None) -> Optional[api.Pod]:
        """Blocking pop of the highest-priority (FIFO within class) pod;
        None on close/timeout.  Waits are clipped to the nearest gang
        hold deadline so a blocked popper (even ``timeout=None``) wakes
        to flush an overdue gang — an incomplete-gang hold must expire
        by wall clock, not only when another add happens to notify."""
        end = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while True:
                self._flush_overdue_gangs()
                while self._heap:
                    _, _, key = heapq.heappop(self._heap)
                    pod = self._items.pop(key, None)
                    if pod is not None:
                        return pod
                if self._closed:
                    return None
                remaining = None if end is None \
                    else end - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return None
                wait_t = remaining
                if self._gang_deadline:
                    until_flush = max(
                        min(self._gang_deadline.values())
                        - time.monotonic(), 0.01)
                    wait_t = until_flush if wait_t is None \
                        else min(wait_t, until_flush)
                self._lock.wait(timeout=wait_t)

    def pop_all(self, wait_first: bool = True,
                timeout: Optional[float] = None) -> list[api.Pod]:
        """Drain the whole pending queue (blocks for the first item when
        ``wait_first``).  The batched scheduling entry point; held gangs
        stay held until complete (or overdue)."""
        first = self.pop(timeout=timeout) if wait_first else None
        out = [first] if first is not None else []
        with self._lock:
            self._flush_overdue_gangs()
            while self._heap:
                _, _, key = heapq.heappop(self._heap)
                pod = self._items.pop(key, None)
                if pod is not None:
                    out.append(pod)
        return out

    def pop_some(self, limit: int, wait_first: bool = True,
                 timeout: Optional[float] = None) -> list[api.Pod]:
        """Drain at most ``limit`` pods (highest priority first) — the
        degraded drain's entry point: each iteration solves one bounded,
        pre-warmed bucket instead of materializing the whole storm as a
        single batch, so per-drain memory stays O(limit) regardless of
        backlog depth."""
        if limit <= 0:
            return self.pop_all(wait_first=wait_first, timeout=timeout)
        first = self.pop(timeout=timeout) if wait_first else None
        out = [first] if first is not None else []
        with self._lock:
            self._flush_overdue_gangs()
            while self._heap and len(out) < limit:
                _, _, key = heapq.heappop(self._heap)
                pod = self._items.pop(key, None)
                if pod is not None:
                    out.append(pod)
        return out
