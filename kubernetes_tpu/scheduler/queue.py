"""Pod queue: the scheduler's FIFO (pkg/client/cache/fifo.go).

Same contract the reference's scheduler relies on: items keyed by pod key;
Add/Update replace in place without changing queue position; Delete removes;
Pop blocks until an item is available and returns the OLDEST item; re-adding
a popped key re-queues it at the back.  ``pop_all`` drains everything at
once — the batched entry point the TPU solver feeds on.
"""

from __future__ import annotations

import collections
import threading
from typing import Callable, Optional

from kubernetes_tpu.api import types as api


class FIFO:
    def __init__(self) -> None:
        self._lock = threading.Condition()
        self._items: dict[str, api.Pod] = {}
        self._queue: collections.deque[str] = collections.deque()
        self._closed = False

    def add(self, pod: api.Pod) -> None:
        with self._lock:
            key = pod.key
            if key not in self._items:
                self._queue.append(key)
            self._items[key] = pod
            self._lock.notify()

    def update(self, pod: api.Pod) -> None:
        self.add(pod)

    def delete(self, pod_key: str) -> None:
        with self._lock:
            self._items.pop(pod_key, None)
            # Lazy removal: stale keys are skipped at pop time.

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._lock.notify_all()

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)

    def pop(self, timeout: Optional[float] = None) -> Optional[api.Pod]:
        """Blocking pop of the oldest pod; None on close/timeout."""
        with self._lock:
            while True:
                while self._queue:
                    key = self._queue.popleft()
                    pod = self._items.pop(key, None)
                    if pod is not None:
                        return pod
                if self._closed:
                    return None
                if not self._lock.wait(timeout=timeout):
                    return None

    def pop_all(self, wait_first: bool = True,
                timeout: Optional[float] = None) -> list[api.Pod]:
        """Drain the whole pending queue (blocks for the first item when
        ``wait_first``).  The batched scheduling entry point."""
        first = self.pop(timeout=timeout) if wait_first else None
        out = [first] if first is not None else []
        with self._lock:
            while self._queue:
                key = self._queue.popleft()
                pod = self._items.pop(key, None)
                if pod is not None:
                    out.append(pod)
        return out
