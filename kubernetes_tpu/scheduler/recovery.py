"""Crash-safe restart reconciliation.

A scheduler killed mid-drain (SIGKILL between solve and bind, a node
dying under the daemon) leaves three classes of orphan behind:

* pods the dead incarnation ASSUMED but whose binds never reached the
  apiserver — unbound at relist; they must requeue, not strand Pending;
* pods whose binds DID land but whose watch confirmations the dead
  incarnation never processed — bound at relist; they must be re-adopted
  into the cache as confirmed capacity, not double-scheduled;
* cache entries with no apiserver record at all (the pod was deleted
  while the scheduler was down) — stale assumes that must expire.

The reflectors converge on all of this EVENTUALLY (relist Replace
semantics); this module turns "eventually" into a verified startup step:
one list against the apiserver, cross-checked against the cache and the
queue, every discrepancy repaired and counted
(``scheduler_restart_reconcile_total{action=}``), and the device-resident
tensors re-seeded from the rebuilt cache (epoch bump → full re-upload)
before the drain loop resumes.  Safety against the in-flight window the
kill abandoned rests on the apiserver's bind CAS: a zombie bind from the
dead incarnation either landed before the list (the pod shows bound and
is adopted) or lands after and loses the CAS to nothing — the pod is on
the queue, gets re-solved, and the zombie's 409 is absorbed by the
normal forget+requeue path.  A pod can therefore never double-bind or
strand across a restart.

``ConfigFactory.run()`` calls :func:`reconcile` after the reflectors
sync and before the drain loop starts (``KT_RECOVERY=0`` opts out).
"""

from __future__ import annotations

import json
import time
from typing import Callable, Optional

from kubernetes_tpu.api import types as api
from kubernetes_tpu.utils import metrics
from kubernetes_tpu.utils.logging import get_logger

log = get_logger("recovery")


def _migration_intent(obj: dict) -> Optional[dict]:
    """The defrag migration-intent annotation (scheduler/defrag.py) on a
    pod dict, parsed, or None.  An unparseable value still counts as an
    intent (it must be cleared) but carries no source node."""
    raw = ((obj.get("metadata") or {}).get("annotations") or {}).get(
        api.DEFRAG_MIGRATION_ANNOTATION_KEY)
    if raw is None:
        return None
    try:
        parsed = json.loads(raw)
        return parsed if isinstance(parsed, dict) else {}
    except ValueError:
        return {}


def _clear_migration_intent(store, obj: dict) -> bool:
    """Drop the intent annotation under CAS.  A lost CAS is fine — the
    live defragmenter's settle pass (or the next restart) retires it."""
    from kubernetes_tpu.client import cas_update
    meta = obj.setdefault("metadata", {})
    ann = dict(meta.get("annotations") or {})
    if ann.pop(api.DEFRAG_MIGRATION_ANNOTATION_KEY, None) is None:
        return False
    meta["annotations"] = ann
    try:
        cas_update(store, "pods", obj)
    except Exception:  # noqa: BLE001 — CAS race: someone else owns it now
        return False
    return True


def reconcile(daemon, store, scheduler_name: Optional[str] = None) -> dict:
    """Reconcile the daemon's cache and queue against one apiserver
    relist; returns the action report (also stored by the factory as
    ``last_recovery`` and served on ``/debug/vars``).

    ``daemon`` is the scheduler (queue + algorithm.cache + resident
    mirror); ``store`` anything with ``list(kind)`` — a MemStore or an
    APIClient.  ``scheduler_name`` filters requeues to pods this daemon
    is responsible for (multi-scheduler dispatch)."""
    t0 = time.perf_counter()
    cache = daemon.config.algorithm.cache
    items, _rv = store.list("pods")
    report = {"readopted": 0, "requeued": 0, "expired": 0, "removed": 0,
              "confirmed": 0, "pods_listed": len(items),
              "migrations_recovered": 0, "migration_intents_cleared": 0}
    seen: set[str] = set()
    for obj in items:
        key = api.key_from_json(obj)
        seen.add(key)
        if api.is_terminated_json(obj):
            continue
        node = (obj.get("spec") or {}).get("nodeName") or ""
        intent = _migration_intent(obj)
        if node:
            if intent is not None:
                # A SIGKILL landed between the defragmenter's intent
                # stamp and its evict (or after the pod already rebound):
                # the pod is bound, so the stale intent just clears.
                if _clear_migration_intent(store, obj):
                    report["migration_intents_cleared"] += 1
                    metrics.DEFRAG_RECOVERED.labels(
                        action="cleared").inc()
            # Bound at the apiserver.  An assumed entry agreeing on the
            # node just flips to confirmed; anything else (unknown pod,
            # or one tracked on a DIFFERENT node) re-adopts through the
            # full add path — add_pod replaces the stale attachment, so
            # the capacity stops being charged to the wrong node.
            if cache.confirm_assumed(key, node):
                report["confirmed"] += 1
            else:
                tracked = cache.get_pod(key)
                if tracked is None or tracked.node_name != node:
                    cache.add_pod(api.pod_from_json(obj))
                    report["readopted"] += 1
            daemon.queue.delete(key)
        else:
            # Unbound: the dead incarnation may have assumed it (bind
            # never landed) — forget the stale assume and requeue.
            if cache.is_assumed(key):
                pod = cache.get_pod(key)
                if pod is not None:
                    cache.forget_pod(pod)
                    pod.node_name = ""
                report["expired"] += 1
            if key not in daemon.queue:
                pod = api.pod_from_json(obj)
                if scheduler_name is None or \
                        pod.scheduler_name == scheduler_name:
                    daemon.enqueue(pod)
                    if key in daemon.queue:
                        report["requeued"] += 1
            if intent is not None:
                # A SIGKILL landed between the defragmenter's evict and
                # the pod's re-bind: the migrant is pending and (by the
                # requeue above, or the reflector sync before this pass)
                # back on the queue — requeued, not stranded.  Clear the
                # intent so nothing mistakes it for an in-flight move.
                if _clear_migration_intent(store, obj):
                    report["migrations_recovered"] += 1
                    metrics.DEFRAG_RECOVERED.labels(
                        action="requeued").inc()
                    fr = daemon.config.flight_recorder
                    if fr is not None:
                        fr.record_defrag(key, "crash-recovered",
                                         from_node=str(
                                             intent.get("from", "")))
    # Cache entries with no apiserver record: ghosts from the previous
    # incarnation (pod deleted while the scheduler was down).
    for key, _node, assumed in cache.tracked_pods():
        if key in seen:
            continue
        pod = cache.get_pod(key)
        if pod is not None:
            cache.remove_pod(pod)
            report["expired" if assumed else "removed"] += 1
    # Re-seed the device-resident tensors from the reconciled cache: the
    # epoch bump forces the next drain's sync to upload everything, so
    # no pre-crash device state survives into post-restart decisions.
    cache.force_resnapshot()
    daemon.config.algorithm.resident.invalidate()
    for action in ("readopted", "requeued", "expired", "removed",
                   "confirmed"):
        if report[action]:
            metrics.RESTART_RECONCILE.labels(action=action).inc(
                report[action])
    report["duration_s"] = round(time.perf_counter() - t0, 4)
    if any(report[a] for a in ("readopted", "requeued", "expired",
                               "removed")):
        log.info("restart reconciliation repaired state: %s", report)
    return report


def reconcile_shard(daemon, store, shard: int, owns,
                    scheduler_name: Optional[str] = None,
                    min_assume_age_s: float = 0.0,
                    assumed_before: Optional[float] = None,
                    now: Callable[[], float] = time.monotonic) -> dict:
    """Shard-takeover reconciliation (active-active HA,
    scheduler/shards.py): the survivor that just won shard ``shard``'s
    orphaned lease re-derives that shard's backlog from one apiserver
    relist BEFORE draining it.

    The dead incarnation's in-flight window decomposes exactly like a
    restart, restricted to the shard:

    * pods it ASSUMED whose binds never landed are unbound at the
      relist — they belong on OUR queue now (the dead daemon's assume
      lived only in its process memory, so there is nothing to forget
      here; our own stale assumes from a previous ownership spell ARE
      forgotten);
    * pods whose binds DID land show bound — our cache either confirmed
      them from the watch already or adopts them here;
    * a ZOMBIE bind still in the dead daemon's pipe either landed
      before the list (adopted above) or lands after and meets the
      apiserver's nodeName CAS: if we re-bound the pod first the zombie
      409s into nothing; if the zombie wins first, OUR bind 409s and
      the ordinary forget+requeue path absorbs it.  Either way the pod
      binds exactly once — the safety argument is the CAS, the lease
      only minimizes how often it is needed.

    ``owns(namespace) -> bool`` is the membership test for the pods
    this takeover covers — the factory passes the single-shard test
    ``shard_of(ns) == shard`` so a takeover never re-walks shards
    already held.  Returns the action report.

    Only the PENDING set is listed (``spec.nodeName=`` server-side,
    where the store supports field selectors): bound pods are already
    live-synced into every incarnation's cache by its assigned-pod
    reflector, so re-walking them here would make each takeover an
    O(all-pods) JSON parse — measured in the HA soak, exactly the load
    spike that starved the renew loop into a handoff death spiral.

    ``assumed_before`` / ``min_assume_age_s`` distinguish the two
    callers' stale-assume tests.  A TAKEOVER passes ``assumed_before``
    = the shard's lease-acquisition timestamp (``time.monotonic``
    base): an assume MINTED BEFORE we won the lease is a leftover of an
    earlier ownership spell (losing the shard forgot our assumes, so
    anything older than the acquisition predates the handoff) and is
    forgotten, while one minted SINCE is our own live in-flight bind —
    the queue gate opens the moment the tick thread flips ownership,
    so the drain loop can legitimately assume pods in the seconds
    before this reconcile runs, and forgetting those would free their
    nodes' capacity while the binds land anyway (transient overcommit
    plus a duplicate 409).  Age alone cannot make that call: a
    pre-handoff leftover can be merely milliseconds older than a
    post-acquisition live assume.  The periodic ownership SWEEP has no
    acquisition edge to anchor on — it runs over shards we are
    steadily draining — so it uses the age threshold instead: a YOUNG
    assume is usually a live in-flight bind, but an OLD one is a leak
    (a bind result lost to chaos) that would otherwise strand its pod
    until the cache TTL; the sweep passes a threshold above any
    healthy bind round-trip (KT_HA_STALE_ASSUME_S, default 3 s),
    forgetting only assumes older than that.  The bind CAS keeps a
    still-racing duplicate safe under either test."""
    t0 = time.perf_counter()
    cache = daemon.config.algorithm.cache
    try:
        items, _rv = store.list("pods", field_selector="spec.nodeName=")
    except TypeError:  # raw MemStore: no field selectors; filter here
        items, _rv = store.list("pods")
    report = {"shard": shard, "readopted": 0, "requeued": 0,
              "expired": 0, "confirmed": 0, "pods_in_shard": 0}
    for obj in items:
        key = api.key_from_json(obj)
        if api.is_terminated_json(obj):
            continue
        if not owns((obj.get("metadata") or {}).get("namespace") or ""):
            continue
        report["pods_in_shard"] += 1
        node = (obj.get("spec") or {}).get("nodeName") or ""
        if node:
            if cache.confirm_assumed(key, node):
                report["confirmed"] += 1
            else:
                tracked = cache.get_pod(key)
                if tracked is None or tracked.node_name != node:
                    cache.add_pod(api.pod_from_json(obj))
                    report["readopted"] += 1
            daemon.queue.delete(key)
        else:
            if cache.is_assumed(key):
                age = cache.assumed_age(key)
                if assumed_before is not None:
                    # ``now`` must share the cutoff's clock base (the
                    # factory passes the shard manager's clock together
                    # with its acquisition stamp; cache ages are
                    # durations, transferable between bases ticking at
                    # wall rate).
                    birth = now() - age if age is not None else None
                    if birth is None or birth >= assumed_before:
                        continue  # minted under OUR ownership: live
                elif min_assume_age_s > 0.0 and \
                        (age is None or age < min_assume_age_s):
                    continue  # live in-flight bind; not ours to undo
                pod = cache.get_pod(key)
                if pod is None:
                    continue  # confirmed/forgotten under us: not ours
                try:
                    cache.forget_pod(pod)
                except ValueError:
                    # A live bind thread confirmed or forgot this
                    # assume between our is_assumed read and here (the
                    # race scheduler._forget_quietly also tolerates) —
                    # the pod is no longer ours to expire, and one
                    # contested pod must not abort the rest of the
                    # pass (nor count as a phantom repair).
                    continue
                pod.node_name = ""
                report["expired"] += 1
            if key not in daemon.queue:
                pod = api.pod_from_json(obj)
                if scheduler_name is None or \
                        pod.scheduler_name == scheduler_name:
                    daemon.enqueue(pod)
                    if key in daemon.queue:
                        report["requeued"] += 1
    for action in ("readopted", "requeued", "expired", "confirmed"):
        if report[action]:
            metrics.RESTART_RECONCILE.labels(action=action).inc(
                report[action])
    report["duration_s"] = round(time.perf_counter() - t0, 4)
    if report["requeued"] or report["expired"] or report["readopted"]:
        log.info("shard %d takeover reconciled: %s", shard, report)
    return report
