"""Crash-safe restart reconciliation.

A scheduler killed mid-drain (SIGKILL between solve and bind, a node
dying under the daemon) leaves three classes of orphan behind:

* pods the dead incarnation ASSUMED but whose binds never reached the
  apiserver — unbound at relist; they must requeue, not strand Pending;
* pods whose binds DID land but whose watch confirmations the dead
  incarnation never processed — bound at relist; they must be re-adopted
  into the cache as confirmed capacity, not double-scheduled;
* cache entries with no apiserver record at all (the pod was deleted
  while the scheduler was down) — stale assumes that must expire.

The reflectors converge on all of this EVENTUALLY (relist Replace
semantics); this module turns "eventually" into a verified startup step:
one list against the apiserver, cross-checked against the cache and the
queue, every discrepancy repaired and counted
(``scheduler_restart_reconcile_total{action=}``), and the device-resident
tensors re-seeded from the rebuilt cache (epoch bump → full re-upload)
before the drain loop resumes.  Safety against the in-flight window the
kill abandoned rests on the apiserver's bind CAS: a zombie bind from the
dead incarnation either landed before the list (the pod shows bound and
is adopted) or lands after and loses the CAS to nothing — the pod is on
the queue, gets re-solved, and the zombie's 409 is absorbed by the
normal forget+requeue path.  A pod can therefore never double-bind or
strand across a restart.

``ConfigFactory.run()`` calls :func:`reconcile` after the reflectors
sync and before the drain loop starts (``KT_RECOVERY=0`` opts out).
"""

from __future__ import annotations

import time
from typing import Optional

from kubernetes_tpu.api import types as api
from kubernetes_tpu.utils import metrics
from kubernetes_tpu.utils.logging import get_logger

log = get_logger("recovery")


def reconcile(daemon, store, scheduler_name: Optional[str] = None) -> dict:
    """Reconcile the daemon's cache and queue against one apiserver
    relist; returns the action report (also stored by the factory as
    ``last_recovery`` and served on ``/debug/vars``).

    ``daemon`` is the scheduler (queue + algorithm.cache + resident
    mirror); ``store`` anything with ``list(kind)`` — a MemStore or an
    APIClient.  ``scheduler_name`` filters requeues to pods this daemon
    is responsible for (multi-scheduler dispatch)."""
    t0 = time.perf_counter()
    cache = daemon.config.algorithm.cache
    items, _rv = store.list("pods")
    report = {"readopted": 0, "requeued": 0, "expired": 0, "removed": 0,
              "confirmed": 0, "pods_listed": len(items)}
    seen: set[str] = set()
    for obj in items:
        key = api.key_from_json(obj)
        seen.add(key)
        if api.is_terminated_json(obj):
            continue
        node = (obj.get("spec") or {}).get("nodeName") or ""
        if node:
            # Bound at the apiserver.  An assumed entry agreeing on the
            # node just flips to confirmed; anything else (unknown pod,
            # or one tracked on a DIFFERENT node) re-adopts through the
            # full add path — add_pod replaces the stale attachment, so
            # the capacity stops being charged to the wrong node.
            if cache.confirm_assumed(key, node):
                report["confirmed"] += 1
            else:
                tracked = cache.get_pod(key)
                if tracked is None or tracked.node_name != node:
                    cache.add_pod(api.pod_from_json(obj))
                    report["readopted"] += 1
            daemon.queue.delete(key)
        else:
            # Unbound: the dead incarnation may have assumed it (bind
            # never landed) — forget the stale assume and requeue.
            if cache.is_assumed(key):
                pod = cache.get_pod(key)
                if pod is not None:
                    cache.forget_pod(pod)
                    pod.node_name = ""
                report["expired"] += 1
            if key not in daemon.queue:
                pod = api.pod_from_json(obj)
                if scheduler_name is None or \
                        pod.scheduler_name == scheduler_name:
                    daemon.enqueue(pod)
                    if key in daemon.queue:
                        report["requeued"] += 1
    # Cache entries with no apiserver record: ghosts from the previous
    # incarnation (pod deleted while the scheduler was down).
    for key, _node, assumed in cache.tracked_pods():
        if key in seen:
            continue
        pod = cache.get_pod(key)
        if pod is not None:
            cache.remove_pod(pod)
            report["expired" if assumed else "removed"] += 1
    # Re-seed the device-resident tensors from the reconciled cache: the
    # epoch bump forces the next drain's sync to upload everything, so
    # no pre-crash device state survives into post-restart decisions.
    cache.force_resnapshot()
    daemon.config.algorithm.resident.invalidate()
    for action in ("readopted", "requeued", "expired", "removed",
                   "confirmed"):
        if report[action]:
            metrics.RESTART_RECONCILE.labels(action=action).inc(
                report[action])
    report["duration_s"] = round(time.perf_counter() - t0, 4)
    if any(report[a] for a in ("readopted", "requeued", "expired",
                               "removed")):
        log.info("restart reconciliation repaired state: %s", report)
    return report
