"""SLO burn-rate monitor: multi-window error-budget burn for the
per-decision latency SLO.

PR 8 gave every pod a measured submit->bind latency
(``scheduler_e2e_decision_latency_microseconds``) and the serving bench
an attainment number — but attainment is a POST-HOc verdict.  What an
operator pages on is the BURN RATE: how fast the error budget is being
consumed right now, over more than one window (the SRE-workbook
multi-window multi-burn-rate shape: a short window catches a fast burn,
a long one a slow bleed; alerting on both windows firing suppresses
blips).  This module computes exactly that from the decision-latency
histogram the commit path already records:

* The SLO is declared as ``KT_SLO_MS`` (default 1000 ms) at
  ``KT_SLO_OBJECTIVE`` (default 99.0 % of decisions inside it) — the
  serving bench's trickle SLO, now a live daemon signal.
* ``tick()`` snapshots (total, good) from the histogram's buckets (good
  = observations at or under the largest bucket bound <= the SLO — the
  conservative read) into a bounded ring; burn over a window is
  ``error_rate / error_budget`` computed from the deltas between the
  newest sample and the oldest one inside the window.  Burn 1.0 means
  "exactly exhausting the budget"; > 1 is an alerting burn.
* Gauges: ``scheduler_slo_burn_rate{window="5m"|"1h"}`` and
  ``scheduler_slo_budget_remaining`` (fraction of the 1h window's
  budget left).  ``report()`` feeds ``/debug/vars`` and the telemetry
  dashboard's burn sparkline.

The monitor is clock-injected (window math is unit-tested with a fake
clock) and runs as one daemon thread started by ``ConfigFactory.run``
(``KT_SLO_PERIOD`` seconds per tick, default 5; 0 disables).
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_right
from typing import Callable, Optional

from kubernetes_tpu.utils import knobs, locktrace, metrics, threadreg
from kubernetes_tpu.utils.logging import get_logger

log = get_logger("slo")

DEFAULT_SLO_MS = 1000.0
DEFAULT_OBJECTIVE_PCT = 99.0
# (label, seconds): the 5m window catches a fast burn, the 1h window a
# slow bleed — the standard multi-window pair scaled to a scheduler's
# decision volume.
WINDOWS = (("5m", 300.0), ("1h", 3600.0))


class SLOMonitor:
    """Error-budget burn over trailing windows of the decision-latency
    histogram."""

    def __init__(self,
                 histogram: Optional[metrics.Histogram] = None,
                 slo_ms: Optional[float] = None,
                 objective_pct: Optional[float] = None,
                 windows=WINDOWS,
                 clock: Callable[[], float] = time.monotonic):
        self.histogram = histogram if histogram is not None \
            else metrics.E2E_DECISION_LATENCY
        self.slo_ms = slo_ms if slo_ms is not None \
            else knobs.get_float("KT_SLO_MS")
        self.objective_pct = objective_pct if objective_pct is not None \
            else knobs.get_float("KT_SLO_OBJECTIVE")
        self.budget = max(1.0 - self.objective_pct / 100.0, 1e-9)
        self.windows = tuple(windows)
        self.clock = clock
        self._lock = locktrace.make_lock("scheduler.SLOMonitor")
        # (t, total, good) samples, oldest first, bounded to the longest
        # window (plus one sample of slack for the delta at the edge).
        self._samples: list[tuple[float, int, int]] = []
        self._longest = max(w for _, w in self.windows)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.last_burn: dict[str, float] = {}
        # Per-tenant burn (multi-tenant solver service): sample rings
        # per tenant child of the {tenant=}-labeled decision histogram,
        # burned over the SHORT window only (the paging signal; the
        # global gauge keeps both windows).  Zero cost until a tenant
        # child exists — i.e. until tenancy actually observes.
        self._tenant_samples: dict[str, list] = {}
        self.last_tenant_burn: dict[str, float] = {}

    # -- histogram reading ------------------------------------------------

    def _counts(self) -> tuple[int, int]:
        """(total, good) observation counts so far.  ``good`` is the
        cumulative count at the largest bucket bound <= the SLO — the
        conservative (under-)estimate the exponential ladder allows."""
        uppers, counts, total, _ = self.histogram.bucket_counts()
        slo_us = self.slo_ms * 1e3
        k = bisect_right(uppers, slo_us)
        return total, sum(counts[:k])

    # -- the tick ---------------------------------------------------------

    def tick(self, now: Optional[float] = None) -> dict[str, float]:
        """Take one sample, recompute every window's burn, drive the
        gauges.  Returns {window_label: burn_rate}."""
        now = self.clock() if now is None else now
        total, good = self._counts()
        with self._lock:
            self._samples.append((now, total, good))
            # Bound the ring: drop samples older than the longest
            # window, keeping ONE older sample as the delta base so a
            # window that spans the whole ring still has an edge.
            cutoff = now - self._longest
            keep = 0
            while keep + 1 < len(self._samples) and \
                    self._samples[keep + 1][0] <= cutoff:
                keep += 1
            del self._samples[:keep]
            samples = list(self._samples)
        burns: dict[str, float] = {}
        for label, span in self.windows:
            burns[label] = self._burn(samples, now - span, total, good)
        for label, burn in burns.items():
            metrics.SLO_BURN_RATE.labels(window=label).set(burn)
        longest_label = max(self.windows, key=lambda w: w[1])[0]
        remaining = max(0.0, 1.0 - burns.get(longest_label, 0.0))
        metrics.SLO_BUDGET_REMAINING.set(remaining)
        self.last_burn = burns
        self._tick_tenants(now)
        return burns

    def _tick_tenants(self, now: float) -> None:
        """Per-tenant burn over the shortest window, one sample ring per
        tenant child of the labeled decision histogram."""
        children = metrics.TENANT_DECISION_LATENCY.children()
        if not children:
            return
        span = min(w for _, w in self.windows)
        slo_us = self.slo_ms * 1e3
        tenant_burns: dict[str, float] = {}
        with self._lock:
            for key, child in children.items():
                tenant = key[0]
                uppers, counts, total, _ = child.bucket_counts()
                k = bisect_right(uppers, slo_us)
                good = sum(counts[:k])
                ring = self._tenant_samples.setdefault(tenant, [])
                ring.append((now, total, good))
                cutoff = now - self._longest
                keep = 0
                while keep + 1 < len(ring) and \
                        ring[keep + 1][0] <= cutoff:
                    keep += 1
                del ring[:keep]
                tenant_burns[tenant] = self._burn(
                    list(ring), now - span, total, good)
        for tenant, burn in tenant_burns.items():
            metrics.TENANT_SLO_BURN.labels(tenant=tenant).set(burn)
        self.last_tenant_burn = tenant_burns

    @staticmethod
    def _base(samples: list, t0: float) -> tuple[int, int]:
        """The (total, good) base for a window starting at ``t0``: the
        newest sample at or before t0 (the monitor was already running),
        else zeros (the window predates the monitor)."""
        base = (0, 0)
        for t, total, good in samples:
            if t <= t0:
                base = (total, good)
            else:
                break
        return base

    def _burn(self, samples: list, t0: float,
              total: int, good: int) -> float:
        base_total, base_good = self._base(samples, t0)
        d_total = total - base_total
        if d_total <= 0:
            return 0.0
        d_bad = d_total - (good - base_good)
        return (d_bad / d_total) / self.budget

    # -- reporting / lifecycle -------------------------------------------

    def report(self) -> dict:
        """The /debug/vars payload."""
        total, good = self._counts()
        out = {"sloMs": self.slo_ms,
               "objectivePct": self.objective_pct,
               "decisionsTotal": total,
               "decisionsOverSlo": total - good,
               "burnRate": {k: round(v, 4)
                            for k, v in self.last_burn.items()},
               "budgetRemaining": round(
                   float(metrics.SLO_BUDGET_REMAINING.value), 4)}
        if self.last_tenant_burn:
            out["tenantBurnRate"] = {t: round(v, 4) for t, v in
                                     self.last_tenant_burn.items()}
        return out

    def run(self, period: float = 5.0) -> threading.Thread:
        def loop():
            while not self._stop.wait(period):
                try:
                    self.tick()
                except Exception:  # noqa: BLE001 — monitor must survive
                    log.exception("slo tick crashed; continuing")
        self._thread = threadreg.spawn(loop, name="slo-burn-monitor")
        return self._thread

    def stop(self) -> None:
        self._stop.set()
