"""Binding: committing a scheduling decision.

The reference commits by POSTing a Binding to the apiserver, which CAS-sets
``spec.nodeName`` only while it is empty (BindingREST.Create -> assignPod ->
setPodHostAndAnnotations, pkg/registry/pod/etcd/etcd.go:286-330) — the
atomic conflict detector for optimistic concurrency.

``Binder`` is the protocol; ``InMemoryBinder`` reproduces the CAS semantics
for the integration/perf rigs (the in-process-apiserver analogue), and
``HTTPBinder`` speaks to a real apiserver.
"""

from __future__ import annotations

import json
import threading
import urllib.request
from typing import Optional, Protocol

from kubernetes_tpu.api import types as api


class BindConflict(Exception):
    """spec.nodeName was already set (the CAS failed)."""


class Binder(Protocol):
    def bind(self, pod: api.Pod, node_name: str) -> None: ...


class InMemoryBinder:
    """CAS-binding against an in-memory pod table (etcd.go:299-330)."""

    def __init__(self) -> None:
        self._bound: dict[str, str] = {}
        self._lock = threading.Lock()

    def bind(self, pod: api.Pod, node_name: str) -> None:
        with self._lock:
            current = self._bound.get(pod.key, "")
            if current:
                raise BindConflict(
                    f"pod {pod.key} is already assigned to node {current}")
            self._bound[pod.key] = node_name

    def bind_many(self, bindings: list[tuple[api.Pod, str]]
                  ) -> list[tuple[api.Pod, str]]:
        """Per-pod CAS under one lock acquisition.  Returns the failures as
        (pod, error) — the bind_many contract every binder shares (the
        daemon surfaces the error text in the FailedScheduling event)."""
        conflicts = []
        with self._lock:
            bound = self._bound
            for pod, node_name in bindings:
                current = bound.get(pod.key, "")
                if current:
                    conflicts.append((pod, BindConflict(
                        f"pod {pod.key} is already assigned to node "
                        f"{current}")))
                else:
                    bound[pod.key] = node_name
        return conflicts

    def bound_node(self, pod_key: str) -> Optional[str]:
        with self._lock:
            return self._bound.get(pod_key)

    def unbind(self, pod_key: str) -> None:
        with self._lock:
            self._bound.pop(pod_key, None)

    def count(self) -> int:
        with self._lock:
            return len(self._bound)


class HTTPBinder:
    """POST /api/v1/namespaces/<ns>/bindings (factory.go:576-587)."""

    def __init__(self, api_base: str, timeout: float = 10.0):
        self.api_base = api_base.rstrip("/")
        self.timeout = timeout

    def bind(self, pod: api.Pod, node_name: str) -> None:
        body = json.dumps({
            "apiVersion": "v1", "kind": "Binding",
            "metadata": {"name": pod.name, "namespace": pod.namespace},
            "target": {"apiVersion": "v1", "kind": "Node",
                       "name": node_name},
        }).encode()
        req = urllib.request.Request(
            f"{self.api_base}/api/v1/namespaces/{pod.namespace}/bindings",
            data=body, headers={"Content-Type": "application/json"},
            method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            if resp.status >= 300:
                raise BindConflict(f"bind failed: HTTP {resp.status}")
