"""Binding: committing a scheduling decision.

The reference commits by POSTing a Binding to the apiserver, which CAS-sets
``spec.nodeName`` only while it is empty (BindingREST.Create -> assignPod ->
setPodHostAndAnnotations, pkg/registry/pod/etcd/etcd.go:286-330) — the
atomic conflict detector for optimistic concurrency.

``Binder`` is the protocol; ``InMemoryBinder`` reproduces the CAS semantics
for the integration/perf rigs (the in-process-apiserver analogue),
``HTTPBinder`` speaks one Binding POST at a time to a real apiserver, and
``APIClientBinder`` is the daemon's wire binder: whole solved chunks ride
the batch bindings subresource through ``APIClient.bind_list``, which
pipelines the chunk POSTs over persistent connections (client/http.py) —
the bind side of the overlapped solve/bind pipeline.
"""

from __future__ import annotations

import json
import threading
import urllib.request
from typing import Optional, Protocol

from kubernetes_tpu.api import types as api


class BindConflict(Exception):
    """spec.nodeName was already set (the CAS failed)."""


class Binder(Protocol):
    def bind(self, pod: api.Pod, node_name: str) -> None: ...


class InMemoryBinder:
    """CAS-binding against an in-memory pod table (etcd.go:299-330)."""

    def __init__(self) -> None:
        self._bound: dict[str, str] = {}
        self._lock = threading.Lock()

    def bind(self, pod: api.Pod, node_name: str) -> None:
        with self._lock:
            current = self._bound.get(pod.key, "")
            if current:
                raise BindConflict(
                    f"pod {pod.key} is already assigned to node {current}")
            self._bound[pod.key] = node_name

    def bind_many(self, bindings: list[tuple[api.Pod, str]]
                  ) -> list[tuple[api.Pod, str]]:
        """Per-pod CAS under one lock acquisition.  Returns the failures as
        (pod, error) — the bind_many contract every binder shares (the
        daemon surfaces the error text in the FailedScheduling event)."""
        conflicts = []
        with self._lock:
            bound = self._bound
            for pod, node_name in bindings:
                current = bound.get(pod.key, "")
                if current:
                    conflicts.append((pod, BindConflict(
                        f"pod {pod.key} is already assigned to node "
                        f"{current}")))
                else:
                    bound[pod.key] = node_name
        return conflicts

    def bound_node(self, pod_key: str) -> Optional[str]:
        with self._lock:
            return self._bound.get(pod_key)

    def unbind(self, pod_key: str) -> None:
        with self._lock:
            self._bound.pop(pod_key, None)

    def evict(self, pod: api.Pod) -> None:
        """Preemption eviction (the daemon's evict->assume->bind path,
        workloads/preemption.py): the victim's binding is released so the
        preemptor's CAS bind can land."""
        self.unbind(pod.key)

    def count(self) -> int:
        with self._lock:
            return len(self._bound)


class HTTPBinder:
    """POST /api/v1/namespaces/<ns>/bindings (factory.go:576-587)."""

    def __init__(self, api_base: str, timeout: float = 10.0):
        self.api_base = api_base.rstrip("/")
        self.timeout = timeout

    def bind(self, pod: api.Pod, node_name: str) -> None:
        body = json.dumps({
            "apiVersion": "v1", "kind": "Binding",
            "metadata": {"name": pod.name, "namespace": pod.namespace},
            "target": {"apiVersion": "v1", "kind": "Node",
                       "name": node_name},
        }).encode()
        req = urllib.request.Request(
            f"{self.api_base}/api/v1/namespaces/{pod.namespace}/bindings",
            data=body, headers={"Content-Type": "application/json"},
            method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            if resp.status >= 300:
                raise BindConflict(f"bind failed: HTTP {resp.status}")


class APIClientBinder:
    """Binder over the wire (factory.go:576-587 POST bindings).

    The batched path rides the batch-bind subresource: the engine decides
    in multi-thousand-pod chunks, so each chunk becomes a handful of
    pipelined bulk requests whose per-pod CAS results map back to
    (pod, err) failures — measured at density rates, per-pod POSTs
    through 16 threads were the wire bottleneck (98 % of engine
    throughput died at the process boundary).  Request chunking and the
    persistent-connection pipelining live in ``APIClient.bind_list``; a
    transport failure falls back to per-pod binds through a persistent
    thread pool so partial progress survives a flaky connection."""

    _POOL = 16  # fallback path concurrency (one goroutine per bind)

    def __init__(self, client):
        self.client = client
        self._pool = None

    def bind(self, pod: api.Pod, node_name: str) -> None:
        self.client.bind(pod.namespace, pod.name, node_name)

    def evict(self, pod: api.Pod) -> None:
        """Preemption eviction over the wire: DELETE the victim pod (the
        reference's preemption deletes victims through the apiserver; the
        watch then confirms the removal cluster-wide)."""
        self.client.delete("pods", pod.key)

    def unbind(self, pod: api.Pod) -> None:
        """Defrag eviction-to-pending (scheduler/defrag.py): clear
        spec.nodeName under CAS so the pod re-enters the pending set
        and the unassigned reflector requeues it — a migration, unlike
        a preemption, must keep the pod alive.  The PUT applies the
        body's resourceVersion as its precondition; a racing writer
        surfaces as the conflict the defragmenter skips on."""
        obj = self.client.get("pods", pod.key)
        if obj is None:
            raise KeyError(f"pods {pod.key} not found")
        obj.setdefault("spec", {})["nodeName"] = ""
        self.client.update("pods", obj)

    def _bind_one(self, item):
        pod, dest = item
        try:
            self.bind(pod, dest)
            return None
        except Exception as err:  # noqa: BLE001 — caller requeues
            return (pod, err)

    def bind_many(self, placed: list) -> list:
        """Bind a batch; returns [(pod, err)] failures (the CAS conflicts
        the batched drain forgets + requeues)."""
        from kubernetes_tpu.apiserver.memstore import ConflictError
        from kubernetes_tpu.client.http import APIError
        from kubernetes_tpu.utils.featuregate import DEFAULT_FEATURE_GATE
        if not DEFAULT_FEATURE_GATE.enabled("BatchBindings"):
            # Gated off: the reference's per-bind-goroutine wire behavior.
            return self._bind_many_fallback(placed)
        if len(placed) <= 2:
            return [f for f in map(self._bind_one, placed) if f is not None]
        try:
            errors = self.client.bind_list(
                [(pod.namespace, pod.name, dest) for pod, dest in placed])
        except Exception:  # noqa: BLE001 — transport hiccup
            return self._bind_many_fallback(placed)
        if len(errors) != len(placed):
            return self._bind_many_fallback(placed)
        # Preserve the per-item status: only a 409 is a CAS conflict;
        # wrapping a 404 (pod deleted mid-bind) as ConflictError would
        # invert the conflict/failure metric split downstream.  One 409
        # inside a pipelined chunk therefore requeues only that pod.
        # Code 0 marks a chunk whose request never completed (transport
        # fault mid-pipeline): re-bind ONLY those pods per-pod — the CAS
        # makes the retry idempotent — leaving the other chunks' results
        # untouched.
        failures = []
        retry = []
        for (pod, dest), res in zip(placed, errors):
            if res is None:
                continue
            code, err = res
            if code == 0:
                retry.append((pod, dest))
            elif code == 409:
                failures.append((pod, ConflictError(err)))
            else:
                failures.append((pod, APIError(code, err)))
        if retry:
            failures.extend(self._bind_many_fallback(retry))
        return failures

    def _bind_many_fallback(self, placed: list) -> list:
        """Per-pod binds through the persistent pool — each worker keeps
        its thread-local keep-alive connection across batches."""
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor
            self._pool = ThreadPoolExecutor(max_workers=self._POOL,
                                            thread_name_prefix="binder")
        return [f for f in self._pool.map(self._bind_one, placed)
                if f is not None]
