"""The scheduling daemon loop.

The reference's ``Scheduler`` (plugin/pkg/scheduler/scheduler.go:46-154)
runs ``scheduleOne`` forever: blocking pop -> Schedule -> optimistic
AssumePod -> async Bind; on bind failure ForgetPod + error handler with
per-pod backoff requeue (factory.go:512-556).  This daemon keeps that state
machine and adds the TPU-native batched drain: ``schedule_pending`` pops the
whole queue and solves it as ONE device batch, assuming and binding every
placement — same observable behavior, three orders of magnitude fewer
device round-trips.

The batched drain itself — batch formation (deadline micro-batching,
scheduler/batchformer.py), mode routing, the overlapped solve/commit
worker, and crash handling — lives in ``scheduler.pipeline.DrainPipeline``;
this module keeps the commit-side state machine (assume/bind,
preemption, failure requeue, backoff) the pipeline calls back into, plus
the daemon lifecycle (run loops, prewarm, stop/abandon).
"""

from __future__ import annotations

import heapq
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from kubernetes_tpu.api import types as api
from kubernetes_tpu.apiserver.memstore import ConflictError
from kubernetes_tpu.engine.extender_client import ExtenderError
from kubernetes_tpu.engine.generic_scheduler import FitError, GenericScheduler
from kubernetes_tpu.scheduler.backoff import PodBackoff
from kubernetes_tpu.scheduler.batchformer import first_seen
from kubernetes_tpu.scheduler.binder import Binder, BindConflict, InMemoryBinder
from kubernetes_tpu.scheduler.flightrecorder import FlightRecorder
from kubernetes_tpu.scheduler.queue import FIFO
from kubernetes_tpu.utils import knobs, threadreg
from kubernetes_tpu.utils import metrics as metrics_mod
from kubernetes_tpu.utils import trace as trace_mod
from kubernetes_tpu.utils.events import EventRecorder
from kubernetes_tpu.utils.logging import get_logger
from kubernetes_tpu.utils.metrics import SchedulerMetrics
from kubernetes_tpu.utils.trace import stage


def _record_bind_failure(err) -> str:
    """409/CAS conflicts and transport faults are different operator
    stories: count them apart (both forget + requeue with backoff).
    Returns the attempts-counter result label for the failure class."""
    if isinstance(err, (BindConflict, ConflictError)):
        metrics_mod.BIND_CONFLICTS.inc()
        return "bind_conflict"
    metrics_mod.BIND_FAILURES.inc()
    return "bind_error"

log = get_logger("daemon")

DEFAULT_SCHEDULER_NAME = api.DEFAULT_SCHEDULER_NAME


def bucket_ladder(floor: int, stream_threshold: int, pad_limit: int,
                  stream_chunk: int = 0) -> list[int]:
    """The fixed set of chunk sizes a daemon's drains can compile at,
    as a pure function of its configuration — shared by the live
    ``Scheduler.effective_ladder`` and the kt-xray compile-surface
    manifest (analysis/xray.py), so the static proof and the runtime
    warmup can never disagree about the ladder.  Two sources: the
    stream chunk, included only when the chunked path is reachable
    (``stream_threshold`` set — at its unset sentinel every large drain
    takes the one-shot path); and the small-drain buckets: the floor
    itself (possibly non-pow2) plus each pow2 strictly above it up to
    the pow2 ceiling of the largest small drain."""
    ladder: set[int] = set()
    if stream_threshold < (1 << 62):
        ladder.add(stream_chunk or min(stream_threshold, 8192))
    small_top = min(stream_threshold, pad_limit)
    if small_top > 1:
        floor = max(floor, 1)
        # pow2 ceiling of the largest small drain (small_top - 1).
        top_bucket = 1 << max(small_top - 2, 0).bit_length()
        ladder.add(floor)
        # Mintable buckets are max(pow2ceil(len), floor): the floor,
        # then pow2 values strictly above it — doubling the floor
        # itself would trace unreachable shapes when it is not a
        # power of two (floor=300 mints {300, 512, ...}, never 600).
        b = 1 << floor.bit_length()  # smallest pow2 > floor
        while b <= top_bucket:
            ladder.add(b)
            b <<= 1
    return sorted(ladder)


def prewarm_plan(ladder: list[int], scatter_rows: list[int],
                 joint: bool = True, preempt: bool = True,
                 topo: bool = True) -> list[str]:
    """The static trace plan: every program key ``prewarm()`` traces
    for a given ladder, WITHOUT touching a device.  kt-xray's X04 rule
    pins the committed shape manifest's warmed-program set against the
    canonical instantiation of this plan, which makes "no live drain
    compiles after prewarm" a parse-time theorem (the PR 9 recompile
    watchdog stays armed as the runtime backstop).  Program keys match
    ``kubernetes_tpu/analysis/xray.py`` program names."""
    progs = [f"scan_first@{b}" for b in ladder]
    progs += [f"scan_carry@{b}" for b in ladder]
    progs += ["single_evaluate@1", "select_hosts@1"]
    progs += [f"scatter@{r}" for r in scatter_rows]
    if preempt:
        progs.append("victim_solve")
    if topo and ladder:
        progs += ["topo_planes", f"oneshot_topo@{min(ladder)}"]
    if joint and ladder:
        progs.append(f"joint@{min(ladder)}")
    return sorted(progs)


@dataclass
class SchedulerConfig:
    """The reference's scheduler.Config (scheduler.go:46-77)."""

    algorithm: GenericScheduler
    binder: Binder = field(default_factory=InMemoryBinder)
    recorder: EventRecorder = field(default_factory=EventRecorder)
    metrics: SchedulerMetrics = field(default_factory=SchedulerMetrics)
    scheduler_name: str = DEFAULT_SCHEDULER_NAME
    # Pod-condition updater analogue (factory.go:589-600); called with
    # (pod, reason, message) when scheduling fails.
    condition_updater: Optional[Callable[[api.Pod, str, str], None]] = None
    async_bind: bool = True
    # Decision flight recorder (/debug/scheduler/decisions); None disables
    # recording entirely (and the failure-detail device pass with it).
    flight_recorder: Optional[FlightRecorder] = \
        field(default_factory=FlightRecorder)


class Scheduler:
    def __init__(self, config: SchedulerConfig):
        self.config = config
        self.queue = FIFO()
        # Failure-requeue backoff, env-tunable (the reference's
        # --pod-backoff knobs): chaos/soak rigs and latency-sensitive
        # fleets compress it; the defaults are the reference's 1s -> 60s.
        self.backoff = PodBackoff(
            default_duration=knobs.get_float("KT_POD_BACKOFF_S"),
            max_duration=knobs.get_float("KT_POD_BACKOFF_MAX_S"))
        # Stream floor, read ONCE at startup: the pre-warm pass and the
        # small-drain bucket computation must agree on the ladder for the
        # daemon's whole lifetime (a later env change would mint shapes
        # the warmup never traced).
        self.stream_min_bucket = knobs.get_int(
            "KT_STREAM_MIN_BUCKET", default=self.STREAM_MIN_BUCKET)
        # Overlapped solve/bind pipeline: while the device scans chunk N,
        # chunk N-1's readback/assume/bind runs on a dedicated commit
        # worker; at most this many chunks are in flight uncommitted
        # (0 = commit synchronously on the drain thread, the pre-pipeline
        # behavior).
        self.pipeline_window = knobs.get_int("KT_PIPELINE_WINDOW")
        # Workload-subsystem prewarm timings (string-keyed; see
        # _prewarm_workloads) — {} until prewarm() runs.
        self.workloads_prewarm_s: dict = {}
        # Per-ladder-bucket persistent-compile-cache hits/misses observed
        # during prewarm (the warm-start audit) — {} until prewarm() runs.
        self.prewarm_cache_stats: dict = {}
        # Live queue depth at expose time (a set-per-mutation gauge would
        # put two lock acquisitions on every enqueue).
        config.metrics.queue_depth.set_fn(lambda: len(self.queue))
        # Bounded-queue degradation surface (live at expose, same reason;
        # the watermark reads through so rigs that retune it after
        # construction stay honest).
        config.metrics.queue_high_watermark.set_fn(
            lambda: self.queue.high_watermark)
        config.metrics.queue_degraded.set_fn(
            lambda: 1.0 if self.queue.degraded() else 0.0)
        # Failure-detail cooldown: an unschedulable pod requeues every
        # backoff period and must not re-pay the explain device pass each
        # round.
        self._explain_ts: dict[str, float] = {}
        # First-seen registry for the e2e decision-latency SLO, keyed by
        # pod key: watch redeliveries (a condition write, any MODIFIED)
        # arrive as FRESH pod objects, so an object-only stamp would
        # reset the SLO clock on exactly the retried tail pods the
        # histogram exists to measure.  Entries clear at bind ack;
        # leftovers (pods deleted while pending) are pruned when the
        # registry outgrows its bound.
        self._first_seen: dict[str, float] = {}
        # Active-active HA hook (scheduler/shards.py): when set, this
        # incarnation enqueues only pods whose namespace shard it holds
        # — the queue feed, the backoff requeue worker, and the
        # cross-shard 409 counter all consult it.  None = own everything
        # (the single-scheduler default).
        self.owns_pod: Optional[Callable[[api.Pod], bool]] = None
        # Multi-tenant solver service (tenancy/service.py), attached by
        # the factory when KT_TENANTS is set (or by a rig): the drain
        # pipeline then packs cross-tenant batches under weighted
        # fairness, routes per-tenant breakers, and the bind path
        # attributes per-tenant SLO metrics.  None = single-owner
        # engine, byte-for-byte the pre-tenancy behavior.
        self.tenancy_service = None
        self._stop = threading.Event()
        self._bind_threads: list[threading.Thread] = []
        # Single requeue worker over a timer heap (a thread per failed pod
        # would explode on a large unschedulable batch).
        self._requeue_heap: list[tuple[float, int, api.Pod]] = []
        self._requeue_cv = threading.Condition()
        self._requeue_seq = 0
        self._requeue_thread: Optional[threading.Thread] = None
        # THE drain path: every batched drain goes queue -> DrainPipeline
        # (form -> solve -> commit); constructed last so the former can
        # read the daemon's ladder/chunk/cap knobs.
        from kubernetes_tpu.scheduler.pipeline import DrainPipeline
        self.pipeline = DrainPipeline(self)

    @property
    def _commit_pool(self):
        """The overlapped commit worker now lives on the pipeline; kept
        as a read-through so rigs inspecting the daemon keep working."""
        return self.pipeline._commit_pool

    @property
    def accumulate_s(self) -> float:
        """DEPRECATED alias for the batch former's deadline (the old
        arrival-coalescing linger window): reads/writes map onto
        ``pipeline.former.deadline_s`` so pre-serving rig configs keep
        their meaning, but the linger loop itself is gone — the former
        is the only place that decides wait-vs-solve."""
        return self.pipeline.former.deadline_s

    @accumulate_s.setter
    def accumulate_s(self, value: float) -> None:
        self.pipeline.former.deadline_s = max(float(value), 0.0)

    # -- queue feed (the reflector-handler analogue) ---------------------

    def responsible_for(self, pod: api.Pod) -> bool:
        """Multi-scheduler dispatch by annotation (factory.go:428-434)."""
        return pod.scheduler_name == self.config.scheduler_name

    def enqueue(self, pod: api.Pod) -> None:
        if self.owns_pod is not None and not self.owns_pod(pod):
            # Sharded HA: another incarnation holds this namespace's
            # shard lease; its owner schedules it.  Takeover relists
            # (recovery.reconcile_shard) re-deliver anything dropped
            # here if the shard later becomes ours.
            return
        if self.responsible_for(pod) and not pod.node_name:
            # Admission timestamp for the e2e decision-latency SLO
            # (first-seen -> bind ack): the registry keeps the EARLIEST
            # admission per key, so requeues and watch redeliveries
            # (fresh objects) never reset the clock; the object carries
            # a copy for the bind path.
            pod._kt_first_seen = self._first_seen.setdefault(
                pod.key, time.perf_counter())
            if len(self._first_seen) > 65536:
                self._prune_first_seen()
            self.queue.add(pod)

    def _prune_first_seen(self) -> None:
        """Drop registry entries for pods no longer anywhere in flight
        (deleted while pending): keep keys still queued, in backoff, or
        assumed — everything else bound (cleared at ack) or vanished.
        If the registry is STILL over its bound (one tenant flooding
        more live pods than the cap), shed per-namespace-fair — oldest
        first WITHIN the largest namespace groups — so a noisy tenant's
        flood can never evict a quiet tenant's stamps and silently
        reset its SLO clock (the pre-fix pruning was global, exactly
        that failure)."""
        from kubernetes_tpu.scheduler.batchformer import \
            prune_first_seen_fair
        cache = self.config.algorithm.cache
        with self._requeue_cv:
            backoff = {pod.key for _, _, pod in self._requeue_heap}
        self._first_seen = {
            k: t for k, t in self._first_seen.items()
            if k in backoff or k in self.queue or cache.contains(k)}
        if len(self._first_seen) > 65536:
            self._first_seen = prune_first_seen_fair(
                self._first_seen, 65536)

    # -- one-pod path (scheduleOne, scheduler.go:93-154) -----------------

    def schedule_one(self, timeout: Optional[float] = None) -> bool:
        """Pop + schedule + assume + bind one pod; False if queue empty."""
        pod = self.queue.pop(timeout=timeout)
        if pod is None:
            return False
        start = time.perf_counter()
        root = trace_mod.begin_span("schedule_one", pod=pod.key)
        try:
            try:
                dest = self.config.algorithm.schedule(pod)
            except (FitError, ExtenderError) as err:
                if isinstance(err, FitError):
                    # The one-pod preemption path (scheduleOne's
                    # post-priority behavior): an executed victim solve
                    # turns the FitError into a nominated placement.
                    filled = self._preempt_failures([pod], [None], {})
                    if filled[0] is not None:
                        pod.nominated_node = filled[0]
                        self._assume_and_bind(pod, filled[0], start)
                        return True
                # Per-predicate failure counts straight off the FitError
                # (failed_predicates: node -> [names]) for the recorder.
                counts: dict[str, int] = {}
                for preds in getattr(err, "failed_predicates",
                                     {}).values():
                    for name in preds:
                        counts[name] = counts.get(name, 0) + 1
                self._handle_failure(pod, "FailedScheduling", str(err),
                                     failed_predicates=counts or None)
                return True
            algo_us = (time.perf_counter() - start) * 1e6
            self.config.metrics.scheduling_algorithm_latency.observe(algo_us)
            if self.config.flight_recorder is not None:
                self.config.flight_recorder.record_batch(
                    [pod], [dest], trace_id=root.trace_id,
                    duration_s=algo_us / 1e6,
                    tenants=(self.tenancy_service.count_tenants([pod])
                             if self.tenancy_service is not None
                             else None))
            self._assume_and_bind(pod, dest, start)
            return True
        finally:
            root.end()

    # -- batched path (the TPU drain) ------------------------------------

    # Queue sizes past this drain through the chunked device pipeline
    # (assume/bind of chunk k overlaps the device scan of chunk k+1).
    # Off by default: measured on the tunneled v5e, each executable launch
    # costs ~250 ms, so one big scan beats any multi-launch pipeline; on
    # locally-attached chips (launch ~1 ms) set KT_STREAM_CHUNK to e.g.
    # 4096 and the pipeline wins.
    STREAM_THRESHOLD = knobs.get_int("KT_STREAM_CHUNK") or (1 << 62)

    # Drains below this size are routed through the stream path with a
    # power-of-two chunk, whose live-flag padding gives them a fixed
    # compiled shape — a live-arrival workload (queue drained while pods
    # trickle in) then compiles at most log2 distinct batch shapes
    # instead of one per queue length.
    _PAD_LIMIT = 4096

    # Floor on the small-drain bucket: pad rows are numerically inert, so
    # padding a 3-pod drain to 256 costs dead scan rows (microseconds),
    # while every distinct bucket below the floor costs an XLA compile
    # (seconds).  Measured on the 500-node kubemark rig: the arrival race
    # produces drains of 1..700 pods, and the 1,2,4,...,128 ladder minted
    # ~8 scan compiles (~4-8 s each on a small host) before the fleet
    # settled; with the floor the ladder is {256, 512, 1024, 2048}.
    # The effective value is captured ONCE per daemon in __init__
    # (self.stream_min_bucket): pre-warm traces the bucket ladder this
    # floor defines, and an env change after warmup would otherwise mint
    # unwarmed shapes mid-run.
    STREAM_MIN_BUCKET = 256

    def schedule_pending(self, wait_first: bool = True,
                         timeout: Optional[float] = None) -> int:
        """Drain the queue through the pipeline (form -> solve ->
        commit; scheduler/pipeline.py).  Returns the number of pods
        popped (scheduled or failed).  This is the ONLY batched drain
        entry path — one-shot, streamed, and joint are solve modes the
        pipeline routes internally, not separate control flows."""
        return self.pipeline.drain(wait_first=wait_first, timeout=timeout)

    def _record_batch_decisions(self, pods: list, placements: list,
                                trace_id: str, duration_s: float) -> None:
        """Feed the flight recorder: the placement map always, plus the
        engine's per-predicate failure detail for failed pods not
        explained within the last 30 s (the explain pass costs a small
        device evaluation, paid only when a drain actually failed pods)."""
        recorder = self.config.flight_recorder
        if recorder is None:
            return
        detail = None
        failed = [pod for pod, dest in zip(pods, placements)
                  if dest is None]
        if failed:
            now = time.monotonic()
            fresh = [p for p in failed
                     if now - self._explain_ts.get(p.key, -1e9) > 30.0]
            if fresh:
                try:
                    detail = self.config.algorithm.explain_failures(fresh)
                except Exception:  # noqa: BLE001 — explain is best-effort
                    log.exception("failure-detail pass crashed; recording "
                                  "decisions without predicate counts")
                for p in fresh:
                    self._explain_ts[p.key] = now
                if len(self._explain_ts) > 4096:
                    cutoff = now - 30.0
                    self._explain_ts = {
                        k: t for k, t in self._explain_ts.items()
                        if t > cutoff}
        recorder.record_batch(pods, placements, trace_id=trace_id,
                              duration_s=duration_s,
                              failure_detail=detail,
                              tenants=(self.tenancy_service
                                       .count_tenants(pods)
                                       if self.tenancy_service is not None
                                       else None))

    def _assume_and_bind_batch(self, pods: list[api.Pod],
                               placements: list, start: float,
                               failure_info: Optional[dict] = None
                               ) -> None:
        """Bulk assume (vectorized), then bind; failures forget + requeue.
        Already-cached pods are skipped, matching the single-pod loop's
        log-and-proceed on assume errors (scheduler.go:116-120).

        ``failure_info`` maps pod key -> (message, result label) for
        failures with a workload-specific story (gang rejections).
        Unschedulable priority pods go through the preemption pass AFTER
        the batch's placements are assumed — the victim solve must see
        this drain's own commitments (else a pod that failed on in-batch
        contention would "preempt" with zero victims onto a node the
        drain just filled, overcommitting it) — and the drain's own
        placements are protected from eviction; an executed decision
        (victims evicted) promotes the pod to placed and it is assumed
        alongside."""
        failure_info = failure_info or {}
        placed = [(pod, dest) for pod, dest in zip(pods, placements)
                  if dest is not None]
        # Sanity-gate backstop (engine/guard.py): a pod whose last solve
        # was gate-rejected and never cleanly re-solved must not bind.
        # Structurally unreachable (the gate raises before placements
        # exist), so the check costs one bool when the rejected set is
        # empty — but a future refactor that swallows DeviceFault would
        # trip the ratcheted scheduler_sanity_rejected_binds_total here
        # instead of binding garbage.
        gd = getattr(self.config.algorithm, "guard", None)
        if gd is not None and gd.enabled and gd.has_rejections():
            placed, refused = gd.filter_rejected(placed)
            for pod, _ in refused:
                self._handle_failure(
                    pod, "SchedulingError",
                    "placement from a sanity-gate-rejected solve refused",
                    result="error")
        with stage("assume", pods=len(placed)):
            skipped = set(self.config.algorithm.cache.assume_pods(
                placed, strict=False,
                agg_handoff=self.config.algorithm.take_agg_handoff()))
        if skipped:
            placed = [(pod, dest) for pod, dest in placed
                      if pod.key not in skipped]
        filled = self._preempt_failures(
            pods, placements, failure_info,
            protected=frozenset(pod.key for pod, _ in placed))
        newly = [(pod, nd) for pod, nd, od in
                 zip(pods, filled, placements)
                 if od is None and nd is not None]
        if newly:
            with stage("assume", pods=len(newly)):
                skipped2 = set(self.config.algorithm.cache.assume_pods(
                    newly, strict=False))
            placed += [(pod, dest) for pod, dest in newly
                       if pod.key not in skipped2]
            placements = filled
        for pod, dest in zip(pods, placements):
            if dest is None:
                msg, result = failure_info.get(
                    pod.key,
                    (f"pod ({pod.name}) failed to fit in any node",
                     "unschedulable"))
                self._handle_failure(pod, "FailedScheduling", msg,
                                     result=result)
        if self.config.async_bind:
            t = threadreg.spawn(self._bind_assumed_batch,
                                args=(placed, start,
                                      trace_mod.current_context()),
                                name="bind-batch", transient=True)
            # Prune finished binders on append: a long-running daemon
            # drains every ~50 ms and must not accumulate dead Thread
            # objects without bound.
            self._bind_threads = [x for x in self._bind_threads
                                  if x.is_alive()]
            self._bind_threads.append(t)
        else:
            self._bind_assumed_batch(placed, start)

    def _preempt_failures(self, pods: list, placements: list,
                          failure_info: dict,
                          protected: frozenset = frozenset()) -> list:
        """The preemption pass: unschedulable priority pods get a victim
        solve (engine.find_preemptions); executed decisions (victims
        evicted, nominated node recorded) rewrite the placement vector so
        the normal assume/bind path commits them.  ``protected`` keys
        (the caller's just-assumed placements) are never victims.  Gang
        members never preempt individually (a partial gang must not
        evict for a placement the reduction would reject)."""
        from kubernetes_tpu.utils.featuregate import DEFAULT_FEATURE_GATE
        if not DEFAULT_FEATURE_GATE.enabled("Preemption"):
            return placements
        cands = [pod for pod, dest in zip(pods, placements)
                 if dest is None and pod.effective_priority > 0
                 and not pod.gang and pod.key not in failure_info]
        if not cands:
            return placements
        try:
            decisions = self.config.algorithm.find_preemptions(
                cands, protected=protected)
        except Exception:  # noqa: BLE001 — preemption is best-effort
            log.exception("preemption pass crashed; pods requeue with "
                          "backoff instead")
            decisions = []
        executed = {}
        for dec in decisions:
            if self._execute_preemption(dec):
                executed[dec.pod_key] = dec
        decided = {d.pod_key for d in decisions}
        for pod in cands:
            if pod.key not in decided:
                metrics_mod.PREEMPTIONS.labels(
                    result="no_candidate").inc()
        if not executed:
            return placements
        out = []
        for pod, dest in zip(pods, placements):
            dec = executed.get(pod.key) if dest is None else None
            if dec is not None:
                pod.nominated_node = dec.node
                out.append(dec.node)
            else:
                out.append(dest)
        return out

    def _execute_preemption(self, dec) -> bool:
        """Evict a decision's victims (cache + binder) so the preemptor
        can assume and bind — the evict->assume->bind path.  Returns
        False (pod stays unschedulable, requeues with backoff) if any
        eviction fails."""
        cache = self.config.algorithm.cache
        evict = getattr(self.config.binder, "evict", None)
        try:
            for vkey in dec.victims:
                vpod = cache.get_pod(vkey)
                if vpod is not None:
                    cache.remove_pod(vpod)
                else:
                    ns, _, name = vkey.partition("/")
                    vpod = api.Pod(name=name or ns,
                                   namespace=ns if name else "default")
                if evict is not None:
                    evict(vpod)
                self.config.recorder.eventf(
                    vkey, "Normal", "Preempted",
                    f"Preempted by {dec.pod_key} (priority) "
                    f"on node {dec.node}")
        except Exception:  # noqa: BLE001 — a failed eviction aborts
            log.exception("preemption eviction failed for %s on %s",
                          dec.pod_key, dec.node)
            metrics_mod.PREEMPTIONS.labels(result="error").inc()
            return False
        metrics_mod.PREEMPTIONS.labels(result="executed").inc()
        metrics_mod.PREEMPTION_VICTIMS.inc(len(dec.victims))
        if self.config.flight_recorder is not None:
            self.config.flight_recorder.record_preemption(
                dec.pod_key, dec.node, dec.victims)
        log.info("preempted %d pod(s) on %s for %s",
                 len(dec.victims), dec.node, dec.pod_key)
        return True

    # Fixed stream chunk override (else derived from STREAM_THRESHOLD).
    stream_chunk: int = 0

    def stream_chunk_size(self) -> int:
        """Chunk size the streamed drain compiles at (harness warmup must
        pre-trace the same shape)."""
        return self.stream_chunk or min(self.STREAM_THRESHOLD, 8192)

    def degraded_drain_cap(self) -> int:
        """Pods per drain while shedding load: the largest bucket the
        pre-warm traced (a degraded drain must never mint a fresh XLA
        compile — the storm is exactly when compile stalls hurt most),
        falling back to the one-shot pad limit when streaming is off."""
        ladder = self.effective_ladder()
        return max(ladder) if ladder else self._PAD_LIMIT

    def effective_ladder(self) -> list[int]:
        """The fixed set of chunk sizes this daemon's drains can compile
        at — pre-warm traces exactly this set; the drain paths can mint
        no other.  Two sources: the stream chunk, included only when the
        chunked path is reachable (STREAM_THRESHOLD set — at its unset
        sentinel every large drain takes the one-shot schedule_batch
        path instead, whose shape follows the live queue length and
        cannot be pre-traced); and the small-drain buckets, reachable
        for drains below min(STREAM_THRESHOLD, _PAD_LIMIT): the
        startup-captured floor itself (possibly non-pow2 — every drain
        at or below it pads to it) plus each pow2 ABOVE the floor up to
        the pow2 ceiling of the largest such drain (4096 included: a
        2049-4095-pod drain legally mints it even when the stream chunk
        is smaller).  The computation itself is the module-level
        ``bucket_ladder`` so the kt-xray manifest shares it."""
        return bucket_ladder(self.stream_min_bucket, self.STREAM_THRESHOLD,
                             self._PAD_LIMIT, self.stream_chunk)

    def prewarm_plan(self) -> list[str]:
        """The program keys ``prewarm()`` will trace for THIS daemon's
        configuration — static introspection, no device, no compile.
        Mirrors ``prewarm()``'s own no-op conditions (StreamingDrain
        gate off, extenders configured, empty cluster -> []), so the
        report is honest exactly where the watchdog matters.  kt-xray
        compares the canonical-config instantiation against the
        committed shape manifest (rule X04); this instance method is
        the live-daemon view (tests pin it against the manifest for
        the default config)."""
        from kubernetes_tpu.engine.solver import ResidentCluster
        from kubernetes_tpu.utils.featuregate import DEFAULT_FEATURE_GATE
        alg = self.config.algorithm
        if not DEFAULT_FEATURE_GATE.enabled("StreamingDrain") or \
                alg.extenders or not alg.cache.nodes():
            return []
        ladder = self.effective_ladder()
        return prewarm_plan(
            ladder, ResidentCluster.scatter_buckets(len(alg.cache.nodes())),
            joint=DEFAULT_FEATURE_GATE.enabled("JointSolver"),
            preempt=DEFAULT_FEATURE_GATE.enabled("Preemption"))

    def prewarm(self, sample_pods: Optional[list] = None) -> dict:
        """Trace the full bucket ladder before the queue opens, so no
        live drain ever pays an XLA compile on the clock.  With the
        persistent compilation cache populated (engine/compile_cache) the
        traces deserialize in well under a second each; cold, the cost is
        paid here once per machine instead of on the first N drains.

        ``sample_pods`` shapes the traced programs (vocab capacities +
        content flags) like the expected workload; without it a minimal
        synthetic pod is used.  Each bucket warms BOTH full-chunk jit
        signatures (first chunk carries no state dict, later chunks do).
        Returns {bucket: seconds}; no-ops when streaming is off, an
        extender is configured, or the cluster is empty."""
        from kubernetes_tpu.engine import devicestats
        from kubernetes_tpu.utils.featuregate import DEFAULT_FEATURE_GATE
        alg = self.config.algorithm
        if not DEFAULT_FEATURE_GATE.enabled("StreamingDrain") or \
                alg.extenders or not alg.cache.nodes():
            return {}
        # Prewarm compiles are never "post-prewarm": disarm for the
        # duration so a fresh rig warming up in an already-armed process
        # (the serving bench builds three in a row) doesn't count its
        # own ladder traces as live-path stalls.  Chaos injection is
        # suppressed the same way: the ladder traces run the live solve
        # sites, but there is no recovery ladder above prewarm — a
        # KT_CHAOS_DEVICE cadence firing here would fail startup
        # instead of exercising recovery (guard.suppressed re-enables
        # on exit even if a trace raises).
        devicestats.disarm()
        import contextlib as _contextlib
        _suppress = alg.guard.suppressed() if alg.guard.enabled \
            else _contextlib.nullcontext()
        with _suppress:
            ladder = self.effective_ladder()
            timings: dict[int, float] = {}
            # Warm-start audit: per-bucket persistent-compile-cache traffic.
            # A bucket whose trace shows misses on a supposedly-warm start is
            # a signature dodging the cache — exactly the 3-4 s "warm" tail
            # ROADMAP item 3 chases.  (The counters ride JAX monitoring
            # events, engine/compile_cache; zero/zero means the executable
            # was already live in process memory.)
            cache_stats: dict = {}

            def audited(key, fn):
                h0 = metrics_mod.COMPILE_CACHE_HITS.value
                m0 = metrics_mod.COMPILE_CACHE_MISSES.value
                t0 = time.perf_counter()
                fn()
                dt = time.perf_counter() - t0
                cache_stats[key] = {
                    "hits": metrics_mod.COMPILE_CACHE_HITS.value - h0,
                    "misses": metrics_mod.COMPILE_CACHE_MISSES.value - m0,
                    "seconds": round(dt, 3)}
                return dt

            # LARGEST bucket first: the monotonic content-axis caps
            # (padcap — spread groups, nz templates, …) grow while the
            # ladder traces, and ascending order would trace the small
            # buckets at a stale cap that the big bucket's richer sample
            # batch then outgrows — minting unwarmed (bucket, final-cap)
            # shapes for every small live drain (measured as two
            # post-prewarm compiles on the wire rig).  Descending order
            # reaches the cap fixed point on the first trace.
            for bucket in sorted(ladder, reverse=True):
                want = 2 * bucket  # both scan signatures (no-carry + carry)
                if sample_pods:
                    pods = list(sample_pods[:want])
                else:
                    pods = []
                pods += [api.Pod(name=f"__warm-{i}", namespace="__warm__")
                         for i in range(want - len(pods))]

                def run_bucket(pods=pods, bucket=bucket):
                    for _ in alg.schedule_batch_stream(pods,
                                                       chunk_size=bucket):
                        pass

                timings[bucket] = audited(bucket, run_bucket)
            # The single-pod decision path (schedule_one / the recovery
            # parity probes): evaluate/masks/select_hosts at P=1 are NOT the
            # scan's signatures, so without this trace the first interactive
            # decision after every start paid ~30 compiles on the clock —
            # a measured 0.3-0.7 s warm-start tail the ladder never covered.
            def run_single():
                try:
                    alg.schedule(api.Pod(name="__warm-one",
                                         namespace="__warm__"))
                except Exception:  # noqa: BLE001 — FitError etc. still traced
                    pass

            audited("single_pod", run_single)
            # The dirty-row scatter kernel compiles per pow2 dirty-row count;
            # untraced, the first drain after any assume paid it mid-drain.
            audited("scatter", lambda: alg.resident.prewarm_scatter())
            # Workload-subsystem signatures warm separately (string-keyed on
            # the daemon, not in the int-keyed bucket dict callers inspect).
            self.workloads_prewarm_s = self._prewarm_workloads(ladder)
            self.prewarm_cache_stats = cache_stats
        # Recompile watchdog: from here on, ANY XLA compile on a live
        # path is a stall the ladder should have traced — counted in
        # scheduler_post_prewarm_compiles_total{path=}, recorded as a
        # post_prewarm_compile span, and failed by the bench ratchet.
        devicestats.arm()
        log.info("pre-warmed stream ladder %s (floor %d, chunk %d): %s "
                 "workloads=%s cache=%s",
                 ladder, self.stream_min_bucket, self.stream_chunk_size(),
                 {b: f"{s:.2f}s" for b, s in timings.items()},
                 {k: f"{s:.2f}s"
                  for k, s in self.workloads_prewarm_s.items()},
                 cache_stats)
        return timings

    def _prewarm_workloads(self, ladder: list[int]) -> dict:
        """Trace the workloads-subsystem solve signatures (ISSUE 6
        satellite): the preemption victim kernel at the cluster's (N, V)
        shape, the topology plane kernel + masked scan at the floor
        bucket, and (when the gate is on) the one-shot joint executable —
        all of which a live drain would otherwise compile on the clock.
        Gang one-shot solves reuse the stream ladder's scan signatures
        (same live-masked _solve_scan), so they need no extra trace."""
        import json as _json

        from kubernetes_tpu.utils.featuregate import DEFAULT_FEATURE_GATE
        alg = self.config.algorithm
        timings: dict = {}
        floor = min(ladder) if ladder else 0
        try:
            if DEFAULT_FEATURE_GATE.enabled("Preemption"):
                from kubernetes_tpu.engine.workloads import preemption
                t0 = time.perf_counter()
                preemption.prewarm_shapes(len(alg.cache.nodes()))
                timings["preempt"] = time.perf_counter() - t0
            if floor:
                tsc = _json.dumps([{
                    "maxSkew": 1, "topologyKey": api.ZONE_LABEL,
                    "whenUnsatisfiable": "DoNotSchedule",
                    "labelSelector": {"matchLabels": {"kt/warm": "1"}}}])
                spods = [api.Pod(
                    name=f"__warm-topo-{i}", namespace="__warm__",
                    labels={"kt/warm": "1"},
                    annotations={api.TOPOLOGY_SPREAD_ANNOTATION_KEY: tsc})
                    for i in range(min(floor, 4))]
                t0 = time.perf_counter()
                alg.schedule_batch(spods, pad_to=floor)
                timings["topology"] = time.perf_counter() - t0
                if DEFAULT_FEATURE_GATE.enabled("JointSolver"):
                    jpods = [api.Pod(name=f"__warm-joint-{i}",
                                     namespace="__warm__")
                             for i in range(min(floor, 4))]
                    t0 = time.perf_counter()
                    alg.schedule_batch(jpods, joint=True, pad_to=floor)
                    timings["joint"] = time.perf_counter() - t0
        except Exception:  # noqa: BLE001 — warmup must never kill startup
            log.exception("workloads prewarm failed; first constrained "
                          "drain will compile on the clock")
        return timings

    # -- run loops --------------------------------------------------------

    def run(self, batched: bool = True) -> threading.Thread:
        """wait.Until(scheduleOne, 0, stop) (scheduler.go:89-91), in a
        daemon thread; batched mode drains the queue per iteration.  A
        crashing iteration is logged and the loop continues — the
        reference's runtime.HandleCrash keeps its daemons alive the same
        way; without this, one bad drain kills scheduling forever."""
        def loop():
            while not self._stop.is_set():
                try:
                    if batched:
                        self.schedule_pending(timeout=0.05)
                    else:
                        self.schedule_one(timeout=0.05)
                except Exception:  # noqa: BLE001 — HandleCrash analogue
                    log.exception("scheduling iteration crashed; "
                                  "continuing")
                    time.sleep(0.5)
        return threadreg.spawn(loop, name="scheduler-loop")

    def stop(self) -> None:
        self._stop.set()
        self.queue.close()
        self.pipeline.shutdown(wait=True)
        for t in self._bind_threads:
            t.join(timeout=5)
        # Graceful shutdown persists the decision ring (KT_FLIGHT_DIR) so
        # `kubectl explain pod` keeps answering across a scheduler bounce.
        recorder = self.config.flight_recorder
        flight_dir = knobs.get("KT_FLIGHT_DIR")
        if recorder is not None and flight_dir:
            try:
                recorder.save(flight_dir)
            except OSError:
                log.exception("flight-recorder dump to %s failed",
                              flight_dir)

    def abandon(self) -> None:
        """SIGKILL-style stop: no graceful drain, no joins, no flight
        dump — the in-flight pipeline window (solved-but-uncommitted
        chunks, dispatched binds) is simply abandoned, exactly what a
        kill between solve and bind leaves behind.  Safety then rests on
        the apiserver's bind CAS (an abandoned bind that still lands
        cannot be double-applied) and the next incarnation's startup
        reconciliation (scheduler/recovery.py), which requeues anything
        left unbound and adopts anything that did land."""
        self._stop.set()
        self.queue.close()
        self.pipeline.shutdown(cancel=True)

    def wait_for_binds(self) -> None:
        for t in list(self._bind_threads):
            t.join()
        self._bind_threads = [t for t in self._bind_threads if t.is_alive()]

    # -- internals --------------------------------------------------------

    def _record_bind_failure(self, err) -> str:
        """The module-level classifier plus the HA plane's cross-shard
        accounting: a CAS conflict observed while running sharded means
        another incarnation (or a chaos rule) bound the pod first —
        near-zero in steady state, bursty during lease handoffs."""
        result = _record_bind_failure(err)
        if result == "bind_conflict" and self.owns_pod is not None:
            metrics_mod.CROSS_SHARD_CONFLICTS.inc()
        return result

    def _forget_quietly(self, pod: api.Pod) -> None:
        """Forget a failed bind's optimistic assume; tolerates the pod
        being gone already — a shard handoff (factory._on_shard_lost
        forgets the lost shard's assumes wholesale) can race the bind
        fan-out, and the loser of that race must requeue-or-drop, not
        die on a ValueError in the bind thread."""
        try:
            self.config.algorithm.cache.forget_pod(pod)
        except ValueError:
            pass

    def _assume_and_bind(self, pod: api.Pod, dest: str, start: float) -> None:
        cache = self.config.algorithm.cache
        # Optimistic assume before the async bind; an assume error is logged
        # and binding proceeds anyway (scheduler.go:116-120).
        assumed = True
        try:
            with stage("assume", pods=1):
                cache.assume_pod(pod, dest)
        except ValueError:
            assumed = False
        ctx = trace_mod.current_context()

        def bind():
            with trace_mod.use_context(ctx):
                self._bind_assumed(pod, dest, start, assumed=assumed)

        if self.config.async_bind:
            t = threadreg.spawn(bind, name="bind-one", transient=True)
            self._bind_threads = [x for x in self._bind_threads
                                  if x.is_alive()]
            self._bind_threads.append(t)
        else:
            bind()

    def _bind_assumed(self, pod: api.Pod, dest: str, start: float,
                      assumed: bool = True) -> None:
        bind_start = time.perf_counter()
        try:
            with stage("bind", pods=1):
                self.config.binder.bind(pod, dest)
        except Exception as err:  # noqa: BLE001 — bind errors requeue
            # ForgetPod + error handler (scheduler.go:139-148).  409 and
            # timeout alike: forget the optimistic assume, emit the event,
            # requeue behind per-pod backoff — never silently drop.
            result = self._record_bind_failure(err)
            if assumed:
                self._forget_quietly(pod)
            self._handle_failure(pod, "FailedScheduling",
                                 f"Binding rejected: {err}",
                                 result=result)
            return
        now = time.perf_counter()
        self.config.metrics.binding_latency.observe(
            (now - bind_start) * 1e6)
        self.config.metrics.e2e_scheduling_latency.observe(
            (now - start) * 1e6)
        seen = first_seen(pod)
        if seen is not None:
            metrics_mod.E2E_DECISION_LATENCY.observe(
                (now - seen) * 1e6,
                exemplar=trace_mod.current_trace_id())
        if self.tenancy_service is not None:
            self.tenancy_service.record_bound(
                pod, (now - seen) if seen is not None else None)
        self._first_seen.pop(pod.key, None)
        self.config.metrics.scheduling_attempts.labels(
            result="scheduled").inc()
        self.config.recorder.eventf(
            pod.key, "Normal", "Scheduled",
            f"Successfully assigned {pod.name} to {dest}")

    def _bind_assumed_batch(self, placed: list[tuple[api.Pod, str]],
                            start: float, trace_ctx=None) -> None:
        """Bind a solved batch: per-pod CAS binds (conflicts forget +
        requeue exactly like _bind_assumed), with the per-pod metric
        observations amortized into one bucket pass each.  ``trace_ctx``
        carries the batch's span context into the async bind thread so the
        fan-out (and its HTTP requests) stays on the batch's trace."""
        if trace_ctx is None:  # sync call: stay on the caller's context
            trace_ctx = trace_mod.current_context()
        with trace_mod.use_context(trace_ctx), \
                stage("bind", pods=len(placed)):
            self._bind_assumed_batch_inner(placed, start)

    def _bind_assumed_batch_inner(self, placed: list[tuple[api.Pod, str]],
                                  start: float) -> None:
        recorder = self.config.recorder
        bind_start = time.perf_counter()
        bind_many = getattr(self.config.binder, "bind_many", None)
        bound_pods: list[api.Pod] = []
        if bind_many is not None:
            failed = {pod.key: err for pod, err in bind_many(placed)}
            ok = 0
            items = []
            for pod, dest in placed:
                if pod.key in failed:
                    result = self._record_bind_failure(failed[pod.key])
                    self._forget_quietly(pod)
                    # Surface the real error: a CAS conflict and a
                    # network failure require different operator action.
                    self._handle_failure(
                        pod, "FailedScheduling",
                        f"Binding rejected: {failed[pod.key]}",
                        result=result)
                else:
                    ok += 1
                    bound_pods.append(pod)
                    items.append((pod.key, "Normal", "Scheduled",
                                  f"Successfully assigned {pod.name} to {dest}"))
            recorder.eventf_many(items)
        else:
            ok = 0
            for pod, dest in placed:
                try:
                    self.config.binder.bind(pod, dest)
                except Exception as err:  # noqa: BLE001 — bind errors requeue
                    result = self._record_bind_failure(err)
                    self._forget_quietly(pod)
                    self._handle_failure(pod, "FailedScheduling",
                                         f"Binding rejected: {err}",
                                         result=result)
                    continue
                ok += 1
                bound_pods.append(pod)
                recorder.eventf(
                    pod.key, "Normal", "Scheduled",
                    f"Successfully assigned {pod.name} to {dest}")
        done = time.perf_counter()
        self.config.metrics.binding_latency.observe_many(
            (done - bind_start) * 1e6 / max(len(placed), 1), ok)
        self.config.metrics.e2e_scheduling_latency.observe_many(
            (done - start) * 1e6, ok)
        # The serving SLO number: per-pod first-seen -> bind ack (NOT
        # amortized — every pod carries its own admission stamp, so the
        # histogram captures the real tail the deadline trades against).
        # The batch's trace id rides along as the bucket exemplar: a bad
        # p99 bucket then names the exact trace to pull from the ring.
        tid = trace_mod.current_trace_id()
        svc = self.tenancy_service
        for pod in bound_pods:
            seen = first_seen(pod)
            if seen is not None:
                metrics_mod.E2E_DECISION_LATENCY.observe(
                    (done - seen) * 1e6, exemplar=tid)
            if svc is not None:
                svc.record_bound(
                    pod, (done - seen) if seen is not None else None)
            self._first_seen.pop(pod.key, None)
        if ok:
            self.config.metrics.scheduling_attempts.labels(
                result="scheduled").inc(ok)

    def _handle_failure(self, pod: api.Pod, reason: str, message: str,
                        result: str = "unschedulable",
                        failed_predicates: Optional[dict] = None) -> None:
        """Event + condition update + backoff requeue (factory.go:512-556).
        Every failure class funnels through here, so this is also where
        the attempts counter and the flight recorder see it."""
        log.debug("scheduling failure for %s: %s", pod.key, message)
        self.config.metrics.scheduling_attempts.labels(result=result).inc()
        if self.config.flight_recorder is not None:
            self.config.flight_recorder.record_failure(
                pod.key, reason, message,
                failed_predicates=failed_predicates)
        self.config.recorder.eventf(pod.key, "Warning", reason, message)
        if self.config.condition_updater is not None:
            self.config.condition_updater(pod, "Unschedulable", message)
        backoff_s = self.backoff.get_backoff(pod.key)
        with self._requeue_cv:
            self._requeue_seq += 1
            heapq.heappush(self._requeue_heap,
                           (time.monotonic() + backoff_s,
                            self._requeue_seq, pod))
            if self._requeue_thread is None or \
                    not self._requeue_thread.is_alive():
                self._requeue_thread = threadreg.spawn(
                    self._requeue_worker, name="backoff-requeue")
            self._requeue_cv.notify()

    def _requeue_worker(self) -> None:
        while not self._stop.is_set():
            with self._requeue_cv:
                while not self._requeue_heap and not self._stop.is_set():
                    self._requeue_cv.wait(timeout=0.5)
                if self._stop.is_set():
                    return
                due, _, pod = self._requeue_heap[0]
                delay = due - time.monotonic()
                if delay > 0:
                    self._requeue_cv.wait(timeout=min(delay, 0.5))
                    continue
                heapq.heappop(self._requeue_heap)
            pod.node_name = ""
            if self.owns_pod is not None and not self.owns_pod(pod):
                # The shard moved while this pod sat in backoff: its new
                # owner schedules it (the takeover relist already
                # requeued it there); re-adding here would race two
                # incarnations on one pod as the steady state.
                self._first_seen.pop(pod.key, None)
                continue
            self.queue.add(pod)
