"""The scheduling daemon loop.

The reference's ``Scheduler`` (plugin/pkg/scheduler/scheduler.go:46-154)
runs ``scheduleOne`` forever: blocking pop -> Schedule -> optimistic
AssumePod -> async Bind; on bind failure ForgetPod + error handler with
per-pod backoff requeue (factory.go:512-556).  This daemon keeps that state
machine and adds the TPU-native batched drain: ``schedule_pending`` pops the
whole queue and solves it as ONE device batch, assuming and binding every
placement — same observable behavior, three orders of magnitude fewer
device round-trips.
"""

from __future__ import annotations

import heapq
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from kubernetes_tpu.api import types as api
from kubernetes_tpu.apiserver.memstore import ConflictError
from kubernetes_tpu.engine.extender_client import ExtenderError
from kubernetes_tpu.engine.generic_scheduler import FitError, GenericScheduler
from kubernetes_tpu.scheduler.backoff import PodBackoff
from kubernetes_tpu.scheduler.binder import Binder, BindConflict, InMemoryBinder
from kubernetes_tpu.scheduler.queue import FIFO
from kubernetes_tpu.utils import metrics as metrics_mod
from kubernetes_tpu.utils.events import EventRecorder
from kubernetes_tpu.utils.logging import get_logger
from kubernetes_tpu.utils.metrics import SchedulerMetrics


def _record_bind_failure(err) -> None:
    """409/CAS conflicts and transport faults are different operator
    stories: count them apart (both forget + requeue with backoff)."""
    if isinstance(err, (BindConflict, ConflictError)):
        metrics_mod.BIND_CONFLICTS.inc()
    else:
        metrics_mod.BIND_FAILURES.inc()

log = get_logger("daemon")

DEFAULT_SCHEDULER_NAME = api.DEFAULT_SCHEDULER_NAME


@dataclass
class SchedulerConfig:
    """The reference's scheduler.Config (scheduler.go:46-77)."""

    algorithm: GenericScheduler
    binder: Binder = field(default_factory=InMemoryBinder)
    recorder: EventRecorder = field(default_factory=EventRecorder)
    metrics: SchedulerMetrics = field(default_factory=SchedulerMetrics)
    scheduler_name: str = DEFAULT_SCHEDULER_NAME
    # Pod-condition updater analogue (factory.go:589-600); called with
    # (pod, reason, message) when scheduling fails.
    condition_updater: Optional[Callable[[api.Pod, str, str], None]] = None
    async_bind: bool = True


class Scheduler:
    def __init__(self, config: SchedulerConfig):
        self.config = config
        self.queue = FIFO()
        self.backoff = PodBackoff()
        self._stop = threading.Event()
        self._bind_threads: list[threading.Thread] = []
        # Single requeue worker over a timer heap (a thread per failed pod
        # would explode on a large unschedulable batch).
        self._requeue_heap: list[tuple[float, int, api.Pod]] = []
        self._requeue_cv = threading.Condition()
        self._requeue_seq = 0
        self._requeue_thread: Optional[threading.Thread] = None

    # -- queue feed (the reflector-handler analogue) ---------------------

    def responsible_for(self, pod: api.Pod) -> bool:
        """Multi-scheduler dispatch by annotation (factory.go:428-434)."""
        return pod.scheduler_name == self.config.scheduler_name

    def enqueue(self, pod: api.Pod) -> None:
        if self.responsible_for(pod) and not pod.node_name:
            self.queue.add(pod)

    # -- one-pod path (scheduleOne, scheduler.go:93-154) -----------------

    def schedule_one(self, timeout: Optional[float] = None) -> bool:
        """Pop + schedule + assume + bind one pod; False if queue empty."""
        pod = self.queue.pop(timeout=timeout)
        if pod is None:
            return False
        start = time.perf_counter()
        try:
            dest = self.config.algorithm.schedule(pod)
        except (FitError, ExtenderError) as err:
            self._handle_failure(pod, "FailedScheduling", str(err))
            return True
        algo_us = (time.perf_counter() - start) * 1e6
        self.config.metrics.scheduling_algorithm_latency.observe(algo_us)
        self._assume_and_bind(pod, dest, start)
        return True

    # -- batched path (the TPU drain) ------------------------------------

    # Queue sizes past this drain through the chunked device pipeline
    # (assume/bind of chunk k overlaps the device scan of chunk k+1).
    # Off by default: measured on the tunneled v5e, each executable launch
    # costs ~250 ms, so one big scan beats any multi-launch pipeline; on
    # locally-attached chips (launch ~1 ms) set KT_STREAM_CHUNK to e.g.
    # 4096 and the pipeline wins.
    STREAM_THRESHOLD = int(os.environ.get("KT_STREAM_CHUNK", "0") or "0") \
        or (1 << 62)

    # Drains below this size are routed through the stream path with a
    # power-of-two chunk, whose live-flag padding gives them a fixed
    # compiled shape — a live-arrival workload (queue drained while pods
    # trickle in) then compiles at most log2 distinct batch shapes
    # instead of one per queue length.
    _PAD_LIMIT = 4096

    # Arrival-coalescing window (seconds): when a drain pops fewer pods
    # than one stream chunk while more are clearly arriving, linger up to
    # this long topping the batch up.  A trickle-fed drain otherwise pays
    # a full padded chunk scan (plus ~250 ms launch overhead on a
    # tunneled chip) for every fragment of the arrival race.  0 = off
    # (the default: interactive paths keep their latency).
    accumulate_s: float = 0.0

    def schedule_pending(self, wait_first: bool = True,
                         timeout: Optional[float] = None) -> int:
        """Drain the queue and solve it as one device batch.  Returns the
        number of pods popped (scheduled or failed)."""
        pods = self.queue.pop_all(wait_first=wait_first, timeout=timeout)
        if not pods:
            return 0
        chunk = self.stream_chunk_size()
        if self.accumulate_s > 0 and len(pods) < chunk:
            deadline = time.monotonic() + self.accumulate_s
            idle_polls = 0
            while len(pods) < chunk and idle_polls < 3 and \
                    time.monotonic() < deadline:
                time.sleep(0.02)
                more = self.queue.pop_all(wait_first=False)
                idle_polls = 0 if more else idle_polls + 1
                pods.extend(more)
        try:
            return self._solve_drain(pods)
        except Exception:  # noqa: BLE001 — HandleCrash analogue
            # The pods were already popped: requeue each through the
            # backoff path (condition + event + delayed retry) so a
            # crashing drain can't silently strand them Pending, and a
            # poison pod retries at most once per 60 s.
            log.exception("drain of %d pods crashed; requeueing", len(pods))
            cache = self.config.algorithm.cache
            for pod in pods:
                # Skip pods the crash didn't strand: anything tracked in
                # the cache (assumed by a completed chunk, or already
                # confirmed bound by the watch) made it through.
                if not cache.contains(pod.key):
                    self._handle_failure(pod, "SchedulingError",
                                         "internal error during scheduling")
            return len(pods)

    def _solve_drain(self, pods: list) -> int:
        from kubernetes_tpu.utils.featuregate import DEFAULT_FEATURE_GATE
        joint = DEFAULT_FEATURE_GATE.enabled("JointSolver")
        # The joint solve needs the whole queue at once (prices couple
        # every pod); it supersedes the streaming split.
        streaming = DEFAULT_FEATURE_GATE.enabled("StreamingDrain") \
            and not joint
        if streaming and len(pods) >= self.STREAM_THRESHOLD and \
                not self.config.algorithm.extenders:
            return self._schedule_pending_stream(pods)
        if streaming and len(pods) < self._PAD_LIMIT and \
                not self.config.algorithm.extenders:
            # Small drain: one power-of-two stream chunk (live-flag
            # padded), so arrival races don't mint a new compiled shape
            # per queue length.
            bucket = 1 << (len(pods) - 1).bit_length()
            return self._schedule_pending_stream(pods, chunk_size=bucket)
        start = time.perf_counter()
        placements = self.config.algorithm.schedule_batch(pods, joint=joint)
        algo_us = (time.perf_counter() - start) * 1e6 / len(pods)
        self.config.metrics.scheduling_algorithm_latency.observe_many(
            algo_us, len(pods))
        if log.isEnabledFor(10):  # V(2)-style guard (predicates.go:478)
            placed_n = sum(1 for d in placements if d is not None)
            log.debug("drained %d pods: %d placed, %.0f us/pod algorithm",
                      len(pods), placed_n, algo_us)
        self._assume_and_bind_batch(pods, placements, start)
        return len(pods)

    def _assume_and_bind_batch(self, pods: list[api.Pod],
                               placements: list, start: float) -> None:
        """Bulk assume (vectorized), then bind; failures forget + requeue.
        Already-cached pods are skipped, matching the single-pod loop's
        log-and-proceed on assume errors (scheduler.go:116-120)."""
        placed = [(pod, dest) for pod, dest in zip(pods, placements)
                  if dest is not None]
        skipped = set(self.config.algorithm.cache.assume_pods(
            placed, strict=False,
            agg_handoff=self.config.algorithm.take_agg_handoff()))
        if skipped:
            placed = [(pod, dest) for pod, dest in placed
                      if pod.key not in skipped]
        for pod, dest in zip(pods, placements):
            if dest is None:
                self._handle_failure(
                    pod, "FailedScheduling",
                    f"pod ({pod.name}) failed to fit in any node")
        if self.config.async_bind:
            t = threading.Thread(target=self._bind_assumed_batch,
                                 args=(placed, start), daemon=True)
            t.start()
            # Prune finished binders on append: a long-running daemon
            # drains every ~50 ms and must not accumulate dead Thread
            # objects without bound.
            self._bind_threads = [x for x in self._bind_threads
                                  if x.is_alive()]
            self._bind_threads.append(t)
        else:
            self._bind_assumed_batch(placed, start)

    # Fixed stream chunk override (else derived from STREAM_THRESHOLD).
    stream_chunk: int = 0

    def stream_chunk_size(self) -> int:
        """Chunk size the streamed drain compiles at (harness warmup must
        pre-trace the same shape)."""
        return self.stream_chunk or min(self.STREAM_THRESHOLD, 8192)

    def _schedule_pending_stream(self, pods: list[api.Pod],
                                 chunk_size: Optional[int] = None) -> int:
        """The pipelined drain: as each device chunk lands, bulk-assume it
        and hand it to an async binder thread while the device scans the
        next chunk.  Same observable state machine as the one-shot path."""
        start = time.perf_counter()
        solve_done = start
        for chunk_pods, placements in \
                self.config.algorithm.schedule_batch_stream(
                    pods, chunk_size=chunk_size or self.stream_chunk_size()):
            solve_done = time.perf_counter()
            self._assume_and_bind_batch(chunk_pods, placements, start)
        # Algorithm latency spans until the LAST chunk's results landed
        # (interleaved assume/bind of earlier chunks overlaps the device
        # and is deliberately excluded, matching the one-shot path).
        algo_us = (solve_done - start) * 1e6 / len(pods)
        self.config.metrics.scheduling_algorithm_latency.observe_many(
            algo_us, len(pods))
        return len(pods)

    # -- run loops --------------------------------------------------------

    def run(self, batched: bool = True) -> threading.Thread:
        """wait.Until(scheduleOne, 0, stop) (scheduler.go:89-91), in a
        daemon thread; batched mode drains the queue per iteration.  A
        crashing iteration is logged and the loop continues — the
        reference's runtime.HandleCrash keeps its daemons alive the same
        way; without this, one bad drain kills scheduling forever."""
        def loop():
            while not self._stop.is_set():
                try:
                    if batched:
                        self.schedule_pending(timeout=0.05)
                    else:
                        self.schedule_one(timeout=0.05)
                except Exception:  # noqa: BLE001 — HandleCrash analogue
                    log.exception("scheduling iteration crashed; "
                                  "continuing")
                    time.sleep(0.5)
        t = threading.Thread(target=loop, daemon=True,
                             name="scheduler-loop")
        t.start()
        return t

    def stop(self) -> None:
        self._stop.set()
        self.queue.close()
        for t in self._bind_threads:
            t.join(timeout=5)

    def wait_for_binds(self) -> None:
        for t in list(self._bind_threads):
            t.join()
        self._bind_threads = [t for t in self._bind_threads if t.is_alive()]

    # -- internals --------------------------------------------------------

    def _assume_and_bind(self, pod: api.Pod, dest: str, start: float) -> None:
        cache = self.config.algorithm.cache
        # Optimistic assume before the async bind; an assume error is logged
        # and binding proceeds anyway (scheduler.go:116-120).
        assumed = True
        try:
            cache.assume_pod(pod, dest)
        except ValueError:
            assumed = False

        def bind():
            self._bind_assumed(pod, dest, start, assumed=assumed)

        if self.config.async_bind:
            t = threading.Thread(target=bind, daemon=True)
            t.start()
            self._bind_threads = [x for x in self._bind_threads
                                  if x.is_alive()]
            self._bind_threads.append(t)
        else:
            bind()

    def _bind_assumed(self, pod: api.Pod, dest: str, start: float,
                      assumed: bool = True) -> None:
        cache = self.config.algorithm.cache
        bind_start = time.perf_counter()
        try:
            self.config.binder.bind(pod, dest)
        except Exception as err:  # noqa: BLE001 — bind errors requeue
            # ForgetPod + error handler (scheduler.go:139-148).  409 and
            # timeout alike: forget the optimistic assume, emit the event,
            # requeue behind per-pod backoff — never silently drop.
            _record_bind_failure(err)
            if assumed:
                cache.forget_pod(pod)
            self._handle_failure(pod, "FailedScheduling",
                                 f"Binding rejected: {err}")
            return
        us = (time.perf_counter() - bind_start) * 1e6
        self.config.metrics.binding_latency.observe(us)
        self.config.metrics.e2e_scheduling_latency.observe(
            (time.perf_counter() - start) * 1e6)
        self.config.recorder.eventf(
            pod.key, "Normal", "Scheduled",
            f"Successfully assigned {pod.name} to {dest}")

    def _bind_assumed_batch(self, placed: list[tuple[api.Pod, str]],
                            start: float) -> None:
        """Bind a solved batch: per-pod CAS binds (conflicts forget +
        requeue exactly like _bind_assumed), with the per-pod metric
        observations amortized into one bucket pass each."""
        cache = self.config.algorithm.cache
        recorder = self.config.recorder
        bind_start = time.perf_counter()
        bind_many = getattr(self.config.binder, "bind_many", None)
        if bind_many is not None:
            failed = {pod.key: err for pod, err in bind_many(placed)}
            ok = 0
            items = []
            for pod, dest in placed:
                if pod.key in failed:
                    _record_bind_failure(failed[pod.key])
                    cache.forget_pod(pod)
                    # Surface the real error: a CAS conflict and a
                    # network failure require different operator action.
                    self._handle_failure(
                        pod, "FailedScheduling",
                        f"Binding rejected: {failed[pod.key]}")
                else:
                    ok += 1
                    items.append((pod.key, "Normal", "Scheduled",
                                  f"Successfully assigned {pod.name} to {dest}"))
            recorder.eventf_many(items)
        else:
            ok = 0
            for pod, dest in placed:
                try:
                    self.config.binder.bind(pod, dest)
                except Exception as err:  # noqa: BLE001 — bind errors requeue
                    _record_bind_failure(err)
                    cache.forget_pod(pod)
                    self._handle_failure(pod, "FailedScheduling",
                                         f"Binding rejected: {err}")
                    continue
                ok += 1
                recorder.eventf(
                    pod.key, "Normal", "Scheduled",
                    f"Successfully assigned {pod.name} to {dest}")
        done = time.perf_counter()
        self.config.metrics.binding_latency.observe_many(
            (done - bind_start) * 1e6 / max(len(placed), 1), ok)
        self.config.metrics.e2e_scheduling_latency.observe_many(
            (done - start) * 1e6, ok)

    def _handle_failure(self, pod: api.Pod, reason: str, message: str) -> None:
        """Event + condition update + backoff requeue (factory.go:512-556)."""
        log.debug("scheduling failure for %s: %s", pod.key, message)
        self.config.recorder.eventf(pod.key, "Warning", reason, message)
        if self.config.condition_updater is not None:
            self.config.condition_updater(pod, "Unschedulable", message)
        backoff_s = self.backoff.get_backoff(pod.key)
        with self._requeue_cv:
            self._requeue_seq += 1
            heapq.heappush(self._requeue_heap,
                           (time.monotonic() + backoff_s,
                            self._requeue_seq, pod))
            if self._requeue_thread is None or \
                    not self._requeue_thread.is_alive():
                self._requeue_thread = threading.Thread(
                    target=self._requeue_worker, daemon=True,
                    name="backoff-requeue")
                self._requeue_thread.start()
            self._requeue_cv.notify()

    def _requeue_worker(self) -> None:
        while not self._stop.is_set():
            with self._requeue_cv:
                while not self._requeue_heap and not self._stop.is_set():
                    self._requeue_cv.wait(timeout=0.5)
                if self._stop.is_set():
                    return
                due, _, pod = self._requeue_heap[0]
                delay = due - time.monotonic()
                if delay > 0:
                    self._requeue_cv.wait(timeout=min(delay, 0.5))
                    continue
                heapq.heappop(self._requeue_heap)
            pod.node_name = ""
            self.queue.add(pod)
