"""Config factory: wire the scheduler daemon to an apiserver
(factory.go:100-227, 387-469) — the standalone watch -> solve -> bind loop.

Three reflectors feed the daemon exactly as the reference's informers do:

* unassigned, non-terminated pods (field selector ``spec.nodeName == ""``,
  factory.go:466-469) -> the scheduling FIFO;
* assigned pods -> the scheduler cache (confirming assumed pods);
* nodes -> the scheduler cache;

plus services/PV/PVC listers kept fresh from the same store, the memstore
CAS binder, and the 1s assumed-pod TTL sweep (cache.go:31).
"""

from __future__ import annotations

import threading
from typing import Optional

from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.policy import Policy
from kubernetes_tpu.apiserver.memstore import ConflictError, MemStore
from kubernetes_tpu.cache.scheduler_cache import CLEANUP_PERIOD
from kubernetes_tpu.client.reflector import Reflector
from kubernetes_tpu.engine.generic_scheduler import GenericScheduler, Listers
from kubernetes_tpu.scheduler.scheduler import Scheduler, SchedulerConfig


class MemStoreBinder:
    """Binder against the in-memory apiserver's binding subresource."""

    def __init__(self, store: MemStore):
        self.store = store

    def bind(self, pod: api.Pod, node_name: str) -> None:
        self.store.bind(pod.namespace, pod.name, node_name)


def _is_terminated(obj: dict) -> bool:
    phase = (obj.get("status") or {}).get("phase", "")
    return phase in ("Succeeded", "Failed")


def _unassigned(obj: dict) -> bool:
    return not (obj.get("spec") or {}).get("nodeName") and \
        not _is_terminated(obj)


def _assigned(obj: dict) -> bool:
    return bool((obj.get("spec") or {}).get("nodeName")) and \
        not _is_terminated(obj)


class ConfigFactory:
    """NewConfigFactory + CreateFromProvider/CreateFromConfig
    (factory.go:100, :251-344)."""

    def __init__(self, store: MemStore, policy: Optional[Policy] = None,
                 scheduler_name: str = api.DEFAULT_SCHEDULER_NAME,
                 batched: bool = True):
        self.store = store
        self.listers = Listers()
        self.algorithm = GenericScheduler(policy=policy, listers=self.listers)
        self.daemon = Scheduler(SchedulerConfig(
            algorithm=self.algorithm, binder=MemStoreBinder(store),
            scheduler_name=scheduler_name, async_bind=False,
            condition_updater=self._update_pod_condition))
        self.batched = batched
        self._reflectors: list[Reflector] = []
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()

    # -- reflector handlers (factory.go:128-227) -------------------------

    def _on_pending_pod(self, etype: str, obj: dict) -> None:
        pod = api.pod_from_json(obj)
        if etype == "DELETED" or pod.node_name:
            self.daemon.queue.delete(pod.key)
            return
        self.daemon.enqueue(pod)

    def _on_assigned_pod(self, etype: str, obj: dict) -> None:
        """addPodToCache / updatePodInCache / deletePodFromCache
        (factory.go:154-200); ADDED confirms an assumed pod."""
        pod = api.pod_from_json(obj)
        cache = self.algorithm.cache
        if etype == "DELETED":
            cache.remove_pod(pod)
        elif etype == "ADDED":
            cache.add_pod(pod)
        else:
            cache.update_pod(pod, pod)

    def _on_node(self, etype: str, obj: dict) -> None:
        node = api.node_from_json(obj)
        cache = self.algorithm.cache
        if etype == "DELETED":
            cache.remove_node(node.name)
        else:
            cache.add_node(node) if etype == "ADDED" else \
                cache.update_node(node)

    def _on_service(self, etype: str, obj: dict) -> None:
        meta = obj.get("metadata") or {}
        svc = api.Service(name=meta.get("name", ""),
                          namespace=meta.get("namespace", "default"),
                          selector=dict((obj.get("spec") or {})
                                        .get("selector") or {}))
        self.listers.services = [
            s for s in self.listers.services
            if (s.namespace, s.name) != (svc.namespace, svc.name)]
        if etype != "DELETED":
            self.listers.services.append(svc)

    def _update_pod_condition(self, pod: api.Pod, reason: str,
                              message: str) -> None:
        """podConditionUpdater (factory.go:589-600): PodScheduled=False."""
        key = pod.key
        obj = self.store.get("pods", key)
        if obj is None:
            return
        conds = obj.setdefault("status", {}).setdefault("conditions", [])
        conds[:] = [c for c in conds if c.get("type") != "PodScheduled"]
        conds.append({"type": "PodScheduled", "status": "False",
                      "reason": reason, "message": message})
        try:
            self.store.update("pods", obj)
        except (KeyError, ConflictError):
            pass

    # -- lifecycle -------------------------------------------------------

    def run(self) -> "ConfigFactory":
        """f.Run (factory.go:387-416) + scheduler.Run."""
        specs = [
            ("pods", self._on_pending_pod, _unassigned),
            ("pods", self._on_assigned_pod, _assigned),
            ("nodes", self._on_node, None),
            ("services", self._on_service, None),
        ]
        for kind, handler, selector in specs:
            r = Reflector(self.store, kind, handler, selector)
            self._reflectors.append(r)
            self._threads.append(r.run())
        for r in self._reflectors:
            r.wait_for_sync()
        self._threads.append(self.daemon.run(batched=self.batched))

        def ttl_sweep():  # cleanupAssumedPods (cache.go:309-330)
            while not self._stop.wait(CLEANUP_PERIOD):
                self.algorithm.cache.cleanup_expired()
        t = threading.Thread(target=ttl_sweep, daemon=True,
                             name="assume-ttl-sweep")
        t.start()
        self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()
        for r in self._reflectors:
            r.stop()
        self.daemon.stop()
