"""Config factory: wire the scheduler daemon to an apiserver
(factory.go:100-227, 387-469) — the standalone watch -> solve -> bind loop.

Two FIELDED pod reflectors and one node reflector feed the daemon,
exactly the reference's informer layout (factory.go:128-149, 466-469):

* ``spec.nodeName=`` (server-side field selector) -> the scheduling
  FIFO; a pod leaving the set on bind arrives as a synthesized DELETED;
* ``spec.nodeName!=`` -> the scheduler cache (confirming assumed pods);
* nodes -> the scheduler cache;

plus services/PV/PVC listers kept fresh from the same source, the CAS
binder, and the 1s assumed-pod TTL sweep (cache.go:31).

The apiserver source is either an in-process ``MemStore`` (integration/perf
rigs, the reference's in-process master) or an HTTP base URL — the real
process boundary: every list/watch/bind/status write then goes over the
wire through a QPS/Burst rate-limited client (factory.go:77-91)."""

from __future__ import annotations

import threading
from typing import Optional, Union

from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.policy import Policy
from kubernetes_tpu.apiserver.memstore import MemStore
from kubernetes_tpu.cache.scheduler_cache import CLEANUP_PERIOD
from kubernetes_tpu.client.http import APIClient
from kubernetes_tpu.client.reflector import Reflector
from kubernetes_tpu.engine.generic_scheduler import GenericScheduler, Listers
from kubernetes_tpu.scheduler.binder import APIClientBinder
from kubernetes_tpu.scheduler.scheduler import Scheduler, SchedulerConfig
from kubernetes_tpu.utils import threadreg
from kubernetes_tpu.utils.events import EventRecorder
from kubernetes_tpu.utils.logging import get_logger

log = get_logger("factory")


class MemStoreBinder:
    """Binder against the in-memory apiserver's binding subresource."""

    def __init__(self, store: MemStore):
        self.store = store

    def bind(self, pod: api.Pod, node_name: str) -> None:
        self.store.bind(pod.namespace, pod.name, node_name)

    def evict(self, pod: api.Pod) -> None:
        """Preemption eviction: delete the victim pod from the store."""
        try:
            self.store.delete("pods", pod.key)
        except KeyError:
            pass  # already gone (watch raced the eviction)

    def unbind(self, pod: api.Pod) -> None:
        """Defrag eviction-to-pending (scheduler/defrag.py): clear
        spec.nodeName under CAS — the pod stays alive and the
        unassigned reflector's set-transition requeues it."""
        obj = self.store.get("pods", pod.key)
        if obj is None:
            raise KeyError(f"pods {pod.key} not found")
        obj.setdefault("spec", {})["nodeName"] = ""
        self.store.update("pods", obj,
                          expected_rv=(obj.get("metadata") or {})
                          .get("resourceVersion"))


def make_event_sink(source: Union[MemStore, APIClient]):
    """An EventRecorder sink that posts Events as API objects
    (pkg/client/record event.go: events are created on the apiserver)."""
    counter = [0]

    def _event_json(ev) -> dict:
        counter[0] += 1
        ns, _, name = ev.object_key.partition("/")
        return {
            "metadata": {"name": f"{name or ns}.{counter[0]}",
                         "namespace": ns if name else "default"},
            "involvedObject": {"kind": "Pod", "namespace": ns,
                               "name": name or ns},
            "type": ev.event_type, "reason": ev.reason,
            "message": ev.message}

    def sink(ev) -> None:
        try:
            source.create("events", _event_json(ev))
        except Exception:  # noqa: BLE001 — event loss is non-fatal
            pass
    sink.event_json = _event_json
    return sink


def make_event_batch_sink(client: APIClient, qps: float, burst: int):
    """Batch wire sink: one POST per drained queue (broadcaster-style
    drop beyond the rate bucket, then a single batch create)."""
    from kubernetes_tpu.utils.flowcontrol import TokenBucketRateLimiter
    single = make_event_sink(client)
    bucket = TokenBucketRateLimiter(qps, burst)

    def batch_sink(evs) -> None:
        allowed = [ev for ev in evs if bucket.try_accept()]
        if not allowed:
            return
        client.create_list("events",
                           [single.event_json(ev) for ev in allowed])
    return batch_sink


def _is_terminated(obj: dict) -> bool:
    phase = (obj.get("status") or {}).get("phase", "")
    return phase in ("Succeeded", "Failed")


class ConfigFactory:
    """NewConfigFactory + CreateFromProvider/CreateFromConfig
    (factory.go:100, :251-344).

    ``store`` is the apiserver source: a MemStore (in-process) or an HTTP
    base URL string / APIClient (separate-process control plane).  QPS and
    burst rate-limit the main client's verbs; events ride a second,
    unthrottled client gated by a drop-on-saturation bucket, the
    broadcaster's behavior under pressure (record/event.go)."""

    def __init__(self, store: Union[MemStore, APIClient, str],
                 policy: Optional[Policy] = None,
                 scheduler_name: str = api.DEFAULT_SCHEDULER_NAME,
                 batched: bool = True,
                 qps: float = 50.0, burst: int = 100, token: str = "",
                 tls=None, ha_shards: Optional[int] = None,
                 incarnation: str = "", solver_service=None,
                 tenant: str = ""):
        if isinstance(store, str):
            store = APIClient(store, qps=qps, burst=burst, token=token,
                              tls=tls)
        self.store = store
        self.listers = Listers()
        if solver_service is not None:
            # Solver-service CLIENT mode: this daemon owns no device —
            # its solve verbs submit to a shared SolverService (or a
            # SolverClient speaking the HTTP /solve surface), tagged
            # with this daemon's tenant; cache feeding, assume/bind,
            # and failure handling stay local (tenancy/service.py).
            from kubernetes_tpu.tenancy.service import ServiceEngine
            self.algorithm = ServiceEngine(solver_service, tenant=tenant,
                                           listers=self.listers)
        else:
            self.algorithm = GenericScheduler(policy=policy,
                                              listers=self.listers)
        if isinstance(store, APIClient):
            binder = APIClientBinder(store)
            events_client = store.clone(qps=0)
            from kubernetes_tpu.utils.events import async_sink
            # The batch sink carries its own rate bucket (broadcaster-
            # style drop beyond qps/burst, then one batch POST per drain).
            recorder = EventRecorder(sink=async_sink(
                None, batch_sink=make_event_batch_sink(events_client, qps,
                                                       burst)))
        else:
            binder = MemStoreBinder(store)
            recorder = EventRecorder(sink=None)
        self.daemon = Scheduler(SchedulerConfig(
            algorithm=self.algorithm, binder=binder,
            # Async binds, like the reference's per-bind goroutine
            # (scheduler.go:122-153): over a real wire a chunk's ~4k bind
            # POSTs take seconds, and the device must be scanning the next
            # chunk meanwhile, not idling behind them.
            scheduler_name=scheduler_name, async_bind=True,
            recorder=recorder,
            condition_updater=self._update_pod_condition))
        self.batched = batched
        self._reflectors: list[Reflector] = []
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()
        # Startup reconciliation report (scheduler/recovery.py), served
        # on /debug/vars; None until run() completes the pass.
        self.last_recovery: Optional[dict] = None
        self.verifier = None
        # Continuous rebalancing loop (scheduler/defrag.py); constructed
        # by run() behind KT_DEFRAG.
        self.defrag = None
        # Decision-latency SLO burn monitor (scheduler/slo.py); started
        # by run() at KT_SLO_PERIOD cadence, reported on /debug/vars.
        from kubernetes_tpu.scheduler.slo import SLOMonitor
        self.slo = SLOMonitor()
        # Active-active HA (scheduler/shards.py): KT_HA_SHARDS > 0 runs
        # this incarnation as one of several over the same apiserver,
        # scheduling only pods in shards whose lease it holds.  0 (the
        # default) is the single-scheduler mode, byte-for-byte the old
        # behavior.
        import uuid

        from kubernetes_tpu.utils import knobs
        if ha_shards is None:
            ha_shards = knobs.get_int("KT_HA_SHARDS")
        self.shards = None
        # Bounded log of shard-takeover reconciles (served on
        # /debug/vars next to lastRecovery).
        self.shard_recoveries: list[dict] = []
        # Multi-tenant solver service (KT_TENANTS, tenancy/): this
        # daemon's engine becomes a shared service — the pipeline packs
        # cross-tenant batches under weighted fairness, attributes
        # faults per tenant (per-tenant breakers, host fallback), and
        # the bind path records {tenant=}-labeled SLO metrics.  Unset =
        # single-owner engine, byte-for-byte the old behavior.
        from kubernetes_tpu import tenancy as tenancy_mod
        self.tenancy = None
        if solver_service is None and tenancy_mod.enabled():
            from kubernetes_tpu.tenancy.service import SolverService
            self.tenancy = SolverService(
                engine=self.algorithm,
                ladder_fn=self.daemon.effective_ladder,
                urgent_s_fn=lambda:
                    self.daemon.pipeline.former.deadline_s)
            self.daemon.tenancy_service = self.tenancy
        if ha_shards > 0:
            from kubernetes_tpu.scheduler.shards import ShardManager
            incarnation = incarnation or \
                knobs.get("KT_INCARNATION") or \
                f"scheduler-{uuid.uuid4().hex[:8]}"
            lease_s = knobs.get_float("KT_HA_LEASE_S")
            # Lease clients must not compete with the drain loop for the
            # main client's rate budget: a QPS-starved renew loses a
            # healthy incarnation its shards mid-storm.
            lease_client = store.clone(qps=0) \
                if isinstance(store, APIClient) else store
            self.shards = ShardManager(
                lease_client, incarnation=incarnation,
                n_shards=ha_shards,
                lease_duration=lease_s,
                renew_deadline=knobs.get_float(
                    "KT_HA_RENEW_S", default=lease_s * 2 / 3),
                retry_period=knobs.get_float(
                    "KT_HA_RETRY_S", default=lease_s / 6),
                on_acquired=self._on_shard_acquired,
                on_lost=self._on_shard_lost)
            self.daemon.owns_pod = self.shards.owns_pod

    # -- reflector handlers (factory.go:128-227) -------------------------

    def _on_assigned_pod(self, etype: str, obj: dict,
                         pod: Optional[api.Pod] = None) -> None:
        """addPodToCache / updatePodInCache / deletePodFromCache
        (factory.go:154-200); ADDED confirms an assumed pod."""
        pod = pod if pod is not None else api.pod_from_json(obj)
        cache = self.algorithm.cache
        if etype == "DELETED":
            cache.remove_pod(pod)
        elif etype == "ADDED":
            cache.add_pod(pod)
        else:
            cache.update_pod(pod, pod)

    def _on_unassigned_pod(self, etype: str, obj: dict) -> None:
        """The queue-side FIELDED informer (factory.go:466-469: the
        reference's unassigned informer lists/watches
        ``spec.nodeName=``).  The server applies set-transition
        semantics, so a pod leaving the set on bind arrives here as
        DELETED — assigned-pod churn never crosses this stream's wire
        (VERDICT r4 missing #4)."""
        meta = obj.get("metadata") or {}
        if etype == "DELETED":
            # Deleted outright, or bound and thus out of the unassigned
            # set: either way it no longer belongs on the queue.
            ns = meta.get("namespace")
            key = f"{ns}/{meta.get('name')}" if ns else meta.get("name", "")
            self.daemon.queue.delete(key)
            return
        pod = api.pod_from_json(obj)
        if _is_terminated(obj):
            self.daemon.queue.delete(pod.key)
            return
        self.daemon.enqueue(pod)

    def _on_assigned_pod_watch(self, etype: str, obj: dict) -> None:
        """The cache-side FIELDED informer (``spec.nodeName!=``,
        factory.go:128-149): a freshly bound pod enters this set as
        ADDED and confirms its assumed cache entry."""
        meta = obj.get("metadata") or {}
        node = (obj.get("spec") or {}).get("nodeName") or ""
        if etype != "DELETED" and node and not _is_terminated(obj):
            # Bind-confirmation fast path: at density rates the confirm
            # stream is one event per scheduled pod, and the full
            # parse + detach/attach per event is reflector-thread GIL
            # time stolen from the solve.
            ns = meta.get("namespace")
            key = f"{ns}/{meta.get('name')}" if ns else meta.get("name", "")
            if self.algorithm.cache.confirm_assumed(key, node):
                return
        pod = api.pod_from_json(obj)
        if etype == "DELETED" or _is_terminated(obj):
            # A set-transition DELETED (the pod left the bound set on an
            # UNBIND — the defrag evict-to-pending path) carries the NEW
            # object, whose nodeName is already empty: remove whatever
            # the cache actually tracks under the key, not the carried
            # object, or the eviction leaves a ghost entry behind.
            cached = self.algorithm.cache.get_pod(pod.key)
            if cached is not None:
                self.algorithm.cache.remove_pod(cached)
            elif pod.node_name:
                self.algorithm.cache.remove_pod(pod)
            return
        self._on_assigned_pod(etype, obj, pod=pod)

    def _on_node(self, etype: str, obj: dict) -> None:
        node = api.node_from_json(obj)
        cache = self.algorithm.cache
        if etype == "DELETED":
            cache.remove_node(node.name)
        else:
            cache.add_node(node) if etype == "ADDED" else \
                cache.update_node(node)

    def _on_service(self, etype: str, obj: dict) -> None:
        meta = obj.get("metadata") or {}
        svc = api.Service(name=meta.get("name", ""),
                          namespace=meta.get("namespace", "default"),
                          selector=dict((obj.get("spec") or {})
                                        .get("selector") or {}))
        self.listers.services = [
            s for s in self.listers.services
            if (s.namespace, s.name) != (svc.namespace, svc.name)]
        if etype != "DELETED":
            self.listers.services.append(svc)

    # The remaining lister feeds (factory.go:387-416 caches PVs, PVCs,
    # controllers, and replica sets with dedicated reflectors): replace-
    # by-identity into the Listers the engine's volume/spread predicates
    # and priorities read.

    @staticmethod
    def _replace(items: list, obj, ident) -> list:
        return [x for x in items if ident(x) != ident(obj)]

    def _on_pv(self, etype: str, obj: dict) -> None:
        pv = api.pv_from_json(obj)
        self.listers.pvs = self._replace(self.listers.pvs, pv,
                                         lambda x: x.name)
        if etype != "DELETED":
            self.listers.pvs.append(pv)

    def _on_pvc(self, etype: str, obj: dict) -> None:
        pvc = api.pvc_from_json(obj)
        self.listers.pvcs = self._replace(
            self.listers.pvcs, pvc, lambda x: (x.namespace, x.name))
        if etype != "DELETED":
            self.listers.pvcs.append(pvc)

    def _on_rc(self, etype: str, obj: dict) -> None:
        rc = api.rc_from_json(obj)
        self.listers.controllers = self._replace(
            self.listers.controllers, rc, lambda x: (x.namespace, x.name))
        if etype != "DELETED":
            self.listers.controllers.append(rc)

    def _on_rs(self, etype: str, obj: dict) -> None:
        rs = api.rs_from_json(obj)
        self.listers.replica_sets = self._replace(
            self.listers.replica_sets, rs, lambda x: (x.namespace, x.name))
        if etype != "DELETED":
            self.listers.replica_sets.append(rs)

    def _update_pod_condition(self, pod: api.Pod, reason: str,
                              message: str) -> None:
        """podConditionUpdater (factory.go:589-600): PodScheduled=False."""
        key = pod.key
        try:
            obj = self.store.get("pods", key)
        except Exception:  # noqa: BLE001 — best-effort: an unreachable
            return         # apiserver must not kill the error path
        if obj is None:
            return
        conds = obj.setdefault("status", {}).setdefault("conditions", [])
        conds[:] = [c for c in conds if c.get("type") != "PodScheduled"]
        conds.append({"type": "PodScheduled", "status": "False",
                      "reason": reason, "message": message})
        try:
            if isinstance(self.store, MemStore):
                # CAS on the version this update read: a condition write
                # racing a concurrent bind (e.g. a replacement scheduler
                # after this one was killed) must lose the CAS rather
                # than clobber the bound spec.  Over HTTP the PUT handler
                # applies the same precondition from the body's
                # resourceVersion.
                self.store.update(
                    "pods", obj,
                    expected_rv=(obj.get("metadata") or {})
                    .get("resourceVersion"))
            else:
                self.store.update("pods", obj)
        except Exception:  # noqa: BLE001 — condition update is best-effort
            pass

    # -- active-active HA (scheduler/shards.py) ---------------------------

    def _shard_ns_test(self, shard: int):
        from kubernetes_tpu.scheduler.shards import shard_of
        n = self.shards.n_shards
        return lambda ns: shard_of(ns, n) == shard

    def _on_shard_acquired(self, shard: int, handoff: bool) -> None:
        """Takeover reconcile BEFORE draining the shard: relist, adopt
        the dead incarnation's landed binds, requeue its orphans (see
        recovery.reconcile_shard for the safety argument).  Runs on the
        shard manager's callback thread.  Retried on failure — a chaos
        cut (or a flaky apiserver) killing THIS relist would otherwise
        strand the shard's backlog until the periodic sweep; the sweep
        is the backstop, not the plan."""
        import time as _time

        from kubernetes_tpu.scheduler import recovery
        last_err = None
        for attempt in range(3):
            try:
                report = recovery.reconcile_shard(
                    self.daemon, self.store, shard,
                    self._shard_ns_test(shard),
                    scheduler_name=self.daemon.config.scheduler_name,
                    # Assumes minted since we won this lease are the
                    # live drain loop (the queue gate opened with the
                    # ownership flip, before this callback ran) — only
                    # pre-acquisition leftovers are stale.  The cutoff
                    # and the clock it is compared under must share a
                    # base, so both come from the shard manager.
                    assumed_before=self.shards.acquired_at(shard),
                    now=self.shards.now)
                break
            except Exception as err:  # noqa: BLE001 — retry the relist
                last_err = err
                _time.sleep(0.2 * (attempt + 1))
        else:
            log.warning("shard %d takeover reconcile failed after "
                        "retries (%s); the periodic ownership sweep "
                        "will converge it", shard, last_err)
            return
        report["handoff"] = handoff
        self.shard_recoveries.append(report)
        del self.shard_recoveries[:-32]

    def _shard_sweep_loop(self, period: float,
                          stale_assume_s: float) -> None:
        """The convergence backstop: periodically re-derive every OWNED
        shard's backlog from one relist.  Any pod a race dropped — an
        event delivered while the shard was unowned, a takeover relist
        lost to chaos, a backoff requeue shed mid-handoff — is picked
        up here at the latest; the enqueue path dedupes (a pod already
        queued, bound, or freshly assumed is skipped), so the sweep is
        idempotent."""
        from kubernetes_tpu.scheduler import recovery
        while not self._stop.wait(period):
            if self.shards is None or not self.shards.owned():
                continue
            try:
                report = recovery.reconcile_shard(
                    self.daemon, self.store, -1,
                    self.shards.owns_namespace,
                    scheduler_name=self.daemon.config.scheduler_name,
                    # Shards we are actively draining: a YOUNG assume
                    # is a live in-flight bind (leave it alone); one
                    # older than any healthy bind round-trip is a leak
                    # to repair (forget + requeue — the CAS keeps a
                    # still-racing duplicate safe).
                    min_assume_age_s=stale_assume_s)
                if report["requeued"] or report["expired"]:
                    log.info("ownership sweep repaired state: %s",
                             report)
            except Exception:  # noqa: BLE001 — next sweep retries
                log.exception("ownership sweep failed; retrying next "
                              "period")

    def _on_shard_lost(self, shard: int) -> None:
        """Shed a lost shard: drop its queued pods (the new owner's
        takeover relist covers them) and forget our optimistic assumes
        there, releasing the phantom capacity.  In-flight binds are NOT
        chased — the apiserver CAS settles those races."""
        in_shard = self._shard_ns_test(shard)
        dropped = self.daemon.queue.delete_matching(
            lambda pod: in_shard(pod.namespace))
        forgotten = self.algorithm.cache.forget_pods_matching(
            lambda pod: in_shard(pod.namespace))
        if dropped or forgotten:
            log.info("shard %d lost: dropped %d queued pod(s), forgot "
                     "%d assume(s)", shard, dropped, len(forgotten))

    # -- lifecycle -------------------------------------------------------

    def run(self) -> "ConfigFactory":
        """f.Run (factory.go:387-416) + scheduler.Run."""
        specs = [
            # The reference's two fielded pod informers (factory.go:
            # 128-149, 466-469): the queue side never sees assigned-pod
            # churn, the cache side never sees pending churn — filtered
            # SERVER-side on both list and watch.
            ("pods", self._on_unassigned_pod, None, "spec.nodeName="),
            ("pods", self._on_assigned_pod_watch, None, "spec.nodeName!="),
            ("nodes", self._on_node, None, ""),
            ("services", self._on_service, None, ""),
            ("persistentvolumes", self._on_pv, None, ""),
            ("persistentvolumeclaims", self._on_pvc, None, ""),
            ("replicationcontrollers", self._on_rc, None, ""),
            ("replicasets", self._on_rs, None, ""),
        ]
        for kind, handler, selector, field_selector in specs:
            r = Reflector(self.store, kind, handler, selector,
                          field_selector=field_selector)
            self._reflectors.append(r)
            self._threads.append(r.run())
        for r in self._reflectors:
            r.wait_for_sync()
        log.info("reflectors synced (%d nodes cached); starting loop",
                 len(self.algorithm.cache.nodes()))
        from kubernetes_tpu.utils import knobs
        if knobs.get_bool("KT_PREWARM"):
            # Trace the bucket ladder before the queue opens (opt-in:
            # interactive rigs keep their startup latency; the perf rigs
            # and production daemons set KT_PREWARM=1 and, with the
            # persistent compile cache populated, pay near-zero here).
            # With tenancy on, the warm batches span the tenant
            # namespaces: the selector-spread group axis is
            # per-namespace, so the FIRST cross-tenant packed batch
            # would otherwise ratchet that capacity past what the
            # single-namespace warmup traced — a compile stall on
            # exactly the first drain the service exists to share.
            samples = None
            if self.tenancy is not None:
                samples = [api.Pod(name=f"__warm-tenant-{i}",
                                   namespace=t)
                           for i, t in enumerate(self.tenancy.tenants)]
            self.daemon.prewarm(sample_pods=samples)
        if knobs.get_bool("KT_RECOVERY"):
            # Crash-safe restart: reconcile cache + queue against one
            # apiserver relist (re-adopt bound pods, requeue orphans,
            # expire stale assumes, re-seed the resident tensors) BEFORE
            # the drain loop resumes — see scheduler/recovery.py.
            from kubernetes_tpu.scheduler import recovery
            self.last_recovery = recovery.reconcile(
                self.daemon, self.store,
                scheduler_name=self.daemon.config.scheduler_name)
        slo_period = knobs.get_float("KT_SLO_PERIOD")
        if slo_period > 0:
            # Multi-window SLO burn: one cheap bucket read per tick
            # feeding scheduler_slo_burn_rate{window=} and the budget
            # gauge (scheduler/slo.py).
            self._threads.append(self.slo.run(period=slo_period))
        verify_period = knobs.get_float("KT_VERIFY_PERIOD")
        if verify_period > 0:
            # Resident-state invariant checker (cache/verifier.py): a
            # low-frequency background cross-check of cache aggregates vs
            # the device-resident tensors vs apiserver truth, self-healing
            # by full re-snapshot on mismatch.
            from kubernetes_tpu.cache.verifier import Verifier
            self.verifier = Verifier(
                self.algorithm.cache, resident=self.algorithm.resident,
                truth=lambda: self.store.list("pods")[0])
            self._threads.append(self.verifier.run(period=verify_period))
        if knobs.get_bool("KT_DEFRAG"):
            # Always-on defragmentation (scheduler/defrag.py): dry joint
            # solves over the bound state propose bounded, PDB-vetoed
            # migration batches.  With tenancy on the probe rides the
            # SolverService's low-priority background lane so defrag
            # never steals device time from live drains; without it the
            # controller's host-side feasibility walk stands in.
            from kubernetes_tpu.scheduler.defrag import DefragController
            probe = None
            if self.tenancy is not None:
                probe = lambda pods: self.tenancy.submit_background(  # noqa: E731
                    pods, joint=True)
            self.defrag = DefragController(self.daemon, self.store,
                                           probe=probe,
                                           verifier=self.verifier)
            self._threads.append(self.defrag.run())
        if self.shards is not None:
            # Shard leases start AFTER reflectors sync and the full
            # startup reconcile: each acquisition's takeover relist then
            # lands on a warm cache, and the drain loop below only ever
            # sees pods in shards this incarnation actually holds.
            self.shards.run()
            self._threads.extend(self.shards.threads)
            sweep_s = knobs.get_float("KT_HA_SWEEP_S")
            stale_assume_s = knobs.get_float("KT_HA_STALE_ASSUME_S")
            if sweep_s > 0:
                self._threads.append(threadreg.spawn(
                    self._shard_sweep_loop,
                    args=(sweep_s, stale_assume_s),
                    name="shard-ownership-sweep"))
        self._threads.append(self.daemon.run(batched=self.batched))

        def ttl_sweep():  # cleanupAssumedPods (cache.go:309-330)
            while not self._stop.wait(CLEANUP_PERIOD):
                self.algorithm.cache.cleanup_expired()
        self._threads.append(threadreg.spawn(ttl_sweep,
                                             name="assume-ttl-sweep"))
        return self

    def stop(self) -> None:
        self._stop.set()
        if self.shards is not None:
            # Release the leases FIRST so peers take over within a
            # retry period instead of waiting out the lease duration.
            self.shards.stop()
        for r in self._reflectors:
            r.stop()
        if self.verifier is not None:
            self.verifier.stop()
        if self.defrag is not None:
            self.defrag.stop()
        self.slo.stop()
        self.daemon.stop()
        sink = getattr(self.daemon.config.recorder, "_sink", None)
        close = getattr(sink, "close", None)
        if close is not None:
            close()

    def abandon(self) -> None:
        """SIGKILL-style teardown for the restart scenarios: reflectors
        and the drain loop stop, but NOTHING is drained or joined — the
        pipeline's in-flight window (solved-but-uncommitted chunks,
        dispatched binds, pending requeues) is abandoned exactly as a
        kill -9 would leave it.  The next incarnation's startup
        reconciliation cleans up (scheduler/recovery.py)."""
        self._stop.set()
        if self.shards is not None:
            # No lease release: a kill -9 leaves the shard leases to
            # expire on their own — the survivors' takeover clock.
            self.shards.abandon()
        for r in self._reflectors:
            r.stop()
        if self.verifier is not None:
            self.verifier.stop()
        if self.defrag is not None:
            # Thread stops, but in-flight migration intents stay on the
            # apiserver exactly as a kill -9 leaves them — the next
            # incarnation's reconcile requeues or clears them.
            self.defrag.stop()
        self.slo.stop()
        self.daemon.abandon()
