"""Per-pod exponential retry backoff (factory.go:602-688): 1s initial,
doubling to a 60s cap; entries garbage-collected after max-duration idle."""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable


@dataclass
class _Entry:
    backoff: float
    last_update: float


@dataclass
class PodBackoff:
    default_duration: float = 1.0   # factory.go:520
    max_duration: float = 60.0
    now: Callable[[], float] = time.monotonic
    _entries: dict[str, _Entry] = field(default_factory=dict)
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def get_backoff(self, pod_key: str) -> float:
        """Current backoff for the pod, doubling for next time
        (getEntry + getBackoff, factory.go:667-682)."""
        with self._lock:
            entry = self._entries.get(pod_key)
            if entry is None:
                entry = _Entry(self.default_duration, self.now())
                self._entries[pod_key] = entry
            entry.last_update = self.now()
            duration = entry.backoff
            entry.backoff = min(duration * 2, self.max_duration)
            return duration

    def gc(self) -> None:
        """Drop entries idle beyond max_duration (factory.go:684-688)."""
        with self._lock:
            now = self.now()
            stale = [k for k, e in self._entries.items()
                     if now - e.last_update > self.max_duration]
            for k in stale:
                del self._entries[k]
