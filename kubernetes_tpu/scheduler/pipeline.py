"""The drain pipeline: ONE form -> solve -> commit path for every drain.

Before this module the daemon carried three separately-instrumented
drain control flows (one-shot ``schedule_batch``, the overlapped
streamed drain, and the joint solve) plus an ad-hoc arrival-coalescing
linger, each with its own stage spans and crash handling.
``DrainPipeline`` unifies them: the daemon's ``schedule_pending`` is now
a single call into ``drain()``, and everything between the queue and the
assume/bind commit — batch formation policy (scheduler/batchformer.py),
the degraded-mode cap, mode routing (gang / joint / streamed /
one-shot), the overlapped solve/commit worker, the batch root span and
stage instrumentation, and the crash-requeue handler — lives behind this
one interface.  Batch-formation policy is therefore pluggable (swap the
former) and instrumented once.

The three modes that remain are SOLVE strategies, not control flows:

* ``stream``  — fixed-shape chunks through ``schedule_batch_stream``,
  with the commit worker overlapping chunk N's device scan against
  chunk N-1's readback/assume/bind (``pipeline_window`` in flight).
* ``oneshot`` — one ``schedule_batch`` solve; gang batches take this
  path padded to a warm bucket (all-or-nothing needs one assignment
  vector), as do extender-constrained and above-pad-limit drains.
* ``joint``   — ``schedule_batch(joint=True)``: prices couple every pod,
  so the whole queue solves at once.

Commit-side semantics (assume-before-bind per pod, flight-recorder
feeds, preemption, failure requeue) stay on the daemon — the pipeline
calls back into it, so the state machine the rest of the repo pins is
byte-for-byte the old one.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from kubernetes_tpu.engine import guard as guard_mod
from kubernetes_tpu.engine.guard import DeviceFault
from kubernetes_tpu.scheduler.batchformer import BatchFormer, FormedBatch
from kubernetes_tpu.utils import trace as trace_mod
from kubernetes_tpu.utils.logging import get_logger
from kubernetes_tpu.utils.trace import Trace

log = get_logger("pipeline")


class DrainPipeline:
    """One drain: form a batch, route it to a solve mode, commit it.

    ``daemon`` is the owning ``scheduler.Scheduler``; the pipeline reads
    its routing knobs (``STREAM_THRESHOLD``, ``stream_chunk``,
    ``pipeline_window``...) live so tests and rigs that retune the
    daemon keep working unchanged."""

    def __init__(self, daemon):
        self.daemon = daemon
        self.former = BatchFormer(
            queue=daemon.queue,
            ladder_fn=daemon.effective_ladder,
            chunk_fn=daemon.stream_chunk_size,
            cap_fn=self._former_cap)
        # The overlapped commit worker (one thread: chunks commit in
        # solve order); created lazily on the first windowed drain.
        self._commit_pool = None
        # The device guard bisects OOM'd batches down the daemon's
        # pre-warmed bucket ladder — it must read the SAME ladder the
        # prewarm traces, or recovery would mint unwarmed shapes.
        guard = getattr(daemon.config.algorithm, "guard", None)
        if guard is not None:
            guard.ladder_fn = daemon.effective_ladder

    def _former_cap(self) -> int:
        """The degraded drain cap.  With tenancy on the former over-pops
        (4x the solve cap) so the cross-tenant packer sees past the
        FIFO head — a flood tenant's pods dominate the queue front, and
        fair selection needs candidates from the quiet tenants behind
        them; the packer then caps the SOLVE back to one warm bucket
        and defers the rest."""
        cap = self.daemon.degraded_drain_cap()
        if getattr(self.daemon, "tenancy_service", None) is not None:
            return cap * 4
        return cap

    # -- the single drain entry path -------------------------------------

    def drain(self, wait_first: bool = True,
              timeout: Optional[float] = None) -> int:
        """Form one batch and solve+commit it.  Returns the number of
        pods popped (scheduled or failed) — the daemon's
        ``schedule_pending`` contract."""
        daemon = self.daemon
        batch = self.former.form(wait_first=wait_first, timeout=timeout)
        pods = batch.pods
        if not pods:
            return 0
        svc = getattr(daemon, "tenancy_service", None)
        if svc is not None:
            # Cross-tenant packing: bound every solve at one warm
            # ladder bucket and fill it urgency-first then by weighted
            # share (tenancy/packer.py); the remainder returns to the
            # queue with its SLO stamps intact.  Degradation still
            # wins: the former already shed to a bounded pop above.
            selected, deferred = svc.packer.pack(
                pods, daemon.degraded_drain_cap())
            for pod in deferred:
                daemon.queue.add(pod)
            pods = batch.pods = selected
        # The batch root span is backdated to cover the wait: queue_wait
        # (blocking pop + deadline batch formation) is the pipeline's
        # first stage, even though the batch only existed at its end.
        root = trace_mod.begin_span("schedule_batch", start=batch.t_wait,
                                    pods=len(pods))
        trace_mod.record_stage("queue_wait", start=batch.t_wait,
                               pods=len(pods))
        daemon.config.metrics.batch_size.set(len(pods))
        tr = Trace(f"Scheduling batch of {len(pods)} pods")
        tr.start = batch.t_wait
        tr.step("Queue drained")
        try:
            return self._solve(batch, tr=tr, trace_id=root.trace_id)
        except Exception:  # noqa: BLE001 — HandleCrash analogue
            # The pods were already popped: requeue each through the
            # backoff path (condition + event + delayed retry) so a
            # crashing drain can't silently strand them Pending, and a
            # poison pod retries at most once per 60 s.  A daemon that
            # was stopped/abandoned mid-drain does NOT requeue: the pods
            # belong to the next incarnation (its startup reconciliation
            # relists them), and a dead daemon writing conditions or
            # requeue events would race the replacement's binds.
            if daemon._stop.is_set():
                log.info("drain interrupted by shutdown; %d pods left "
                         "to the next incarnation", len(pods))
                return len(pods)
            log.exception("drain of %d pods crashed; requeueing",
                          len(pods))
            cache = daemon.config.algorithm.cache
            for pod in pods:
                # Skip pods the crash didn't strand: anything tracked in
                # the cache (assumed by a completed chunk, or already
                # confirmed bound by the watch) made it through.
                if not cache.contains(pod.key):
                    daemon._handle_failure(
                        pod, "SchedulingError",
                        "internal error during scheduling",
                        result="error")
            return len(pods)
        finally:
            root.end()
            # The reference's 20 ms slow-log (generic_scheduler.go:79-85),
            # now fed by the batched drain too; a slow batch also records
            # as a span with the step breakdown.
            tr.log_if_long()

    # -- mode routing + the device-fault recovery ladder -------------------

    def _solve(self, batch: FormedBatch, tr: Optional[Trace] = None,
               trace_id: str = "") -> int:
        """Route the batch to a solve mode under the device guard's
        recovery ladder: a classified ``DeviceFault`` re-dispatches the
        still-uncommitted pods per the guard's decision — unchanged
        (retry), chunked at the next smaller pre-warmed bucket (bisect),
        or on the host fallback engine (breaker open) — for at most
        ``max_rounds`` rounds; exhaustion surfaces to ``drain()``'s
        crash handler, which requeues rather than drops.  Chunks that
        committed before the fault stay committed (the cache knows
        them), so progress is monotone across rounds."""
        daemon = self.daemon
        pods = batch.pods
        if getattr(daemon, "tenancy_service", None) is not None:
            return self._solve_tenants(pods, tr, trace_id)
        guard = getattr(daemon.config.algorithm, "guard", None)
        if guard is None or not guard.enabled:
            return self._dispatch(pods, tr, trace_id)
        total = len(pods)
        remaining = pods
        fault: Optional[DeviceFault] = None
        for _ in range(max(guard.max_rounds, 1)):
            mode = guard.solve_mode()
            try:
                if mode == "host":
                    self._dispatch(remaining, tr, trace_id, host=True)
                else:
                    self._dispatch(remaining, tr, trace_id)
                    guard.note_success(probe=(mode == "probe"))
                return total
            except DeviceFault as f:
                fault = f
                remaining = self._uncommitted(remaining)
                if not remaining:
                    return total
                action = guard.recover(
                    f, can_bisect=self._can_bisect(remaining))
                log.warning("device fault [%s] on %s path: %d pod(s) "
                            "re-dispatched via %s", f.kind, f.path,
                            len(remaining), action)
        raise fault  # ladder exhausted: crash handler requeues

    def _uncommitted(self, pods: list) -> list:
        """The stranded remainder of a faulted dispatch: pods a
        completed chunk already assumed (or the watch confirmed) are in
        the cache, and pods a completed chunk already FAILED are in the
        backoff heap / back on the queue — re-solving those would
        schedule the same pod twice (once here, once when its requeue
        pops)."""
        daemon = self.daemon
        cache = daemon.config.algorithm.cache
        with daemon._requeue_cv:
            handled = {p.key for _, _, p in daemon._requeue_heap}
        return [p for p in pods
                if not cache.contains(p.key)
                and p.key not in handled
                and p.key not in daemon.queue]

    def _solve_tenants(self, pods: list, tr: Optional[Trace],
                       trace_id: str) -> int:
        """The multi-tenant solve path: per-tenant breaker routing,
        mixed-batch fault ATTRIBUTION by per-tenant split, and
        per-tenant accounting — one tenant's poison batch degrades that
        tenant to the host engine; the service and the other tenants
        stay on device.

        A ``lost`` fault still escalates the GLOBAL guard (a dead chip
        is not one tenant's fault) and OOM still runs the global
        eviction/bisect-cap ladder; the per-tenant breaker owns the
        ATTRIBUTABLE kinds (a tenant's poison readbacks, its repeated
        OOM-sized batches) — it trips at KT_TENANT_BREAKER consecutive
        faults, before the global breaker's threshold can."""
        daemon = self.daemon
        svc = daemon.tenancy_service
        guard = getattr(daemon.config.algorithm, "guard", None)
        guard_on = guard is not None and guard.enabled
        total = len(pods)
        gmode = guard.solve_mode() if guard_on else "device"
        if gmode == "host":
            # Whole-device outage (global breaker open, no probe due):
            # every tenant decides on the host engine this drain.
            self._dispatch(pods, tr, trace_id, host=True)
            return total
        device_pods, host_pods, probing = svc.partition(pods)
        if host_pods:
            self._dispatch(host_pods, tr, trace_id, host=True)
            for t, n in svc.count_tenants(host_pods).items():
                svc.note_host_fallback(t, n)
        if device_pods:
            # One solver at a time on the shared engine: the service's
            # packed submits (remote control planes) and this drain
            # must not race GenericScheduler's solve state.
            with svc.engine_lock:
                self._solve_tenant_groups(
                    device_pods, probing, gmode, tr, trace_id)
        return total

    def _solve_tenant_groups(self, device_pods: list, probing: set,
                             gmode: str, tr: Optional[Trace],
                             trace_id: str) -> None:
        """The device section of a tenant drain (caller holds the
        service's engine lock): dispatch, attribution splits, and the
        per-tenant breaker routing."""
        from collections import deque

        from kubernetes_tpu.chaos import device as chaos_device
        from kubernetes_tpu.engine import devicestats
        from kubernetes_tpu.engine.guard import ACT_HOST, KIND_LOST, KIND_OOM
        daemon = self.daemon
        svc = daemon.tenancy_service
        guard = getattr(daemon.config.algorithm, "guard", None)
        guard_on = guard is not None and guard.enabled
        # Transfer attribution covers the DEVICE section only — a
        # host-degraded tenant must not be billed for device bytes it
        # never moved.
        transfers0 = sum(devicestats.transfer_snapshot().values())
        groups = deque([device_pods])
        rounds = 0
        budget = (guard.max_rounds if guard_on else 1) + \
            len(svc.tenants) + 2
        while groups:
            group = groups.popleft()
            tenants_g = svc.tenants_of(group)
            try:
                with chaos_device.tenant_context(tenants_g):
                    self._dispatch(group, tr, trace_id)
                if guard_on:
                    guard.note_success(probe=(gmode == "probe"))
                for t in tenants_g:
                    svc.note_success(t, probe=(t in probing))
            except DeviceFault as f:
                rounds += 1
                remaining = self._uncommitted(group)
                if not remaining:
                    continue
                if rounds > budget:
                    raise  # crash handler requeues — never drops
                tenants_r = svc.tenants_of(remaining)
                if len(tenants_r) > 1:
                    # Attribution bisection: split per tenant and
                    # re-solve each alone — the culprit's solo batch
                    # keeps faulting and trips ITS breaker.
                    svc.note_split(f)
                    groups.extend(svc.split_by_tenant(remaining))
                    log.warning("device fault [%s] on a %d-tenant "
                                "batch: split per tenant for "
                                "attribution", f.kind, len(tenants_r))
                    continue
                tenant = tenants_r[0]
                tripped = svc.note_fault(tenant, f.kind,
                                         probe=(tenant in probing))
                to_host = tripped or f.kind == KIND_LOST
                if guard_on and f.kind in (KIND_LOST, KIND_OOM):
                    action = guard.recover(
                        f, can_bisect=self._can_bisect(remaining))
                    to_host = to_host or action == ACT_HOST
                if to_host:
                    self._dispatch(remaining, tr, trace_id, host=True)
                    svc.note_host_fallback(tenant, len(remaining))
                else:
                    groups.append(remaining)
        svc.record_solve(
            device_pods, sum(devicestats.transfer_snapshot().values())
            - transfers0)

    def _can_bisect(self, pods: list) -> bool:
        """OOM bisection re-solves the remainder as stream chunks at a
        smaller warmed bucket — available only where chunking is legal:
        no gang (one assignment vector), no joint (prices couple the
        queue), no extenders, and a non-empty pre-warmed ladder."""
        from kubernetes_tpu.engine.workloads import gang as gang_mod
        from kubernetes_tpu.utils.featuregate import DEFAULT_FEATURE_GATE
        daemon = self.daemon
        if daemon.config.algorithm.extenders:
            return False
        if not DEFAULT_FEATURE_GATE.enabled("StreamingDrain") or \
                DEFAULT_FEATURE_GATE.enabled("JointSolver"):
            return False
        if DEFAULT_FEATURE_GATE.enabled("GangScheduling") and \
                gang_mod.batch_has_gangs(pods):
            return False
        return bool(daemon.effective_ladder())

    def _dispatch(self, pods: list, tr: Optional[Trace] = None,
                  trace_id: str = "", host: bool = False) -> int:
        from kubernetes_tpu.engine.workloads import gang as gang_mod
        from kubernetes_tpu.utils.featuregate import DEFAULT_FEATURE_GATE
        daemon = self.daemon
        joint = DEFAULT_FEATURE_GATE.enabled("JointSolver")
        # Gangs must be admitted all-or-nothing over ONE assignment
        # vector — a chunked stream could split a gang across chunk
        # boundaries, so gang batches take the one-shot solve (padded to
        # a warm bucket below).
        gangs = DEFAULT_FEATURE_GATE.enabled("GangScheduling") and \
            gang_mod.batch_has_gangs(pods)
        if host:
            # Breaker open: the whole batch decides on the host engine
            # (sequential NumPy — chunking and buckets are meaningless
            # there; gang reduction still applies to its output).
            return self._solve_oneshot(pods, joint=False, gangs=gangs,
                                       tr=tr, trace_id=trace_id,
                                       host=True)
        # The joint solve needs the whole queue at once (prices couple
        # every pod); it supersedes the streaming split.
        streaming = DEFAULT_FEATURE_GATE.enabled("StreamingDrain") \
            and not joint and not gangs \
            and not daemon.config.algorithm.extenders
        guard = getattr(daemon.config.algorithm, "guard", None)
        cap = guard.bucket_cap() \
            if guard is not None and guard.enabled else None
        if streaming and cap is not None:
            # Bisected (or HBM-watermark-capped) regime: every
            # streamable drain chunks at the cap — a pre-warmed ladder
            # bucket, never a fresh shape.
            return self._solve_stream(pods, chunk_size=cap,
                                      trace_id=trace_id)
        if streaming and len(pods) >= daemon.STREAM_THRESHOLD:
            return self._solve_stream(pods, trace_id=trace_id)
        if streaming and len(pods) < daemon._PAD_LIMIT:
            # Small drain: one power-of-two stream chunk (live-flag
            # padded), so arrival races don't mint a new compiled shape
            # per queue length; floored so the tail of the ladder doesn't
            # either.
            bucket = max(1 << (len(pods) - 1).bit_length(),
                         daemon.stream_min_bucket)
            return self._solve_stream(pods, chunk_size=bucket,
                                      trace_id=trace_id)
        return self._solve_oneshot(pods, joint=joint, gangs=gangs,
                                   tr=tr, trace_id=trace_id)

    # -- one-shot / joint / gang / host solve ------------------------------

    def _solve_oneshot(self, pods: list, joint: bool, gangs: bool,
                       tr: Optional[Trace], trace_id: str,
                       host: bool = False) -> int:
        from kubernetes_tpu.engine.workloads import gang as gang_mod
        from kubernetes_tpu.utils import metrics as metrics_mod
        daemon = self.daemon
        start = time.perf_counter()
        if host:
            placements = daemon.config.algorithm.schedule_batch_host(pods)
        else:
            # Workload-constrained one-shot drains pad to the same
            # bucket ladder the stream path compiles at, so gang/joint
            # solves hit pre-warmed shapes instead of minting one per
            # queue length.
            pad_to = 0
            if (gangs or joint) and len(pods) < daemon._PAD_LIMIT and \
                    not daemon.config.algorithm.extenders:
                pad_to = max(1 << (len(pods) - 1).bit_length(),
                             daemon.stream_min_bucket)
            placements = daemon.config.algorithm.schedule_batch(
                pods, joint=joint, pad_to=pad_to)
        failure_info: dict[str, tuple[str, str]] = {}
        if gangs:
            placements, rejected = gang_mod.reduce_all_or_nothing(
                pods, placements)
            for name, info in rejected.items():
                metrics_mod.GANG_ADMISSIONS.labels(
                    result="rejected").inc()
                msg = gang_mod.gang_failure_message(name, info)
                log.debug("gang rejection: %s", msg)
                for i in info["members"]:
                    failure_info[pods[i].key] = (msg, "gang_rejected")
            admitted = [name for name in gang_mod.gang_groups(pods)
                        if name not in rejected]
            for _ in admitted:
                metrics_mod.GANG_ADMISSIONS.labels(
                    result="admitted").inc()
        if tr is not None:
            tr.step("Computed placements")
        algo_us = (time.perf_counter() - start) * 1e6 / len(pods)
        daemon.config.metrics.scheduling_algorithm_latency.observe_many(
            algo_us, len(pods))
        if log.isEnabledFor(10):  # V(2)-style guard (predicates.go:478)
            placed_n = sum(1 for d in placements if d is not None)
            log.debug("drained %d pods: %d placed, %.0f us/pod algorithm",
                      len(pods), placed_n, algo_us)
        daemon._record_batch_decisions(pods, placements, trace_id,
                                       time.perf_counter() - start)
        daemon._assume_and_bind_batch(pods, placements, start,
                                      failure_info=failure_info)
        if tr is not None:
            tr.step("Assumed and dispatched binds")
        return len(pods)

    # -- streamed solve with the overlapped commit worker ------------------

    def _solve_stream(self, pods: list, chunk_size: Optional[int] = None,
                      trace_id: str = "") -> int:
        """The overlapped drain: while the device scans chunk N, chunk
        N-1's readback/assume/bind runs on a single commit worker, with
        at most ``pipeline_window`` chunks in flight uncommitted.  The
        one worker keeps chunks committing in solve order, and within a
        chunk assume completes before its bind fan-out dispatches — the
        per-pod assume-before-bind ordering of the one-shot path.
        Commits are joined before returning, so the caller-observable
        state machine (every popped pod assumed-or-failed by return) is
        unchanged."""
        daemon = self.daemon
        start = time.perf_counter()
        window = max(daemon.pipeline_window, 0)
        chunk = chunk_size or daemon.stream_chunk_size()
        if window == 0:
            solve_done = start
            for chunk_pods, placements in \
                    daemon.config.algorithm.schedule_batch_stream(
                        pods, chunk_size=chunk):
                solve_done = time.perf_counter()
                daemon._record_batch_decisions(chunk_pods, placements,
                                               trace_id,
                                               solve_done - start)
                daemon._assume_and_bind_batch(chunk_pods, placements,
                                              start)
        else:
            if self._commit_pool is None:
                from concurrent.futures import ThreadPoolExecutor
                self._commit_pool = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="chunk-commit")
            sem = threading.BoundedSemaphore(window)
            ctx = trace_mod.current_context()
            # A mutable cell: the commit worker stamps when each chunk's
            # readback landed; the last stamp bounds algorithm latency.
            solve_done_cell = [start]
            futures = []
            err = None
            try:
                for _, resolve in \
                        daemon.config.algorithm.schedule_batch_stream(
                            pods, chunk_size=chunk, defer_readback=True):
                    # Bounded in-flight window: block the drain thread
                    # (and with it further device launches) until an
                    # outstanding chunk commits.
                    sem.acquire()
                    futures.append(self._commit_pool.submit(
                        self._commit_chunk, resolve, start, trace_id,
                        sem, ctx, solve_done_cell))
            finally:
                # Join EVERY submitted commit before surfacing anything:
                # drain()'s crash handler requeues pods not yet assumed,
                # and a still-running commit assuming them concurrently
                # would double-track the pod.
                for fut in futures:
                    try:
                        fut.result()
                    except Exception as exc:  # noqa: BLE001 — requeue
                        err = err or exc
            if err is not None:
                # Surface the first commit failure to drain()'s crash
                # handler, which requeues every pod the completed
                # commits didn't assume.
                raise err
            solve_done = solve_done_cell[0]
        # Algorithm latency spans until the LAST chunk's results landed
        # (interleaved assume/bind of earlier chunks overlaps the device
        # and is deliberately excluded, matching the one-shot path).
        algo_us = (solve_done - start) * 1e6 / len(pods)
        daemon.config.metrics.scheduling_algorithm_latency.observe_many(
            algo_us, len(pods))
        return len(pods)

    def _commit_chunk(self, resolve, start: float, trace_id: str, sem,
                      trace_ctx, solve_done_cell: list) -> None:
        """One chunk's commit on the pipeline worker: blocking readback,
        flight-recorder feed, bulk assume, bind dispatch."""
        daemon = self.daemon
        try:
            with trace_mod.use_context(trace_ctx):
                chunk_pods, placements = resolve()
                solve_done_cell[0] = time.perf_counter()
                daemon._record_batch_decisions(
                    chunk_pods, placements, trace_id,
                    solve_done_cell[0] - start)
                daemon._assume_and_bind_batch(chunk_pods, placements,
                                              start)
        finally:
            sem.release()

    # -- lifecycle --------------------------------------------------------

    def shutdown(self, wait: bool = True, cancel: bool = False) -> None:
        if self._commit_pool is not None:
            if cancel:
                self._commit_pool.shutdown(wait=False,
                                           cancel_futures=True)
            else:
                self._commit_pool.shutdown(wait=wait)
