"""Decision-parity oracle: the reference scheduler's semantics as slow,
obvious Python.

This module re-derives every default-provider predicate and priority
directly from the Go sources (cited per function) with per-pod-per-node
loops and NO shared code with the tensor path — so differential tests
comparing it against the device solver surface real bugs in either side.

Used by tests/test_parity.py over randomized clusters, and available as a
debugging tool (``oracle.explain``).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from kubernetes_tpu.api import types as api

MAX_PRIORITY = 10


@dataclass
class ClusterState:
    """Everything the reference scheduler reads through its listers."""

    nodes: list[api.Node] = field(default_factory=list)
    pods: list[api.Pod] = field(default_factory=list)  # assigned, alive
    services: list[api.Service] = field(default_factory=list)
    controllers: list[api.ReplicationController] = field(default_factory=list)
    replica_sets: list[api.ReplicaSet] = field(default_factory=list)
    pvs: list[api.PersistentVolume] = field(default_factory=list)
    pvcs: list[api.PersistentVolumeClaim] = field(default_factory=list)
    hard_pod_affinity_weight: int = 1

    def node(self, name: str) -> Optional[api.Node]:
        for n in self.nodes:
            if n.name == name:
                return n
        return None

    def node_pods(self, name: str) -> list[api.Pod]:
        return [p for p in self.pods if p.node_name == name]

    def ready_nodes(self) -> list[api.Node]:
        """getNodeConditionPredicate (factory.go:436-462)."""
        return [n for n in self.nodes if n.is_ready()]

    def affinity_pods(self) -> list[api.Pod]:
        """Pods carrying any affinity annotation — the reference's
        PodsWithAffinity precompute (node_info.go:32-54, maintained by
        addPod/removePod) that bounds the anti-affinity scans to pods that
        can actually contribute terms.  Pods without affinity contribute
        nothing to those loops, so restricting them is semantics-neutral."""
        return [p for p in self.pods if p.affinity() is not None]


# ---------------------------------------------------------------------------
# Label / selector matching (pkg/labels)
# ---------------------------------------------------------------------------

def _node_selector_term_matches(term: api.NodeSelectorTerm,
                                node: api.Node) -> bool:
    """NodeSelectorRequirementsAsSelector semantics (predicates.go:504-554):
    empty term matches nothing; unknown operator or bad value poisons the
    term."""
    if not term.match_expressions:
        return False
    for e in term.match_expressions:
        val = node.labels.get(e.key)
        if e.operator == api.NS_OP_IN:
            if val is None or val not in e.values:
                return False
        elif e.operator == api.NS_OP_NOT_IN:
            if val is not None and val in e.values:
                return False
        elif e.operator == api.NS_OP_EXISTS:
            if val is None:
                return False
        elif e.operator == api.NS_OP_DOES_NOT_EXIST:
            if val is not None:
                return False
        elif e.operator in (api.NS_OP_GT, api.NS_OP_LT):
            if len(e.values) != 1 or val is None:
                return False
            try:
                lhs, rhs = int(val), int(e.values[0])
            except ValueError:
                return False
            if e.operator == api.NS_OP_GT and not lhs > rhs:
                return False
            if e.operator == api.NS_OP_LT and not lhs < rhs:
                return False
        else:
            return False
    return True


def pod_matches_node_labels(pod: api.Pod, node: api.Node) -> bool:
    """podMatchesNodeLabels (predicates.go:504-554): nodeSelector AND
    required node affinity (terms OR'd; empty terms list matches nothing)."""
    for k, v in pod.node_selector.items():
        if node.labels.get(k) != v:
            return False
    aff = pod.affinity()
    if aff is not None and aff.node_affinity is not None \
            and aff.node_affinity.required is not None:
        terms = aff.node_affinity.required.node_selector_terms
        if not any(_node_selector_term_matches(t, node) for t in terms):
            return False
    return True


def _term_selector_matches(term: api.PodAffinityTerm,
                           labels: dict[str, str]) -> bool:
    """LabelSelectorAsSelector: nil selector matches nothing."""
    if term.label_selector is None:
        return False
    return term.label_selector.matches(labels)


def pod_matches_term(pod: api.Pod, affinity_pod: api.Pod,
                     term: api.PodAffinityTerm) -> bool:
    """PodMatchesTermsNamespaceAndSelector (topologies.go:42-54)."""
    if term.namespaces is None:
        namespaces = {affinity_pod.namespace}
    else:
        namespaces = set(term.namespaces)
    if namespaces and pod.namespace not in namespaces:
        return False
    return _term_selector_matches(term, pod.labels)


def nodes_same_topology(node_a: api.Node, node_b: api.Node,
                        key: str) -> bool:
    """NodesHaveSameTopologyKey (topologies.go:57-76)."""
    def same(k):
        va, vb = node_a.labels.get(k), node_b.labels.get(k)
        return bool(va) and va == vb
    if not key:
        return any(same(k) for k in api.DEFAULT_FAILURE_DOMAINS)
    return same(key)


def _affinity_terms(pod: api.Pod):
    aff = pod.affinity()
    req_a = req_aa = pref_a = pref_aa = ()
    if aff is not None:
        if aff.pod_affinity is not None:
            req_a = aff.pod_affinity.required
            pref_a = aff.pod_affinity.preferred
        if aff.pod_anti_affinity is not None:
            req_aa = aff.pod_anti_affinity.required
            pref_aa = aff.pod_anti_affinity.preferred
    return req_a, req_aa, pref_a, pref_aa


# ---------------------------------------------------------------------------
# Predicates (algorithm/predicates/predicates.go)
# ---------------------------------------------------------------------------

def pod_fits_resources(pod: api.Pod, node: api.Node,
                       node_pods: list[api.Pod]) -> bool:
    """predicates.go:444-485."""
    if len(node_pods) + 1 > node.allocatable_pods:
        return False
    req = pod.resource_request()
    if req.milli_cpu == 0 and req.memory == 0 and req.nvidia_gpu == 0:
        return True
    used = api.Resource()
    for p in node_pods:
        used = used.add(p.resource_request())
    return (used.milli_cpu + req.milli_cpu <= node.allocatable_milli_cpu and
            used.memory + req.memory <= node.allocatable_memory and
            used.nvidia_gpu + req.nvidia_gpu <= node.allocatable_gpu)


def pod_fits_host(pod: api.Pod, node: api.Node) -> bool:
    """predicates.go:567-581."""
    return not pod.node_name or pod.node_name == node.name


def pod_fits_host_ports(pod: api.Pod, node_pods: list[api.Pod]) -> bool:
    """predicates.go:721-761."""
    wanted = pod.used_host_ports()
    if not wanted:
        return True
    existing = set()
    for p in node_pods:
        existing |= p.used_host_ports()
    return not (wanted & existing)


def no_disk_conflict(pod: api.Pod, node_pods: list[api.Pod]) -> bool:
    """predicates.go:100-153: GCE PD (read-only-only sharing OK), EBS
    (never shared), RBD (never shared when any monitor matches)."""
    for v in pod.volumes:
        for ev in (e for p in node_pods for e in p.volumes):
            if v.gce_pd_name and v.gce_pd_name == ev.gce_pd_name:
                if not (v.gce_read_only and ev.gce_read_only):
                    return False
            if v.aws_ebs_id and v.aws_ebs_id == ev.aws_ebs_id:
                return False
            if v.rbd_key and ev.rbd_key:
                mons_a, pool_a, img_a = (v.rbd_key.split("#") + ["", ""])[:3]
                mons_b, pool_b, img_b = (ev.rbd_key.split("#") + ["", ""])[:3]
                if pool_a == pool_b and img_a == img_b and \
                        set(mons_a.split(",")) & set(mons_b.split(",")):
                    if not (v.rbd_read_only and ev.rbd_read_only):
                        return False
    return True


def pod_tolerates_node_taints(pod: api.Pod, node: api.Node) -> bool:
    """predicates.go:1070-1117."""
    taints = [t for t in node.taints()
              if t.effect != api.TAINT_EFFECT_PREFER_NO_SCHEDULE]
    all_taints = node.taints()
    if not all_taints:
        return True
    tols = pod.tolerations()
    if not tols:
        return False
    return all(t.tolerated_by(tols) for t in taints)


def check_node_memory_pressure(pod: api.Pod, node: api.Node) -> bool:
    """predicates.go:1125-1153."""
    if not pod.is_best_effort():
        return True
    return node.condition(api.NODE_MEMORY_PRESSURE) != "True"


def check_node_disk_pressure(pod: api.Pod, node: api.Node) -> bool:
    """predicates.go:1156-1172."""
    return node.condition(api.NODE_DISK_PRESSURE) != "True"


def _pd_filter_ids(pod: api.Pod, family: str,
                   cluster: ClusterState) -> tuple[set, int, bool]:
    """filterVolumes (predicates.go:188-241): (ids, extras, hard_error)."""
    ids: set[str] = set()
    extra = 0
    counter = [0]
    for v in pod.volumes:
        if family == "ebs" and v.aws_ebs_id:
            ids.add(v.aws_ebs_id)
        elif family == "gce" and v.gce_pd_name:
            ids.add(v.gce_pd_name)
        elif v.pvc_claim_name:
            pvc = next((c for c in cluster.pvcs
                        if c.namespace == pod.namespace
                        and c.name == v.pvc_claim_name), None)
            if pvc is None:
                extra += 1
                continue
            if not pvc.volume_name:
                return ids, extra, True
            pv = next((x for x in cluster.pvs
                       if x.name == pvc.volume_name), None)
            if pv is None:
                extra += 1
                continue
            if family == "ebs" and pv.aws_ebs_id:
                ids.add(pv.aws_ebs_id)
            elif family == "gce" and pv.gce_pd_name:
                ids.add(pv.gce_pd_name)
    del counter
    return ids, extra, False


def max_pd_volume_count(pod: api.Pod, node_pods: list[api.Pod],
                        family: str, max_volumes: int,
                        cluster: ClusterState) -> bool:
    """predicates.go:243-282."""
    if not pod.volumes:
        return True
    new_ids, new_extra, hard = _pd_filter_ids(pod, family, cluster)
    if hard:
        return False
    if not new_ids and not new_extra:
        return True
    existing: set[str] = set()
    existing_extra = 0
    for p in node_pods:
        ids, extra, hard = _pd_filter_ids(p, family, cluster)
        if hard:
            return False
        existing |= ids
        existing_extra += extra
    num_new = len(new_ids - existing) + new_extra
    return len(existing) + existing_extra + num_new <= max_volumes


def volume_zone(pod: api.Pod, node: api.Node,
                cluster: ClusterState) -> bool:
    """predicates.go:348-418."""
    if not pod.volumes:
        return True
    constraints = {k: v for k, v in node.labels.items()
                   if k in (api.ZONE_LABEL, api.REGION_LABEL)}
    if not constraints:
        return True
    for v in pod.volumes:
        if not v.pvc_claim_name:
            continue
        pvc = next((c for c in cluster.pvcs
                    if c.namespace == pod.namespace
                    and c.name == v.pvc_claim_name), None)
        if pvc is None or not pvc.volume_name:
            return False  # hard error
        pv = next((x for x in cluster.pvs if x.name == pvc.volume_name), None)
        if pv is None:
            return False
        for k, val in pv.labels.items():
            if k not in (api.ZONE_LABEL, api.REGION_LABEL):
                continue
            if val != constraints.get(k, ""):
                return False
    return True


def matching_anti_affinity_terms(pod: api.Pod, cluster: ClusterState
                                 ) -> list[tuple[api.Node,
                                                 api.PodAffinityTerm]]:
    """getMatchingAntiAffinityTerms (predicates.go:881-906): the per-pod
    precompute of predicateMetadata — (existing pod's node, term) for every
    existing anti-affinity term that matches the pending pod.  Only
    affinity-carrying pods can contribute (PodsWithAffinity,
    node_info.go:32-54)."""
    out = []
    for epod in cluster.affinity_pods():
        enode = cluster.node(epod.node_name)
        if enode is None:
            continue
        _, req_aa, _, _ = _affinity_terms(epod)
        for term in req_aa:
            if pod_matches_term(pod, epod, term):
                out.append((enode, term))
    return out


def inter_pod_affinity(pod: api.Pod, node: api.Node,
                       cluster: ClusterState, meta=None) -> bool:
    """InterPodAffinityMatches (predicates.go:825-1068).  ``meta``: the
    matching_anti_affinity_terms precompute (predicateMetadata,
    predicates.go:71-98); derived on the fly when absent."""
    # 1. Existing pods' anti-affinity (satisfiesExistingPodsAntiAffinity).
    if meta is None:
        meta = matching_anti_affinity_terms(pod, cluster)
    for enode, term in meta:
        if nodes_same_topology(node, enode, term.topology_key):
            return False
    # 2. The pod's own required terms.
    req_a, req_aa, _, _ = _affinity_terms(pod)
    for term in req_a:
        term_matches = False
        matching_exists = False
        for epod in cluster.pods:
            if pod_matches_term(epod, pod, term):
                matching_exists = True
                enode = cluster.node(epod.node_name)
                if enode is not None and \
                        nodes_same_topology(node, enode, term.topology_key):
                    term_matches = True
                    break
        if not term_matches:
            # Self-match escape hatch (predicates.go:1038-1048).
            if not (pod_matches_term(pod, pod, term) and not matching_exists):
                return False
    for term in req_aa:
        for epod in cluster.pods:
            if pod_matches_term(epod, pod, term):
                enode = cluster.node(epod.node_name)
                if enode is not None and \
                        nodes_same_topology(node, enode, term.topology_key):
                    return False
    return True


DEFAULT_MAX_EBS = 39
DEFAULT_MAX_GCE = 16


def find_nodes_that_fit(pod: api.Pod, cluster: ClusterState
                        ) -> tuple[list[api.Node], dict[str, list[str]]]:
    """findNodesThatFit with the DefaultProvider predicate set
    (defaults.go:113-163), over ready nodes."""
    fits = []
    failures: dict[str, list[str]] = {}
    meta = matching_anti_affinity_terms(pod, cluster)
    for node in cluster.ready_nodes():
        node_pods = cluster.node_pods(node.name)
        checks = [
            ("NoVolumeZoneConflict", volume_zone(pod, node, cluster)),
            ("MaxEBSVolumeCount", max_pd_volume_count(
                pod, node_pods, "ebs", DEFAULT_MAX_EBS, cluster)),
            ("MaxGCEPDVolumeCount", max_pd_volume_count(
                pod, node_pods, "gce", DEFAULT_MAX_GCE, cluster)),
            ("MatchInterPodAffinity", inter_pod_affinity(pod, node, cluster,
                                                         meta)),
            ("NoDiskConflict", no_disk_conflict(pod, node_pods)),
            ("PodFitsResources", pod_fits_resources(pod, node, node_pods)),
            ("PodFitsHost", pod_fits_host(pod, node)),
            ("PodFitsHostPorts", pod_fits_host_ports(pod, node_pods)),
            ("MatchNodeSelector", pod_matches_node_labels(pod, node)),
            ("PodToleratesNodeTaints", pod_tolerates_node_taints(pod, node)),
            ("CheckNodeMemoryPressure",
             check_node_memory_pressure(pod, node)),
            ("CheckNodeDiskPressure", check_node_disk_pressure(pod, node)),
        ]
        failed = [name for name, ok in checks if not ok]
        if failed:
            failures[node.name] = failed
        else:
            fits.append(node)
    return fits, failures


# ---------------------------------------------------------------------------
# Priorities (algorithm/priorities/)
# ---------------------------------------------------------------------------

def _nonzero_sum(pods: Sequence[api.Pod]) -> tuple[int, int]:
    cpu = mem = 0
    for p in pods:
        c, m = p.non_zero_request()
        cpu += c
        mem += m
    return cpu, mem


def least_requested(pod: api.Pod, node: api.Node,
                    node_pods: list[api.Pod]) -> int:
    """priorities.go:81-149 (int64 arithmetic; memory in bytes)."""
    def unused(requested, capacity):
        if capacity == 0 or requested > capacity:
            return 0
        return ((capacity - requested) * 10) // capacity
    ec, em = _nonzero_sum(node_pods)
    pc, pm = pod.non_zero_request()
    cpu = unused(ec + pc, node.allocatable_milli_cpu)
    mem = unused(em + pm, node.allocatable_memory)
    return (cpu + mem) // 2


def balanced_resource_allocation(pod: api.Pod, node: api.Node,
                                 node_pods: list[api.Pod]) -> int:
    """priorities.go:271-317."""
    def frac(req, cap):
        return 1.0 if cap == 0 else req / cap
    ec, em = _nonzero_sum(node_pods)
    pc, pm = pod.non_zero_request()
    cf = frac(ec + pc, node.allocatable_milli_cpu)
    mf = frac(em + pm, node.allocatable_memory)
    if cf >= 1 or mf >= 1:
        return 0
    return int(10 - abs(cf - mf) * 10)


def _spread_selectors(pod: api.Pod, cluster: ClusterState) -> list:
    sels: list = []
    for s in cluster.services:
        if s.namespace == pod.namespace and s.selector and \
                all(pod.labels.get(k) == v for k, v in s.selector.items()):
            sels.append(dict(s.selector))
    if pod.labels:
        for rc in cluster.controllers:
            if rc.namespace == pod.namespace and rc.selector and \
                    all(pod.labels.get(k) == v for k, v in rc.selector.items()):
                sels.append(dict(rc.selector))
        for rs in cluster.replica_sets:
            if rs.namespace == pod.namespace and rs.selector is not None and \
                    (rs.selector.match_labels or rs.selector.match_expressions) \
                    and rs.selector.matches(pod.labels):
                sels.append(rs.selector)
    return sels


def _sel_matches(sel, labels: dict[str, str]) -> bool:
    if isinstance(sel, dict):
        return bool(sel) and all(labels.get(k) == v for k, v in sel.items())
    return sel.matches(labels)


def first_matching_service(pod: api.Pod, services) -> Optional[api.Service]:
    """GetPodServices[0] — ServiceAffinity/ServiceAntiAffinity read only
    the FIRST matching service (predicates.go:676-678)."""
    for s in services:
        if s.namespace == pod.namespace and _sel_matches(s.selector,
                                                         pod.labels):
            return s
    return None


def selector_spread(pod: api.Pod, cluster: ClusterState) -> dict[str, int]:
    """CalculateSpreadPriority (selector_spreading.go:63-175), over ready
    nodes."""
    nodes = cluster.ready_nodes()
    selectors = _spread_selectors(pod, cluster)
    counts: dict[str, float] = {}
    counts_by_zone: dict[str, float] = {}
    max_count = 0.0
    if selectors:
        for node in nodes:
            count = 0.0
            for npod in cluster.node_pods(node.name):
                if npod.namespace != pod.namespace or \
                        npod.deletion_timestamp is not None:
                    continue
                if any(_sel_matches(s, npod.labels) for s in selectors):
                    count += 1
            counts[node.name] = count
            max_count = max(max_count, count)
            zone = node.zone_key()
            if zone:
                counts_by_zone[zone] = counts_by_zone.get(zone, 0) + count
    have_zones = len(counts_by_zone) != 0
    max_zone = max(counts_by_zone.values()) if have_zones else 0.0
    # The reference's fScore is a Go float32 (selector_spreading.go:139);
    # the blend must round through float32 or edge values truncate
    # differently than both the reference and the tensor engine (observed:
    # a blend that is exactly 6.0 in f32 lands at 5.9999996 in f64 and
    # int-truncates to 5).
    f32 = np.float32
    result = {}
    for node in nodes:
        f = f32(MAX_PRIORITY)
        if max_count > 0:
            f = f32(MAX_PRIORITY) * ((f32(max_count)
                                      - f32(counts.get(node.name, 0)))
                                     / f32(max_count))
        if have_zones and max_zone > 0:
            # The reference divides unguarded (selector_spreading.go:160);
            # with zero matches everywhere that's 0/0 -> NaN whose int
            # conversion is Go/amd64-implementation-defined.  Both this
            # oracle and the tensor engine take the only sane reading: no
            # zone signal, keep the node score.
            zone = node.zone_key()
            if zone:
                zscore = f32(MAX_PRIORITY) * (
                    (f32(max_zone) - f32(counts_by_zone.get(zone, 0)))
                    / f32(max_zone))
                f = f * f32(1 - 2 / 3) + f32(2 / 3) * zscore
        result[node.name] = int(f)
    return result


def service_anti_affinity(pod: api.Pod, cluster: ClusterState,
                          label: str) -> dict[str, int]:
    """CalculateAntiAffinityPriority (selector_spreading.go:193-253): spread
    the pods of the pod's FIRST matching service across values of a node
    label.  Ready nodes carrying the label score
    int(10 * (numServicePods - countsOnValue) / numServicePods); nodes
    without the label score 0; every labeled node scores 10 when the
    service has no pods."""
    nodes = cluster.ready_nodes()
    svc = first_matching_service(pod, cluster.services)
    peers: list[api.Pod] = []
    if svc is not None:
        peers = [p for p in cluster.pods
                 if p.namespace == svc.namespace and p.node_name and
                 _sel_matches(svc.selector, p.labels)]
    num = len(peers)
    counts: dict[str, int] = {}
    for peer in peers:
        pn = cluster.node(peer.node_name)
        if pn is not None and pn.is_ready() and label in pn.labels:
            counts[pn.labels[label]] = counts.get(pn.labels[label], 0) + 1
    out = {}
    for node in nodes:
        if label not in node.labels:
            out[node.name] = 0
        elif num == 0:
            out[node.name] = MAX_PRIORITY
        else:
            v = node.labels[label]
            out[node.name] = int(10.0 * (num - counts.get(v, 0)) / num)
    return out


def node_prefer_avoid(pod: api.Pod, cluster: ClusterState) -> dict[str, int]:
    """priorities.go:326-398: 0 when the node's preferAvoidPods annotation
    names one of the pod's controllers, else 10."""
    import json as _json
    refs = []
    if pod.labels:
        for rc in cluster.controllers:
            if rc.namespace == pod.namespace and rc.selector and \
                    all(pod.labels.get(k) == v for k, v in rc.selector.items()):
                refs.append(("ReplicationController", f"{rc.namespace}/{rc.name}"))
        for rs in cluster.replica_sets:
            if rs.namespace == pod.namespace and rs.selector is not None and \
                    (rs.selector.match_labels or rs.selector.match_expressions) \
                    and rs.selector.matches(pod.labels):
                refs.append(("ReplicaSet", f"{rs.namespace}/{rs.name}"))
    result = {}
    for node in cluster.ready_nodes():
        score = MAX_PRIORITY
        raw = node.annotations.get(api.PREFER_AVOID_PODS_ANNOTATION_KEY, "")
        if raw and refs:
            try:
                d = _json.loads(raw)
                for e in d.get("preferAvoidPods") or ():
                    pc = (e.get("podSignature") or {}).get("podController") or {}
                    if (pc.get("kind", ""), pc.get("uid", "")) in refs:
                        score = 0
            except ValueError:
                pass
        result[node.name] = score
    return result


def node_affinity_priority(pod: api.Pod,
                           cluster: ClusterState) -> dict[str, int]:
    """node_affinity.go:32-86."""
    nodes = cluster.ready_nodes()
    counts = {}
    max_count = 0
    aff = pod.affinity()
    for node in nodes:
        count = 0
        if aff is not None and aff.node_affinity is not None:
            for term in aff.node_affinity.preferred:
                if term.weight == 0:
                    continue
                if _node_selector_term_matches(term.preference, node):
                    count += term.weight
        counts[node.name] = count
        max_count = max(max_count, count)
    return {n.name: (int(counts[n.name] * MAX_PRIORITY / max_count)
                     if max_count > 0 else 0) for n in nodes}


def taint_toleration_priority(pod: api.Pod,
                              cluster: ClusterState) -> dict[str, int]:
    """taint_toleration.go:54-105."""
    nodes = cluster.ready_nodes()
    tols = [t for t in pod.tolerations()
            if not t.effect or t.effect == api.TAINT_EFFECT_PREFER_NO_SCHEDULE]
    counts = {}
    max_count = 0
    for node in nodes:
        count = 0
        for taint in node.taints():
            if taint.effect != api.TAINT_EFFECT_PREFER_NO_SCHEDULE:
                continue
            if not taint.tolerated_by(tols):
                count += 1
        counts[node.name] = count
        max_count = max(max_count, count)
    out = {}
    for node in nodes:
        if max_count > 0:
            out[node.name] = int((1.0 - counts[node.name] / max_count) * 10)
        else:
            out[node.name] = MAX_PRIORITY
    return out


def inter_pod_affinity_priority(pod: api.Pod,
                                cluster: ClusterState) -> dict[str, int]:
    """interpod_affinity.go:117-260."""
    nodes = cluster.ready_nodes()
    counts: dict[str, float] = {}

    def process_term(term, affinity_pod, check_pod, fixed_node, weight):
        if weight == 0 or fixed_node is None:
            return
        if pod_matches_term(check_pod, affinity_pod, term):
            for node in nodes:
                if nodes_same_topology(node, fixed_node, term.topology_key):
                    counts[node.name] = counts.get(node.name, 0) + weight

    req_a, req_aa, pref_a, pref_aa = _affinity_terms(pod)
    # The pending pod's own preferred terms are checked against EVERY
    # existing pod (their labels matter, not their affinity)...
    if pref_a or pref_aa:
        for epod in cluster.pods:
            enode = cluster.node(epod.node_name)
            if enode is None:
                continue
            for wt in pref_a:
                process_term(wt.pod_affinity_term, pod, epod, enode,
                             wt.weight)
            for wt in pref_aa:
                process_term(wt.pod_affinity_term, pod, epod, enode,
                             -wt.weight)
    # ...while existing pods' terms can only come from affinity-carrying
    # pods (PodsWithAffinity, node_info.go:32-54).
    for epod in cluster.affinity_pods():
        enode = cluster.node(epod.node_name)
        if enode is None:
            continue
        ereq_a, _, epref_a, epref_aa = _affinity_terms(epod)
        if cluster.hard_pod_affinity_weight > 0:
            for term in ereq_a:
                process_term(term, epod, pod, enode,
                             cluster.hard_pod_affinity_weight)
        for wt in epref_a:
            process_term(wt.pod_affinity_term, epod, pod, enode, wt.weight)
        for wt in epref_aa:
            process_term(wt.pod_affinity_term, epod, pod, enode, -wt.weight)

    max_c = max([counts.get(n.name, 0) for n in nodes] + [0])
    min_c = min([counts.get(n.name, 0) for n in nodes] + [0])
    out = {}
    for node in nodes:
        if max_c - min_c > 0:
            out[node.name] = int(
                10 * ((counts.get(node.name, 0) - min_c) / (max_c - min_c)))
        else:
            out[node.name] = 0
    return out


def prioritize(pod: api.Pod, cluster: ClusterState) -> dict[str, int]:
    """PrioritizeNodes with DefaultProvider weights (defaults.go:165-206):
    SelectorSpread x1, InterPodAffinity x1, LeastRequested x1,
    BalancedResourceAllocation x1, NodePreferAvoidPods x10000,
    NodeAffinity x1, TaintToleration x1."""
    nodes = cluster.ready_nodes()
    spread = selector_spread(pod, cluster)
    interpod = inter_pod_affinity_priority(pod, cluster)
    avoid = node_prefer_avoid(pod, cluster)
    naff = node_affinity_priority(pod, cluster)
    taint = taint_toleration_priority(pod, cluster)
    out = {}
    for node in nodes:
        node_pods = cluster.node_pods(node.name)
        out[node.name] = (
            spread[node.name]
            + interpod[node.name]
            + least_requested(pod, node, node_pods)
            + balanced_resource_allocation(pod, node, node_pods)
            + 10000 * avoid[node.name]
            + naff[node.name]
            + taint[node.name])
    return out


def preempt_candidates(pod: api.Pod, cluster: ClusterState,
                       max_victims: int = 16) -> dict[str, tuple[int, int]]:
    """Per-node minimal-cost victim prefix for an unschedulable priority
    pod — the pure-Python mirror of the tensor victim solve
    (engine/workloads/preemption.py), for differential parity testing.

    For each ready node whose NON-resource predicates pass with the
    victims still present, victims (pods of strictly lower priority) are
    sorted ascending by (priority, key) and the smallest prefix k whose
    eviction lets the pod fit is found.  Returns node name ->
    (k, summed victim priority) for feasible nodes."""
    out: dict[str, tuple[int, int]] = {}
    prio = pod.effective_priority
    meta = matching_anti_affinity_terms(pod, cluster)
    for node in cluster.ready_nodes():
        node_pods = cluster.node_pods(node.name)
        checks = [
            volume_zone(pod, node, cluster),
            max_pd_volume_count(pod, node_pods, "ebs", DEFAULT_MAX_EBS,
                                cluster),
            max_pd_volume_count(pod, node_pods, "gce", DEFAULT_MAX_GCE,
                                cluster),
            inter_pod_affinity(pod, node, cluster, meta),
            no_disk_conflict(pod, node_pods),
            pod_fits_host(pod, node),
            pod_fits_host_ports(pod, node_pods),
            pod_matches_node_labels(pod, node),
            pod_tolerates_node_taints(pod, node),
            check_node_memory_pressure(pod, node),
            check_node_disk_pressure(pod, node),
        ]
        if not all(checks):
            continue
        victims = sorted(node_pods,
                         key=lambda p: (p.effective_priority, p.key))
        victims = victims[:max_victims]
        eligible = [v for v in victims if v.effective_priority < prio]
        for k in range(len(eligible) + 1):
            remaining = [p for p in node_pods
                         if p.key not in {v.key for v in eligible[:k]}]
            if pod_fits_resources(pod, node, remaining):
                out[node.name] = (
                    k, sum(v.effective_priority for v in eligible[:k]))
                break
    return out


def preempt(pod: api.Pod, cluster: ClusterState,
            max_victims: int = 16) -> Optional[tuple[str, int, int]]:
    """The argmin preemption decision: (node, victim count, priority
    cost), minimizing (victim count, summed victim priority, node index
    in cluster order) — the engine's deterministic cost order.  None when
    no node works even after evictions."""
    cands = preempt_candidates(pod, cluster, max_victims)
    if not cands:
        return None
    node_order = {n.name: i for i, n in enumerate(cluster.nodes)}
    name = min(cands, key=lambda nm: (*cands[nm], node_order[nm]))
    return (name, *cands[name])


def schedule(pod: api.Pod, cluster: ClusterState) -> set[str]:
    """The reference Schedule's argmax set: all hosts selectHost could pick
    (its tie order is nondeterministic Go map iteration, so parity is
    membership in this set)."""
    fits, _ = find_nodes_that_fit(pod, cluster)
    if not fits:
        return set()
    scores = prioritize(pod, cluster)
    best = max(scores[n.name] for n in fits)
    return {n.name for n in fits if scores[n.name] == best}
