"""Multi-tenant solver service: tenant identity and share configuration.

PAPER.md's production story ("millions of users") is a FLEET of virtual
control planes sharing scarce accelerators, not one giant cluster.  The
``tenancy`` package is the solver-service layer that lets N scheduler
daemons (or one daemon serving N tenants' namespaces) share ONE device:

* this module — tenant identity (``KT_TENANTS``) and weighted shares
  (``KT_TENANT_WEIGHTS``), read once per daemon like every other knob;
* ``tenancy/packer.py`` — cross-tenant batch packing with weighted
  fairness and deadline-aware admission (a noisy tenant's burst queues
  behind its share; a trickle tenant's deadline batch preempts the
  packing order; gangs are never split);
* ``tenancy/service.py`` — the ``SolverService`` boundary: per-tenant
  circuit breakers and probe re-promotion (one tenant's poison batch
  degrades THAT tenant to the host engine, the service and the other
  tenants stay on device), packed multi-request solves, and the HTTP
  exposure for out-of-process submitters.

Tenant identity follows the PR 11 namespace-shard rule: a namespace that
IS a configured tenant name maps to itself; every other namespace maps
onto the tenant ring by crc32 — cross-process deterministic, so N
daemons (and the apiserver-side accounting) agree on who owns what
without coordination.
"""

from __future__ import annotations

import os
import zlib


def tenant_names() -> list[str]:
    """The configured tenant set (``KT_TENANTS="t-a,t-b,t-c"``); empty
    list = tenancy disabled (the single-owner engine, byte-for-byte the
    pre-tenancy behavior)."""
    from kubernetes_tpu.utils import knobs
    raw = knobs.get("KT_TENANTS")
    if not raw:
        return []
    return [t.strip() for t in raw.split(",") if t.strip()]


def enabled() -> bool:
    return bool(tenant_names())


def tenant_weights(tenants: list[str] | None = None) -> dict[str, float]:
    """Weighted shares from ``KT_TENANT_WEIGHTS="t-a:3,t-b:1"`` (default
    1.0 each; unknown names and bad numbers are ignored — a typo must
    not zero a tenant's share)."""
    if tenants is None:
        tenants = tenant_names()
    weights = {t: 1.0 for t in tenants}
    from kubernetes_tpu.utils import knobs
    raw = knobs.get("KT_TENANT_WEIGHTS")
    for entry in raw.split(","):
        entry = entry.strip()
        if not entry or ":" not in entry:
            continue
        name, _, val = entry.rpartition(":")
        name = name.strip()
        if name not in weights:
            continue
        try:
            w = float(val)
        except ValueError:
            continue
        if w > 0:
            weights[name] = w
    return weights


def tenant_of(namespace: str, tenants: list[str]) -> str:
    """Deterministic namespace -> tenant mapping: an exact tenant-name
    namespace maps to itself; everything else lands on the tenant ring
    by crc32 (the PR 11 shard hash — stable across processes, so every
    daemon and the service agree)."""
    if not tenants:
        return ""
    if namespace in tenants:
        return namespace
    return tenants[zlib.crc32(namespace.encode("utf-8")) % len(tenants)]


def pod_tenant(pod, tenants: list[str]) -> str:
    return tenant_of(pod.namespace, tenants)
