"""Cross-tenant batch packing: weighted fairness with deadline-aware
admission, deficit-round-robin over atomic gang units.

The single-owner drain solves whatever the queue holds; a SHARED solver
must decide whose pods ride each solve.  The packer makes that decision
in one place, with three rules:

* **Deadline preempts — within a budget.**  A pod whose queue age has
  crossed the urgency threshold (its formation deadline — the serving
  SLO's front half) is admitted FIRST, oldest first, regardless of
  share: a trickle tenant's 100 ms-deadline pod never waits behind a
  noisy neighbor's backlog.  Urgent admission is capped at a quarter of
  the drain and CHARGED against the tenant's deficit, so a saturating
  tenant (whose whole backlog is "urgent" by age) cannot launder its
  flood through the urgency lane — what it takes urgently it repays in
  later drains, and long-run shares still converge to the weights.
* **Weighted shares under saturation.**  Remaining capacity is filled
  by deficit round robin (DRR) over the tenants: each cycle a tenant
  earns quantum proportional to its ``KT_TENANT_WEIGHTS`` share and
  spends it admitting its oldest units; deficits persist across drains
  (bounded), so long-run admitted-pod shares converge to the weights
  even when drains are small.  A tenant with nothing pending earns
  nothing (no banked credit for idle tenants — classic DRR reset).
* **Gangs are atomic.**  A gang's members form ONE unit: admitted
  together or deferred together — the packer can never split a gang
  across solves (the all-or-nothing reduction's precondition).  A gang
  larger than the whole cap is admitted alone rather than starved.

Deferred pods return to the queue (their first-seen stamps survive — the
SLO clock keeps running) and count in
``scheduler_tenant_deferred_pods_total{tenant=}``: the fairness story's
observable — a noisy tenant's deferrals grow while its share is capped.
"""

from __future__ import annotations

from typing import Callable, Optional

from kubernetes_tpu.scheduler.batchformer import first_seen
from kubernetes_tpu.utils import knobs
from kubernetes_tpu.utils import metrics as metrics_mod

# Deficit carried across drains is clamped to this many drains' worth of
# quantum: enough memory for shares to converge, not enough for a tenant
# idle-then-bursting to monopolize one drain.
_DEFICIT_CLAMP_DRAINS = 2.0


class TenantPacker:
    """Selects and orders one drain's worth of pods across tenants.

    ``tenant_of`` maps a pod to its tenant; ``weights`` are the
    configured shares; ``urgent_s`` is the queue age past which a pod
    preempts the packing order (default: the formation deadline via
    ``urgent_s_fn``, falling back to ``KT_TENANT_URGENT_MS``)."""

    def __init__(self, tenant_of: Callable, weights: dict[str, float],
                 urgent_s_fn: Optional[Callable[[], float]] = None):
        self.tenant_of = tenant_of
        self.weights = dict(weights)
        self.urgent_s_fn = urgent_s_fn
        env = knobs.get("KT_TENANT_URGENT_MS")
        self._urgent_override = float(env) / 1e3 if env else None
        self._deficit: dict[str, float] = {}

    def urgent_s(self) -> float:
        if self._urgent_override is not None:
            return self._urgent_override
        if self.urgent_s_fn is not None:
            return max(self.urgent_s_fn() or 0.0, 0.0)
        return 0.0

    def _weight(self, tenant: str) -> float:
        return self.weights.get(tenant, 1.0)

    @staticmethod
    def _units(pods: list) -> list[list]:
        """Atomic admission units in arrival order: single pods, or the
        whole gang for annotated members (grouped at the FIRST member's
        position — the queue released them contiguously, but a chaos
        requeue can interleave)."""
        units: list[list] = []
        gang_unit: dict[str, list] = {}
        for pod in pods:
            name = getattr(pod, "gang", "")
            if name and getattr(pod, "gang_size", 0) > 1:
                unit = gang_unit.get(name)
                if unit is None:
                    unit = gang_unit[name] = []
                    units.append(unit)
                unit.append(pod)
            else:
                units.append([pod])
        return units

    def pack(self, pods: list, cap: int,
             now: Optional[float] = None) -> tuple[list, list]:
        """(selected, deferred): at most ``cap`` pods chosen urgency-
        first then by weighted DRR, FIFO within tenant; the remainder is
        the caller's to re-queue.  ``cap <= 0`` selects everything (the
        packer still orders: urgent units lead, tenants interleave by
        share — chunked streaming then serves the tail-latency-critical
        rows first)."""
        if not pods:
            return [], []
        import time as _time
        now = _time.perf_counter() if now is None else now
        units = self._units(pods)
        if cap <= 0:
            cap = sum(len(u) for u in units)
        urgent_s = self.urgent_s()

        def unit_age(unit) -> float:
            seen = [first_seen(p) for p in unit]
            seen = [s for s in seen if s is not None]
            return now - min(seen) if seen else 0.0

        selected: list = []
        space = cap
        per_tenant: dict[str, list] = {}
        urgent: list[tuple[float, int, list]] = []
        for i, unit in enumerate(units):
            age = unit_age(unit)
            if urgent_s > 0 and age >= urgent_s:
                urgent.append((-age, i, unit))
            else:
                tenant = self.tenant_of(unit[0])
                per_tenant.setdefault(tenant, []).append(unit)
        # Urgent units first, oldest first, within the urgency budget
        # (a quarter of the drain) and CHARGED to the tenant's deficit;
        # overflow rejoins the tenant's DRR queue in age order.  A unit
        # that no longer fits is deferred (never split) unless NOTHING
        # was admitted yet — one oversized gang must make progress
        # rather than starve.
        deferred: list = []
        urgent_budget = max(cap // 4, 1)
        overflow: dict[str, list] = {}
        for _, _, unit in sorted(urgent):
            tenant = self.tenant_of(unit[0])
            if len(unit) <= min(space, urgent_budget) or not selected:
                selected.extend(unit)
                space -= len(unit)
                urgent_budget -= len(unit)
                self._deficit[tenant] = \
                    self._deficit.get(tenant, 0.0) - len(unit)
            else:
                overflow.setdefault(tenant, []).append(unit)
        # Budget overflow rejoins the tenant's DRR queue AHEAD of its
        # non-urgent units (overflow is older by definition, and within
        # itself already age-sorted) — FIFO within tenant holds.
        for tenant, units_o in overflow.items():
            per_tenant[tenant] = units_o + per_tenant.get(tenant, [])
        # Weighted DRR over the non-urgent backlog.  Quantum scales to
        # the remaining space so one full cycle roughly fills the drain.
        pending = {t: us for t, us in per_tenant.items() if us}
        if pending and space > 0:
            total_w = sum(self._weight(t) for t in pending) or 1.0
            # Quantum covers the FULL cap, not just the post-urgency
            # remainder: urgent admissions were charged to their
            # tenants' deficits above, so the earn side must account
            # for the same capacity or every urgency lane user would be
            # under-paid its share.
            quantum = max(cap / total_w, 1.0)
            clamp = quantum * _DEFICIT_CLAMP_DRAINS
            while space > 0 and pending:
                progress = False
                for tenant in sorted(pending):
                    units_t = pending.get(tenant)
                    if not units_t:
                        continue
                    w = self._weight(tenant)
                    # Clamped both ways: banked credit is bounded (an
                    # idle-then-bursting tenant cannot monopolize), and
                    # urgency debt is bounded (a starving repayment
                    # spiral cannot lock a tenant out forever).
                    self._deficit[tenant] = min(
                        max(self._deficit.get(tenant, 0.0) + w * quantum,
                            -2.0 * cap),
                        w * clamp)
                    while units_t and space > 0:
                        unit = units_t[0]
                        size = len(unit)
                        if size > space and selected:
                            break  # doesn't fit this drain: defer whole
                        if self._deficit[tenant] < size and selected:
                            break  # share spent: wait for more quantum
                        units_t.pop(0)
                        selected.extend(unit)
                        space -= size
                        self._deficit[tenant] -= size
                        progress = True
                    if not units_t:
                        # DRR reset: an emptied queue banks no credit.
                        pending.pop(tenant, None)
                        self._deficit.pop(tenant, None)
                if not progress:
                    break
        deferred_tenants = set()
        for tenant, units_t in pending.items():
            if units_t:
                deferred_tenants.add(tenant)
            for unit in units_t:
                deferred.extend(unit)
        # Empty-queue debt forgiveness (the DRR reset, extended to the
        # urgency lane): a tenant whose backlog fully drained is not
        # saturating — carrying its urgency overdraft forward would
        # lock a trickle tenant out of a future burst it has not
        # earned... against credit it also never banks.
        for tenant in list(self._deficit):
            if tenant not in deferred_tenants and \
                    self._deficit[tenant] < 0:
                self._deficit[tenant] = 0.0
        if deferred:
            counts: dict[str, int] = {}
            for pod in deferred:
                t = self.tenant_of(pod)
                counts[t] = counts.get(t, 0) + 1
            for t, n in counts.items():
                metrics_mod.TENANT_DEFERRED.labels(tenant=t).inc(n)
        return selected, deferred
