"""The solver-service boundary: one device engine shared by N tenants,
with per-tenant fault isolation and a packed multi-request solve API.

``SolverService`` owns the relationship between tenants and ONE engine
(a ``GenericScheduler`` with its resident cluster state, guard ladder,
and pre-warmed buckets).  Three surfaces:

* **Daemon-embedded** (``KT_TENANTS`` on a ConfigFactory): the drain
  pipeline consults the service for weighted packing
  (``tenancy/packer.py``), per-tenant breaker routing, and fault
  attribution — one daemon's queue serves N tenants' namespaces.
* **In-process submit** (``submit(tenant, pods)``): N daemons (or any
  rig) share one service; concurrent submissions inside the pack
  window coalesce into ONE padded device solve — tenant-tagged row
  slices, the pad's live mask covering them all — and the results
  split back per request.  The sequential-greedy scan gives later rows
  in-batch visibility of earlier ones, so a packed solve decides
  exactly like solving each request in sequence (the parity the tests
  pin).
* **HTTP** (``serve_solver`` / ``SolverClient`` / ``ServiceEngine``):
  the same submit API over the wire — POST ``/solve`` with pod JSON —
  so a remote ConfigFactory schedules against a device it doesn't own.

**Fault isolation.**  Device faults are attributed per tenant: a mixed
batch that faults is SPLIT per tenant and re-solved (the attribution
bisection); the tenant whose sub-batch keeps faulting trips ITS breaker
(``KT_TENANT_BREAKER`` consecutive, default 2) and degrades to the host
fallback engine while every other tenant stays on device.  Probe solves
every ``KT_TENANT_PROBE_S`` (default 10 s) re-promote a broken tenant
once its solves come back clean.  A ``lost`` fault is a whole-device
event and still escalates through the global guard (engine/guard.py) —
per-tenant isolation covers the ATTRIBUTABLE faults (poison batches,
one tenant's OOM-sized rows), not a dead chip.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Optional

from kubernetes_tpu import tenancy as tenancy_mod
from kubernetes_tpu.tenancy.packer import TenantPacker
from kubernetes_tpu.utils import knobs, locktrace, metrics, threadreg
from kubernetes_tpu.utils.logging import get_logger

log = get_logger("tenancy")

MODE_DEVICE = "device"
MODE_HOST = "host"


class TenantState:
    """One tenant's breaker: consecutive attributable faults, mode, and
    the probe clock (mirrors the global guard's state machine, scoped)."""

    __slots__ = ("mode", "consecutive", "trips", "last_probe",
                 "opened_at", "host_s", "faults", "host_pods")

    def __init__(self):
        self.mode = MODE_DEVICE
        self.consecutive = 0
        self.trips = 0
        self.last_probe = 0.0
        self.opened_at = 0.0
        self.host_s = 0.0
        self.faults: dict[str, int] = {}
        self.host_pods = 0


class SolverService:
    """Per-tenant policy + the packed solve API over one shared engine."""

    def __init__(self, engine=None, tenants: Optional[list[str]] = None,
                 weights: Optional[dict[str, float]] = None,
                 ladder_fn: Optional[Callable[[], list]] = None,
                 urgent_s_fn: Optional[Callable[[], float]] = None):
        self.engine = engine
        self.tenants = list(tenants) if tenants is not None \
            else tenancy_mod.tenant_names()
        self.weights = dict(weights) if weights is not None \
            else tenancy_mod.tenant_weights(self.tenants)
        self.ladder_fn = ladder_fn or (lambda: [])
        self.breaker_threshold = knobs.get_int("KT_TENANT_BREAKER")
        self.probe_period_s = knobs.get_float("KT_TENANT_PROBE_S")
        self.pack_window_s = knobs.get_float("KT_TENANT_PACK_MS") / 1e3
        self.packer = TenantPacker(self.pod_tenant, self.weights,
                                   urgent_s_fn=urgent_s_fn)
        self._lock = locktrace.make_lock("tenancy.SolverService.state")
        self._states: dict[str, TenantState] = {}
        # Fault-attribution accounting: splits of mixed faulted batches,
        # and faults that landed on a batch carrying NO tenant currently
        # under suspicion (the cross-tenant leak the ratchet pins to 0).
        self.fault_splits = 0
        self.cross_tenant_faults = 0
        # Packed-submit accounting (the service API surface).
        self.packed_solves = 0
        self.packed_requests = 0
        # Per-tenant row-share EMA for HBM attribution (+ the 1/s
        # refresh stamp bounding the live-arrays walk).
        self._share_ema: dict[str, float] = {}
        self._hbm_stamp = 0.0
        # In-process submit coalescing.  ``engine_lock`` serializes
        # EVERY solve against the shared engine — packed submits here
        # AND the embedded daemon's drain dispatches (the pipeline
        # takes it around its tenant solve path): GenericScheduler's
        # solve state (last_node_index, agg handoff, resident arrays)
        # is not safe under two concurrent solvers.
        self._pending: list[dict] = []
        self._pending_lock = locktrace.make_lock(
            "tenancy.SolverService.pending")
        # hold_ms=0: this lock IS the device occupancy — packed submits
        # and the embedded daemon's drain serialize on one solver, so
        # its hold time is the solve itself (measured by stage spans),
        # not a long-hold bug.  Order tracking stays on.
        self.engine_lock = locktrace.make_lock(
            "tenancy.SolverService.engine", hold_ms=0)
        for t in self.tenants:
            metrics.TENANT_ENGINE_MODE.labels(tenant=t).set(0.0)

    # -- identity ---------------------------------------------------------

    def pod_tenant(self, pod) -> str:
        return tenancy_mod.tenant_of(pod.namespace, self.tenants)

    def tenants_of(self, pods: list) -> list[str]:
        return sorted({self.pod_tenant(p) for p in pods})

    def split_by_tenant(self, pods: list) -> list[list]:
        """Per-tenant sub-batches (arrival order preserved) — the fault
        attribution bisection's unit."""
        groups: dict[str, list] = {}
        for pod in pods:
            groups.setdefault(self.pod_tenant(pod), []).append(pod)
        return [groups[t] for t in sorted(groups)]

    def count_tenants(self, pods: list) -> dict[str, int]:
        counts: dict[str, int] = {}
        for pod in pods:
            t = self.pod_tenant(pod)
            counts[t] = counts.get(t, 0) + 1
        return counts

    def _state(self, tenant: str) -> TenantState:
        st = self._states.get(tenant)
        if st is None:
            st = self._states[tenant] = TenantState()
        return st

    # -- the per-tenant breaker -------------------------------------------

    def partition(self, pods: list) -> tuple[list, list, set]:
        """(device_pods, host_pods, probing_tenants): host-mode tenants'
        pods route to the host engine, EXCEPT a tenant whose probe is
        due — its pods ride the device set as a probe (success closes
        its breaker; a fault sends it back without re-escalating)."""
        device: list = []
        host: list = []
        probing: set = set()
        now = time.monotonic()
        with self._lock:
            for pod in pods:
                t = self.pod_tenant(pod)
                st = self._state(t)
                if st.mode == MODE_HOST:
                    if t in probing:
                        device.append(pod)
                    elif now - st.last_probe >= self.probe_period_s:
                        st.last_probe = now
                        probing.add(t)
                        device.append(pod)
                    else:
                        host.append(pod)
                else:
                    device.append(pod)
        return device, host, probing

    def note_fault(self, tenant: str, kind: str,
                   probe: bool = False) -> bool:
        """An attributable device fault on this tenant's (single-tenant)
        sub-batch.  Returns True when the tenant's breaker is (now)
        open — the caller routes the remainder to the host engine."""
        metrics.TENANT_FAULTS.labels(tenant=tenant, kind=kind).inc()
        with self._lock:
            st = self._state(tenant)
            st.faults[kind] = st.faults.get(kind, 0) + 1
            if probe and st.mode == MODE_HOST:
                # A failed probe never re-escalates: stay on host, reset
                # the probe clock.
                st.last_probe = time.monotonic()
                return True
            st.consecutive += 1
            if st.mode == MODE_HOST:
                return True
            if st.consecutive >= self.breaker_threshold:
                st.mode = MODE_HOST
                st.trips += 1
                st.opened_at = time.monotonic()
                st.last_probe = st.opened_at
                metrics.TENANT_BREAKER_TRIPS.labels(tenant=tenant).inc()
                metrics.TENANT_ENGINE_MODE.labels(tenant=tenant).set(1.0)
                log.warning(
                    "tenant %s breaker OPEN after %d consecutive "
                    "attributable fault(s); tenant falls back to the "
                    "host engine (probe every %.1fs) — other tenants "
                    "stay on device", tenant, st.consecutive,
                    self.probe_period_s)
                return True
        return False

    def note_success(self, tenant: str, probe: bool = False) -> None:
        with self._lock:
            st = self._state(tenant)
            st.consecutive = 0
            if probe and st.mode == MODE_HOST:
                st.host_s += time.monotonic() - st.opened_at
                st.mode = MODE_DEVICE
                metrics.TENANT_ENGINE_MODE.labels(tenant=tenant).set(0.0)
                log.info("tenant %s probe succeeded; breaker closed, "
                         "tenant re-promoted to device", tenant)

    def note_split(self, fault) -> None:
        """A mixed-tenant batch faulted: the caller is splitting it per
        tenant to attribute.  If NO tenant in flight is under suspicion
        yet this is the first sighting, not a leak — leaks are faults
        that keep landing on clean tenants' SOLO batches, counted by
        note_fault attribution in the artifact's cross-tenant row."""
        with self._lock:
            self.fault_splits += 1

    def note_cross_tenant_fault(self) -> None:
        with self._lock:
            self.cross_tenant_faults += 1

    def note_host_fallback(self, tenant: str, pods: int) -> None:
        with self._lock:
            self._state(tenant).host_pods += pods

    def tenant_mode(self, tenant: str) -> str:
        with self._lock:
            return self._state(tenant).mode

    # -- accounting (the PR 9 plane, per tenant) --------------------------

    def record_bound(self, pod, latency_s: Optional[float]) -> None:
        """Bind-ack hook: per-tenant bound counter + decision-latency
        histogram (the per-tenant SLO's source)."""
        t = self.pod_tenant(pod)
        metrics.TENANT_BOUND.labels(tenant=t).inc()
        if latency_s is not None:
            metrics.TENANT_DECISION_LATENCY.labels(tenant=t).observe(
                latency_s * 1e6)

    def record_solve(self, pods: list, transfer_bytes: int) -> None:
        """Post-solve attribution: the solve's host<->device bytes split
        by tenant row share, and the live-HBM gauge attributed by an
        EMA of row shares (the resident tensors serve every tenant; the
        EMA answers 'whose load is the device carrying')."""
        if not pods:
            return
        counts = self.count_tenants(pods)
        total = sum(counts.values()) or 1
        if transfer_bytes > 0:
            for t, n in counts.items():
                metrics.TENANT_TRANSFER_BYTES.labels(tenant=t).inc(
                    int(transfer_bytes * n / total))
        # The live-HBM read walks jax.live_arrays() on backends without
        # memory_stats — refresh the attribution gauge at most 1/s, not
        # per drain.
        from kubernetes_tpu.engine import devicestats
        now = time.monotonic()
        refresh = now - self._hbm_stamp >= 1.0
        hbm = devicestats.hbm_live_bytes() if refresh else 0
        with self._lock:
            if refresh:
                self._hbm_stamp = now
            for t in self.tenants:
                share = counts.get(t, 0) / total
                ema = self._share_ema.get(t, share)
                self._share_ema[t] = ema = 0.8 * ema + 0.2 * share
                if hbm:
                    metrics.TENANT_HBM_BYTES.labels(tenant=t).set(
                        hbm * ema)

    def report(self) -> dict:
        """The /debug/vars + artifact payload."""
        now = time.monotonic()
        with self._lock:
            per_tenant = {}
            for t in self.tenants:
                st = self._state(t)
                per_tenant[t] = {
                    "mode": st.mode,
                    "weight": self.weights.get(t, 1.0),
                    "breakerTrips": st.trips,
                    "faults": dict(st.faults),
                    "hostPods": st.host_pods,
                    "hostModeSeconds": round(
                        st.host_s + (now - st.opened_at
                                     if st.mode == MODE_HOST else 0.0),
                        2),
                }
            return {
                "tenants": per_tenant,
                "faultSplits": self.fault_splits,
                "crossTenantFaults": self.cross_tenant_faults,
                "packedSolves": self.packed_solves,
                "packedRequests": self.packed_requests,
            }

    # -- the packed submit API (in-process service boundary) --------------

    def _pad_bucket(self, n: int) -> int:
        """The warm ladder bucket a packed solve pads to (never an
        unwarmed shape); above the ladder, no pad (the one-shot path's
        own shape discipline applies)."""
        ladder = sorted(self.ladder_fn() or [])
        for b in ladder:
            if n <= b:
                return b
        return 0

    def submit(self, tenant: str, pods: list,
               timeout: float = 60.0) -> list:
        """Solve one tenant's pods against the shared engine.  Returns
        placements (node name or None per pod).  Concurrent submissions
        inside the pack window coalesce into one padded solve."""
        if not pods:
            return []
        if tenant not in self.tenants:
            # Client-supplied tenant strings are NOT trusted to name
            # state: map them onto the configured ring exactly like a
            # foreign namespace, so per-tenant state (and the
            # {tenant=} metric families) stay bounded by KT_TENANTS.
            tenant = tenancy_mod.tenant_of(tenant, self.tenants)
        req = {"tenant": tenant, "pods": list(pods),
               "done": threading.Event(), "result": None, "err": None}
        with self._pending_lock:
            self._pending.append(req)
        deadline = time.monotonic() + timeout
        while not req["done"].is_set():
            if not self.engine_lock.acquire(timeout=0.05):
                if time.monotonic() > deadline:
                    raise TimeoutError("solver service submit timed out")
                continue
            try:
                if req["done"].is_set():
                    break
                # Leader: linger one pack window so concurrent tenants'
                # requests coalesce, then take the whole pending set.
                if self.pack_window_s > 0:
                    time.sleep(self.pack_window_s)
                with self._pending_lock:
                    batch, self._pending = self._pending, []
                if batch:
                    self._solve_packed(batch)
            finally:
                self.engine_lock.release()
        if req["err"] is not None:
            raise req["err"]
        return req["result"]

    def submit_background(self, pods: list, timeout: float = 30.0,
                          joint: bool = True) -> Optional[list]:
        """Low-priority solve lane (the defragmenter's tenancy seat,
        ISSUE 17): solve only when NO live submit is pending or holding
        the engine — a background solve never queues ahead of a drain,
        so defrag dry-solves cannot steal device time from live
        tenants.  Returns placements, or None if the engine stayed busy
        for the whole ``timeout`` (the caller skips this round)."""
        if not pods:
            return []
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._pending_lock:
                busy = bool(self._pending)
            if busy:
                time.sleep(0.02)
                continue
            if not self.engine_lock.acquire(timeout=0.05):
                continue
            try:
                with self._pending_lock:
                    if self._pending:
                        # A live submit arrived while we took the lock:
                        # yield immediately — it becomes the leader on
                        # our release and drains the pending set.
                        continue
                return self.engine.schedule_batch(
                    pods, joint=joint, pad_to=self._pad_bucket(len(pods)))
            finally:
                self.engine_lock.release()
        return None

    def _solve_packed(self, batch: list[dict]) -> None:
        """One packed solve for every pending request: host-tenant
        requests route to the host engine per request; the device set
        concatenates into ONE padded solve (tenant-tagged slices) whose
        sequential scan gives later requests in-batch visibility of
        earlier placements — decision parity with solving them in
        sequence.  A device fault splits per tenant for attribution,
        exactly like the pipeline path."""
        with self._pending_lock:
            self.packed_solves += 1
            self.packed_requests += len(batch)
        device_reqs: list[dict] = []
        for req in batch:
            if self.tenant_mode(req["tenant"]) == MODE_HOST:
                self._solve_host_req(req)
            else:
                device_reqs.append(req)
        if not device_reqs:
            return
        combined: list = []
        slices: list[tuple[dict, int, int]] = []
        for req in device_reqs:
            start = len(combined)
            combined.extend(req["pods"])
            slices.append((req, start, len(combined)))
        try:
            placements = self._solve_device(combined)
        except Exception as err:  # noqa: BLE001 — attribute per tenant
            self._solve_split(device_reqs, err)
            return
        for req, a, b in slices:
            req["result"] = placements[a:b]
            req["done"].set()
        for t in {r["tenant"] for r in device_reqs}:
            self.note_success(t)
        self.record_solve(combined, 0)

    def _solve_device(self, pods: list) -> list:
        from kubernetes_tpu.chaos import device as chaos_device
        with chaos_device.tenant_context(self.tenants_of(pods)):
            return self.engine.schedule_batch(
                pods, pad_to=self._pad_bucket(len(pods)))

    def _solve_host_req(self, req: dict) -> None:
        try:
            req["result"] = self.engine.schedule_batch_host(req["pods"])
            self.note_host_fallback(req["tenant"], len(req["pods"]))
        except Exception as err:  # noqa: BLE001 — per-request failure
            req["err"] = err
        req["done"].set()

    def _solve_split(self, reqs: list[dict], fault) -> None:
        """Attribution on the submit path: re-solve each request alone;
        the one that still faults trips ITS tenant's breaker and falls
        to the host engine — the rest stay on device."""
        from kubernetes_tpu.engine.guard import DeviceFault
        if len(reqs) > 1:
            self.note_split(fault)
        for req in reqs:
            try:
                req["result"] = self._solve_device(req["pods"])
                req["done"].set()
                self.note_success(req["tenant"])
            except DeviceFault as f:
                self.note_fault(req["tenant"], f.kind)
                self._solve_host_req(req)
            except Exception as err:  # noqa: BLE001 — not a device fault
                req["err"] = err
                req["done"].set()


# -- HTTP exposure -----------------------------------------------------------


def solve_route(service: SolverService, body: bytes
                ) -> tuple[int, bytes, str]:
    """POST /solve handler body, shared by the standalone solver server
    and the scheduler daemon's status mux: ``{"tenant": t, "pods":
    [pod JSON, ...]}`` -> ``{"placements": [node|null, ...]}``."""
    from kubernetes_tpu.api import types as api
    try:
        obj = json.loads(body or b"{}")
        tenant = obj.get("tenant", "")
        pods = [api.pod_from_json(p) for p in obj.get("pods") or []]
    except (ValueError, KeyError, TypeError) as err:
        return 400, json.dumps({"error": f"bad request: {err}"}).encode(), \
            "application/json"
    try:
        placements = service.submit(tenant, pods)
    except Exception as err:  # noqa: BLE001 — surface as a 500 payload
        return 500, json.dumps({"error": str(err)}).encode(), \
            "application/json"
    return 200, json.dumps({"tenant": tenant,
                            "placements": placements}).encode(), \
        "application/json"


def serve_solver(service: SolverService, port: int = 0,
                 host: str = "127.0.0.1"):
    """The standalone solver-service HTTP surface (the scheduler's
    status mux serves the same routes when tenancy is on): POST /solve,
    GET /tenancy (the report), GET /healthz."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def _send(self, code: int, body: bytes,
                  ctype: str = "application/json") -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            path = self.path.partition("?")[0]
            if path == "/healthz":
                self._send(200, b"ok", "text/plain")
            elif path == "/tenancy":
                self._send(200, json.dumps(service.report()).encode())
            else:
                self._send(404, b'{"error": "not found"}')

        def do_POST(self):
            path = self.path.partition("?")[0]
            if path != "/solve":
                self._send(404, b'{"error": "not found"}')
                return
            try:
                clen = int(self.headers.get("Content-Length", "0") or 0)
            except ValueError:
                clen = 0
            body = self.rfile.read(clen) if clen else b""
            self._send(*solve_route(service, body))

    server = ThreadingHTTPServer((host, port), Handler)
    threadreg.spawn(server.serve_forever, name="solver-service-http")
    return server


class SolverClient:
    """Client side of the HTTP solve surface."""

    def __init__(self, base_url: str, timeout: float = 60.0):
        from urllib.parse import urlparse
        u = urlparse(base_url)
        self.host = u.hostname or "127.0.0.1"
        self.port = u.port or 80
        self.timeout = timeout

    def solve(self, tenant: str, pods: list) -> list:
        """``pods``: api.Pod objects (serialized via pod_to_json) or raw
        pod JSON dicts."""
        import http.client

        from kubernetes_tpu.api import types as api
        payload = json.dumps({
            "tenant": tenant,
            "pods": [p if isinstance(p, dict) else api.pod_to_json(p)
                     for p in pods]}).encode()
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=self.timeout)
        try:
            conn.request("POST", "/solve", body=payload,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            body = resp.read()
        finally:
            conn.close()
        obj = json.loads(body or b"{}")
        if resp.status != 200:
            raise RuntimeError(f"solver service {resp.status}: "
                               f"{obj.get('error')}")
        return obj.get("placements") or []


class ServiceEngine:
    """A drop-in solve façade for a ConfigFactory whose daemon submits
    to a SHARED solver service instead of owning a device: the solve
    verbs forward to ``service.submit`` (in-process) or a
    ``SolverClient`` (HTTP), tagged with this daemon's tenant; cache
    feeding, assume/bind, and failure handling stay on the daemon.
    Built by ``ConfigFactory(solver_service=...)``."""

    def __init__(self, backend, tenant: str = "",
                 cache=None, listers=None):
        from kubernetes_tpu.cache.scheduler_cache import SchedulerCache
        from kubernetes_tpu.engine.generic_scheduler import Listers
        self.backend = backend
        self.tenant = tenant
        self.cache = cache if cache is not None else SchedulerCache()
        self.listers = listers if listers is not None else Listers()
        self.extenders = []
        # The client daemon runs no device solves of its own: its guard
        # is a disabled shim so the pipeline takes the plain dispatch
        # path (faults are handled service-side).
        from kubernetes_tpu.engine.guard import DeviceGuard
        self.guard = DeviceGuard()
        self.guard.enabled = False

    # The resident mirror lives with the service's engine; recovery's
    # force_resnapshot hook degrades to a no-op shim here.
    @property
    def resident(self):
        class _Shim:
            def invalidate(self):
                pass

            def prewarm_scatter(self):
                pass
        return _Shim()

    def _submit(self, pods: list) -> list:
        if hasattr(self.backend, "submit"):
            return self.backend.submit(self.tenant, pods)
        return self.backend.solve(self.tenant, pods)

    def schedule_batch(self, pods: list, joint: bool = False,
                       pad_to: int = 0) -> list:
        return self._submit(pods) if pods else []

    def schedule_batch_host(self, pods: list) -> list:
        return self._submit(pods) if pods else []

    def schedule_batch_stream(self, pods: list, chunk_size: int = 0,
                              defer_readback: bool = False):
        chunk = max(chunk_size or len(pods), 1)
        for i in range(0, len(pods), chunk):
            part = pods[i:i + chunk]
            placements = self._submit(part)
            if defer_readback:
                yield part, (lambda p=part, r=placements: (p, r))
            else:
                yield part, placements

    def schedule(self, pod):
        from kubernetes_tpu.engine.generic_scheduler import FitError
        dest = self._submit([pod])[0]
        if dest is None:
            raise FitError(pod, {})
        return dest

    def explain_failures(self, pods: list) -> dict:
        return {}

    def find_preemptions(self, pods: list, protected=frozenset()) -> list:
        return []

    def take_agg_handoff(self):
        return None
