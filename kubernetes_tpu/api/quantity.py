"""Kubernetes resource.Quantity parsing.

Behavioral reference: ``pkg/api/resource/quantity.go`` (suffixes at
``pkg/api/resource/suffix.go``).  We only need the subset the scheduler
touches: parse a quantity string to an exact integer value (``Value()``)
or milli-value (``MilliValue()``).
"""

from __future__ import annotations

import functools
from fractions import Fraction

# Binary SI (1024-based) and decimal SI (1000-based) suffix tables, per
# pkg/api/resource/suffix.go.
_BIN = {"Ki": 1024, "Mi": 1024**2, "Gi": 1024**3, "Ti": 1024**4,
        "Pi": 1024**5, "Ei": 1024**6}
_DEC = {"n": Fraction(1, 10**9), "u": Fraction(1, 10**6),
        "m": Fraction(1, 1000), "": Fraction(1), "k": Fraction(10**3),
        "M": Fraction(10**6), "G": Fraction(10**9), "T": Fraction(10**12),
        "P": Fraction(10**15), "E": Fraction(10**18)}


def parse_quantity(s: str | int | float) -> Fraction:
    """Parse a Kubernetes quantity ("100m", "2Gi", "1500M", 2) to a Fraction."""
    if isinstance(s, (int, float)):
        return Fraction(s)
    s = s.strip()
    if not s:
        raise ValueError("empty quantity")
    for suf, mult in _BIN.items():
        if s.endswith(suf):
            return Fraction(s[: -len(suf)]) * mult
    # decimal exponent form e.g. "12e6"
    for suf, mult in _DEC.items():
        if suf and s.endswith(suf):
            return Fraction(s[: -len(suf)]) * mult
    if "e" in s or "E" in s:
        mantissa, _, exp = s.replace("E", "e").partition("e")
        return Fraction(mantissa) * Fraction(10) ** int(exp)
    return Fraction(s)


@functools.lru_cache(maxsize=4096)
def value(s: str | int | float) -> int:
    """Quantity.Value(): ceil to integer (quantity.go rounds up).  Memoized:
    cluster workloads reuse a handful of distinct quantity strings, and the
    batch compiler parses them per pod."""
    f = parse_quantity(s)
    return int(-((-f.numerator) // f.denominator))  # ceil


@functools.lru_cache(maxsize=4096)
def milli_value(s: str | int | float) -> int:
    """Quantity.MilliValue(): value * 1000, ceil to integer.  Memoized."""
    f = parse_quantity(s) * 1000
    return int(-((-f.numerator) // f.denominator))  # ceil
