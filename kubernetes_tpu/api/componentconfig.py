"""Typed component configuration — the componentconfig API group slice.

The reference gives every daemon a versioned config struct
(``KubeSchedulerConfiguration``, pkg/apis/componentconfig/types.go:426-457)
with defaults applied by the scheme and a ``--config``-style file path on
the binary; flags override file values.  This module is that struct for
the scheduler daemon: JSON both ways, reference defaults, collect-all
validation (the field-error list style of pkg/api/validation).
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass, field

from kubernetes_tpu.api import types as api

DEFAULT_PORT = 10251  # options/options.go:49 SchedulerDefaultPort
DEFAULT_FAILURE_DOMAINS = (
    "kubernetes.io/hostname,"
    "failure-domain.beta.kubernetes.io/zone,"
    "failure-domain.beta.kubernetes.io/region")  # pkg/api/types.go:3053-3063


@dataclass
class LeaderElectionConfiguration:
    """componentconfig.LeaderElectionConfiguration (types.go:398-424);
    the scheduler's default is LeaderElect=true in the reference's
    defaulting (options/options.go:46) but opt-in here, matching the
    daemon flag surface."""

    leader_elect: bool = False
    lease_duration: float = 15.0
    renew_deadline: float = 10.0
    retry_period: float = 2.0


@dataclass
class KubeSchedulerConfiguration:
    """componentconfig.KubeSchedulerConfiguration (types.go:426-457)."""

    port: int = DEFAULT_PORT
    algorithm_provider: str = "DefaultProvider"
    policy_config_file: str = ""
    scheduler_name: str = api.DEFAULT_SCHEDULER_NAME
    kube_api_qps: float = 50.0
    kube_api_burst: int = 100
    hard_pod_affinity_symmetric_weight: int = 1
    failure_domains: str = DEFAULT_FAILURE_DOMAINS
    # The reference's scheme default for the scheduler is
    # EnableProfiling=true (v1alpha1 defaults) — a config file that never
    # mentions it must not silently turn /debug off.
    enable_profiling: bool = True
    feature_gates: str = ""          # "Name=true,Other=false"
    leader_election: LeaderElectionConfiguration = field(
        default_factory=LeaderElectionConfiguration)

    # -- codec -----------------------------------------------------------

    _KEYS = {
        "port": "port",
        "algorithmProvider": "algorithm_provider",
        "policyConfigFile": "policy_config_file",
        "schedulerName": "scheduler_name",
        "kubeAPIQPS": "kube_api_qps",
        "kubeAPIBurst": "kube_api_burst",
        "hardPodAffinitySymmetricWeight":
            "hard_pod_affinity_symmetric_weight",
        "failureDomains": "failure_domains",
        "enableProfiling": "enable_profiling",
        "featureGates": "feature_gates",
    }
    _LE_KEYS = {
        "leaderElect": "leader_elect",
        "leaseDuration": "lease_duration",
        "renewDeadline": "renew_deadline",
        "retryPeriod": "retry_period",
    }

    @classmethod
    def from_json(cls, text: str) -> "KubeSchedulerConfiguration":
        raw = json.loads(text)
        if not isinstance(raw, dict):
            raise ValueError("KubeSchedulerConfiguration must be an object")
        kind = raw.get("kind", "KubeSchedulerConfiguration")
        if kind != "KubeSchedulerConfiguration":
            raise ValueError(f"wrong kind {kind!r}")
        cfg = cls()
        unknown = [k for k in raw
                   if k not in cls._KEYS
                   and k not in ("kind", "apiVersion", "leaderElection")]
        if unknown:
            raise ValueError(f"unknown fields: {', '.join(sorted(unknown))}")
        for wire, attr in cls._KEYS.items():
            if wire in raw:
                setattr(cfg, attr, raw[wire])
        le = raw.get("leaderElection") or {}
        unknown_le = [k for k in le if k not in cls._LE_KEYS]
        if unknown_le:
            raise ValueError("unknown leaderElection fields: "
                             + ", ".join(sorted(unknown_le)))
        for wire, attr in cls._LE_KEYS.items():
            if wire in le:
                setattr(cfg.leader_election, attr, le[wire])
        return cfg

    def to_json(self) -> str:
        out: dict = {"kind": "KubeSchedulerConfiguration",
                     "apiVersion": "componentconfig/v1alpha1"}
        for wire, attr in self._KEYS.items():
            out[wire] = getattr(self, attr)
        out["leaderElection"] = {
            wire: getattr(self.leader_election, attr)
            for wire, attr in self._LE_KEYS.items()}
        return json.dumps(out, indent=1)

    def validate(self) -> list[str]:
        """Collect-all field errors (validation.go style).  Type errors
        (a JSON string where a number belongs) are collected too, not
        raised — the contract is one list with every problem."""
        errors: list[str] = []
        num = (int, float)
        typed = [("port", self.port, num),
                 ("kubeAPIQPS", self.kube_api_qps, num),
                 ("kubeAPIBurst", self.kube_api_burst, num),
                 ("hardPodAffinitySymmetricWeight",
                  self.hard_pod_affinity_symmetric_weight, num),
                 ("enableProfiling", self.enable_profiling, bool),
                 ("leaderElection.leaseDuration",
                  self.leader_election.lease_duration, num),
                 ("leaderElection.renewDeadline",
                  self.leader_election.renew_deadline, num)]
        bad_types = set()
        for fieldname, value, kinds in typed:
            # bool is an int subclass: a JSON true for a numeric field
            # should still be flagged.
            if not isinstance(value, kinds) or \
                    (kinds is num and isinstance(value, bool)):
                errors.append(f"{fieldname}: expected a "
                              f"{'number' if kinds is num else 'boolean'},"
                              f" got {value!r}")
                bad_types.add(fieldname)
        if "port" not in bad_types and not 0 <= self.port <= 65535:
            errors.append(f"port: {self.port} not in 0-65535")
        if "hardPodAffinitySymmetricWeight" not in bad_types and \
                not 0 <= self.hard_pod_affinity_symmetric_weight <= 100:
            errors.append("hardPodAffinitySymmetricWeight: "
                          f"{self.hard_pod_affinity_symmetric_weight} "
                          "not in 0-100")
        if "kubeAPIQPS" not in bad_types and self.kube_api_qps < 0:
            errors.append(f"kubeAPIQPS: {self.kube_api_qps} negative")
        if "kubeAPIBurst" not in bad_types and self.kube_api_burst < 0:
            errors.append(f"kubeAPIBurst: {self.kube_api_burst} negative")
        if self.algorithm_provider not in ("DefaultProvider",
                                           "ClusterAutoscalerProvider"):
            errors.append("algorithmProvider: unknown "
                          f"{self.algorithm_provider!r}")
        if self.failure_domains != DEFAULT_FAILURE_DOMAINS:
            # The engine's topology tables pin the default key set
            # (features/affinity.py _DomainTable, ops/interpod.py
            # N_DEFAULT_KEYS); a custom set silently doing nothing would
            # be worse than an explicit refusal.
            errors.append("failureDomains: custom domains are not "
                          "supported by this build (fixed to "
                          f"{DEFAULT_FAILURE_DOMAINS!r})")
        le = self.leader_election
        if "leaderElection.leaseDuration" not in bad_types and \
                "leaderElection.renewDeadline" not in bad_types and \
                le.renew_deadline >= le.lease_duration:
            errors.append("leaderElection: renewDeadline "
                          f"{le.renew_deadline} must be < leaseDuration "
                          f"{le.lease_duration}")
        try:
            from kubernetes_tpu.utils.featuregate import FeatureGate
            FeatureGate.parse(self.feature_gates)
        except ValueError as err:
            errors.append(f"featureGates: {err}")
        return errors

    def asdict(self) -> dict:
        return asdict(self)
