"""Field selectors — server-side list/watch filtering (pkg/fields).

The reference's scheduler never sees assigned pods on its queue watch:
it lists/watches with ``fieldSelector=spec.nodeName=`` (factory.go:
466-469 ``selector.Everything`` + the nodeName field requirement), and
kubelets watch only their own pods via ``spec.nodeName=<node>``
(pkg/kubelet/config/apiserver.go).  Until round 5 this repo filtered
client-side, so at 30k-pod density every pod event crossed the wire to
every watcher — the VERDICT r4 wire lever.

Grammar (pkg/fields/selector.go ParseSelector): comma-separated
requirements, each ``path=value``, ``path==value`` or ``path!=value``.
A field missing from the object compares as ``""`` (fields.Set maps a
pod to a flat string map the same way, pkg/api/pod_fieldselector).

Matching walks the object's JSON dict by the dotted path; scalar
values compare by their string form.  This is deliberately generic
where the reference registers per-kind conversion functions — any
stored field is selectable, which the conformance tests pin on both
apiservers (Python and native).
"""

from __future__ import annotations

from typing import Callable, Optional

__all__ = ["Requirement", "parse", "matcher"]


class Requirement:
    __slots__ = ("path", "op", "value")

    def __init__(self, path: tuple[str, ...], op: str, value: str):
        self.path = path
        self.op = op        # "=" or "!="
        self.value = value

    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        return f"Requirement({'.'.join(self.path)}{self.op}{self.value})"


def parse(selector: str) -> tuple[Requirement, ...]:
    """ParseSelector: raises ValueError on a malformed requirement so the
    server can 400 instead of silently matching everything."""
    reqs: list[Requirement] = []
    for part in selector.split(","):
        part = part.strip()
        if not part:
            continue
        if "!=" in part:
            field, _, value = part.partition("!=")
            op = "!="
        elif "==" in part:
            field, _, value = part.partition("==")
            op = "="
        elif "=" in part:
            field, _, value = part.partition("=")
            op = "="
        else:
            raise ValueError(f"invalid field selector {part!r}")
        field = field.strip()
        if not field:
            raise ValueError(f"invalid field selector {part!r}")
        reqs.append(Requirement(tuple(field.split(".")), op, value.strip()))
    return tuple(reqs)


def _get_field(obj: dict, path: tuple[str, ...]) -> str:
    cur = obj
    for seg in path:
        if not isinstance(cur, dict):
            return ""
        cur = cur.get(seg)
    if cur is None or isinstance(cur, (dict, list)):
        return ""
    if isinstance(cur, bool):  # JSON booleans stringify lowercase
        return "true" if cur else "false"
    return str(cur)


def matcher(selector: str) -> Optional[Callable[[dict], bool]]:
    """Compile a selector string to a predicate; None when the selector
    is empty (match-everything — the caller can skip filtering)."""
    reqs = parse(selector)
    if not reqs:
        return None

    def match(obj: dict) -> bool:
        for r in reqs:
            got = _get_field(obj, r.path)
            if (got == r.value) != (r.op == "="):
                return False
        return True
    return match
