"""Policy validation (plugin/pkg/scheduler/api/validation/validation.go +
the plugin registry's unknown-name rejection, factory/plugins.go:251,266).

``validate_policy`` collects EVERY error before failing (the reference's
utilerrors.NewAggregate behavior) and raises ``PolicyValidationError``.
"""

from __future__ import annotations

from kubernetes_tpu.api.policy import (Policy, canonical_predicate_name,
                                       canonical_priority_name)

# Every registered fit predicate name (factory/plugins.go registrations via
# algorithmprovider/defaults/defaults.go:65-163 + legacy aliases).
KNOWN_PREDICATES = frozenset({
    "PodFitsResources", "PodFitsHost", "HostName", "PodFitsHostPorts",
    "PodFitsPorts", "MatchNodeSelector", "NoDiskConflict",
    "NoVolumeZoneConflict", "MaxEBSVolumeCount", "MaxGCEPDVolumeCount",
    "GeneralPredicates", "PodToleratesNodeTaints",
    "CheckNodeMemoryPressure", "CheckNodeDiskPressure",
    "MatchInterPodAffinity", "ServiceAffinity", "NewNodeLabelPredicate",
})

KNOWN_PRIORITIES = frozenset({
    "LeastRequestedPriority", "MostRequestedPriority",
    "BalancedResourceAllocation", "SelectorSpreadPriority",
    "ServiceSpreadingPriority", "NodePreferAvoidPodsPriority",
    "NodeAffinityPriority", "TaintTolerationPriority",
    "InterPodAffinityPriority", "ImageLocalityPriority", "EqualPriority",
    "ServiceAntiAffinityPriority", "NodeLabelPriority",
})


class PolicyValidationError(ValueError):
    def __init__(self, errors: list[str]):
        self.errors = errors
        super().__init__("; ".join(errors))


def validate_policy(policy: Policy) -> None:
    """Raise PolicyValidationError listing every problem (validation.go:28
    'does not return early so that it can find as many errors as possible')."""
    errors: list[str] = []
    for pred in policy.predicates:
        name = canonical_predicate_name(pred)
        if name not in KNOWN_PREDICATES:
            errors.append(
                f'Invalid predicate name "{pred.name}" specified - no '
                f"corresponding function found")
    for prio in policy.priorities:
        # validation.go:31-34: weight must be positive.
        if prio.weight <= 0:
            errors.append(f"Priority {prio.name} should have a positive "
                          f"weight applied to it")
        name = canonical_priority_name(prio)
        if name not in KNOWN_PRIORITIES:
            errors.append(f"Invalid priority name {prio.name} specified - "
                          f"no corresponding function found")
    for ext in policy.extenders:
        # validation.go:37-41: extender weight must be non-negative.
        if ext.weight < 0:
            errors.append(f"Priority for extender {ext.url_prefix} should "
                          f"have a non negative weight applied to it")
        if not ext.url_prefix:
            errors.append("Extender is missing urlPrefix")
        if not ext.filter_verb and not ext.prioritize_verb:
            errors.append(f"Extender {ext.url_prefix} must configure a "
                          f"filterVerb or prioritizeVerb")
    if not 0 <= policy.hard_pod_affinity_symmetric_weight <= 100:
        # factory.go:305 rejects values outside 0-100.
        errors.append("hardPodAffinitySymmetricWeight must be in [0, 100]")
    if errors:
        raise PolicyValidationError(errors)
