"""Scheduler policy: which predicates/priorities run, with what weights.

Mirrors the reference's policy API (``plugin/pkg/scheduler/api/types.go:27-131``,
JSON-compatible), the plugin registries (``factory/plugins.go``), and the
default algorithm providers (``algorithmprovider/defaults/defaults.go``).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Optional

# defaults.go:42-54 — provider-configured volume caps (env-overridable).
DEFAULT_MAX_EBS_VOLUMES = 39
DEFAULT_MAX_GCE_PD_VOLUMES = 16

# options/options.go:46, pkg/api/types.go:3053
DEFAULT_HARD_POD_AFFINITY_SYMMETRIC_WEIGHT = 1


@dataclass(frozen=True)
class PredicateSpec:
    name: str
    # LabelsPresence argument (api/types.go:58-70)
    labels: tuple[str, ...] = ()
    presence: bool = False
    # ServiceAffinity argument
    affinity_labels: tuple[str, ...] = ()
    # MaxEBSVolumeCount / MaxGCEPDVolumeCount cap; 0 = provider default
    # (39 / 16, env KUBE_MAX_PD_VOLS override — defaults.go:42-54)
    max_volumes: int = 0


@dataclass(frozen=True)
class PrioritySpec:
    name: str
    weight: int = 1
    # LabelPreference argument (api/types.go:95-110)
    label: str = ""
    presence: bool = False
    # ServiceAntiAffinity argument
    anti_affinity_label: str = ""


@dataclass(frozen=True)
class ExtenderConfig:
    """api/types.go:114-131."""

    url_prefix: str = ""
    filter_verb: str = ""
    prioritize_verb: str = ""
    weight: int = 1
    api_version: str = "v1"
    enable_https: bool = False
    http_timeout_s: float = 5.0  # extender.go:34-36


@dataclass
class Policy:
    predicates: list[PredicateSpec] = field(default_factory=list)
    priorities: list[PrioritySpec] = field(default_factory=list)
    extenders: list[ExtenderConfig] = field(default_factory=list)
    hard_pod_affinity_symmetric_weight: int = DEFAULT_HARD_POD_AFFINITY_SYMMETRIC_WEIGHT


# GeneralPredicates composite (predicates.go:773-823) — also re-run by the
# kubelet at admission (pkg/kubelet/lifecycle/predicate.go), which is why it
# is factored as one named unit.
GENERAL_PREDICATES = ("PodFitsResources", "PodFitsHost", "PodFitsHostPorts",
                      "MatchNodeSelector")


def default_provider() -> Policy:
    """DefaultProvider (defaults.go:113-206)."""
    return Policy(
        predicates=[
            PredicateSpec("NoVolumeZoneConflict"),
            PredicateSpec("MaxEBSVolumeCount"),
            PredicateSpec("MaxGCEPDVolumeCount"),
            PredicateSpec("MatchInterPodAffinity"),
            PredicateSpec("NoDiskConflict"),
            PredicateSpec("GeneralPredicates"),
            PredicateSpec("PodToleratesNodeTaints"),
            PredicateSpec("CheckNodeMemoryPressure"),
            PredicateSpec("CheckNodeDiskPressure"),
        ],
        priorities=[
            PrioritySpec("SelectorSpreadPriority", 1),
            PrioritySpec("InterPodAffinityPriority", 1),
            PrioritySpec("LeastRequestedPriority", 1),
            PrioritySpec("BalancedResourceAllocation", 1),
            PrioritySpec("NodePreferAvoidPodsPriority", 10000),
            PrioritySpec("NodeAffinityPriority", 1),
            PrioritySpec("TaintTolerationPriority", 1),
        ])


def cluster_autoscaler_provider() -> Policy:
    """ClusterAutoscalerProvider (defaults.go:58-60): MostRequested replaces
    LeastRequested."""
    p = default_provider()
    p.priorities = [
        PrioritySpec("MostRequestedPriority", s.weight)
        if s.name == "LeastRequestedPriority" else s
        for s in p.priorities]
    return p


PROVIDERS = {
    "DefaultProvider": default_provider,
    "ClusterAutoscalerProvider": cluster_autoscaler_provider,
}


def policy_from_json(text: str) -> Policy:
    """Parse a scheduler policy config file (CreateFromConfig,
    factory.go:267-300; wire schema api/v1/types.go)."""
    d = json.loads(text)
    preds = []
    for p in d.get("predicates") or ():
        arg = p.get("argument") or {}
        lp = arg.get("labelsPresence") or {}
        sa = arg.get("serviceAffinity") or {}
        preds.append(PredicateSpec(
            name=p.get("name", ""),
            labels=tuple(lp.get("labels") or ()),
            presence=bool(lp.get("presence", False)),
            affinity_labels=tuple(sa.get("labels") or ())))
    prios = []
    for p in d.get("priorities") or ():
        arg = p.get("argument") or {}
        lp = arg.get("labelPreference") or {}
        saa = arg.get("serviceAntiAffinity") or {}
        prios.append(PrioritySpec(
            name=p.get("name", ""), weight=int(p.get("weight", 1)),
            label=lp.get("label", ""), presence=bool(lp.get("presence", False)),
            anti_affinity_label=saa.get("label", "")))
    exts = []
    for e in d.get("extenders") or ():
        exts.append(ExtenderConfig(
            url_prefix=e.get("urlPrefix", ""),
            filter_verb=e.get("filterVerb", ""),
            prioritize_verb=e.get("prioritizeVerb", ""),
            weight=int(e.get("weight", 1)),
            api_version=e.get("apiVersion", "v1"),
            enable_https=bool(e.get("enableHttps", False)),
            http_timeout_s=float(e.get("httpTimeout", 5_000_000_000)) / 1e9))
    return Policy(predicates=preds, priorities=prios, extenders=exts)


def canonical_predicate_name(spec: PredicateSpec) -> str:
    """RegisterCustomFitPredicate (plugins.go:96-142) keys policy entries by
    ARGUMENT, not by the user-chosen name: any entry carrying a
    serviceAffinity argument IS the ServiceAffinity predicate, and a
    labelsPresence argument IS CheckNodeLabelPresence."""
    if spec.affinity_labels:
        return "ServiceAffinity"
    if spec.labels:
        return "NewNodeLabelPredicate"
    return spec.name


def canonical_priority_name(spec: PrioritySpec) -> str:
    """RegisterCustomPriorityFunction (plugins.go:149-186): argument-keyed."""
    if spec.anti_affinity_label:
        return "ServiceAntiAffinityPriority"
    if spec.label:
        return "NodeLabelPriority"
    return spec.name


def service_affinity_labels(policy: Policy) -> tuple[str, ...]:
    """Labels of the (single supported) ServiceAffinity predicate instance."""
    for p in policy.predicates:
        if canonical_predicate_name(p) == "ServiceAffinity" and \
                p.affinity_labels:
            return p.affinity_labels
    return ()


def service_anti_affinity_labels(policy: Policy) -> tuple[str, ...]:
    """Per-instance labels of ServiceAntiAffinity entries, in policy order
    (matches the solver's aux index assignment)."""
    return tuple(
        s.anti_affinity_label for s in policy.priorities
        if canonical_priority_name(s) == "ServiceAntiAffinityPriority"
        and s.weight != 0)


def node_label_args(policy: Policy):
    """(labels, presence) of the CheckNodeLabelPresence predicate, or None."""
    for p in policy.predicates:
        if canonical_predicate_name(p) == "NewNodeLabelPredicate" and p.labels:
            return (p.labels, p.presence)
    return None


def node_label_prio_args(policy: Policy) -> tuple[tuple[str, bool], ...]:
    return tuple((s.label, s.presence) for s in policy.priorities
                 if canonical_priority_name(s) == "NodeLabelPriority"
                 and s.weight != 0)


def expand_predicates(policy: Policy) -> list[PredicateSpec]:
    """Expand the GeneralPredicates composite into its members."""
    out: list[PredicateSpec] = []
    for p in policy.predicates:
        if p.name == "GeneralPredicates":
            out.extend(PredicateSpec(n) for n in GENERAL_PREDICATES)
        else:
            out.append(p)
    return out
