"""Scheduler-facing object model.

A minimal typed mirror of the reference API objects, restricted to the fields
the scheduling path reads (behavioral reference: ``pkg/api/types.go``,
annotation helpers ``pkg/api/helpers.go:414-505``).  In the v1.4.0-alpha era,
affinity, tolerations, and taints live in *annotations* as serialized JSON
(``scheduler.alpha.kubernetes.io/{affinity,tolerations,taints}``); the model
parses both those annotations and first-class fields so callers can use either.

Everything here is pure host-side Python; the feature compiler
(``kubernetes_tpu.features.compiler``) turns these into device tensors.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Optional

from kubernetes_tpu.api.quantity import milli_value, value

# Annotation keys (pkg/api/helpers.go:414-424, pkg/api/types.go:3053).
# Resource kinds whose storage keys carry a namespace segment — one shared
# definition so the apiserver's key derivation and the client's URL paths
# can never drift apart.
NAMESPACED_KINDS = frozenset({"pods", "services", "persistentvolumeclaims",
                              "replicationcontrollers", "replicasets",
                              "events", "endpoints", "deployments",
                              "limitranges", "resourcequotas",
                              "daemonsets", "jobs",
                              "roles", "rolebindings",
                              "horizontalpodautoscalers",
                              "poddisruptionbudgets", "scheduledjobs",
                              "petsets",
                              "secrets", "configmaps", "serviceaccounts"})

AFFINITY_ANNOTATION_KEY = "scheduler.alpha.kubernetes.io/affinity"
TOLERATIONS_ANNOTATION_KEY = "scheduler.alpha.kubernetes.io/tolerations"
TAINTS_ANNOTATION_KEY = "scheduler.alpha.kubernetes.io/taints"
SCHEDULER_NAME_ANNOTATION_KEY = "scheduler.alpha.kubernetes.io/name"
PREFER_AVOID_PODS_ANNOTATION_KEY = "scheduler.alpha.kubernetes.io/preferAvoidPods"
CREATED_BY_ANNOTATION_KEY = "kubernetes.io/created-by"

# Workload-constraint annotations (the workloads subsystem,
# engine/workloads/): gang membership, all-or-nothing gang size, priority
# for preemption, and topology-spread constraints.  All live in
# annotations like the v1.4.0-alpha affinity/toleration surface above.
GANG_ANNOTATION_KEY = "scheduling.kt.io/gang"
GANG_SIZE_ANNOTATION_KEY = "scheduling.kt.io/gang-size"
PRIORITY_ANNOTATION_KEY = "scheduling.kt.io/priority"
TOPOLOGY_SPREAD_ANNOTATION_KEY = \
    "scheduling.kt.io/topologySpreadConstraints"
# Two-phase defrag migration intent (scheduler/defrag.py).  Stamped on a
# pod *before* its evict-to-pending; cleared once the pod rebinds (or by
# the startup reconciler after a crash).  Value: JSON {"from": node,
# "round": n}.  Lives here — not in the scheduler package — so the
# recovery reconciler, the chaos bind monitor, and the defragmenter can
# all read it without import cycles.
DEFRAG_MIGRATION_ANNOTATION_KEY = "scheduling.kt.io/defrag-migration"

DEFAULT_SCHEDULER_NAME = "default-scheduler"

# Taint effects (pkg/api/types.go TaintEffect consts).
TAINT_EFFECT_NO_SCHEDULE = "NoSchedule"
TAINT_EFFECT_PREFER_NO_SCHEDULE = "PreferNoSchedule"

# Node selector operators (pkg/api/types.go NodeSelectorOperator).
NS_OP_IN = "In"
NS_OP_NOT_IN = "NotIn"
NS_OP_EXISTS = "Exists"
NS_OP_DOES_NOT_EXIST = "DoesNotExist"
NS_OP_GT = "Gt"
NS_OP_LT = "Lt"

# Node condition types read by the scheduler.
NODE_READY = "Ready"
NODE_OUT_OF_DISK = "OutOfDisk"
NODE_MEMORY_PRESSURE = "MemoryPressure"
NODE_DISK_PRESSURE = "DiskPressure"
NODE_NETWORK_UNAVAILABLE = "NetworkUnavailable"

# Well-known topology label keys (pkg/api/types.go / unversioned labels).
HOSTNAME_LABEL = "kubernetes.io/hostname"
ZONE_LABEL = "failure-domain.beta.kubernetes.io/zone"
REGION_LABEL = "failure-domain.beta.kubernetes.io/region"
DEFAULT_FAILURE_DOMAINS = (HOSTNAME_LABEL, ZONE_LABEL, REGION_LABEL)


@dataclass(frozen=True)
class Resource:
    """Aggregated compute resources (schedulercache/node_info.go:57-61)."""

    milli_cpu: int = 0
    memory: int = 0  # bytes
    nvidia_gpu: int = 0

    def add(self, other: "Resource") -> "Resource":
        return Resource(self.milli_cpu + other.milli_cpu,
                        self.memory + other.memory,
                        self.nvidia_gpu + other.nvidia_gpu)


@dataclass(frozen=True)
class ContainerPort:
    host_port: int = 0
    container_port: int = 0
    protocol: str = "TCP"


@dataclass
class Container:
    name: str = ""
    image: str = ""
    requests: dict[str, Any] = field(default_factory=dict)  # resource name -> quantity
    limits: dict[str, Any] = field(default_factory=dict)
    ports: list[ContainerPort] = field(default_factory=list)


@dataclass(frozen=True)
class Toleration:
    key: str = ""
    operator: str = ""  # "" / "Equal" / "Exists"
    value: str = ""
    effect: str = ""  # "" tolerates any effect

    def tolerates(self, taint: "Taint") -> bool:
        """TolerationToleratesTaint (pkg/api/helpers.go)."""
        if self.effect and self.effect != taint.effect:
            return False
        if self.key != taint.key:
            return False
        if (not self.operator or self.operator == "Equal") and self.value == taint.value:
            return True
        return self.operator == "Exists"


@dataclass(frozen=True)
class Taint:
    key: str = ""
    value: str = ""
    effect: str = ""

    def tolerated_by(self, tolerations: list[Toleration]) -> bool:
        return any(t.tolerates(self) for t in tolerations)


@dataclass(frozen=True)
class NodeSelectorRequirement:
    key: str
    operator: str
    values: tuple[str, ...] = ()


@dataclass(frozen=True)
class NodeSelectorTerm:
    match_expressions: tuple[NodeSelectorRequirement, ...] = ()


@dataclass(frozen=True)
class NodeSelector:
    node_selector_terms: tuple[NodeSelectorTerm, ...] = ()


@dataclass(frozen=True)
class PreferredSchedulingTerm:
    weight: int
    preference: NodeSelectorTerm


@dataclass(frozen=True)
class NodeAffinity:
    required: Optional[NodeSelector] = None
    preferred: tuple[PreferredSchedulingTerm, ...] = ()


@dataclass(frozen=True)
class LabelSelectorRequirement:
    key: str
    operator: str  # In/NotIn/Exists/DoesNotExist
    values: tuple[str, ...] = ()


@dataclass(frozen=True)
class LabelSelector:
    """unversioned.LabelSelector. None selector matches NO objects; an empty
    selector (no labels, no exprs) matches ALL objects."""

    match_labels: tuple[tuple[str, str], ...] = ()
    match_expressions: tuple[LabelSelectorRequirement, ...] = ()

    def matches(self, labels: dict[str, str]) -> bool:
        for k, v in self.match_labels:
            if labels.get(k) != v:
                return False
        for req in self.match_expressions:
            has = req.key in labels
            if req.operator == "In":
                if not has or labels[req.key] not in req.values:
                    return False
            elif req.operator == "NotIn":
                if has and labels[req.key] in req.values:
                    return False
            elif req.operator == "Exists":
                if not has:
                    return False
            elif req.operator == "DoesNotExist":
                if has:
                    return False
            else:
                return False
        return True


@dataclass(frozen=True)
class PodAffinityTerm:
    """getNamespacesFromPodAffinityTerm (priorities/util/topologies.go:31-38)
    distinguishes nil namespaces (=> the affinity pod's own namespace) from an
    empty list (=> every namespace), hence Optional here."""

    label_selector: Optional[LabelSelector] = None
    namespaces: Optional[tuple[str, ...]] = None  # None => own ns; () => all
    topology_key: str = ""


@dataclass(frozen=True)
class WeightedPodAffinityTerm:
    weight: int
    pod_affinity_term: PodAffinityTerm


@dataclass(frozen=True)
class PodAffinity:
    required: tuple[PodAffinityTerm, ...] = ()
    preferred: tuple[WeightedPodAffinityTerm, ...] = ()


@dataclass(frozen=True)
class PodAntiAffinity:
    required: tuple[PodAffinityTerm, ...] = ()
    preferred: tuple[WeightedPodAffinityTerm, ...] = ()


@dataclass(frozen=True)
class Affinity:
    node_affinity: Optional[NodeAffinity] = None
    pod_affinity: Optional[PodAffinity] = None
    pod_anti_affinity: Optional[PodAntiAffinity] = None


@dataclass(frozen=True)
class TopologySpreadConstraint:
    """Spread pods matching ``label_selector`` evenly across values of the
    node label ``topology_key``: placing on a domain must not push its
    matching-pod count more than ``max_skew`` above the least-loaded
    domain.  ``when_unsatisfiable``: "DoNotSchedule" is a hard mask plane;
    "ScheduleAnyway" a soft score plane (engine/workloads/topology.py)."""

    max_skew: int = 1
    topology_key: str = ""
    when_unsatisfiable: str = "DoNotSchedule"
    label_selector: Optional[LabelSelector] = None

    @property
    def hard(self) -> bool:
        return self.when_unsatisfiable != "ScheduleAnyway"


@dataclass(frozen=True)
class Volume:
    """Only the conflict-relevant volume sources (predicates.go:63-144)."""

    name: str = ""
    gce_pd_name: str = ""
    gce_read_only: bool = False
    aws_ebs_id: str = ""
    aws_read_only: bool = False
    rbd_key: str = ""  # "monitors#pool#image" uniqueness key
    rbd_read_only: bool = False
    iscsi_key: str = ""  # "iqn#lun" uniqueness key (targetPortal ignored, predicates.go:77-87)
    iscsi_read_only: bool = False
    nfs_key: str = ""  # "server#path"
    nfs_read_only: bool = False
    pvc_claim_name: str = ""


@dataclass
class Pod:
    name: str = ""
    namespace: str = "default"
    uid: str = ""
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    node_name: str = ""  # spec.nodeName; "" = unscheduled
    node_selector: dict[str, str] = field(default_factory=dict)  # spec.nodeSelector
    containers: list[Container] = field(default_factory=list)
    volumes: list[Volume] = field(default_factory=list)
    deletion_timestamp: Optional[float] = None
    # spec.priority analogue: higher schedules first and may preempt
    # strictly-lower-priority pods (engine/workloads/preemption.py).  The
    # annotation (PRIORITY_ANNOTATION_KEY) overrides when present.
    priority: int = 0
    # Scheduler-set nominated node after a preemption decision (the
    # reference's status.nominatedNodeName): victims were evicted from
    # this node on the pod's behalf.
    nominated_node: str = ""
    # Parsed-from-annotation caches (set lazily).
    _affinity: Optional[Affinity] = field(default=None, repr=False)
    _affinity_parsed: bool = field(default=False, repr=False)
    _key: Optional[str] = field(default=None, repr=False)

    @property
    def key(self) -> str:
        k = self._key
        if k is None:
            k = self._key = f"{self.namespace}/{self.name}"
        return k

    @property
    def scheduler_name(self) -> str:
        return self.annotations.get(SCHEDULER_NAME_ANNOTATION_KEY,
                                    DEFAULT_SCHEDULER_NAME)

    def affinity(self) -> Optional[Affinity]:
        """GetAffinityFromPodAnnotations (pkg/api/helpers.go:459-469)."""
        if not self._affinity_parsed:
            raw = self.annotations.get(AFFINITY_ANNOTATION_KEY, "")
            self._affinity = _parse_affinity_json(json.loads(raw)) if raw else None
            self._affinity_parsed = True
        return self._affinity

    @property
    def effective_priority(self) -> int:
        """The pod's scheduling priority: the annotation when present
        (and parseable), else the ``priority`` field, else 0."""
        raw = self.annotations.get(PRIORITY_ANNOTATION_KEY, "")
        if raw:
            try:
                return int(raw)
            except ValueError:
                pass
        return self.priority

    @property
    def gang(self) -> str:
        """Gang group name ("" = not a gang member).  Gang members are
        drained as a unit and admitted all-or-nothing
        (engine/workloads/gang.py)."""
        return self.annotations.get(GANG_ANNOTATION_KEY, "")

    @property
    def gang_size(self) -> int:
        """Declared gang member count (0 = undeclared).  The queue holds
        gang members until this many are present; the solver's
        all-or-nothing reduction requires at least this many placed."""
        raw = self.annotations.get(GANG_SIZE_ANNOTATION_KEY, "")
        try:
            return int(raw) if raw else 0
        except ValueError:
            return 0

    def topology_spread_constraints(self) -> list[TopologySpreadConstraint]:
        """Parsed topologySpreadConstraints annotation (JSON list of
        {maxSkew, topologyKey, whenUnsatisfiable, labelSelector})."""
        raw = self.annotations.get(TOPOLOGY_SPREAD_ANNOTATION_KEY, "")
        if not raw:
            return []
        out = []
        for d in json.loads(raw):
            out.append(TopologySpreadConstraint(
                max_skew=max(int(d.get("maxSkew", 1)), 1),
                topology_key=d.get("topologyKey", ""),
                when_unsatisfiable=d.get("whenUnsatisfiable",
                                         "DoNotSchedule"),
                label_selector=_parse_label_selector(
                    d.get("labelSelector"))))
        return out

    def tolerations(self) -> list[Toleration]:
        """GetTolerationsFromPodAnnotations (pkg/api/helpers.go:471-482)."""
        raw = self.annotations.get(TOLERATIONS_ANNOTATION_KEY, "")
        if not raw:
            return []
        return [Toleration(key=t.get("key", ""), operator=t.get("operator", ""),
                           value=t.get("value", ""), effect=t.get("effect", ""))
                for t in json.loads(raw)]

    def resource_request(self) -> Resource:
        """getResourceRequest (predicates.go:420-436): sum of container requests."""
        cpu = mem = gpu = 0
        for c in self.containers:
            cpu += milli_value(c.requests.get("cpu", 0)) if "cpu" in c.requests else 0
            mem += value(c.requests.get("memory", 0)) if "memory" in c.requests else 0
            gpu += value(c.requests.get("alpha.kubernetes.io/nvidia-gpu", 0)) \
                if "alpha.kubernetes.io/nvidia-gpu" in c.requests else 0
        return Resource(cpu, mem, gpu)

    def non_zero_request(self) -> tuple[int, int]:
        """GetNonzeroRequests summed over containers (non_zero.go:39-55):
        containers with unset cpu/memory contribute 100 mCPU / 200 MiB."""
        cpu = mem = 0
        for c in self.containers:
            cpu += milli_value(c.requests["cpu"]) if "cpu" in c.requests \
                else DEFAULT_MILLI_CPU_REQUEST
            mem += value(c.requests["memory"]) if "memory" in c.requests \
                else DEFAULT_MEMORY_REQUEST
        return cpu, mem

    def is_best_effort(self) -> bool:
        """qos.GetPodQOS == BestEffort (pkg/kubelet/qos/util/qos.go): no
        container has any cpu/memory request or limit set."""
        for c in self.containers:
            for d in (c.requests, c.limits):
                for r in ("cpu", "memory"):
                    if r in d:
                        return False
        return True

    def used_host_ports(self) -> set[int]:
        """getUsedPorts (predicates.go:746-761); 0 excluded at check site."""
        return {p.host_port for c in self.containers for p in c.ports
                if p.host_port != 0}


# non_zero.go:46-47
DEFAULT_MILLI_CPU_REQUEST = 100
DEFAULT_MEMORY_REQUEST = 200 * 1024 * 1024


@dataclass(frozen=True)
class NodeCondition:
    type: str
    status: str  # "True"/"False"/"Unknown"


@dataclass(frozen=True)
class ContainerImage:
    names: tuple[str, ...]
    size_bytes: int


@dataclass
class Node:
    name: str = ""
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    unschedulable: bool = False
    # status.allocatable — what the scheduler budgets against
    # (NodeInfo.AllocatableResource, node_info.go:245-255).
    allocatable_milli_cpu: int = 0
    allocatable_memory: int = 0
    allocatable_gpu: int = 0
    allocatable_pods: int = 110
    conditions: list[NodeCondition] = field(default_factory=list)
    images: list[ContainerImage] = field(default_factory=list)

    def taints(self) -> list[Taint]:
        """GetTaintsFromNodeAnnotations (pkg/api/helpers.go:490-505)."""
        raw = self.annotations.get(TAINTS_ANNOTATION_KEY, "")
        if not raw:
            return []
        return [Taint(key=t.get("key", ""), value=t.get("value", ""),
                      effect=t.get("effect", "")) for t in json.loads(raw)]

    def condition(self, ctype: str) -> Optional[str]:
        for c in self.conditions:
            if c.type == ctype:
                return c.status
        return None

    def is_ready(self) -> bool:
        """getNodeConditionPredicate (factory.go:436-462): Ready must be True,
        OutOfDisk and NetworkUnavailable must not be True, not unschedulable."""
        if self.unschedulable:
            return False
        for c in self.conditions:
            if c.type == NODE_READY and c.status != "True":
                return False
            if c.type == NODE_OUT_OF_DISK and c.status == "True":
                return False
            if c.type == NODE_NETWORK_UNAVAILABLE and c.status == "True":
                return False
        return True

    def zone_key(self) -> str:
        """utilnode.GetZoneKey: region + ":\\x00:" + zone, "" if neither."""
        region = self.labels.get(REGION_LABEL, "")
        zone = self.labels.get(ZONE_LABEL, "")
        if not region and not zone:
            return ""
        return region + ":\x00:" + zone


@dataclass
class PersistentVolume:
    """Scheduler-relevant PV fields (MaxPDVolumeCountChecker filters
    predicates.go:284-316; VolumeZoneChecker reads zone/region labels
    predicates.go:391-407)."""

    name: str = ""
    labels: dict[str, str] = field(default_factory=dict)
    gce_pd_name: str = ""
    aws_ebs_id: str = ""


@dataclass
class PersistentVolumeClaim:
    name: str = ""
    namespace: str = "default"
    volume_name: str = ""  # spec.volumeName; "" = unbound


@dataclass
class Service:
    name: str = ""
    namespace: str = "default"
    selector: dict[str, str] = field(default_factory=dict)  # empty = selects nothing


@dataclass
class ReplicationController:
    name: str = ""
    namespace: str = "default"
    selector: dict[str, str] = field(default_factory=dict)


@dataclass
class ReplicaSet:
    name: str = ""
    namespace: str = "default"
    selector: Optional[LabelSelector] = None


# ---------------------------------------------------------------------------
# JSON decoding (the wire format the extender endpoint receives: versioned v1
# api.Pod / api.NodeList JSON).
# ---------------------------------------------------------------------------

def _parse_node_selector_term(d: dict) -> NodeSelectorTerm:
    exprs = tuple(
        NodeSelectorRequirement(key=e.get("key", ""), operator=e.get("operator", ""),
                                values=tuple(e.get("values") or ()))
        for e in d.get("matchExpressions") or ())
    return NodeSelectorTerm(match_expressions=exprs)


def _parse_label_selector(d: Optional[dict]) -> Optional[LabelSelector]:
    if d is None:
        return None
    return LabelSelector(
        match_labels=tuple(sorted((d.get("matchLabels") or {}).items())),
        match_expressions=tuple(
            LabelSelectorRequirement(key=e.get("key", ""),
                                     operator=e.get("operator", ""),
                                     values=tuple(e.get("values") or ()))
            for e in d.get("matchExpressions") or ()))


def _parse_pod_affinity_term(d: dict) -> PodAffinityTerm:
    ns = d.get("namespaces")
    return PodAffinityTerm(
        label_selector=_parse_label_selector(d.get("labelSelector")),
        namespaces=None if ns is None else tuple(ns),
        topology_key=d.get("topologyKey", ""))


def _parse_affinity_json(d: dict) -> Affinity:
    na = pa = paa = None
    if d.get("nodeAffinity"):
        n = d["nodeAffinity"]
        req = None
        if n.get("requiredDuringSchedulingIgnoredDuringExecution"):
            req = NodeSelector(node_selector_terms=tuple(
                _parse_node_selector_term(t) for t in
                n["requiredDuringSchedulingIgnoredDuringExecution"]
                .get("nodeSelectorTerms") or ()))
        pref = tuple(
            PreferredSchedulingTerm(weight=int(t.get("weight", 0)),
                                    preference=_parse_node_selector_term(
                                        t.get("preference") or {}))
            for t in n.get("preferredDuringSchedulingIgnoredDuringExecution") or ())
        na = NodeAffinity(required=req, preferred=pref)
    if d.get("podAffinity"):
        p = d["podAffinity"]
        pa = PodAffinity(
            required=tuple(_parse_pod_affinity_term(t) for t in
                           p.get("requiredDuringSchedulingIgnoredDuringExecution") or ()),
            preferred=tuple(
                WeightedPodAffinityTerm(weight=int(t.get("weight", 0)),
                                        pod_affinity_term=_parse_pod_affinity_term(
                                            t.get("podAffinityTerm") or {}))
                for t in p.get("preferredDuringSchedulingIgnoredDuringExecution") or ()))
    if d.get("podAntiAffinity"):
        p = d["podAntiAffinity"]
        paa = PodAntiAffinity(
            required=tuple(_parse_pod_affinity_term(t) for t in
                           p.get("requiredDuringSchedulingIgnoredDuringExecution") or ()),
            preferred=tuple(
                WeightedPodAffinityTerm(weight=int(t.get("weight", 0)),
                                        pod_affinity_term=_parse_pod_affinity_term(
                                            t.get("podAffinityTerm") or {}))
                for t in p.get("preferredDuringSchedulingIgnoredDuringExecution") or ()))
    return Affinity(node_affinity=na, pod_affinity=pa, pod_anti_affinity=paa)


def _parse_volume(v: dict) -> Volume:
    out = Volume(name=v.get("name", ""))
    if v.get("gcePersistentDisk"):
        g = v["gcePersistentDisk"]
        out = Volume(name=out.name, gce_pd_name=g.get("pdName", ""),
                     gce_read_only=bool(g.get("readOnly", False)))
    elif v.get("awsElasticBlockStore"):
        a = v["awsElasticBlockStore"]
        out = Volume(name=out.name, aws_ebs_id=a.get("volumeID", ""),
                     aws_read_only=bool(a.get("readOnly", False)))
    elif v.get("rbd"):
        r = v["rbd"]
        mons = ",".join(sorted(r.get("monitors") or ()))
        out = Volume(name=out.name,
                     rbd_key=f"{mons}#{r.get('pool', 'rbd')}#{r.get('image', '')}",
                     rbd_read_only=bool(r.get("readOnly", False)))
    elif v.get("iscsi"):
        i = v["iscsi"]
        out = Volume(name=out.name,
                     iscsi_key=f"{i.get('iqn', '')}#{i.get('lun', 0)}",
                     iscsi_read_only=bool(i.get("readOnly", False)))
    elif v.get("nfs"):
        n = v["nfs"]
        out = Volume(name=out.name, nfs_key=f"{n.get('server', '')}#{n.get('path', '')}",
                     nfs_read_only=bool(n.get("readOnly", False)))
    elif v.get("persistentVolumeClaim"):
        out = Volume(name=out.name,
                     pvc_claim_name=v["persistentVolumeClaim"].get("claimName", ""))
    return out


def pod_to_json(pod: Pod) -> dict:
    """Encode a Pod as v1 JSON (ExtenderArgs.Pod wire shape)."""
    containers = []
    for c in pod.containers:
        entry: dict = {"name": c.name}
        if c.image:
            entry["image"] = c.image
        res: dict = {}
        if c.requests:
            res["requests"] = {k: str(v) for k, v in c.requests.items()}
        if c.limits:
            res["limits"] = {k: str(v) for k, v in c.limits.items()}
        if res:
            entry["resources"] = res
        if c.ports:
            entry["ports"] = [
                {"hostPort": p.host_port, "containerPort": p.container_port,
                 "protocol": p.protocol} for p in c.ports]
        containers.append(entry)
    volumes = []
    for v in pod.volumes:
        if v.gce_pd_name:
            volumes.append({"name": v.name, "gcePersistentDisk": {
                "pdName": v.gce_pd_name, "readOnly": v.gce_read_only}})
        elif v.aws_ebs_id:
            volumes.append({"name": v.name, "awsElasticBlockStore": {
                "volumeID": v.aws_ebs_id, "readOnly": v.aws_read_only}})
        elif v.pvc_claim_name:
            volumes.append({"name": v.name, "persistentVolumeClaim": {
                "claimName": v.pvc_claim_name}})
        else:
            volumes.append({"name": v.name})
    spec: dict = {"containers": containers}
    if pod.node_name:
        spec["nodeName"] = pod.node_name
    if pod.node_selector:
        spec["nodeSelector"] = dict(pod.node_selector)
    if volumes:
        spec["volumes"] = volumes
    if pod.priority:
        spec["priority"] = pod.priority
    return {
        "metadata": {"name": pod.name, "namespace": pod.namespace,
                     "uid": pod.uid, "labels": dict(pod.labels),
                     "annotations": dict(pod.annotations)},
        "spec": spec,
    }


def node_to_json(node: Node) -> dict:
    """Encode a Node as v1 JSON (ExtenderArgs.Nodes items)."""
    return {
        "metadata": {"name": node.name, "labels": dict(node.labels),
                     "annotations": dict(node.annotations)},
        "spec": {"unschedulable": node.unschedulable},
        "status": {
            "allocatable": {
                "cpu": f"{node.allocatable_milli_cpu}m",
                "memory": str(node.allocatable_memory),
                "pods": str(node.allocatable_pods),
                "alpha.kubernetes.io/nvidia-gpu": str(node.allocatable_gpu),
            },
            "conditions": [{"type": c.type, "status": c.status}
                           for c in node.conditions],
            "images": [{"names": list(i.names), "sizeBytes": i.size_bytes}
                       for i in node.images],
        },
    }


def key_from_json(d: dict) -> str:
    """The "namespace/name" (or bare name) store key of an object dict —
    ONE implementation shared by the restart reconciler, the invariant
    checker, and the soak driver, so they can never disagree on
    identity."""
    meta = d.get("metadata") or {}
    ns = meta.get("namespace")
    return f"{ns}/{meta.get('name')}" if ns else meta.get("name", "")


def is_terminated_json(d: dict) -> bool:
    """Terminal-phase test on a pod dict (Succeeded/Failed) — shared for
    the same reason as :func:`key_from_json`: the reconciler and the
    verifier must agree on which pods still count."""
    return (d.get("status") or {}).get("phase", "") in ("Succeeded",
                                                        "Failed")


def pod_from_json(d: dict) -> Pod:
    """Decode a v1 api.Pod JSON object (as sent in ExtenderArgs.Pod)."""
    meta = d.get("metadata") or {}
    spec = d.get("spec") or {}
    containers = []
    for c in spec.get("containers") or ():
        res = c.get("resources") or {}
        containers.append(Container(
            name=c.get("name", ""), image=c.get("image", ""),
            requests=dict(res.get("requests") or {}),
            limits=dict(res.get("limits") or {}),
            ports=[ContainerPort(host_port=int(p.get("hostPort", 0)),
                                 container_port=int(p.get("containerPort", 0)),
                                 protocol=p.get("protocol", "TCP"))
                   for p in c.get("ports") or ()]))
    return Pod(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        uid=meta.get("uid", ""),
        labels=dict(meta.get("labels") or {}),
        annotations=dict(meta.get("annotations") or {}),
        node_name=spec.get("nodeName", ""),
        node_selector=dict(spec.get("nodeSelector") or {}),
        containers=containers,
        volumes=[_parse_volume(v) for v in spec.get("volumes") or ()],
        deletion_timestamp=1.0 if meta.get("deletionTimestamp") else None,
        priority=int(spec.get("priority") or 0))


def node_from_json(d: dict) -> Node:
    """Decode a v1 api.Node JSON object (as sent in ExtenderArgs.Nodes)."""
    meta = d.get("metadata") or {}
    spec = d.get("spec") or {}
    status = d.get("status") or {}
    alloc = status.get("allocatable") or status.get("capacity") or {}
    return Node(
        name=meta.get("name", ""),
        labels=dict(meta.get("labels") or {}),
        annotations=dict(meta.get("annotations") or {}),
        unschedulable=bool(spec.get("unschedulable", False)),
        allocatable_milli_cpu=milli_value(alloc["cpu"]) if "cpu" in alloc else 0,
        allocatable_memory=value(alloc["memory"]) if "memory" in alloc else 0,
        allocatable_gpu=value(alloc["alpha.kubernetes.io/nvidia-gpu"])
        if "alpha.kubernetes.io/nvidia-gpu" in alloc else 0,
        allocatable_pods=value(alloc["pods"]) if "pods" in alloc else 110,
        conditions=[NodeCondition(type=c.get("type", ""), status=c.get("status", ""))
                    for c in status.get("conditions") or ()],
        images=[ContainerImage(names=tuple(i.get("names") or ()),
                               size_bytes=int(i.get("sizeBytes", 0)))
                for i in status.get("images") or ()])


def pv_from_json(d: dict) -> PersistentVolume:
    """Decode a v1 PersistentVolume (the fields MaxPDVolumeCountChecker's
    filters and VolumeZoneChecker read, predicates.go:284-316, :391-407)."""
    meta = d.get("metadata") or {}
    spec = d.get("spec") or {}
    gce = spec.get("gcePersistentDisk") or {}
    ebs = spec.get("awsElasticBlockStore") or {}
    return PersistentVolume(
        name=meta.get("name", ""),
        labels=dict(meta.get("labels") or {}),
        gce_pd_name=gce.get("pdName", ""),
        aws_ebs_id=ebs.get("volumeID", ""))


def pvc_from_json(d: dict) -> PersistentVolumeClaim:
    """Decode a v1 PersistentVolumeClaim (spec.volumeName binding)."""
    meta = d.get("metadata") or {}
    spec = d.get("spec") or {}
    return PersistentVolumeClaim(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        volume_name=spec.get("volumeName", ""))


def rc_from_json(d: dict) -> ReplicationController:
    """Decode a v1 ReplicationController (spec.selector is a plain
    label map)."""
    meta = d.get("metadata") or {}
    spec = d.get("spec") or {}
    return ReplicationController(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        selector=dict(spec.get("selector") or {}))


def rs_from_json(d: dict) -> ReplicaSet:
    """Decode an extensions/v1beta1 ReplicaSet (spec.selector is a
    LabelSelector)."""
    meta = d.get("metadata") or {}
    spec = d.get("spec") or {}
    return ReplicaSet(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        selector=_parse_label_selector(spec.get("selector")))
