"""APF-style priority-level flow control for the apiserver request loop.

The reference solved control-plane overload with API Priority & Fairness
(KEP-1040: per-priority-level max-inflight, bounded wait queues, 429 +
Retry-After shedding).  This module is the kt-native collapse of that
contract to three levels:

* ``system`` — lease/presence CAS (endpoints, leases) and node status
  heartbeats.  Reserved inflight slots, **no queue**: a renewal either
  runs now or sheds instantly and retries inside its own retry period.
  Because the lane is structurally separate, a pod-create avalanche can
  never timeshare a healthy scheduler's lease renewal past its
  ``renew_deadline`` (ROADMAP 4c).
* ``workload`` — binds, evictions, scheduler watches, solve traffic.
* ``best-effort`` — pod-create storms, LISTs, everything else.

Each queueable level has a max-inflight gate plus a bounded FIFO wait
queue; queue-full or wait-deadline-exceeded sheds with 429 and an honest
Retry-After derived from the wait deadline and current queue occupancy.
Watch streams hold their handler thread for the stream's whole life, so
they are admitted-or-rejected against a dedicated stream cap and never
queued.  ``/healthz``, ``/metrics`` and ``/debug/*`` are exempt: liveness
probes and the observability surface must keep answering precisely when
the server is shedding (upstream APF's ``exempt`` level).

All caps come from the ``KT_APF*`` knob family, read once at construction (the
knobs registry's init-only contract); per-level gauges/counters land in
the shared metric inventory.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from kubernetes_tpu.utils import knobs
from kubernetes_tpu.utils.metrics import (APISERVER_INFLIGHT,
                                          APISERVER_QUEUE_DEPTH,
                                          APISERVER_QUEUE_WAIT,
                                          APISERVER_REJECTED)

LEVEL_SYSTEM = "system"
LEVEL_WORKLOAD = "workload"
LEVEL_BEST_EFFORT = "best-effort"
LEVEL_WATCH = "watch"

# Kinds whose traffic IS the control plane's own liveness: shard leases
# and the presence object live in endpoints (leaderelection.py), and the
# leases kind is the reference's coordination.k8s.io successor.
_SYSTEM_RESOURCES = frozenset({"endpoints", "leases"})

# Mux paths outside admission entirely (the exempt level).
_EXEMPT_RESOURCES = frozenset({"healthz", "metrics", "debug"})


def classify(method: str, resource: str, is_watch: bool,
             subresource: str = "") -> Optional[str]:
    """Map one request to its priority level; None = exempt."""
    if resource in _EXEMPT_RESOURCES or not resource:
        return None
    if is_watch:
        return LEVEL_WATCH
    if resource in _SYSTEM_RESOURCES:
        return LEVEL_SYSTEM
    if method == "PUT" and resource == "nodes":
        return LEVEL_SYSTEM  # kubelet status heartbeats
    if resource == "bindings":
        return LEVEL_WORKLOAD
    if subresource == "eviction":
        return LEVEL_WORKLOAD
    if method in ("PUT", "DELETE") and resource == "pods":
        return LEVEL_WORKLOAD  # status publish / preemption deletes
    return LEVEL_BEST_EFFORT


class Ticket:
    """The admission outcome the request loop holds: either admitted
    (release() MUST run when the request — or watch stream — ends) or
    shed (ok=False, retry_after carries the honest hint)."""

    __slots__ = ("ok", "reason", "retry_after", "_release")

    def __init__(self, ok: bool, reason: str = "",
                 retry_after: Optional[float] = None,
                 release: Optional[Callable[[], None]] = None):
        self.ok = ok
        self.reason = reason
        self.retry_after = retry_after
        self._release = release

    def release(self) -> None:
        if self._release is not None:
            self._release()
            self._release = None  # idempotent: finally paths may double-run


_EXEMPT_TICKET = Ticket(True)


class _Level:
    """One priority level: a max-inflight gate plus (when queue_limit >
    0) a bounded FIFO wait queue with a wall-clock wait deadline."""

    def __init__(self, name: str, max_inflight: int, queue_limit: int,
                 queue_wait_s: float, retry_floor: float,
                 now: Callable[[], float] = time.monotonic):
        self.name = name
        self.max_inflight = max(0, int(max_inflight))
        self.queue_limit = max(0, int(queue_limit))
        self.queue_wait_s = max(0.0, float(queue_wait_s))
        self.retry_floor = max(0.05, float(retry_floor))
        self._now = now
        self._cv = threading.Condition(threading.Lock())
        self._inflight = 0
        self._queued = 0
        self.admitted_total = 0
        self.queued_total = 0
        self.rejected: dict[str, int] = {}
        # Labeled children resolved ONCE: acquire/release run per
        # request, and the .labels() tuple build is measurable there.
        self._m_inflight = APISERVER_INFLIGHT.labels(level=name)
        self._m_queue_depth = APISERVER_QUEUE_DEPTH.labels(level=name)
        self._m_queue_wait = APISERVER_QUEUE_WAIT.labels(level=name)
        self._m_inflight.set(0)
        self._m_queue_depth.set(0)

    def _retry_after(self) -> float:
        """Honest hint, caller holds the lock: scale the wait deadline by
        queue occupancy — a full queue earns a longer back-off than a
        freshly saturated gate — floored so clients never busy-spin."""
        occupancy = (self._queued + 1) / max(1, self.queue_limit) \
            if self.queue_limit else 1.0
        return round(max(self.retry_floor,
                         self.queue_wait_s * occupancy), 3)

    def _reject(self, reason: str) -> Ticket:
        self.rejected[reason] = self.rejected.get(reason, 0) + 1
        APISERVER_REJECTED.labels(level=self.name, reason=reason).inc()
        return Ticket(False, reason, self._retry_after())

    def acquire(self) -> Ticket:
        with self._cv:
            if self._inflight < self.max_inflight:
                self._inflight += 1
                self.admitted_total += 1
                self._m_inflight.set(self._inflight)
                return Ticket(True, release=self._release_slot)
            if self.queue_limit <= 0:
                return self._reject("inflight-full")
            if self._queued >= self.queue_limit:
                return self._reject("queue-full")
            # Park in the bounded FIFO: Condition waiters wake in wait
            # order, so the queue is FIFO by construction.
            self._queued += 1
            self.queued_total += 1
            self._m_queue_depth.set(self._queued)
            t0 = self._now()
            deadline = t0 + self.queue_wait_s
            try:
                while self._inflight >= self.max_inflight:
                    remaining = deadline - self._now()
                    if remaining <= 0:
                        return self._reject("deadline")
                    self._cv.wait(remaining)
                self._inflight += 1
                self.admitted_total += 1
                self._m_inflight.set(self._inflight)
                self._m_queue_wait.observe((self._now() - t0) * 1e6)
                return Ticket(True, release=self._release_slot)
            finally:
                self._queued -= 1
                self._m_queue_depth.set(self._queued)

    def _release_slot(self) -> None:
        with self._cv:
            self._inflight = max(0, self._inflight - 1)
            self._m_inflight.set(self._inflight)
            self._cv.notify()

    def report(self) -> dict:
        with self._cv:
            return {"inflight": self._inflight,
                    "maxInflight": self.max_inflight,
                    "queued": self._queued,
                    "queueLimit": self.queue_limit,
                    "admitted": self.admitted_total,
                    "queuedTotal": self.queued_total,
                    "rejected": dict(self.rejected)}


class FlowController:
    """The per-server admission front: classify -> level gate -> ticket.

    Constructed once per serve() (knobs read at init, never per
    request); ``enabled=False`` (KT_APF=0) admits everything through the
    exempt ticket — the pre-PR-16 request loop, one branch."""

    def __init__(self, enabled: bool = True,
                 system_inflight: int = 16, workload_inflight: int = 32,
                 besteffort_inflight: int = 16, watch_inflight: int = 128,
                 queue_limit: int = 64, queue_wait_s: float = 1.0,
                 retry_floor: float = 0.25,
                 now: Callable[[], float] = time.monotonic):
        self.enabled = enabled
        self.levels = {
            # system: reserved slots, no queue — renewals shed instantly
            # rather than aging in line behind an avalanche.
            LEVEL_SYSTEM: _Level(LEVEL_SYSTEM, system_inflight, 0,
                                 queue_wait_s, retry_floor, now),
            LEVEL_WORKLOAD: _Level(LEVEL_WORKLOAD, workload_inflight,
                                   queue_limit, queue_wait_s,
                                   retry_floor, now),
            LEVEL_BEST_EFFORT: _Level(LEVEL_BEST_EFFORT,
                                      besteffort_inflight, queue_limit,
                                      queue_wait_s, retry_floor, now),
            # watch: admitted-or-rejected, never queued (a stream holds
            # its handler thread for its whole life).
            LEVEL_WATCH: _Level(LEVEL_WATCH, watch_inflight, 0,
                                queue_wait_s, retry_floor, now),
        }

    @classmethod
    def from_knobs(cls) -> "FlowController":
        return cls(
            enabled=knobs.get_bool("KT_APF"),
            system_inflight=knobs.get_int("KT_APF_SYSTEM_INFLIGHT"),
            workload_inflight=knobs.get_int("KT_APF_WORKLOAD_INFLIGHT"),
            besteffort_inflight=knobs.get_int("KT_APF_BESTEFFORT_INFLIGHT"),
            watch_inflight=knobs.get_int("KT_APF_WATCH_INFLIGHT"),
            queue_limit=knobs.get_int("KT_APF_QUEUE"),
            queue_wait_s=knobs.get_float("KT_APF_QUEUE_WAIT_S"),
            retry_floor=knobs.get_float("KT_APF_RETRY_AFTER_S"))

    def admit(self, method: str, resource: str, is_watch: bool,
              subresource: str = "") -> Ticket:
        if not self.enabled:
            return _EXEMPT_TICKET
        level = classify(method, resource, is_watch, subresource)
        if level is None:
            return _EXEMPT_TICKET
        return self.levels[level].acquire()

    def report(self) -> dict:
        return {"enabled": self.enabled,
                "levels": {name: lvl.report()
                           for name, lvl in self.levels.items()}}
