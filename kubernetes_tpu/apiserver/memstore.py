"""Versioned in-memory object store with watch — the apiserver analogue.

Collapses the reference's storage stack (``storage.Interface`` over etcd,
``pkg/storage/etcd_helper.go``; the ``Cacher`` watch window,
``pkg/storage/cacher.go:129``; and the registry REST semantics) into one
in-process component with the same observable contract the scheduler
depends on:

* monotonically increasing cluster-wide resourceVersion on every write;
* List returns (items, rv) — the snapshot a Reflector lists at;
* Watch(from_rv) replays buffered events after from_rv, then streams live
  events; a from_rv older than the buffer window raises ``TooOldError``
  (410 Gone), forcing the client to relist — exactly the reflector's
  relist-on-staleness path (reflector.go ListAndWatch);
* CAS binding: ``bind`` sets ``spec.nodeName`` only while empty
  (BindingREST.Create -> setPodHostAndAnnotations,
  pkg/registry/pod/etcd/etcd.go:286-330) — the scheduler's optimistic
  concurrency backstop;
* ``GuaranteedUpdate``-style CAS on resourceVersion for generic updates.

Objects are stored as plain dicts keyed by "namespace/name" (or name for
nodes); copies go in and out so callers can't mutate store state.
"""

from __future__ import annotations

import copy
import json
import os
import queue
import threading
from dataclasses import dataclass
from typing import Callable, Iterable, Optional

WATCH_WINDOW = 1024  # Cacher event window (cacher.go's watchCache capacity)
# WAL appends between snapshot rotations (a snapshot is one json.dump of
# the whole object set; 4096 amortizes it to noise at control-plane rates).
SNAPSHOT_EVERY = 4096


def _now_rfc3339() -> str:
    import time as _time
    return _time.strftime("%Y-%m-%dT%H:%M:%SZ", _time.gmtime())


class TooOldError(Exception):
    """HTTP 410 Gone: requested watch RV fell out of the event window."""


class ConflictError(Exception):
    """CAS failure (resourceVersion conflict or bind on a bound pod)."""


@dataclass(frozen=True)
class Event:
    type: str       # ADDED | MODIFIED | DELETED
    kind: str       # pods | nodes | services | ...
    key: str
    object: dict
    rv: int
    # The object's state BEFORE this write (None for creates).  Fielded
    # watchers need it to classify set transitions: a pod leaving the
    # ``spec.nodeName=`` set on bind is a DELETED to that watcher even
    # though the store event is MODIFIED (pkg/storage/cacher's
    # watchCache computes event types against prevObject the same way).
    prev: Optional[dict] = None

    def _obj_json(self) -> bytes:
        """The object serialized once, shared between the event's own
        wire line and any re-typed (fielded-watch) lines — at density
        rates every bind synthesizes a DELETED for the scheduler's
        unassigned watch, and re-serializing the identical pod per
        rewrite was GIL time in the watch-serving threads."""
        cached = self.__dict__.get("_oj")
        if cached is None:
            cached = json.dumps(self.object,
                                separators=(",", ":")).encode()
            object.__setattr__(self, "_oj", cached)
        return cached

    def wire_json(self) -> bytes:
        """The bare ``{"type":...,"object":...}`` envelope (no trailing
        newline) — the unit the framed watch encoding joins into one
        length-prefixed ``{"items":[...]}`` batch."""
        cached = self.__dict__.get("_env")
        if cached is None:
            cached = (b'{"type":"' + self.type.encode() +
                      b'","object":' + self._obj_json() + b'}')
            object.__setattr__(self, "_env", cached)
        return cached

    def wire_line(self) -> bytes:
        """The NDJSON watch-wire form, serialized once and shared by every
        HTTP watch stream carrying this event (the same Event instance is
        delivered to all watchers) — at density rates the per-stream
        re-serialization was a measurable slice of apiserver GIL time."""
        cached = self.__dict__.get("_wire")
        if cached is None:
            cached = self.wire_json() + b"\n"
            object.__setattr__(self, "_wire", cached)
        return cached

    def as_type(self, etype: str) -> "Event":
        """This event re-typed for a fielded watcher: shares the object
        AND its cached serialization; only the tiny envelope differs.
        Re-typed instances are memoized per target type, so N watchers
        sharing a field selector (HA shards) also share the re-typed
        event's serialized envelope — the watch-cache leg that kept
        each stream re-serializing the same DELETED at density rates."""
        memo = self.__dict__.get("_retyped")
        if memo is None:
            memo = {}
            object.__setattr__(self, "_retyped", memo)
        ev = memo.get(etype)
        if ev is None:
            ev = Event(etype, self.kind, self.key, self.object, self.rv,
                       self.prev)
            oj = self.__dict__.get("_oj")
            if oj is not None:
                object.__setattr__(ev, "_oj", oj)
            memo[etype] = ev
        return ev


_DROP = object()  # classification-cache sentinel: "not for this set"


class Watcher:
    def __init__(self, store: "MemStore", kinds: tuple[str, ...],
                 selector=None, selector_key: Optional[str] = None):
        self._q: "queue.Queue[Optional[Event]]" = queue.Queue()
        self._store = store
        self.kinds = kinds
        self.selector = selector  # fielded watch predicate (or None)
        # Watch-cache key: watchers opened with the same field-selector
        # STRING share one set-transition classification per event (N
        # HA shards watching ``spec.nodeName=`` classify once, not N
        # times).  None = uncacheable local callable.
        self.selector_key = selector_key

    def _classify(self, ev: Event) -> "Event | None":
        """The set-transition classification (cacher.go watchCache):

        * entered the set  -> ADDED
        * stayed in        -> event as-is
        * left the set     -> DELETED (carrying the new object state)
        * never in         -> None (dropped)
        """
        sel = self.selector
        m_new = sel(ev.object)
        m_prev = ev.prev is not None and sel(ev.prev)
        if ev.type == "DELETED":
            return ev if (m_prev or m_new) else None
        if ev.type == "ADDED":
            return ev if m_new else None
        if m_new:
            return ev if m_prev else ev.as_type("ADDED")
        if m_prev:
            return ev.as_type("DELETED")
        return None

    def _deliver(self, ev: Event) -> None:
        """Called under the store lock.  An unfielded watcher forwards
        the shared event; a fielded one classifies the set transition —
        through the per-event memo when the selector has a cache key."""
        if self.selector is None:
            self._q.put(ev)
            return
        if self.selector_key is not None:
            memo = ev.__dict__.get("_cls")
            if memo is None:
                memo = {}
                object.__setattr__(ev, "_cls", memo)
            out = memo.get(self.selector_key)
            if out is None:
                out = self._classify(ev)
                memo[self.selector_key] = _DROP if out is None else out
            if out is not _DROP and out is not None:
                self._q.put(out)
            return
        out = self._classify(ev)
        if out is not None:
            self._q.put(out)

    def next(self, timeout: Optional[float] = None) -> Optional[Event]:
        try:
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return None

    def stop(self) -> None:
        self._store._drop_watcher(self)
        self._q.put(None)


class MemStore:
    def __init__(self, share_events: bool = False,
                 storage_dir: Optional[str] = None,
                 fsync: bool = False) -> None:
        """``share_events=True`` lets events reference stored objects
        directly instead of deep-copying a snapshot per write.  Safe ONLY
        when every consumer is read-only — the standalone apiserver binary
        qualifies (its watchers just serialize events to sockets, and no
        store code mutates a stored object in place: bind is
        copy-on-write).  In-process rigs keep the default: their reflector
        handlers receive the event dicts and may mutate them.

        ``storage_dir`` makes the store durable — the one contract the
        pure-memory store broke vs the reference (an apiserver restart
        lost the cluster; etcd never does, pkg/storage/etcd3/store.go):
        every write appends one JSON line to ``wal.jsonl``, a full
        ``snapshot.json`` is rotated every SNAPSHOT_EVERY appends, and a
        fresh store on the same directory replays snapshot + WAL,
        preserving objects AND the resourceVersion counter (so reflectors
        resume their watches without a 410 storm).  ``fsync=True`` forces
        the WAL line to disk per write (etcd's default); off, durability
        is to the OS page cache (survives process crash, not power loss)."""
        self._lock = threading.Lock()
        self._objects: dict[str, dict[str, dict]] = {}   # kind -> key -> obj
        self._rv = 0
        self._events: list[Event] = []                   # ring window
        self._watchers: list[Watcher] = []
        self._share_events = share_events
        self._fsync = fsync
        self._dir = storage_dir
        self._wal = None
        self._wal_count = 0
        # Server-side capacity validation at bind (KT_BIND_CAPACITY,
        # default on): per-node used-capacity accounting, maintained
        # incrementally on bind/create/update/delete so the check is
        # O(containers) per bind, never a walk over the pod set.  A
        # bind that would overcommit the target node's allocatable is
        # rejected with the 409 the scheduler already absorbs via
        # forget + requeue — watch-lagged schedulers can no longer land
        # transient overcommit in the store.
        from kubernetes_tpu.utils import knobs
        self._capacity_check = knobs.get_bool("KT_BIND_CAPACITY")
        self._node_used: dict[str, list] = {}  # node -> [milli, mem, pods]
        if storage_dir is not None:
            os.makedirs(storage_dir, exist_ok=True)
            self._recover(storage_dir)
            self._recompute_node_used()
            self._wal = open(os.path.join(storage_dir, "wal.jsonl"),
                             "a", encoding="utf-8")

    # -- durability ------------------------------------------------------

    def _recover(self, d: str) -> None:
        snap = os.path.join(d, "snapshot.json")
        if os.path.exists(snap):
            with open(snap, encoding="utf-8") as f:
                data = json.load(f)
            self._objects = data["objects"]
            self._rv = data["rv"]
        wal = os.path.join(d, "wal.jsonl")
        if os.path.exists(wal):
            good_end = 0
            with open(wal, "rb") as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                        # Extract EVERY field before counting the record
                        # good: a tear can land exactly on a line
                        # boundary and leave valid JSON that is not a
                        # complete record (e.g. '{"t": "ADDED"}' from a
                        # truncated buffer flush) — replaying it would
                        # crash recovery, and counting it would weld
                        # later appends onto a half-record.
                        etype, kind, key = rec["t"], rec["k"], rec["key"]
                        rv, obj = int(rec["rv"]), rec["o"]
                    except (ValueError, KeyError, TypeError):
                        break  # torn/partial final record: stop replay
                    good_end += len(line)
                    self._wal_count += 1
                    bucket = self._objects.setdefault(kind, {})
                    if etype == "DELETED":
                        bucket.pop(key, None)
                    else:
                        bucket[key] = obj
                    # Monotonic: the RV counter never regresses across a
                    # crash — resumed watches and CAS preconditions rely
                    # on it.
                    self._rv = max(self._rv, rv)
            if good_end < os.path.getsize(wal):
                # Drop the torn tail NOW: appending after it would weld
                # the next record onto the fragment, and the restart after
                # that would abort replay at the weld — silently losing
                # every acknowledged write from this incarnation.
                with open(wal, "rb+") as f:
                    f.truncate(good_end)

    def _append_wal(self, etype: str, kind: str, key: str,
                    obj: dict, rv: int) -> None:
        """Called under the store lock (from _emit)."""
        rec = {"t": etype, "k": kind, "key": key, "rv": rv,
               "o": None if etype == "DELETED" else obj}
        self._wal.write(json.dumps(rec, separators=(",", ":")) + "\n")
        self._wal.flush()
        if self._fsync:
            os.fsync(self._wal.fileno())
        self._wal_count += 1
        if self._wal_count >= SNAPSHOT_EVERY:
            self._rotate_snapshot()

    def _rotate_snapshot(self) -> None:
        """Write a full snapshot atomically, then truncate the WAL.  Under
        the lock — a brief stall every SNAPSHOT_EVERY writes, the price of
        never replaying an unbounded log."""
        tmp = os.path.join(self._dir, "snapshot.json.tmp")
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"rv": self._rv, "objects": self._objects}, f,
                      separators=(",", ":"))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self._dir, "snapshot.json"))
        self._wal.close()
        self._wal = open(os.path.join(self._dir, "wal.jsonl"),
                         "w", encoding="utf-8")
        self._wal_count = 0

    def close(self) -> None:
        with self._lock:
            if self._wal is not None:
                self._wal.flush()
                self._wal.close()
                self._wal = None

    # -- server-side bind capacity accounting -----------------------------

    @staticmethod
    def _pod_requests(obj: dict) -> tuple[int, int, int]:
        """(milli_cpu, memory_bytes, 1) summed over a pod JSON's
        container requests; malformed quantities count as zero (the
        check must never 500 a bind over a typo'd request)."""
        from kubernetes_tpu.api.quantity import milli_value, value
        milli = mem = 0
        for c in (obj.get("spec") or {}).get("containers") or []:
            req = ((c.get("resources") or {}).get("requests") or {})
            try:
                if "cpu" in req:
                    milli += milli_value(str(req["cpu"]))
                if "memory" in req:
                    mem += value(str(req["memory"]))
            except (ValueError, ZeroDivisionError):
                continue
        return milli, mem, 1

    def _node_alloc(self, node_name: str):
        """(milli_cpu, memory, pods) allocatable of a stored node, None
        per missing field (nothing to validate there), or None when the
        node object itself is unknown to the store."""
        from kubernetes_tpu.api.quantity import milli_value, value
        node = self._objects.get("nodes", {}).get(node_name)
        if node is None:
            return None
        alloc = (node.get("status") or {}).get("allocatable") or {}
        out = []
        for field_name, parse in (("cpu", milli_value), ("memory", value),
                                  ("pods", value)):
            raw = alloc.get(field_name)
            if raw is None:
                out.append(None)
                continue
            try:
                out.append(parse(str(raw)))
            except (ValueError, ZeroDivisionError):
                out.append(None)
        return out

    def _account_pod(self, obj: dict, sign: int) -> None:
        """Add (+1) or remove (-1) a bound pod's requests from its
        node's used-capacity row.  Caller holds the lock.  A no-op when
        the capacity check is off — KT_BIND_CAPACITY=0 must restore the
        old write path byte-for-byte, not keep paying the quantity
        parsing on every pod write."""
        if not self._capacity_check:
            return
        node_name = (obj.get("spec") or {}).get("nodeName") or ""
        if not node_name:
            return
        req = self._pod_requests(obj)
        used = self._node_used.setdefault(node_name, [0, 0, 0])
        for i in range(3):
            used[i] = max(used[i] + sign * req[i], 0)

    def _recompute_node_used(self) -> None:
        self._node_used = {}
        for obj in self._objects.get("pods", {}).values():
            self._account_pod(obj, +1)

    def _check_bind_capacity(self, key: str, pod: dict,
                             node_name: str) -> None:
        """Reject a bind that would overcommit the target node (the
        PR 11 REMAINING item: near-capacity fleets could transiently
        overcommit a node during watch lag — pod double-binds were
        already impossible; node overcommit now is too).  Unknown nodes
        and absent allocatable fields validate nothing (the server
        cannot invent capacity it was never told about)."""
        alloc = self._node_alloc(node_name)
        if alloc is None:
            return
        req = self._pod_requests(pod)
        used = self._node_used.get(node_name, [0, 0, 0])
        dims = ("cpu", "memory", "pods")
        for i, dim in enumerate(dims):
            if alloc[i] is None:
                continue
            if used[i] + req[i] > alloc[i]:
                from kubernetes_tpu.utils import metrics
                metrics.BIND_CAPACITY_REJECTS.inc()
                raise ConflictError(
                    f"binding pod {key} to node {node_name} would "
                    f"overcommit {dim} (used {used[i]} + requested "
                    f"{req[i]} > allocatable {alloc[i]})")

    # -- helpers ---------------------------------------------------------

    @staticmethod
    def object_key(obj: dict) -> str:
        meta = obj.get("metadata") or {}
        ns = meta.get("namespace")
        return f"{ns}/{meta['name']}" if ns else meta["name"]

    def _emit(self, etype: str, kind: str, key: str, obj: dict,
              prev: Optional[dict] = None) -> Event:
        self._rv += 1
        obj.setdefault("metadata", {})["resourceVersion"] = str(self._rv)
        if self._wal is not None:
            self._append_wal(etype, kind, key, obj, self._rv)
        snapshot = obj if self._share_events else copy.deepcopy(obj)
        # prev is read only by fielded-watch predicates (never handed to
        # handlers), so it can reference the retired stored dict directly.
        ev = Event(etype, kind, key, snapshot, self._rv, prev)
        self._events.append(ev)
        if len(self._events) > WATCH_WINDOW:
            self._events = self._events[-WATCH_WINDOW:]
        for w in self._watchers:
            if kind in w.kinds:
                w._deliver(ev)
        return ev

    # -- REST verbs ------------------------------------------------------

    def create(self, kind: str, obj: dict, owned: bool = False) -> dict:
        """``owned=True``: the caller transfers ownership of ``obj`` (the
        HTTP handlers own their freshly parsed bodies) — the store keeps
        it directly and returns the event's snapshot, skipping two of the
        three deepcopies a defensive create pays.  Default semantics are
        unchanged for in-process callers that may keep mutating theirs."""
        with self._lock:
            key = self.object_key(obj)
            bucket = self._objects.setdefault(kind, {})
            if key in bucket:
                raise ConflictError(f"{kind} {key} already exists")
            if not owned:
                obj = copy.deepcopy(obj)
            meta = obj.setdefault("metadata", {})
            meta.setdefault("generation", 1)
            # RFC3339 creation stamp (ObjectMeta.CreationTimestamp): age
            # ordering for pod GC, and the scheduled-job controller's
            # earliest-possible-start when lastScheduleTime is unset.
            meta.setdefault("creationTimestamp", _now_rfc3339())
            bucket[key] = obj
            if kind == "pods":
                self._account_pod(obj, +1)
            ev = self._emit("ADDED", kind, key, obj)
            # The event snapshot is already shared read-only with every
            # watcher; handing it to an owned caller (which serializes it
            # and moves on) adds no new aliasing.
            return ev.object if owned else copy.deepcopy(obj)

    def update(self, kind: str, obj: dict,
               expected_rv: Optional[str] = None,
               owned: bool = False) -> dict:
        with self._lock:
            key = self.object_key(obj)
            bucket = self._objects.setdefault(kind, {})
            current = bucket.get(key)
            if current is None:
                raise KeyError(f"{kind} {key} not found")
            if expected_rv is not None and \
                    current["metadata"].get("resourceVersion") != expected_rv:
                raise ConflictError(f"{kind} {key} resourceVersion conflict")
            if not owned:
                obj = copy.deepcopy(obj)
            # metadata.generation increments on spec changes (the
            # reference registries' PrepareForUpdate): controllers gate
            # "have I reconciled the latest spec?" on it —
            # status.observedGeneration >= metadata.generation.
            meta = obj.setdefault("metadata", {})
            old_gen = int((current.get("metadata") or {})
                          .get("generation", 1) or 1)
            if current.get("spec") != obj.get("spec"):
                meta["generation"] = old_gen + 1
            else:
                meta["generation"] = old_gen
            bucket[key] = obj
            if kind == "pods":
                # Re-account (a direct update can move or resize a
                # bound pod — the bind subresource is just the common
                # path).
                self._account_pod(current, -1)
                self._account_pod(obj, +1)
            ev = self._emit("MODIFIED", kind, key, obj, prev=current)
            return ev.object if owned else copy.deepcopy(obj)

    def delete(self, kind: str, key: str) -> None:
        with self._lock:
            bucket = self._objects.setdefault(kind, {})
            obj = bucket.pop(key, None)
            if obj is None:
                raise KeyError(f"{kind} {key} not found")
            if kind == "pods":
                self._account_pod(obj, -1)
            # COW before the rv stamp: the popped dict may still be
            # referenced by earlier in-flight events (share_events mode).
            prev = obj
            obj = dict(obj)
            obj["metadata"] = dict(obj.get("metadata") or {})
            self._emit("DELETED", kind, key, obj, prev=prev)

    def get(self, kind: str, key: str) -> Optional[dict]:
        with self._lock:
            obj = self._objects.get(kind, {}).get(key)
            return copy.deepcopy(obj) if obj is not None else None

    def list(self, kind: str,
             selector: Optional[Callable[[dict], bool]] = None
             ) -> tuple[list[dict], int]:
        with self._lock:
            items = [copy.deepcopy(o) for o in
                     self._objects.get(kind, {}).values()
                     if selector is None or selector(o)]
            return items, self._rv

    # -- watch -----------------------------------------------------------

    def watch(self, kinds: Iterable[str], from_rv: int,
              selector=None, selector_key: Optional[str] = None) -> Watcher:
        """``selector``: a fielded-watch predicate (api.fieldsel.matcher)
        applied server-side with set-transition semantics — see
        Watcher._deliver.  ``selector_key`` (the selector's source
        string) lets watchers sharing one selector share the per-event
        classification (the watch cache)."""
        with self._lock:
            if self._events and from_rv < self._events[0].rv - 1 and \
                    from_rv < self._rv - len(self._events):
                raise TooOldError(f"rv {from_rv} too old")
            w = Watcher(self, tuple(kinds), selector=selector,
                        selector_key=selector_key)
            for ev in self._events:
                if ev.rv > from_rv and ev.kind in w.kinds:
                    w._deliver(ev)
            self._watchers.append(w)
            return w

    def _drop_watcher(self, w: Watcher) -> None:
        with self._lock:
            if w in self._watchers:
                self._watchers.remove(w)

    # -- the binding subresource ----------------------------------------

    def bind(self, namespace: str, pod_name: str, node_name: str) -> None:
        """BindingREST.Create (etcd.go:286-330): CAS spec.nodeName while
        empty; MODIFIED event on success, ConflictError otherwise."""
        with self._lock:
            self._bind_locked(namespace, pod_name, node_name)

    def _bind_locked(self, namespace: str, pod_name: str,
                     node_name: str) -> None:
        key = f"{namespace}/{pod_name}"
        pod = self._objects.get("pods", {}).get(key)
        if pod is None:
            raise KeyError(f"pod {key} not found")
        if (pod.get("spec") or {}).get("nodeName"):
            raise ConflictError(
                f"pod {key} is already assigned to node "
                f"{pod['spec']['nodeName']}")
        if self._capacity_check:
            # Server-side capacity validation: the 409 the scheduler
            # absorbs via forget + requeue, so watch lag can never land
            # an overcommitting bind.
            self._check_bind_capacity(key, pod, node_name)
        # Copy-on-write (pod + the two sub-dicts this write touches): the
        # previous version may still be referenced by in-flight events, so
        # no stored object is ever mutated in place.
        prev = pod
        pod = dict(pod)
        pod["spec"] = dict(pod.get("spec") or {})
        pod["metadata"] = dict(pod.get("metadata") or {})
        pod["spec"]["nodeName"] = node_name
        self._objects["pods"][key] = pod
        self._account_pod(pod, +1)
        self._emit("MODIFIED", "pods", key, pod, prev=prev)

    def bind_many(self, bindings: list[tuple[str, str, str]]
                  ) -> list[Optional[str]]:
        """Per-pod CAS under ONE lock acquisition: each (namespace, pod,
        node) binds independently — a conflict on one never blocks the
        rest, exactly as N sequential BindingREST.Create calls would
        behave.  Returns a per-item error string (None = bound)."""
        results: list[Optional[str]] = []
        with self._lock:
            for namespace, pod_name, node_name in bindings:
                try:
                    self._bind_locked(namespace, pod_name, node_name)
                    results.append(None)
                except (KeyError, ConflictError) as err:
                    results.append(str(err))
        return results
