"""Standalone apiserver: ``python -m kubernetes_tpu.apiserver --port 8080``
serves the MemStore-backed HTTP surface (the in-process master the perf rig
uses, run as its own process — test/integration/framework/master_utils.go
RunAMaster's role)."""

from __future__ import annotations

import argparse
import signal
import sys
import threading

from kubernetes_tpu.apiserver.memstore import MemStore
from kubernetes_tpu.apiserver.server import serve


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="kube-apiserver (kubernetes_tpu)")
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--token-auth-file", default="",
                   help="CSV token,user,uid[,group1|group2] per line "
                        "(tokenfile authenticator)")
    p.add_argument("--authorization-policy-file", default="",
                   help="ABAC policy file, one JSON object per line")
    p.add_argument("--storage-dir", default="",
                   help="durable storage directory (snapshot + WAL): a "
                        "restart recovers objects and the resourceVersion "
                        "counter, like etcd behind the reference apiserver")
    p.add_argument("--storage-fsync", action="store_true",
                   help="fsync the WAL per write (etcd's default "
                        "durability; slower)")
    opts = p.parse_args(argv)
    auth = None
    if opts.token_auth_file or opts.authorization_policy_file:
        from kubernetes_tpu.apiserver.auth import (ABACAuthorizer,
                                                   AuthConfig,
                                                   TokenAuthenticator)
        auth = AuthConfig(
            authenticator=TokenAuthenticator.from_file(opts.token_auth_file)
            if opts.token_auth_file else None,
            authorizer=ABACAuthorizer.from_file(
                opts.authorization_policy_file)
            if opts.authorization_policy_file else None)
    # share_events: this process's only consumers are HTTP watch streams
    # (read-only serializers), so events may reference stored objects
    # directly — no per-write deepcopy (see MemStore.__init__).
    store = MemStore(share_events=True,
                     storage_dir=opts.storage_dir or None,
                     fsync=opts.storage_fsync)
    server = serve(store, port=opts.port, host=opts.host, auth=auth)
    print(f"apiserver listening on {server.server_address[0]}:"
          f"{server.server_address[1]}", file=sys.stderr, flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    server.shutdown()
    store.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
