"""Standalone apiserver: ``python -m kubernetes_tpu.apiserver --port 8080``
serves the MemStore-backed HTTP surface (the in-process master the perf rig
uses, run as its own process — test/integration/framework/master_utils.go
RunAMaster's role)."""

from __future__ import annotations

import argparse
import signal
import sys
import threading

from kubernetes_tpu.apiserver.memstore import MemStore
from kubernetes_tpu.apiserver.server import serve


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="kube-apiserver (kubernetes_tpu)")
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--token-auth-file", default="",
                   help="CSV token,user,uid[,group1|group2] per line "
                        "(tokenfile authenticator)")
    p.add_argument("--basic-auth-file", default="",
                   help="CSV password,user,uid[,group1|group2] per line "
                        "(HTTP basic authenticator)")
    p.add_argument("--authentication-token-webhook-url", default="",
                   help="TokenReview webhook URL (the reference "
                        "configures it via a kubeconfig file; a flat "
                        "URL here)")
    p.add_argument("--authorization-policy-file", default="",
                   help="ABAC policy file, one JSON object per line")
    p.add_argument("--authorization-webhook-url", default="",
                   help="SubjectAccessReview webhook URL for "
                        "--authorization-mode Webhook")
    p.add_argument("--authorization-mode", default="",
                   choices=["", "ABAC", "RBAC", "Webhook"],
                   help="RBAC authorizes from live Role/RoleBinding/"
                        "ClusterRole/ClusterRoleBinding objects "
                        "(system:masters group bypasses, the bootstrap "
                        "superuser convention); Webhook defers to "
                        "--authorization-webhook-url; default ABAC when "
                        "a policy file is given")
    p.add_argument("--storage-dir", default="",
                   help="durable storage directory (snapshot + WAL): a "
                        "restart recovers objects and the resourceVersion "
                        "counter, like etcd behind the reference apiserver")
    p.add_argument("--storage-fsync", action="store_true",
                   help="fsync the WAL per write (etcd's default "
                        "durability; slower)")
    p.add_argument("--tls-cert-file", default="",
                   help="serve HTTPS with this certificate (the secure "
                        "port)")
    p.add_argument("--tls-private-key-file", default="")
    p.add_argument("--client-ca-file", default="",
                   help="verify client certificates against this CA; a "
                        "verified cert's CN/O become the request's "
                        "user/groups (x509 authenticator)")
    p.add_argument("--admission-control", default="",
                   help="comma-separated admission plugins applied in "
                        "order (default: NamespaceLifecycle,"
                        "ServiceAccount,LimitPodHardAntiAffinity"
                        "Topology,LimitRanger,ResourceQuota; also: "
                        "AlwaysPullImages, SecurityContextDeny, "
                        "AlwaysAdmit, AlwaysDeny)")
    opts = p.parse_args(argv)
    # share_events: this process's only consumers are HTTP watch streams
    # (read-only serializers), so events may reference stored objects
    # directly — no per-write deepcopy (see MemStore.__init__).
    store = MemStore(share_events=True,
                     storage_dir=opts.storage_dir or None,
                     fsync=opts.storage_fsync)
    auth = None
    if opts.token_auth_file or opts.basic_auth_file or \
            opts.authentication_token_webhook_url or \
            opts.authorization_policy_file or \
            opts.authorization_mode in ("RBAC", "Webhook"):
        from kubernetes_tpu.apiserver.auth import (
            ABACAuthorizer, AuthConfig, BasicAuthenticator,
            RBACAuthorizer, ServiceAccountAuthenticator,
            TokenAuthenticator, UnionAuthenticator,
            WebhookAuthorizer, WebhookTokenAuthenticator)
        if opts.authorization_mode == "RBAC":
            authorizer = RBACAuthorizer(store)
        elif opts.authorization_mode == "Webhook":
            if not opts.authorization_webhook_url:
                p.error("--authorization-mode Webhook needs "
                        "--authorization-webhook-url")
            authorizer = WebhookAuthorizer(opts.authorization_webhook_url)
        elif opts.authorization_policy_file:
            authorizer = ABACAuthorizer.from_file(
                opts.authorization_policy_file)
        else:
            authorizer = None
        # Union authenticator (the reference's request-auth union):
        # static tokenfile entries, basic-auth passwords, live
        # service-account token secrets and the token-review webhook
        # all authenticate.
        auth = AuthConfig(
            authenticator=UnionAuthenticator(
                TokenAuthenticator.from_file(opts.token_auth_file)
                if opts.token_auth_file else None,
                BasicAuthenticator.from_file(opts.basic_auth_file)
                if opts.basic_auth_file else None,
                ServiceAccountAuthenticator(store),
                WebhookTokenAuthenticator(
                    opts.authentication_token_webhook_url)
                if opts.authentication_token_webhook_url else None),
            authorizer=authorizer,
            # No credential source at all -> the x509-only posture,
            # where a certless, tokenless request is system:anonymous
            # for the authorizer (r4's secure-port behavior); with any
            # credential source (tokenfile, password file, token
            # webhook), credential-less requests are 401.
            anonymous=not (opts.token_auth_file or
                           opts.basic_auth_file or
                           opts.authentication_token_webhook_url))
    server = serve(store, port=opts.port, host=opts.host, auth=auth,
                   tls_cert=opts.tls_cert_file,
                   tls_key=opts.tls_private_key_file,
                   client_ca=opts.client_ca_file,
                   admission_control=opts.admission_control.split(",")
                   if opts.admission_control else None)
    print(f"apiserver listening on {server.server_address[0]}:"
          f"{server.server_address[1]}", file=sys.stderr, flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    server.shutdown()
    store.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
