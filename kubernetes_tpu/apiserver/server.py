"""HTTP front end over the in-memory store — the apiserver surface the
scheduler stack speaks (pkg/apiserver handler chain, scheduler-relevant
subset):

    GET    /api/v1/{kind}                      list (+ ?watch=1&resourceVersion=N)
    GET    /api/v1/namespaces/{ns}/{kind}/{name}
    POST   /api/v1/{kind}                      create
    PUT    /api/v1/namespaces/{ns}/{kind}/{name}   update (CAS on resourceVersion)
    DELETE /api/v1/namespaces/{ns}/{kind}/{name}
    POST   /api/v1/namespaces/{ns}/bindings    the binding subresource
    GET    /healthz, /metrics

Watches stream newline-delimited JSON events ({"type": ..., "object": ...})
over a chunked response, the reference's watch wire shape; a stale
resourceVersion returns 410 Gone, telling the client to relist.  Nodes are
cluster-scoped (no namespace segment), pods/services namespaced.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from kubernetes_tpu.apiserver.memstore import (ConflictError, MemStore,
                                               TooOldError)
from kubernetes_tpu.apiserver.validation import (AdmissionError,
                                                 admit_and_validate)

from kubernetes_tpu.api.types import NAMESPACED_KINDS as _NAMESPACED

# Idle watch streams carry a blank heartbeat chunk this often so clients'
# read deadlines only fire on genuinely dead sockets.
WATCH_HEARTBEAT_PERIOD = 10.0


def make_handler(store: MemStore):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        # Response header/body write pairs on keep-alive connections stall
        # ~40 ms under Nagle + the peer's delayed ACK; verbs are small.
        disable_nagle_algorithm = True

        def log_message(self, *a):
            pass

        def _send_json(self, code: int, obj) -> None:
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _read_body(self) -> dict:
            length = int(self.headers.get("Content-Length", 0))
            return json.loads(self.rfile.read(length) or b"{}")

        def _admit(self, kind: str, body: dict) -> bool:
            """Write-path chain (pkg/apiserver: admission -> validation):
            403 on an admission veto, 422 with collected reasons on a
            structurally invalid object.  True = proceed to the store."""
            try:
                errors = admit_and_validate(kind, body)
            except AdmissionError as err:
                self._send_json(403, {"error": str(err)})
                return False
            if errors:
                self._send_json(422, {"error": "validation failed",
                                      "reasons": errors})
                return False
            return True

        def _parts(self):
            parsed = urlparse(self.path)
            return [p for p in parsed.path.split("/") if p], \
                parse_qs(parsed.query)

        def do_GET(self):
            parts, query = self._parts()
            if parts == ["healthz"]:
                self.send_response(200)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", "2")
                self.end_headers()
                self.wfile.write(b"ok")
                return
            if len(parts) == 3 and parts[:2] == ["api", "v1"]:
                kind = parts[2]
                if query.get("watch", ["0"])[0] in ("1", "true"):
                    self._serve_watch(kind, query)
                    return
                items, rv = store.list(kind)
                self._send_json(200, {"kind": kind.capitalize() + "List",
                                      "items": items,
                                      "metadata": {"resourceVersion": str(rv)}})
                return
            if len(parts) == 6 and parts[2] == "namespaces":
                # /api/v1/namespaces/{ns}/{kind}/{name}
                _, _, _, ns, kind, name = parts
                obj = store.get(kind, f"{ns}/{name}")
                if obj is None:
                    self._send_json(404, {"error": "not found"})
                else:
                    self._send_json(200, obj)
                return
            if len(parts) == 4 and parts[:2] == ["api", "v1"]:
                obj = store.get(parts[2], parts[3])
                if obj is None:
                    self._send_json(404, {"error": "not found"})
                else:
                    self._send_json(200, obj)
                return
            self._send_json(404, {"error": "unknown path"})

        def _serve_watch(self, kind: str, query) -> None:
            rv = int(query.get("resourceVersion", ["0"])[0])
            try:
                watcher = store.watch([kind], rv)
            except TooOldError:
                self._send_json(410, {"error": "too old resource version"})
                return
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            try:
                idle = 0.0
                while True:
                    ev = watcher.next(timeout=0.5)
                    if ev is None:
                        # Idle: send a blank-line heartbeat chunk every
                        # ~10 s so clients can tell a quiet stream from a
                        # dead socket (their read timeout only fires when
                        # heartbeats stop — reflector.go bounds watches
                        # the same way server-side).
                        idle += 0.5
                        if idle >= WATCH_HEARTBEAT_PERIOD:
                            idle = 0.0
                            self.wfile.write(b"1\r\n\n\r\n")
                            self.wfile.flush()
                        continue
                    idle = 0.0
                    line = json.dumps({"type": ev.type,
                                       "object": ev.object}) + "\n"
                    data = line.encode()
                    self.wfile.write(f"{len(data):x}\r\n".encode())
                    self.wfile.write(data + b"\r\n")
                    self.wfile.flush()
            except (BrokenPipeError, ConnectionResetError):
                pass
            finally:
                watcher.stop()

        def do_POST(self):
            parts, _ = self._parts()
            try:
                body = self._read_body()
            except ValueError:
                self._send_json(400, {"error": "bad json"})
                return
            try:
                if len(parts) == 5 and parts[2] == "namespaces" and \
                        parts[4] == "bindings":
                    ns = parts[3]
                    name = (body.get("metadata") or {}).get("name", "")
                    target = (body.get("target") or {}).get("name", "")
                    store.bind(ns, name, target)
                    self._send_json(201, {"status": "Success"})
                    return
                if len(parts) == 3 and parts[:2] == ["api", "v1"]:
                    kind = parts[2]
                    if kind in _NAMESPACED:
                        body.setdefault("metadata", {}).setdefault(
                            "namespace", "default")
                    if not self._admit(kind, body):
                        return
                    created = store.create(kind, body)
                    self._send_json(201, created)
                    return
            except ConflictError as err:
                self._send_json(409, {"error": str(err)})
                return
            except KeyError as err:
                self._send_json(404, {"error": str(err)})
                return
            self._send_json(404, {"error": "unknown path"})

        def do_PUT(self):
            parts, _ = self._parts()
            try:
                body = self._read_body()
            except ValueError:
                self._send_json(400, {"error": "bad json"})
                return
            try:
                if len(parts) == 6 and parts[2] == "namespaces":
                    kind = parts[4]
                elif len(parts) == 4 and parts[:2] == ["api", "v1"]:
                    kind = parts[2]
                else:
                    self._send_json(404, {"error": "unknown path"})
                    return
                if not self._admit(kind, body):
                    return
                # GuaranteedUpdate semantics: a submitted resourceVersion is
                # a CAS precondition (pkg/storage/etcd/etcd_helper.go).
                rv = (body.get("metadata") or {}).get("resourceVersion")
                updated = store.update(kind, body, expected_rv=rv)
                self._send_json(200, updated)
            except ConflictError as err:
                self._send_json(409, {"error": str(err)})
            except KeyError as err:
                self._send_json(404, {"error": str(err)})

        def do_DELETE(self):
            parts, _ = self._parts()
            try:
                if len(parts) == 6 and parts[2] == "namespaces":
                    store.delete(parts[4], f"{parts[3]}/{parts[5]}")
                elif len(parts) == 4 and parts[:2] == ["api", "v1"]:
                    store.delete(parts[2], parts[3])
                else:
                    self._send_json(404, {"error": "unknown path"})
                    return
                self._send_json(200, {"status": "Success"})
            except KeyError as err:
                self._send_json(404, {"error": str(err)})

    return Handler


def serve(store: MemStore, port: int = 0,
          host: str = "127.0.0.1") -> ThreadingHTTPServer:
    server = ThreadingHTTPServer((host, port), make_handler(store))
    t = threading.Thread(target=server.serve_forever, daemon=True,
                         name="apiserver-http")
    t.start()
    return server
