"""HTTP front end over the in-memory store — the apiserver surface the
scheduler stack speaks (pkg/apiserver handler chain, scheduler-relevant
subset):

    GET    /api/v1/{kind}                      list (+ ?watch=1&resourceVersion=N)
    GET    /api/v1/namespaces/{ns}/{kind}/{name}
    POST   /api/v1/{kind}                      create
    PUT    /api/v1/namespaces/{ns}/{kind}/{name}   update (CAS on resourceVersion)
    DELETE /api/v1/namespaces/{ns}/{kind}/{name}
    POST   /api/v1/namespaces/{ns}/bindings    the binding subresource
    GET    /healthz, /metrics

Watches stream newline-delimited JSON events ({"type": ..., "object": ...})
over a chunked response, the reference's watch wire shape; a stale
resourceVersion returns 410 Gone, telling the client to relist.  Nodes are
cluster-scoped (no namespace segment), pods/services namespaced.

The request loop is hand-parsed HTTP/1.1 (request line + the one header
the routes need) rather than ``BaseHTTPRequestHandler``: at wire bind
rates the stdlib handler's email-module header parsing and per-response
string plumbing cost ~300 µs of a 380 µs request — measured 3.2× more
verbs/s per connection with this loop, with JSON itself at only ~2% of
request cost (so a binary codec would buy nothing; the framing layer was
the bottleneck).
"""

from __future__ import annotations

import contextlib
import json
import socketserver
import threading
import time
from urllib.parse import parse_qs, urlparse

from kubernetes_tpu.api import fieldsel
from kubernetes_tpu.api.types import NAMESPACED_KINDS as _NAMESPACED
from kubernetes_tpu.apiserver import flowcontrol as apf
from kubernetes_tpu.apiserver.memstore import (ConflictError, MemStore,
                                               TooOldError)
from kubernetes_tpu.apiserver.validation import (AdmissionError,
                                                 admit_and_validate,
                                                 store_admission)
from kubernetes_tpu.utils import trace as trace_mod
from kubernetes_tpu.utils.metrics import (APISERVER_REQUEST_LATENCY,
                                          APISERVER_SERIALIZE_OPS,
                                          APISERVER_SERIALIZE_SECONDS,
                                          expose_registry)

# Watch-stream serialize accounting children, resolved once — the
# stream loop flushes per coalesced batch, not per event.
_WATCH_SER_S = APISERVER_SERIALIZE_SECONDS.labels(verb="WATCH")
_WATCH_SER_OPS = APISERVER_SERIALIZE_OPS.labels(verb="WATCH")

# Idle watch streams carry a blank heartbeat chunk this often so clients'
# read deadlines only fire on genuinely dead sockets.
WATCH_HEARTBEAT_PERIOD = 10.0


_NULL_GATE = contextlib.nullcontext()


def _resource_of(parts: list) -> str:
    """The {kind} segment of an (already group-rebased) API path; top-level
    paths (healthz, metrics) are their own nameable resources — the one
    mapping both authorization and the request-latency metric use."""
    if len(parts) >= 5 and parts[2] == "namespaces":
        return parts[4]
    if len(parts) >= 3 and parts[:2] == ["api", "v1"]:
        return parts[2]
    return parts[0] if parts else ""


# Resource values admitted as a metric label: the known kind table plus
# the cluster-scoped kinds and the mux's own top-level paths.  Everything
# else (scanner probes, typos) collapses to "other" — label values are
# memoized forever, so client-controlled strings must not mint series.
_METRIC_RESOURCES = frozenset(_NAMESPACED) | frozenset({
    "nodes", "namespaces", "persistentvolumes", "bindings", "watch",
    "clusterroles", "clusterrolebindings", "healthz", "metrics", "debug"})
_METRIC_VERBS = frozenset({"GET", "POST", "PUT", "DELETE", "HEAD",
                           "PATCH", "WATCH"})


def _metric_resource(parts: list) -> str:
    resource = _resource_of(parts)
    return resource if resource in _METRIC_RESOURCES else "other"


def _rebase_group_path(parts: list) -> list:
    """Group API paths (/apis/{group}/{version}/...) serve the same kind
    table as the legacy core path — the reference's clients address
    extensions/v1beta1 replicasets, batch/v1 jobs, autoscaling/v1 HPAs
    etc.; kind names are globally unique here, so the group/version
    segments just route.  ONE helper used by both the auth block and
    the dispatcher, so authorization always names the resource dispatch
    serves."""
    if len(parts) >= 3 and parts[0] == "apis":
        return ["api", "v1"] + parts[3:]
    return parts

_STATUS_LINES = {
    200: b"HTTP/1.1 200 OK\r\n",
    201: b"HTTP/1.1 201 Created\r\n",
    400: b"HTTP/1.1 400 Bad Request\r\n",
    401: b"HTTP/1.1 401 Unauthorized\r\n",
    403: b"HTTP/1.1 403 Forbidden\r\n",
    404: b"HTTP/1.1 404 Not Found\r\n",
    409: b"HTTP/1.1 409 Conflict\r\n",
    410: b"HTTP/1.1 410 Gone\r\n",
    422: b"HTTP/1.1 422 Unprocessable Entity\r\n",
    429: b"HTTP/1.1 429 Too Many Requests\r\n",
    500: b"HTTP/1.1 500 Internal Server Error\r\n",
    501: b"HTTP/1.1 501 Not Implemented\r\n",
}


def make_handler(store: MemStore, auth=None, admission_control=None,
                 flow=None):
    # Store-aware admission chain (--admission-control order; default:
    # NamespaceLifecycle -> ServiceAccount -> anti-affinity veto ->
    # LimitRanger defaulting -> ResourceQuota), built once per server.
    # Pod admit+create pairs serialize under one gate: ResourceQuota is
    # check-then-act against the stored pod list, and two concurrent
    # creates racing the same quota headroom must not both pass before
    # either lands (the reference serializes via CAS on quota status).
    admission_chain = store_admission(store, admission_control)
    pod_write_gate = threading.Lock()

    class Handler(socketserver.StreamRequestHandler):
        # Response header/body write pairs on keep-alive connections stall
        # ~40 ms under Nagle + the peer's delayed ACK; verbs are small.
        disable_nagle_algorithm = True

        def setup(self):
            super().setup()
            import socket as _socket
            self.connection.setsockopt(_socket.IPPROTO_TCP,
                                       _socket.TCP_NODELAY, 1)
            # Per-operation socket deadline: bounds a peer that stalls
            # mid-body and reaps idle keep-alive connections (clients
            # transparently reconnect); without it one lying client pins
            # a handler thread forever.
            self.connection.settimeout(120.0)

        def handle(self):
            # Deferred TLS handshake (see serve()): completes here, in
            # this connection's thread, bounded by setup()'s 120 s socket
            # deadline.  A verified client certificate then authenticates
            # the whole connection (x509 request authenticator): subject
            # CN is the user, O entries the groups; it outranks tokens.
            self._peer_user = None
            if hasattr(self.connection, "do_handshake"):
                import ssl
                try:
                    self.connection.do_handshake()
                except (ssl.SSLError, TimeoutError, OSError):
                    return  # bad/absent TLS from the peer: drop quietly
                try:
                    cert = self.connection.getpeercert()
                except ValueError:
                    cert = None
                if cert:
                    from kubernetes_tpu.apiserver.auth import user_from_cert
                    self._peer_user = user_from_cert(cert)
            try:
                self._handle_loop()
            except (TimeoutError, OSError):
                return  # stalled/idle peer: reap the connection quietly

        def _handle_loop(self):
            # Keep-alive loop: one request per iteration until the peer
            # closes (or a watch takes the connection over).
            while True:
                line = self.rfile.readline(65536)
                if not line or line in (b"\r\n", b"\n"):
                    return
                try:
                    method, target, _ = line.split(b" ", 2)
                except ValueError:
                    return
                clen = 0
                authz = ""
                traceparent = ""
                chunked = False
                while True:
                    h = self.rfile.readline(65536)
                    if h in (b"\r\n", b"\n", b""):
                        break
                    if h[:15].lower() == b"content-length:":
                        try:
                            clen = int(h[15:].strip())
                        except ValueError:
                            return
                    elif h[:18].lower() == b"transfer-encoding:":
                        chunked = True
                    elif h[:12].lower() == b"traceparent:":
                        # Trace propagation: the request span joins the
                        # caller's trace (the scheduler's bind fan-out).
                        traceparent = h[12:].strip().decode(
                            errors="replace")
                    elif auth is not None and \
                            h[:14].lower() == b"authorization:":
                        authz = h[14:].strip().decode(errors="replace")
                if chunked:
                    # This loop only understands Content-Length framing.
                    # Silently treating a chunked body as empty would make
                    # the body bytes misparse as the next pipelined
                    # request — reject and close instead.
                    self._send_json(501, {"error":
                                          "chunked requests unsupported"})
                    return
                # Bound the body: a negative length would read-to-EOF and
                # an overstated one would block the thread until the peer
                # gives up (mutual deadlock).
                if not 0 <= clen <= 64 * 1024 * 1024:
                    return
                raw = self.rfile.read(clen) if clen else b""
                if len(raw) < clen:
                    return  # short body: peer lied or died
                try:
                    if auth is not None:
                        # Auth runs FIRST in the chain (pkg/apiserver:
                        # auth -> admission -> validation -> registry).
                        target_s = target.decode()
                        parts = _rebase_group_path(
                            [p for p in
                             target_s.split("?", 1)[0].split("/") if p])
                        # Resource name for ABAC: the {kind} segment of
                        # API paths; top-level paths (healthz, metrics)
                        # are their own nameable resources — the same
                        # mapping the request-latency metric labels use.
                        resource = _resource_of(parts)
                        ns = parts[3] if len(parts) >= 5 and \
                            parts[2] == "namespaces" else ""
                        denied = auth.check(authz, method.decode(),
                                            resource, ns,
                                            peer_user=self._peer_user)
                        if denied is not None:
                            code, msg = denied
                            self._send_json(code, {"error": msg})
                            continue
                    if not self._dispatch(method.decode(), target.decode(),
                                          raw, traceparent):
                        return  # watch served; connection consumed
                except (BrokenPipeError, ConnectionResetError):
                    return

        # Per-request serialize accounting (kt-prof wire attribution):
        # _send_json accumulates dumps() nanoseconds here; _dispatch
        # flushes the sum under the request's verb in its finally.
        _ser_ns = 0
        _ser_ops = 0

        def _send_json(self, code: int, obj, retry_after=None) -> None:
            t0 = time.perf_counter_ns()
            body = json.dumps(obj).encode()
            self._ser_ns += time.perf_counter_ns() - t0
            self._ser_ops += 1
            self._send_raw(code, body, "application/json", retry_after)

        def _send_raw(self, code: int, body: bytes, ctype: str,
                      retry_after=None) -> None:
            """One response-assembly path for every content type.
            ``retry_after`` (seconds, float ok — our clients parse it as
            one) rides shed responses as a Retry-After header."""
            self._code = code
            extra = b"" if retry_after is None else \
                b"Retry-After: " + f"{retry_after:g}".encode() + b"\r\n"
            self.wfile.write(
                _STATUS_LINES.get(code, _STATUS_LINES[400])
                + b"Content-Type: " + ctype.encode()
                + b"\r\n" + extra + b"Content-Length: "
                + str(len(body)).encode() + b"\r\n\r\n" + body)
            self.wfile.flush()

        def _send_json_bytes(self, code: int, body: bytes) -> None:
            """Pre-serialized JSON body (the trace export)."""
            self._send_raw(code, body, "application/json")

        def _send_text(self, code: int, body: bytes) -> None:
            self._send_raw(code, body, "text/plain")

        def _admit(self, kind: str, body: dict,
                   op: str = "create") -> bool:
            """Write-path chain (pkg/apiserver: admission -> validation):
            403 on an admission veto, 422 with collected reasons on a
            structurally invalid object.  True = proceed to the store."""
            try:
                errors = admit_and_validate(kind, body, admission_chain, op)
            except AdmissionError as err:
                self._send_json(403, {"error": str(err)})
                return False
            if errors:
                self._send_json(422, {"error": "validation failed",
                                      "reasons": errors})
                return False
            return True

        def _dispatch(self, method: str, target: str, raw: bytes,
                      traceparent: str = "") -> bool:
            """Route one request.  Returns False when the connection was
            taken over by a watch stream (caller must stop the loop).
            Every handled request records its latency in the per-
            verb/resource/code histogram and (when tracing is on) a
            request span under the caller's propagated trace."""
            parsed = urlparse(target)
            parts = _rebase_group_path(
                [p for p in parsed.path.split("/") if p])
            query = parse_qs(parsed.query)
            is_watch = method == "GET" and \
                query.get("watch", ["0"])[0] in ("1", "true")
            t0 = time.perf_counter()
            self._code = 200
            ticket = None
            try:
                if flow is not None:
                    # Priority-level admission BEFORE any routing work:
                    # shed requests must cost the server nothing but the
                    # classification and a 429 write.  The ticket spans
                    # the whole request — for a watch, the whole stream.
                    sub = parts[6] if len(parts) == 7 else ""
                    ticket = flow.admit(method, _resource_of(parts),
                                        is_watch, sub)
                    if not ticket.ok:
                        self._send_json(
                            429, {"error": "the server is overloaded "
                                  f"({ticket.reason}); retry later"},
                            retry_after=ticket.retry_after)
                        return True
                return self._dispatch_inner(method, parts, query, raw)
            finally:
                if ticket is not None:
                    ticket.release()
                dur = time.perf_counter() - t0
                verb = "WATCH" if is_watch else (
                    method if method in _METRIC_VERBS else "other")
                resource = _metric_resource(parts)
                APISERVER_REQUEST_LATENCY.labels(
                    verb=verb, resource=resource,
                    code=str(self._code)).observe(dur * 1e6)
                if self._ser_ns:
                    APISERVER_SERIALIZE_SECONDS.labels(verb=verb).inc(
                        self._ser_ns / 1e9)
                    APISERVER_SERIALIZE_OPS.labels(verb=verb).inc(
                        self._ser_ops)
                    self._ser_ns = self._ser_ops = 0
                trace_mod.record_server_span(
                    "apiserver.request", traceparent, dur,
                    verb=verb, resource=resource, code=self._code)

        def _dispatch_inner(self, method: str, parts: list, query,
                            raw: bytes) -> bool:
            if method == "GET":
                return self._do_get(parts, query)
            body_obj: dict = {}
            if raw:
                try:
                    body_obj = json.loads(raw)
                except ValueError:
                    self._send_json(400, {"error": "bad json"})
                    return True
                if not isinstance(body_obj, dict):
                    self._send_json(400, {"error": "body must be an object"})
                    return True
                if body_obj.get("metadata") is None:
                    # Normalize "metadata": null so downstream setdefault
                    # paths never trip on None.
                    body_obj["metadata"] = {}
            if method == "POST":
                self._do_post(parts, body_obj)
            elif method == "PUT":
                self._do_put(parts, body_obj)
            elif method == "DELETE":
                self._do_delete(parts)
            else:
                self._send_json(404, {"error": "unknown method"})
            return True

        def _do_get(self, parts, query) -> bool:
            if parts == ["healthz"]:
                self._send_text(200, b"ok")
                return True
            if parts == ["metrics"]:
                if query.get("format", [""])[0] == "openmetrics":
                    # Exemplar-carrying OpenMetrics rendering.
                    from kubernetes_tpu.utils.debugmux import \
                        OPENMETRICS_CTYPE
                    from kubernetes_tpu.utils.metrics import \
                        expose_registry_openmetrics
                    body = expose_registry_openmetrics().encode()
                    self._send_raw(200, body, OPENMETRICS_CTYPE)
                    return True
                # Prometheus text exposition: the default registry carries
                # the per-verb/resource/code request latencies this server
                # records plus the shared client/breaker counters.
                self._send_text(200, expose_registry().encode())
                return True
            if parts == ["debug", "traces"]:
                # The span ring as Chrome trace-event JSON (Perfetto):
                # request spans land here under the caller's trace id when
                # a traceparent header was propagated.
                self._send_json_bytes(200,
                                      trace_mod.to_chrome_trace().encode())
                return True
            if parts == ["debug", "timeseries"]:
                from kubernetes_tpu.utils import telemetry
                self._send_json_bytes(
                    200, telemetry.timeseries_json().encode())
                return True
            if parts == ["debug", "dashboard"]:
                from kubernetes_tpu.utils import telemetry
                self._send_raw(200, telemetry.dashboard_html().encode(),
                               "text/html; charset=utf-8")
                return True
            if parts == ["debug", "profile"]:
                # kt-prof continuous CPU profile; disabled (KT_PROF=0)
                # is a client-visible 404, never a 500.
                from kubernetes_tpu.utils import profiler
                resolved = profiler.render(query)
                if resolved is None:
                    self._send_raw(404,
                                   b"profiling disabled (KT_PROF=0)",
                                   "text/plain")
                else:
                    body, ctype = resolved
                    self._send_raw(200, body, ctype)
                return True
            if parts == ["debug", "vars"]:
                # Live flow-control state (the scheduler's /debug/vars
                # idiom): per-level inflight/queue/shed counters — what
                # the soak overload wave scrapes for its queue-depth
                # bound.
                self._send_json(200, {"overload": flow.report()
                                      if flow is not None else None})
                return True
            if len(parts) == 3 and parts[:2] == ["api", "v1"]:
                kind = parts[2]
                try:
                    selector = fieldsel.matcher(
                        query.get("fieldSelector", [""])[0])
                except ValueError as err:
                    self._send_json(400, {"error": str(err)})
                    return True
                if query.get("watch", ["0"])[0] in ("1", "true"):
                    self._serve_watch(kind, query, selector)
                    return False
                items, rv = store.list(kind, selector)
                self._send_json(200, {"kind": kind.capitalize() + "List",
                                      "items": items,
                                      "metadata": {
                                          "resourceVersion": str(rv)}})
                return True
            if len(parts) == 6 and parts[2] == "namespaces":
                # /api/v1/namespaces/{ns}/{kind}/{name}
                _, _, _, ns, kind, name = parts
                obj = store.get(kind, f"{ns}/{name}")
                if obj is None:
                    self._send_json(404, {"error": "not found"})
                else:
                    self._send_json(200, obj)
                return True
            if len(parts) == 4 and parts[:2] == ["api", "v1"]:
                obj = store.get(parts[2], parts[3])
                if obj is None:
                    self._send_json(404, {"error": "not found"})
                else:
                    self._send_json(200, obj)
                return True
            self._send_json(404, {"error": "unknown path"})
            return True

        def _serve_watch(self, kind: str, query, selector=None) -> None:
            rv = int(query.get("resourceVersion", ["0"])[0])
            # Framed multi-event encoding (opt-in via ?frames=1): queued
            # events coalesce into ONE length-prefixed {"items":[...]}
            # batch per write — the client decodes a whole batch with a
            # single json.loads instead of one per event line, and the
            # length prefix lets its pump slice without rescanning the
            # buffer for newlines.  The NDJSON per-event form stays the
            # default for compatibility.
            frames = query.get("frames", ["0"])[0] in ("1", "true")
            sel_key = query.get("fieldSelector", [""])[0] or None
            try:
                watcher = store.watch([kind], rv, selector=selector,
                                      selector_key=sel_key)
            except TooOldError:
                self._send_json(410, {"error": "too old resource version"})
                return
            self.wfile.write(
                b"HTTP/1.1 200 OK\r\nContent-Type: application/json\r\n"
                b"Transfer-Encoding: chunked\r\n\r\n")
            self.wfile.flush()
            try:
                idle = 0.0
                while True:
                    ev = watcher.next(timeout=0.5)
                    if ev is None:
                        # Idle: send a blank-line heartbeat chunk every
                        # ~10 s so clients can tell a quiet stream from a
                        # dead socket (their read timeout only fires when
                        # heartbeats stop — reflector.go bounds watches
                        # the same way server-side).
                        idle += 0.5
                        if idle >= WATCH_HEARTBEAT_PERIOD:
                            idle = 0.0
                            self.wfile.write(b"1\r\n\n\r\n")
                            self.wfile.flush()
                        continue
                    idle = 0.0
                    # Coalesce whatever else is already queued into ONE
                    # chunk write (bounded): under a density burst the
                    # per-event write+flush pair — not serialization — was
                    # the stream cost, and the NDJSON framing is unchanged
                    # (clients parse by lines, not chunks).
                    batch = [ev]
                    while len(batch) < 512:
                        nxt = watcher.next(timeout=0)
                        if nxt is None:
                            break
                        batch.append(nxt)
                    t0 = time.perf_counter_ns()
                    if frames:
                        body = b'{"items":[' + b",".join(
                            e.wire_json() for e in batch) + b"]}"
                        payload = b"=%d\n%s\n" % (len(body), body)
                    else:
                        payload = b"".join(e.wire_line() for e in batch)
                    _WATCH_SER_S.inc((time.perf_counter_ns() - t0) / 1e9)
                    _WATCH_SER_OPS.inc(len(batch))
                    self.wfile.write(f"{len(payload):x}\r\n".encode()
                                     + payload + b"\r\n")
                    self.wfile.flush()
            except (BrokenPipeError, ConnectionResetError):
                pass
            finally:
                watcher.stop()

        def _do_post(self, parts, body) -> None:
            try:
                if len(parts) == 7 and parts[2] == "namespaces" and \
                        parts[4] == "pods" and parts[6] == "eviction":
                    self._do_eviction(parts[3], parts[5])
                    return
                if len(parts) == 5 and parts[2] == "namespaces" and \
                        parts[4] == "bindings":
                    ns = parts[3]
                    if isinstance(body.get("triples"), list):
                        # Compact bulk-bind fast path: [ns, pod, node]
                        # rows, no per-item Binding scaffolding to parse.
                        self._do_bind_triples([
                            ((t[0] if len(t) > 0 and t[0] else ns),
                             t[1] if len(t) > 1 else "",
                             t[2] if len(t) > 2 else "")
                            for t in body["triples"]
                            if isinstance(t, (list, tuple))])
                        return
                    if isinstance(body.get("items"), list):
                        self._do_bind_list(ns, body["items"])
                        return
                    name = (body.get("metadata") or {}).get("name", "")
                    target = (body.get("target") or {}).get("name", "")
                    store.bind(ns, name, target)
                    self._send_json(201, {"status": "Success"})
                    return
                if len(parts) == 3 and parts[:2] == ["api", "v1"]:
                    kind = parts[2]
                    if isinstance(body.get("items"), list):
                        self._do_create_list(kind, body["items"])
                        return
                    if kind in _NAMESPACED:
                        body.setdefault("metadata", {}).setdefault(
                            "namespace", "default")
                    if kind == "pods":
                        with pod_write_gate:
                            if not self._admit(kind, body):
                                return
                            created = store.create(kind, body, owned=True)
                    else:
                        if not self._admit(kind, body):
                            return
                        # owned: the handler's parsed body dies with this
                        # request — the store may keep it without copying.
                        created = store.create(kind, body, owned=True)
                    self._send_json(201, created)
                    return
            except ConflictError as err:
                self._send_json(409, {"error": str(err)})
                return
            except KeyError as err:
                self._send_json(404, {"error": str(err)})
                return
            self._send_json(404, {"error": "unknown path"})

        def _do_eviction(self, ns: str, name: str) -> None:
            """The eviction subresource (POST .../pods/{name}/eviction —
            EvictionREST, pkg/registry/pod/etcd/etcd.go:138-230): delete
            the pod ONLY if its PodDisruptionBudget allows it, with a
            CAS verify-and-decrement on ``status.disruptionAllowed`` so
            two racing evictions can't both spend the same budget slot.
            429 when the budget blocks; >1 matching PDB is the
            reference's unsupported 500."""
            from kubernetes_tpu.controller.replication import _matches
            pod = store.get("pods", f"{ns}/{name}")
            if pod is None:
                self._send_json(404, {"error": f"pod {ns}/{name} "
                                      f"not found"})
                return
            pdbs, _ = store.list(
                "poddisruptionbudgets",
                lambda o: (o.get("metadata") or {})
                .get("namespace", "default") == ns)
            matching = [p for p in pdbs
                        if _matches((p.get("spec") or {})
                                    .get("selector") or {}, pod)]
            if len(matching) > 1:
                self._send_json(500, {"error":
                                      "This pod has more than one "
                                      "PodDisruptionBudget, which the "
                                      "eviction subresource does not "
                                      "support."})
                return
            if matching:
                pdb_key = f"{ns}/" + (matching[0].get("metadata") or {}) \
                    .get("name", "")
                for _attempt in range(3):
                    cur = store.get("poddisruptionbudgets", pdb_key)
                    if cur is None:
                        break  # PDB vanished: no budget to honor
                    if not (cur.get("status") or {}) \
                            .get("disruptionAllowed"):
                        self._send_json(429, {
                            "error": "Cannot evict pod as it would "
                                     "violate the pod's disruption "
                                     "budget."})
                        return
                    # verify-and-decrement: flip allowed -> False under
                    # CAS; the disruption controller recomputes it after
                    # the delete lands.
                    cur.setdefault("status", {})["disruptionAllowed"] = \
                        False
                    try:
                        store.update(
                            "poddisruptionbudgets", cur,
                            expected_rv=(cur.get("metadata") or {})
                            .get("resourceVersion"))
                        break
                    except ConflictError:
                        continue  # racing eviction/controller: re-check
                else:
                    self._send_json(429, {"error":
                                          "disruption budget contended; "
                                          "retry"})
                    return
            try:
                store.delete("pods", f"{ns}/{name}")
            except KeyError:
                self._send_json(404, {"error": "not found"})
                return
            self._send_json(201, {"status": "Success"})

        def _do_bind_list(self, default_ns: str, items: list) -> None:
            """Batch form of the binding subresource: per-item CAS under
            one store lock — semantically identical to N sequential
            BindingREST.Create POSTs, without N requests through the
            framing layer (the measured wire bottleneck at density rates).
            Per-item results keep the conflict detector observable."""
            triples = []
            for it in items:
                it = it if isinstance(it, dict) else {}
                meta = it.get("metadata") or {}
                triples.append((meta.get("namespace") or default_ns,
                                meta.get("name", ""),
                                (it.get("target") or {}).get("name", "")))
            self._do_bind_triples(triples)

        def _do_bind_triples(self, triples: list) -> None:
            """Bulk CAS over fully-resolved (ns, pod, node) rows; callers
            default empty namespaces before reaching here."""
            errors = store.bind_many(triples)
            failed = sum(1 for e in errors if e is not None)
            if failed == 0:
                # All bound: per-item results would be N copies of
                # {"code": 201} — serialized, shipped and parsed for
                # nothing at density rates.  The count is the contract;
                # items are detailed only when something failed.
                self._send_json(200, {"kind": "BindingListResult",
                                      "failed": 0,
                                      "bound": len(errors)})
                return
            results = [{"code": 201} if e is None else
                       {"code": 404 if "not found" in e else 409,
                        "error": e}
                       for e in errors]
            self._send_json(200, {"kind": "BindingListResult",
                                  "failed": failed, "results": results})

        def _do_create_list(self, kind: str, items: list) -> None:
            """Batch create (a v1 List body): each item runs the same
            admission -> validation -> store chain as a single POST;
            per-item results, partial success allowed."""
            results = []
            created = 0
            for it in items:
                if not isinstance(it, dict):
                    results.append({"code": 400, "error": "not an object"})
                    continue
                if it.get("metadata") is None:
                    it["metadata"] = {}
                if kind in _NAMESPACED:
                    it["metadata"].setdefault("namespace", "default")
                gate = pod_write_gate if kind == "pods" else \
                    _NULL_GATE
                with gate:
                    try:
                        errors = admit_and_validate(kind, it,
                                                    admission_chain)
                    except AdmissionError as err:
                        results.append({"code": 403, "error": str(err)})
                        continue
                    if errors:
                        results.append({"code": 422,
                                        "error": "validation failed",
                                        "reasons": errors})
                        continue
                    try:
                        obj = store.create(kind, it, owned=True)
                    except ConflictError as err:
                        results.append({"code": 409, "error": str(err)})
                        continue
                created += 1
                results.append({"code": 201, "resourceVersion":
                                obj["metadata"]["resourceVersion"]})
            self._send_json(200, {"kind": "CreateListResult",
                                  "created": created, "results": results})

        def _do_put(self, parts, body) -> None:
            try:
                if len(parts) == 6 and parts[2] == "namespaces":
                    kind = parts[4]
                    # The path names the namespace; an object missing
                    # metadata.namespace would otherwise key as
                    # cluster-scoped and miss the stored object.
                    body.setdefault("metadata", {}).setdefault(
                        "namespace", parts[3])
                elif len(parts) == 4 and parts[:2] == ["api", "v1"]:
                    kind = parts[2]
                else:
                    self._send_json(404, {"error": "unknown path"})
                    return
                gate = pod_write_gate if kind == "pods" else _NULL_GATE
                with gate:
                    if not self._admit(kind, body, op="update"):
                        return
                    # GuaranteedUpdate semantics: a submitted
                    # resourceVersion is a CAS precondition
                    # (pkg/storage/etcd/etcd_helper.go).
                    rv = (body.get("metadata") or {}).get("resourceVersion")
                    updated = store.update(kind, body, expected_rv=rv,
                                           owned=True)
                self._send_json(200, updated)
            except ConflictError as err:
                self._send_json(409, {"error": str(err)})
            except KeyError as err:
                self._send_json(404, {"error": str(err)})

        def _do_delete(self, parts) -> None:
            try:
                if len(parts) == 6 and parts[2] == "namespaces":
                    store.delete(parts[4], f"{parts[3]}/{parts[5]}")
                elif len(parts) == 4 and parts[:2] == ["api", "v1"]:
                    store.delete(parts[2], parts[3])
                else:
                    self._send_json(404, {"error": "unknown path"})
                    return
                self._send_json(200, {"status": "Success"})
            except KeyError as err:
                self._send_json(404, {"error": str(err)})

    return Handler


class _Server(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True
    request_queue_size = 128

    def handle_error(self, request, client_address):
        # TLS handshake failures and peer resets are routine connection
        # noise (a port scanner, a curl without the CA), not tracebacks.
        # Deliberately NOT the blanket OSError: fd exhaustion and other
        # OSError-derived faults must still surface.
        import ssl
        import sys
        exc = sys.exc_info()[1]
        if isinstance(exc, (ssl.SSLError, ConnectionError, TimeoutError)):
            return
        super().handle_error(request, client_address)


def serve(store: MemStore, port: int = 0,
          host: str = "127.0.0.1", auth=None,
          tls_cert: str = "", tls_key: str = "",
          client_ca: str = "", admission_control=None,
          flow=None) -> _Server:
    """``auth``: an apiserver.auth.AuthConfig; None = the reference's
    insecure port (no authn/z).

    ``tls_cert``/``tls_key`` serve HTTPS (the reference's secure port);
    ``client_ca`` additionally verifies client certificates against that
    CA, and a verified cert's subject becomes the request's user (CN ->
    name, O -> groups — the x509 request authenticator,
    plugin/pkg/auth/authenticator/request/x509), taking precedence over
    bearer tokens."""
    # The apiserver self-scrapes like every other daemon: its request-
    # latency registry lands in the ring /debug/timeseries serves.
    from kubernetes_tpu.utils import profiler, telemetry
    telemetry.ensure_started()
    # kt-prof sampling starts with the daemon (one branch when KT_PROF=0)
    # so /debug/profile covers the server's whole life.
    profiler.ensure_started()
    # Priority-level flow control, knobs read once here (never per
    # request); pass an explicit FlowController to override caps in
    # tests/rigs.
    if flow is None:
        flow = apf.FlowController.from_knobs()
    server = _Server((host, port),
                     make_handler(store, auth, admission_control, flow))
    if tls_cert:
        import ssl
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(tls_cert, tls_key or None)
        if client_ca:
            ctx.load_verify_locations(client_ca)
            # OPTIONAL: token-bearing clients without certs still pass
            # TLS and then authenticate at the token layer.
            ctx.verify_mode = ssl.CERT_OPTIONAL
        # Handshake-on-first-read, NOT on accept: with the default, the
        # handshake runs inside the single serve_forever accept loop, so
        # one stalled client (connect, send nothing) would freeze every
        # new connection.  Deferred, it runs in the per-connection handler
        # thread under that connection's own timeout.
        server.socket = ctx.wrap_socket(server.socket, server_side=True,
                                        do_handshake_on_connect=False)
    t = threading.Thread(target=server.serve_forever, daemon=True,
                         name="apiserver-http")
    t.start()
    return server
