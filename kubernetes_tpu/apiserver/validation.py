"""Object validation + admission for the apiserver write path.

The reference runs every write through structural validation
(``pkg/api/validation/validation.go`` — ValidatePod/ValidateNode collect
field errors) and then a configured admission chain
(``pkg/admission``, plugins under ``plugin/pkg/admission/*``) before the
object reaches the registry.  This module is that slice for the
scheduler-relevant resources: malformed pods/nodes bounce with 422 and the
collected reasons; admission plugins can veto with 403.

Validation collects ALL errors (field.ErrorList behavior) rather than
stopping at the first.
"""

from __future__ import annotations

from kubernetes_tpu.api.quantity import parse_quantity
from kubernetes_tpu.api.types import NAMESPACED_KINDS

# pkg/api/validation/name.go: DNS-1123 subset — enough to catch junk
# without re-implementing the full RFC grammar.
_NAME_OK = set("abcdefghijklmnopqrstuvwxyz0123456789.-")

def _check_name(meta: dict, errors: list[str], what: str) -> None:
    name = meta.get("name", "")
    if not name or not isinstance(name, str):
        errors.append(f"{what}.metadata.name: required")
        return
    if len(name) > 253:
        errors.append(f"{what}.metadata.name: must be <= 253 chars")
    if not all(c in _NAME_OK for c in name):
        # DNS-1123 is lowercase-only: 'MyPod' is invalid, not normalized.
        errors.append(f"{what}.metadata.name: invalid characters in "
                      f"{name!r}")


def _check_quantity(val, path: str, errors: list[str]) -> None:
    try:
        q = parse_quantity(val)
    except (ValueError, TypeError, ArithmeticError):
        errors.append(f"{path}: unparseable quantity {val!r}")
        return
    if q < 0:
        errors.append(f"{path}: must be non-negative, got {val!r}")


def validate_pod(obj: dict) -> list[str]:
    """ValidatePod (validation.go): name present, containers named and
    unique, resource requests/limits parseable and non-negative."""
    errors: list[str] = []
    meta = obj.get("metadata") or {}
    _check_name(meta, errors, "pod")
    spec = obj.get("spec") or {}
    containers = spec.get("containers")
    if not isinstance(containers, list) or not containers:
        errors.append("pod.spec.containers: at least one container required")
        containers = []
    seen = set()
    for i, c in enumerate(containers):
        if not isinstance(c, dict):
            errors.append(f"pod.spec.containers[{i}]: not an object")
            continue
        cname = c.get("name", "")
        if not cname:
            errors.append(f"pod.spec.containers[{i}].name: required")
        elif cname in seen:
            errors.append(f"pod.spec.containers[{i}].name: duplicate "
                          f"{cname!r}")
        seen.add(cname)
        res = c.get("resources") or {}
        for kind in ("requests", "limits"):
            for rname, val in (res.get(kind) or {}).items():
                _check_quantity(
                    val, f"pod.spec.containers[{i}].resources."
                    f"{kind}[{rname}]", errors)
    return errors


def validate_node(obj: dict) -> list[str]:
    """ValidateNode (validation.go): name present, allocatable/capacity
    quantities parseable and non-negative, condition entries well-formed."""
    errors: list[str] = []
    meta = obj.get("metadata") or {}
    _check_name(meta, errors, "node")
    status = obj.get("status") or {}
    for fieldname in ("allocatable", "capacity"):
        for rname, val in (status.get(fieldname) or {}).items():
            _check_quantity(val, f"node.status.{fieldname}[{rname}]", errors)
    for i, cond in enumerate(status.get("conditions") or ()):
        if not isinstance(cond, dict):
            errors.append(f"node.status.conditions[{i}]: not an object")
            continue
        # Unknown condition TYPES are allowed (the reference's ValidateNode
        # doesn't restrict them; consumers ignore types they don't read) —
        # only the shape is enforced.
        if not cond.get("type", ""):
            errors.append(f"node.status.conditions[{i}].type: required")
        if cond.get("status") not in ("True", "False", "Unknown"):
            errors.append(f"node.status.conditions[{i}].status: must be "
                          f"True/False/Unknown")
    return errors


def validate_limit_range(obj: dict) -> list[str]:
    """ValidateLimitRange: every quantity in every limit item parseable —
    a stored garbage quantity would poison every later pod admission in
    the namespace."""
    errors: list[str] = []
    _check_name(obj.get("metadata") or {}, errors, "limitrange")
    items = ((obj.get("spec") or {}).get("limits")) or []
    if not isinstance(items, list):
        return ["limitrange.spec.limits: not a list"]
    for i, item in enumerate(items):
        if not isinstance(item, dict):
            errors.append(f"limitrange.spec.limits[{i}]: not an object")
            continue
        for fieldname in ("default", "defaultRequest", "min", "max"):
            vals = item.get(fieldname) or {}
            if not isinstance(vals, dict):
                errors.append(
                    f"limitrange.spec.limits[{i}].{fieldname}: not a map")
                continue
            for rname, val in vals.items():
                _check_quantity(
                    val, f"limitrange.spec.limits[{i}].{fieldname}"
                    f"[{rname}]", errors)
    return errors


def validate_resource_quota(obj: dict) -> list[str]:
    """ValidateResourceQuota: hard caps parseable and non-negative."""
    errors: list[str] = []
    _check_name(obj.get("metadata") or {}, errors, "resourcequota")
    hard = ((obj.get("spec") or {}).get("hard")) or {}
    if not isinstance(hard, dict):
        return ["resourcequota.spec.hard: not a map"]
    for rname, val in hard.items():
        _check_quantity(val, f"resourcequota.spec.hard[{rname}]", errors)
    return errors


def validate_hpa(obj: dict) -> list[str]:
    """ValidateHorizontalPodAutoscaler (pkg/apis/autoscaling/validation):
    maxReplicas required and >= 1, and >= minReplicas.  Without this an
    HPA missing maxReplicas would silently disable all scale-up in the
    controller (ADVICE r4)."""
    errors: list[str] = []
    _check_name(obj.get("metadata") or {}, errors, "horizontalpodautoscaler")
    spec = obj.get("spec") or {}
    maxr = spec.get("maxReplicas")
    if not isinstance(maxr, int) or maxr < 1:
        errors.append("horizontalpodautoscaler.spec.maxReplicas: must be "
                      "an integer >= 1")
    minr = spec.get("minReplicas")
    if minr is None:
        minr = 1  # optional; unset/null defaults to 1 like the controller
    if not isinstance(minr, int) or minr < 1:
        errors.append("horizontalpodautoscaler.spec.minReplicas: must be "
                      "an integer >= 1")
    elif isinstance(maxr, int) and maxr < minr:
        errors.append("horizontalpodautoscaler.spec.maxReplicas: must be "
                      ">= minReplicas")
    if not spec.get("scaleTargetRef"):
        errors.append("horizontalpodautoscaler.spec.scaleTargetRef: "
                      "required")
    return errors


def validate_pdb(obj: dict) -> list[str]:
    """ValidatePodDisruptionBudget (pkg/apis/policy/validation):
    minAvailable is an int >= 0 or a percentage string."""
    errors: list[str] = []
    _check_name(obj.get("metadata") or {}, errors, "poddisruptionbudget")
    ma = (obj.get("spec") or {}).get("minAvailable", 0)
    if isinstance(ma, bool) or not isinstance(ma, (int, str)):
        errors.append("poddisruptionbudget.spec.minAvailable: must be an "
                      "integer or a percentage string")
    elif isinstance(ma, int) and ma < 0:
        errors.append("poddisruptionbudget.spec.minAvailable: must be "
                      "non-negative")
    elif isinstance(ma, str):
        if not ma.endswith("%"):
            errors.append("poddisruptionbudget.spec.minAvailable: string "
                          "form must be a percentage, e.g. '30%'")
        else:
            try:
                pct = float(ma[:-1])
            except ValueError:
                errors.append("poddisruptionbudget.spec.minAvailable: "
                              f"unparseable percentage {ma!r}")
            else:
                if pct < 0:
                    # A negative percentage silently disables the budget
                    # (desiredHealthy <= 0 allows every eviction).
                    errors.append("poddisruptionbudget.spec."
                                  "minAvailable: must be non-negative")
    return errors


def validate_scheduled_job(obj: dict) -> list[str]:
    """ValidateScheduledJob (pkg/apis/batch/validation): the schedule
    must parse, the concurrency policy must be a known value, and a job
    template must exist — a stored garbage schedule would wedge the
    controller's every sync."""
    from kubernetes_tpu.utils import cron
    errors: list[str] = []
    _check_name(obj.get("metadata") or {}, errors, "scheduledjob")
    spec = obj.get("spec") or {}
    try:
        cron.parse(spec.get("schedule", ""))
    except ValueError as err:
        errors.append(f"scheduledjob.spec.schedule: {err}")
    if spec.get("concurrencyPolicy", "Allow") not in (
            "Allow", "Forbid", "Replace"):
        errors.append("scheduledjob.spec.concurrencyPolicy: must be "
                      "Allow, Forbid or Replace")
    if not isinstance(spec.get("jobTemplate"), dict):
        errors.append("scheduledjob.spec.jobTemplate: required")
    return errors


def validate_petset(obj: dict) -> list[str]:
    """ValidatePetSet (pkg/apis/apps/validation): non-negative replicas
    and a pod template."""
    errors: list[str] = []
    _check_name(obj.get("metadata") or {}, errors, "petset")
    spec = obj.get("spec") or {}
    reps = spec.get("replicas", 1)
    if isinstance(reps, bool) or not isinstance(reps, int) or reps < 0:
        errors.append("petset.spec.replicas: must be a non-negative "
                      "integer")
    if not isinstance(spec.get("template"), dict):
        errors.append("petset.spec.template: required")
    return errors


def validate_cluster_role_binding(obj: dict) -> list[str]:
    """pkg/apis/rbac/validation: a ClusterRoleBinding's roleRef must name
    a ClusterRole — stored otherwise it would either silently grant
    nothing (our authorizer skips it) or, resolved naively, grant
    cluster-wide authority from a namespaced Role."""
    errors: list[str] = []
    _check_name(obj.get("metadata") or {}, errors, "clusterrolebinding")
    ref = obj.get("roleRef") or {}
    if ref.get("kind", "Role") != "ClusterRole":
        errors.append("clusterrolebinding.roleRef.kind: must be "
                      "'ClusterRole'")
    if not ref.get("name"):
        errors.append("clusterrolebinding.roleRef.name: required")
    return errors


VALIDATORS = {"pods": validate_pod, "nodes": validate_node,
              "limitranges": validate_limit_range,
              "resourcequotas": validate_resource_quota,
              "horizontalpodautoscalers": validate_hpa,
              "clusterrolebindings": validate_cluster_role_binding,
              "poddisruptionbudgets": validate_pdb,
              "scheduledjobs": validate_scheduled_job,
              "petsets": validate_petset}


class AdmissionError(Exception):
    """A plugin vetoed the write (admission.Handler denial -> 403)."""


class LimitPodHardAntiAffinityTopology:
    """plugin/pkg/admission/antiaffinity: reject pods whose REQUIRED
    anti-affinity uses a topology key other than the hostname label —
    cluster-wide hard anti-affinity lets one pod fence off whole zones."""

    name = "LimitPodHardAntiAffinityTopology"

    def admit(self, kind: str, obj: dict, op: str = "create") -> None:
        if kind != "pods":
            return
        import json as _json
        ann = (obj.get("metadata") or {}).get("annotations") or {}
        raw = ann.get("scheduler.alpha.kubernetes.io/affinity", "")
        if not raw:
            return
        try:
            aff = _json.loads(raw) if isinstance(raw, str) else raw
        except ValueError:
            return  # malformed affinity is the engine's concern, not ours
        terms = ((aff.get("podAntiAffinity") or {})
                 .get("requiredDuringSchedulingIgnoredDuringExecution")) or ()
        for term in terms:
            key = term.get("topologyKey", "")
            if key and key != "kubernetes.io/hostname":
                raise AdmissionError(
                    f"{self.name}: required pod anti-affinity with topology "
                    f"key {key!r} is not allowed (hostname only)")


def _pod_containers(obj: dict) -> list[dict]:
    spec = obj.get("spec") or {}
    cs = spec.get("containers")
    return [c for c in cs if isinstance(c, dict)] \
        if isinstance(cs, list) else []


def _milli(val) -> int | None:
    """Quantity -> milli-units for comparison (requestLimitEnforcedValues
    does milli-precision comparison when values allow).  None for garbage:
    admission runs BEFORE validation in the chain, so an unparseable
    quantity must fall through to the validator's 422, not crash the
    connection — and a stored-by-other-means garbage LimitRange/quota
    value must not brick the namespace."""
    try:
        return int(parse_quantity(val) * 1000)
    except (ValueError, TypeError, ArithmeticError):
        return None


class LimitRanger:
    """plugin/pkg/admission/limitranger/admission.go: apply the namespace's
    LimitRange Container-type defaults to unset container requests/limits
    (defaultContainerResourceRequirements :190-209, merge :212-247), then
    enforce Min/Max constraints (PodLimitFunc :422-520).  Runs BEFORE
    ResourceQuota, as in the reference plugin order — quota must count the
    post-default requests.

    On a real cluster most pods get their scheduler-visible requests HERE,
    not from their authors; without this plugin the scheduler packs by the
    100m/200Mi nonzero fallback instead of namespace policy."""

    name = "LimitRanger"

    def __init__(self, store=None):
        self._store = store

    def _ranges(self, namespace: str) -> list[dict]:
        if self._store is None:
            return []
        items, _ = self._store.list(
            "limitranges",
            lambda o: (o.get("metadata") or {})
            .get("namespace", "default") == namespace)
        return items

    def admit(self, kind: str, obj: dict, op: str = "create") -> None:
        if kind != "pods":
            return
        ns = (obj.get("metadata") or {}).get("namespace") or "default"
        violations: list[str] = []
        for lr in self._ranges(ns):
            limits = ((lr.get("spec") or {}).get("limits")) or []
            # Defaults first (mergePodResourceRequirements), then Min/Max
            # against the merged values.
            dreq: dict = {}
            dlim: dict = {}
            for item in limits:
                if item.get("type", "Container") != "Container":
                    continue
                dreq.update(item.get("defaultRequest") or {})
                dlim.update(item.get("default") or {})
            applied: list[str] = []
            for c in _pod_containers(obj):
                res = c.get("resources")
                if not isinstance(res, dict):
                    res = {}       # explicit null: default the whole block
                    c["resources"] = res
                req = res.get("requests")
                if not isinstance(req, dict):
                    req = {}
                    res["requests"] = req
                lim = res.get("limits")
                if not isinstance(lim, dict):
                    lim = {}
                    res["limits"] = lim
                set_r = [k for k in dreq if k not in req]
                set_l = [k for k in dlim if k not in lim]
                for k in set_r:
                    req[k] = dreq[k]
                for k in set_l:
                    lim[k] = dlim[k]
                if set_r:
                    applied.append(f"{', '.join(sorted(set_r))} request for "
                                   f"container {c.get('name', '')}")
                if set_l:
                    applied.append(f"{', '.join(sorted(set_l))} limit for "
                                   f"container {c.get('name', '')}")
            if applied:
                ann = (obj.setdefault("metadata", {})
                       .setdefault("annotations", {}))
                ann["kubernetes.io/limit-ranger"] = \
                    "LimitRanger plugin set: " + "; ".join(applied)
            for item in limits:
                if item.get("type", "Container") != "Container":
                    continue
                for c in _pod_containers(obj):
                    res = c.get("resources") if \
                        isinstance(c.get("resources"), dict) else {}
                    req = res.get("requests") if \
                        isinstance(res.get("requests"), dict) else {}
                    lim = res.get("limits") if \
                        isinstance(res.get("limits"), dict) else {}
                    for rname, floor in (item.get("min") or {}).items():
                        fv = _milli(floor)
                        if rname not in req:
                            violations.append(
                                f"minimum {rname} usage per Container is "
                                f"{floor}.  No request is specified.")
                            continue
                        rv = _milli(req[rname])
                        # None (unparseable) on either side: leave it to
                        # the validator's 422.
                        if fv is not None and rv is not None and rv < fv:
                            violations.append(
                                f"minimum {rname} usage per Container is "
                                f"{floor}, but request is {req[rname]}.")
                    for rname, cap in (item.get("max") or {}).items():
                        cv = _milli(cap)
                        if cv is None:
                            continue
                        lv = _milli(lim[rname]) if rname in lim else None
                        rv = _milli(req[rname]) if rname in req else None
                        if lv is not None and lv > cv:
                            violations.append(
                                f"maximum {rname} usage per Container is "
                                f"{cap}, but limit is {lim[rname]}.")
                        elif rname not in lim and rv is not None and rv > cv:
                            violations.append(
                                f"maximum {rname} usage per Container is "
                                f"{cap}, but request is {req[rname]}.")
        if violations:
            raise AdmissionError(f"{self.name}: " + "; ".join(violations))


# Quota resource names tracked for pods (pkg/quota/evaluator/core/pods.go:
# podUsageHelper — pods count, cpu/memory from requests, the requests.*
# aliases mirror them).
_QUOTA_COMPUTE = {"cpu": "cpu", "requests.cpu": "cpu",
                  "memory": "memory", "requests.memory": "memory"}


class ResourceQuota:
    """plugin/pkg/admission/resourcequota: bound namespace usage.  A write
    that would push any tracked resource past the quota's hard limit
    bounces 403 (admission.go:71-…) — creates charge their full usage,
    updates charge their delta (old self excluded from the recompute); a
    quota tracking a compute resource requires every container to specify
    it (the evaluator's Constraints — this is why LimitRanger runs first).

    Usage is recomputed from the live pod list on every admit rather than
    incrementally CAS-maintained: writes are control-plane-rate, the
    recompute is O(pods-in-namespace), and it self-heals after deletes
    without needing the reference's quota controller resync.  The server
    serializes pod admit+store under one write gate so concurrent creates
    cannot both pass the check before either lands."""

    name = "ResourceQuota"

    def __init__(self, store=None):
        self._store = store

    def admit(self, kind: str, obj: dict, op: str = "create") -> None:
        if kind != "pods" or self._store is None:
            return
        meta = obj.get("metadata") or {}
        ns = meta.get("namespace") or "default"
        # Selector pushed into list(): MemStore filters BEFORE its
        # per-item deepcopy, so a quota'd namespace never pays an
        # O(whole-cluster) copy per pod write under the serializing gate.
        in_ns = (lambda o: (o.get("metadata") or {})
                 .get("namespace", "default") == ns)
        quotas, _ = self._store.list("resourcequotas", in_ns)
        if not quotas:
            return
        new_usage = self._pod_usage(obj)
        self_key = f"{ns}/{meta.get('name', '')}"
        pods, _ = self._store.list("pods", in_ns)
        used = {"pods": 0, "cpu": 0, "memory": 0}
        for p in pods:
            pmeta = p.get("metadata") or {}
            if op == "update" and \
                    f"{ns}/{pmeta.get('name', '')}" == self_key:
                continue  # replaced by new_usage: a PUT that inflates
                # requests is charged its delta, not waved through
            phase = (p.get("status") or {}).get("phase", "")
            if phase in ("Succeeded", "Failed"):
                continue  # terminal pods stop counting (pods.go:52-58)
            u = self._pod_usage(p)
            for k in used:
                used[k] += u[k]
        # Surface CURRENT usage (stored pods only, not the pod being
        # admitted) on the quota objects FIRST — admission runs before the
        # store, so a later 422/409 must not leave a phantom pod in
        # status.used, and a 403 below should still record live usage.
        # Status goes through a fresh read + CAS touching ONLY status
        # (the reference's quota CAS): rewriting the listed copy would
        # silently revert a concurrent admin PUT to spec.hard.  Unchanged
        # usage writes nothing — no event, no WAL append, no watcher wake.
        for q in quotas:
            qname = (q.get("metadata") or {}).get("name", "")
            try:
                cur = self._store.get("resourcequotas", f"{ns}/{qname}")
                if cur is None:
                    continue
                status = {
                    "hard": dict(((cur.get("spec") or {}).get("hard"))
                                 or {}),
                    "used": {
                        "pods": str(used["pods"] // 1000),
                        "requests.cpu": f"{used['cpu']}m",
                        "requests.memory": str(used["memory"] // 1000),
                    }}
                if cur.get("status") == status:
                    continue
                self._store.update(
                    "resourcequotas", {**cur, "status": status},
                    expected_rv=(cur.get("metadata") or {})
                    .get("resourceVersion"))
            except Exception:  # noqa: BLE001 — deleted or CAS-raced by a
                pass           # concurrent PUT: surfacing is best-effort
        for q in quotas:
            hard = ((q.get("spec") or {}).get("hard")) or {}
            for rname, cap in hard.items():
                dim = _QUOTA_COMPUTE.get(rname)
                if dim is None and rname != "pods":
                    continue
                if dim is not None and new_usage[f"unset_{dim}"]:
                    raise AdmissionError(
                        f"{self.name}: must specify {dim} — quota "
                        f"{(q.get('metadata') or {}).get('name', '')} "
                        f"tracks {rname}")
                key = dim or "pods"
                cap_v = _milli(cap)
                if cap_v is not None and \
                        used[key] + new_usage[key] > cap_v:
                    raise AdmissionError(
                        f"{self.name}: exceeded quota "
                        f"{(q.get('metadata') or {}).get('name', '')}: "
                        f"requested {rname}, used {used[key]}m of {cap}")

    @staticmethod
    def _pod_usage(obj: dict) -> dict:
        cpu = mem = 0
        unset_cpu = unset_mem = False
        for c in _pod_containers(obj):
            res = c.get("resources")
            req = res.get("requests") if isinstance(res, dict) else None
            req = req if isinstance(req, dict) else {}
            # Unparseable values count 0 and fall through to the
            # validator's 422 (admission must neither crash nor mask the
            # structural error with a quota 403).
            if "cpu" in req:
                cpu += _milli(req["cpu"]) or 0
            else:
                unset_cpu = True
            if "memory" in req:
                mem += _milli(req["memory"]) or 0
            else:
                unset_mem = True
        # All dimensions in milli-units so they compare directly against
        # _milli(hard-cap) — one pod counts 1000 against a "pods: 10" cap
        # of 10000.
        return {"pods": 1000, "cpu": cpu, "memory": mem,
                "unset_cpu": unset_cpu, "unset_memory": unset_mem}


class AlwaysPullImages:
    """plugin/pkg/admission/alwayspullimages: force every container's
    imagePullPolicy to Always — in a multitenant cluster a cached image
    must not let one tenant run another's private bytes without
    registry-side credential checks."""

    name = "AlwaysPullImages"

    def admit(self, kind: str, obj: dict, op: str = "create") -> None:
        if kind != "pods":
            return
        for c in _pod_containers(obj):
            c["imagePullPolicy"] = "Always"


class SecurityContextDeny:
    """plugin/pkg/admission/securitycontext/scdeny: reject pods that set
    the privilege-adjacent SecurityContext fields (SELinuxOptions,
    RunAsUser, SupplementalGroups, FSGroup) at the pod OR container
    level — the cluster posture where user-controlled UID/SELinux
    assignment is forbidden."""

    name = "SecurityContextDeny"

    _POD_FIELDS = ("seLinuxOptions", "runAsUser", "supplementalGroups",
                   "fsGroup")
    _CONTAINER_FIELDS = ("seLinuxOptions", "runAsUser")

    def admit(self, kind: str, obj: dict, op: str = "create") -> None:
        if kind != "pods":
            return
        spec = obj.get("spec") or {}
        sc = spec.get("securityContext") or {}
        for f in self._POD_FIELDS:
            if sc.get(f) is not None:
                raise AdmissionError(
                    f"{self.name}: pod.spec.securityContext.{f} "
                    f"is forbidden")
        for c in _pod_containers(obj):
            csc = c.get("securityContext") or {}
            for f in self._CONTAINER_FIELDS:
                if csc.get(f) is not None:
                    raise AdmissionError(
                        f"{self.name}: securityContext.{f} is forbidden "
                        f"for container {c.get('name', '')}")


SA_MOUNT_PATH = "/var/run/secrets/kubernetes.io/serviceaccount"


class ServiceAccount:
    """plugin/pkg/admission/serviceaccount/admission.go: every pod runs
    AS a service account —

    * an unset ``spec.serviceAccountName`` defaults to ``default``
      (admission.go DefaultServiceAccountName);
    * a pod naming a MISSING non-default SA bounces 403 (admission.go
      getServiceAccount error path) — it would run with credentials
      that don't exist;
    * the SA's token secret is mounted at the canonical path into every
      container that doesn't already mount one (admission.go
      mountServiceAccountToken).

    Deviation, documented: a missing ``default`` SA skips the mount
    instead of rejecting — the serviceaccounts controller creates it
    asynchronously, and the reference's own perf master runs
    AlwaysAdmit precisely to avoid this bootstrap coupling."""

    name = "ServiceAccount"

    def __init__(self, store=None):
        self._store = store

    def admit(self, kind: str, obj: dict, op: str = "create") -> None:
        if kind != "pods" or op != "create" or self._store is None:
            return
        meta = obj.get("metadata") or {}
        ns = meta.get("namespace") or "default"
        spec = obj.setdefault("spec", {})
        sa_name = spec.get("serviceAccountName") or \
            spec.get("serviceAccount") or "default"
        spec["serviceAccountName"] = sa_name
        spec["serviceAccount"] = sa_name  # 1.x carries both fields
        sa = self._store.get("serviceaccounts", f"{ns}/{sa_name}")
        if sa is None:
            if sa_name != "default":
                raise AdmissionError(
                    f"{self.name}: service account {ns}/{sa_name} "
                    f"does not exist")
            return  # bootstrap window: controller will create it
        refs = sa.get("secrets") or []
        token_name = refs[0].get("name", "") if refs else ""
        if not token_name:
            if sa_name != "default":
                # The reference rejects until the token exists
                # (admission.go mountServiceAccountToken: "no API token
                # found ... retry after the token is automatically
                # created") — admitting now would run the pod without
                # credentials forever, since nothing reconciles mounts
                # post-create.
                raise AdmissionError(
                    f"{self.name}: no API token found for service "
                    f"account {ns}/{sa_name}; retry after the token "
                    f"controller creates it")
            return  # default-SA bootstrap window (documented deviation)
        volumes = spec.setdefault("volumes", [])
        vol_name = None
        for v in volumes:
            if (v.get("secret") or {}).get("secretName") == token_name:
                vol_name = v.get("name")
                break
        if vol_name is None:
            vol_name = f"{token_name}-volume"
            volumes.append({"name": vol_name,
                            "secret": {"secretName": token_name}})
        for c in _pod_containers(obj):
            mounts = c.setdefault("volumeMounts", [])
            if any(m.get("mountPath") == SA_MOUNT_PATH for m in mounts):
                continue
            mounts.append({"name": vol_name, "readOnly": True,
                           "mountPath": SA_MOUNT_PATH})


class NamespaceLifecycle:
    """plugin/pkg/admission/namespace/lifecycle: reject creates into a
    namespace that is being torn down.  Unlike the reference, a namespace
    with no Namespace object is allowed (implicit namespaces are this
    store's default; only an explicit Terminating namespace blocks)."""

    name = "NamespaceLifecycle"

    def __init__(self, store=None):
        self._store = store

    def admit(self, kind: str, obj: dict, op: str = "create") -> None:
        if op != "create" or self._store is None or \
                kind == "namespaces" or kind not in NAMESPACED_KINDS:
            return
        ns = (obj.get("metadata") or {}).get("namespace") or "default"
        nsobj = self._store.get("namespaces", ns)
        if nsobj is None:
            return
        if (nsobj.get("status") or {}).get("phase") == "Terminating" or \
                (nsobj.get("metadata") or {}).get("deletionTimestamp"):
            raise AdmissionError(
                f"{self.name}: namespace {ns} is terminating")


DEFAULT_ADMISSION = (LimitPodHardAntiAffinityTopology(),)


# --admission-control registry (pkg/admission RegisterPlugin): name ->
# factory(store).  AlwaysDeny/AlwaysAdmit are the reference's testing
# plugins; the perf master runs AlwaysAdmit (master_utils.go:220).
ADMISSION_PLUGINS = {
    "NamespaceLifecycle": NamespaceLifecycle,
    "ServiceAccount": ServiceAccount,
    "LimitPodHardAntiAffinityTopology":
        lambda store: LimitPodHardAntiAffinityTopology(),
    "LimitRanger": LimitRanger,
    "ResourceQuota": ResourceQuota,
    "AlwaysPullImages": lambda store: AlwaysPullImages(),
    "SecurityContextDeny": lambda store: SecurityContextDeny(),
    "AlwaysAdmit": lambda store: None,
    "AlwaysDeny": lambda store: _AlwaysDeny(),
}

# The default chain, in the reference's plugin order: namespace
# lifecycle first, ServiceAccount defaulting/mounting, the
# anti-affinity veto, LimitRanger defaulting, then ResourceQuota
# against the post-default requests.
DEFAULT_ADMISSION_CONTROL = (
    "NamespaceLifecycle", "ServiceAccount",
    "LimitPodHardAntiAffinityTopology", "LimitRanger", "ResourceQuota")


class _AlwaysDeny:
    name = "AlwaysDeny"

    def admit(self, kind: str, obj: dict, op: str = "create") -> None:
        raise AdmissionError("AlwaysDeny: admission is disabled")


def store_admission(store, names=None) -> tuple:
    """Build the admission chain in the order ``names`` lists the
    plugins (the reference applies --admission-control in flag order);
    None = the default chain.  Unknown names raise — a typo'd plugin
    silently skipped would be a silently-open cluster."""
    if names is None:
        names = DEFAULT_ADMISSION_CONTROL
    chain = []
    for name in names:
        name = name.strip()
        if not name:
            continue
        if name not in ADMISSION_PLUGINS:
            raise ValueError(f"unknown admission plugin {name!r}")
        plugin = ADMISSION_PLUGINS[name](store)
        if plugin is not None:  # AlwaysAdmit contributes nothing
            chain.append(plugin)
    return tuple(chain)


def admit_and_validate(kind: str, obj: dict,
                       admission=DEFAULT_ADMISSION,
                       op: str = "create") -> list[str]:
    """The write-path chain (pkg/apiserver: admission -> validation ->
    registry).  Returns validation errors; raises AdmissionError on veto."""
    for plugin in admission:
        plugin.admit(kind, obj, op)
    validator = VALIDATORS.get(kind)
    return validator(obj) if validator else []
