"""Object validation + admission for the apiserver write path.

The reference runs every write through structural validation
(``pkg/api/validation/validation.go`` — ValidatePod/ValidateNode collect
field errors) and then a configured admission chain
(``pkg/admission``, plugins under ``plugin/pkg/admission/*``) before the
object reaches the registry.  This module is that slice for the
scheduler-relevant resources: malformed pods/nodes bounce with 422 and the
collected reasons; admission plugins can veto with 403.

Validation collects ALL errors (field.ErrorList behavior) rather than
stopping at the first.
"""

from __future__ import annotations

from kubernetes_tpu.api.quantity import parse_quantity

# pkg/api/validation/name.go: DNS-1123 subset — enough to catch junk
# without re-implementing the full RFC grammar.
_NAME_OK = set("abcdefghijklmnopqrstuvwxyz0123456789.-")

def _check_name(meta: dict, errors: list[str], what: str) -> None:
    name = meta.get("name", "")
    if not name or not isinstance(name, str):
        errors.append(f"{what}.metadata.name: required")
        return
    if len(name) > 253:
        errors.append(f"{what}.metadata.name: must be <= 253 chars")
    if not all(c in _NAME_OK for c in name):
        # DNS-1123 is lowercase-only: 'MyPod' is invalid, not normalized.
        errors.append(f"{what}.metadata.name: invalid characters in "
                      f"{name!r}")


def _check_quantity(val, path: str, errors: list[str]) -> None:
    try:
        q = parse_quantity(val)
    except (ValueError, TypeError, ArithmeticError):
        errors.append(f"{path}: unparseable quantity {val!r}")
        return
    if q < 0:
        errors.append(f"{path}: must be non-negative, got {val!r}")


def validate_pod(obj: dict) -> list[str]:
    """ValidatePod (validation.go): name present, containers named and
    unique, resource requests/limits parseable and non-negative."""
    errors: list[str] = []
    meta = obj.get("metadata") or {}
    _check_name(meta, errors, "pod")
    spec = obj.get("spec") or {}
    containers = spec.get("containers")
    if not isinstance(containers, list) or not containers:
        errors.append("pod.spec.containers: at least one container required")
        containers = []
    seen = set()
    for i, c in enumerate(containers):
        if not isinstance(c, dict):
            errors.append(f"pod.spec.containers[{i}]: not an object")
            continue
        cname = c.get("name", "")
        if not cname:
            errors.append(f"pod.spec.containers[{i}].name: required")
        elif cname in seen:
            errors.append(f"pod.spec.containers[{i}].name: duplicate "
                          f"{cname!r}")
        seen.add(cname)
        res = c.get("resources") or {}
        for kind in ("requests", "limits"):
            for rname, val in (res.get(kind) or {}).items():
                _check_quantity(
                    val, f"pod.spec.containers[{i}].resources."
                    f"{kind}[{rname}]", errors)
    return errors


def validate_node(obj: dict) -> list[str]:
    """ValidateNode (validation.go): name present, allocatable/capacity
    quantities parseable and non-negative, condition entries well-formed."""
    errors: list[str] = []
    meta = obj.get("metadata") or {}
    _check_name(meta, errors, "node")
    status = obj.get("status") or {}
    for fieldname in ("allocatable", "capacity"):
        for rname, val in (status.get(fieldname) or {}).items():
            _check_quantity(val, f"node.status.{fieldname}[{rname}]", errors)
    for i, cond in enumerate(status.get("conditions") or ()):
        if not isinstance(cond, dict):
            errors.append(f"node.status.conditions[{i}]: not an object")
            continue
        # Unknown condition TYPES are allowed (the reference's ValidateNode
        # doesn't restrict them; consumers ignore types they don't read) —
        # only the shape is enforced.
        if not cond.get("type", ""):
            errors.append(f"node.status.conditions[{i}].type: required")
        if cond.get("status") not in ("True", "False", "Unknown"):
            errors.append(f"node.status.conditions[{i}].status: must be "
                          f"True/False/Unknown")
    return errors


VALIDATORS = {"pods": validate_pod, "nodes": validate_node}


class AdmissionError(Exception):
    """A plugin vetoed the write (admission.Handler denial -> 403)."""


class LimitPodHardAntiAffinityTopology:
    """plugin/pkg/admission/antiaffinity: reject pods whose REQUIRED
    anti-affinity uses a topology key other than the hostname label —
    cluster-wide hard anti-affinity lets one pod fence off whole zones."""

    name = "LimitPodHardAntiAffinityTopology"

    def admit(self, kind: str, obj: dict) -> None:
        if kind != "pods":
            return
        import json as _json
        ann = (obj.get("metadata") or {}).get("annotations") or {}
        raw = ann.get("scheduler.alpha.kubernetes.io/affinity", "")
        if not raw:
            return
        try:
            aff = _json.loads(raw) if isinstance(raw, str) else raw
        except ValueError:
            return  # malformed affinity is the engine's concern, not ours
        terms = ((aff.get("podAntiAffinity") or {})
                 .get("requiredDuringSchedulingIgnoredDuringExecution")) or ()
        for term in terms:
            key = term.get("topologyKey", "")
            if key and key != "kubernetes.io/hostname":
                raise AdmissionError(
                    f"{self.name}: required pod anti-affinity with topology "
                    f"key {key!r} is not allowed (hostname only)")


DEFAULT_ADMISSION = (LimitPodHardAntiAffinityTopology(),)


def admit_and_validate(kind: str, obj: dict,
                       admission=DEFAULT_ADMISSION) -> list[str]:
    """The write-path chain (pkg/apiserver: admission -> validation ->
    registry).  Returns validation errors; raises AdmissionError on veto."""
    for plugin in admission:
        plugin.admit(kind, obj)
    validator = VALIDATORS.get(kind)
    return validator(obj) if validator else []
