"""AuthN/Z for the apiserver write path (pkg/auth + plugin/pkg/auth,
scheduler-relevant slice).

Authentication: static token file, the reference's tokenfile authenticator
(plugin/pkg/auth/authenticator/token/tokenfile) — CSV lines of
``token,user,uid[,group1|group2]``; requests carry
``Authorization: Bearer <token>``.

Authorization: ABAC-lite (pkg/auth/authorizer/abac): an ordered list of
policy dicts ``{"user": ..., "group": ..., "resource": ..., "readonly":
bool}`` — empty/"*" fields match anything; a request is allowed if ANY
policy matches (readonly policies only allow GET).  The file format is the
reference's one-JSON-object-per-line policy file.

Both are OFF unless configured — matching the reference's default
insecure port — and wired in front of the handler chain
(auth -> admission -> validation -> registry, pkg/apiserver).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Optional


@dataclass(frozen=True)
class UserInfo:
    name: str
    uid: str = ""
    groups: tuple[str, ...] = ()


class AuthenticationError(Exception):
    """No/unknown credentials -> 401."""


class TokenAuthenticator:
    """tokenfile.TokenAuthenticator: token -> UserInfo."""

    def __init__(self, tokens: dict[str, UserInfo]):
        self._tokens = dict(tokens)

    @classmethod
    def from_file(cls, path: str) -> "TokenAuthenticator":
        tokens: dict[str, UserInfo] = {}
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = [p.strip() for p in line.split(",")]
                if len(parts) < 3:
                    raise ValueError(
                        f"token file line needs token,user,uid: {line!r}")
                groups = tuple(g for g in parts[3].split("|")) \
                    if len(parts) > 3 and parts[3] else ()
                tokens[parts[0]] = UserInfo(name=parts[1], uid=parts[2],
                                            groups=groups)
        return cls(tokens)

    def authenticate(self, authorization: str) -> UserInfo:
        """``Authorization: Bearer <token>`` -> UserInfo or raises."""
        scheme, _, token = authorization.partition(" ")
        if scheme.lower() != "bearer" or not token.strip():
            raise AuthenticationError("expected a bearer token")
        user = self._tokens.get(token.strip())
        if user is None:
            raise AuthenticationError("unknown token")
        return user


@dataclass
class ABACAuthorizer:
    """abac.PolicyList.Authorize: any matching policy allows."""

    policies: list[dict] = field(default_factory=list)

    @classmethod
    def from_file(cls, path: str) -> "ABACAuthorizer":
        policies = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                policies.append(json.loads(line))
        return cls(policies)

    def authorize(self, user: UserInfo, verb: str, resource: str) -> bool:
        readonly_verb = verb in ("GET", "HEAD")
        for p in self.policies:
            pu = p.get("user", "")
            pg = p.get("group", "")
            if pu and pu != "*" and pu != user.name:
                continue
            if pg and pg != "*" and pg not in user.groups:
                continue
            pr = p.get("resource", "")
            if pr and pr != "*" and pr != resource:
                continue
            if p.get("readonly", False) and not readonly_verb:
                continue
            return True
        return False


@dataclass
class AuthConfig:
    """The chain the server consults; either part may be absent."""

    authenticator: Optional[TokenAuthenticator] = None
    authorizer: Optional[ABACAuthorizer] = None

    def check(self, authorization: str, verb: str,
              resource: str) -> Optional[tuple[int, str]]:
        """None = allowed; else (status, message)."""
        user = None
        if self.authenticator is not None:
            try:
                user = self.authenticator.authenticate(authorization)
            except AuthenticationError as err:
                return 401, str(err)
        if self.authorizer is not None:
            if user is None:
                user = UserInfo(name="system:anonymous")
            if not self.authorizer.authorize(user, verb, resource):
                return 403, (f"user {user.name!r} is not allowed to "
                             f"{verb} {resource}")
        return None
