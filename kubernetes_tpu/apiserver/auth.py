"""AuthN/Z for the apiserver write path (pkg/auth + plugin/pkg/auth,
scheduler-relevant slice).

Authentication: static token file, the reference's tokenfile authenticator
(plugin/pkg/auth/authenticator/token/tokenfile) — CSV lines of
``token,user,uid[,group1|group2]``; requests carry
``Authorization: Bearer <token>``.

Authorization: ABAC-lite (pkg/auth/authorizer/abac): an ordered list of
policy dicts ``{"user": ..., "group": ..., "resource": ..., "readonly":
bool}`` — empty/"*" fields match anything; a request is allowed if ANY
policy matches (readonly policies only allow GET).  The file format is the
reference's one-JSON-object-per-line policy file.

Both are OFF unless configured — matching the reference's default
insecure port — and wired in front of the handler chain
(auth -> admission -> validation -> registry, pkg/apiserver).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Optional


@dataclass(frozen=True)
class UserInfo:
    name: str
    uid: str = ""
    groups: tuple[str, ...] = ()


class AuthenticationError(Exception):
    """No/unknown credentials -> 401."""


class TokenAuthenticator:
    """tokenfile.TokenAuthenticator: token -> UserInfo."""

    def __init__(self, tokens: dict[str, UserInfo]):
        self._tokens = dict(tokens)

    @classmethod
    def from_file(cls, path: str) -> "TokenAuthenticator":
        tokens: dict[str, UserInfo] = {}
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = [p.strip() for p in line.split(",")]
                if len(parts) < 3:
                    raise ValueError(
                        f"token file line needs token,user,uid: {line!r}")
                groups = tuple(g for g in parts[3].split("|")) \
                    if len(parts) > 3 and parts[3] else ()
                tokens[parts[0]] = UserInfo(name=parts[1], uid=parts[2],
                                            groups=groups)
        return cls(tokens)

    def authenticate(self, authorization: str) -> UserInfo:
        """``Authorization: Bearer <token>`` -> UserInfo or raises."""
        scheme, _, token = authorization.partition(" ")
        if scheme.lower() != "bearer" or not token.strip():
            raise AuthenticationError("expected a bearer token")
        user = self._tokens.get(token.strip())
        if user is None:
            raise AuthenticationError("unknown token")
        return user


SA_TOKEN_TYPE = "kubernetes.io/service-account-token"
SA_NAME_ANNOTATION = "kubernetes.io/service-account.name"


def serviceaccount_username(namespace: str, name: str) -> str:
    """pkg/serviceaccount MakeUsername."""
    return f"system:serviceaccount:{namespace}:{name}"


class ServiceAccountAuthenticator:
    """ServiceAccount token authentication (pkg/serviceaccount/jwt.go's
    role): a bearer token is valid iff a live Secret of type
    ``kubernetes.io/service-account-token`` carries it, and resolves to
    the SA identity ``system:serviceaccount:<ns>:<name>`` with the
    ``system:serviceaccounts`` group pair.  Where the reference verifies
    a JWT signature offline, this store-backed check gives the same
    revocation story the reference ALSO enforces (tokens die with their
    secret, jwt.go lookup = true path)."""

    def __init__(self, store):
        self._store = store
        # token -> (namespace, sa_name), maintained from a secrets
        # watch: the authenticator sits on the request hot path, and an
        # O(all-secrets) scan per bearer token would grow with the
        # cluster.  Started lazily so constructing the authenticator
        # stays side-effect free.
        import threading
        self._index: dict[str, tuple[str, str]] = {}
        self._index_lock = threading.Lock()
        self._reflector = None
        self._ready = threading.Event()

    def _on_secret(self, etype: str, obj: dict) -> None:
        if obj.get("type") != SA_TOKEN_TYPE:
            return
        meta = obj.get("metadata") or {}
        token = (obj.get("data") or {}).get("token")
        sa_name = (meta.get("annotations") or {}).get(
            SA_NAME_ANNOTATION, "")
        if not token or not sa_name:
            return
        with self._index_lock:
            if etype == "DELETED":
                self._index.pop(token, None)
            else:
                self._index[token] = (meta.get("namespace", "default"),
                                      sa_name)

    def _ensure_watch(self) -> None:
        if self._ready.is_set():
            return
        with self._index_lock:
            starter = self._reflector is None
            if starter:
                from kubernetes_tpu.client.reflector import Reflector
                self._reflector = Reflector(self._store, "secrets",
                                            self._on_secret)
        if starter:
            # run() outside the index lock: the initial list delivers
            # through _on_secret, which takes it.
            self._reflector.run()
            self._reflector.wait_for_sync()
            self._ready.set()
        else:
            self._ready.wait(timeout=10)

    def authenticate(self, authorization: str) -> UserInfo:
        scheme, _, token = authorization.partition(" ")
        token = token.strip()
        if scheme.lower() != "bearer" or not token:
            raise AuthenticationError("expected a bearer token")
        try:
            self._ensure_watch()
        except Exception as err:  # noqa: BLE001 — store unreadable: 401
            raise AuthenticationError("token lookup failed") from err
        with self._index_lock:
            hit = self._index.get(token)
        if hit is None:
            raise AuthenticationError("unknown token")
        ns, sa_name = hit
        return UserInfo(
            name=serviceaccount_username(ns, sa_name),
            groups=("system:serviceaccounts",
                    f"system:serviceaccounts:{ns}"))


class BasicAuthenticator:
    """HTTP basic auth from a password file (plugin/pkg/auth/
    authenticator/password/passwordfile): CSV lines of
    ``password,user,uid[,group1|group2]``; requests carry
    ``Authorization: Basic base64(user:password)``."""

    def __init__(self, entries: dict[str, tuple[str, UserInfo]]):
        # user -> (password, UserInfo)
        self._entries = dict(entries)

    @classmethod
    def from_file(cls, path: str) -> "BasicAuthenticator":
        entries: dict[str, tuple[str, UserInfo]] = {}
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = [p.strip() for p in line.split(",")]
                if len(parts) < 3:
                    raise ValueError(
                        f"basic-auth line needs password,user,uid: "
                        f"{line!r}")
                groups = tuple(parts[3].split("|")) \
                    if len(parts) > 3 and parts[3] else ()
                entries[parts[1]] = (parts[0], UserInfo(
                    name=parts[1], uid=parts[2], groups=groups))
        return cls(entries)

    def authenticate(self, authorization: str) -> UserInfo:
        import base64
        import hmac
        scheme, _, blob = authorization.partition(" ")
        if scheme.lower() != "basic" or not blob.strip():
            raise AuthenticationError("expected basic credentials")
        try:
            user, _, password = base64.b64decode(
                blob.strip()).decode().partition(":")
        except Exception as err:  # noqa: BLE001 — garbage b64
            raise AuthenticationError("malformed basic credentials") \
                from err
        entry = self._entries.get(user)
        # Constant-time compare on BYTES (str compare_digest rejects
        # non-ASCII with a TypeError — a remotely triggerable crash);
        # an unknown user burns the same compare so the 401 timing
        # doesn't enumerate accounts.
        expected = entry[0] if entry else ""
        if not hmac.compare_digest(password.encode(),
                                   expected.encode()) or entry is None:
            raise AuthenticationError("invalid user/password")
        return entry[1]


class WebhookTokenAuthenticator:
    """Token-review webhook (plugin/pkg/auth/authenticator/token/
    webhook): POST a TokenReview to the configured URL; the remote
    answers ``status.authenticated`` + ``status.user``.  Positive AND
    negative verdicts are cached with a TTL (the reference's
    cached_token_authenticator) so a chatty client doesn't hammer the
    webhook."""

    def __init__(self, url: str, cache_ttl: float = 120.0,
                 timeout: float = 5.0):
        import threading
        self.url = url
        self.cache_ttl = cache_ttl
        self.timeout = timeout
        self._cache: dict[str, tuple[float, Optional[UserInfo]]] = {}
        self._lock = threading.Lock()

    def authenticate(self, authorization: str) -> UserInfo:
        import time
        scheme, _, token = authorization.partition(" ")
        token = token.strip()
        if scheme.lower() != "bearer" or not token:
            raise AuthenticationError("expected a bearer token")
        now = time.monotonic()
        with self._lock:
            hit = self._cache.get(token)
            if hit is not None and now - hit[0] < self.cache_ttl:
                if hit[1] is None:
                    raise AuthenticationError("token rejected (cached)")
                return hit[1]
        user = self._review(token)
        with self._lock:
            self._cache[token] = (now, user)
            if len(self._cache) > 4096:  # bound the negative cache
                self._cache.pop(next(iter(self._cache)))
        if user is None:
            raise AuthenticationError("token rejected by webhook")
        return user

    def _review(self, token: str) -> Optional[UserInfo]:
        import urllib.request
        body = json.dumps({
            "apiVersion": "authentication.k8s.io/v1beta1",
            "kind": "TokenReview",
            "spec": {"token": token}}).encode()
        req = urllib.request.Request(
            self.url, data=body, method="POST",
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout) as resp:
                answer = json.loads(resp.read() or b"{}")
        except Exception as err:  # noqa: BLE001 — webhook down: 401
            raise AuthenticationError(
                f"token webhook unavailable: {err}") from err
        status = answer.get("status") or {}
        if not status.get("authenticated"):
            return None
        u = status.get("user") or {}
        return UserInfo(name=u.get("username", "") or "system:anonymous",
                        uid=str(u.get("uid", "")),
                        groups=tuple(u.get("groups") or ()))


class WebhookAuthorizer:
    """SubjectAccessReview webhook (plugin/pkg/auth/authorizer/webhook):
    POST the request's attributes; the remote answers
    ``status.allowed``.  Verdicts cached with a TTL (the reference's
    authorized/unauthorized TTL pair)."""

    def __init__(self, url: str, cache_ttl: float = 60.0,
                 timeout: float = 5.0):
        import threading
        self.url = url
        self.cache_ttl = cache_ttl
        self.timeout = timeout
        self._cache: dict[tuple, tuple[float, bool]] = {}
        self._lock = threading.Lock()

    def authorize(self, user: UserInfo, verb: str, resource: str,
                  namespace: str = "") -> bool:
        import time
        import urllib.request
        rbac_verb = _METHOD_VERBS.get(verb, verb.lower())
        key = (user.name, user.groups, rbac_verb, resource, namespace)
        now = time.monotonic()
        with self._lock:
            hit = self._cache.get(key)
            if hit is not None and now - hit[0] < self.cache_ttl:
                return hit[1]
        body = json.dumps({
            "apiVersion": "authorization.k8s.io/v1beta1",
            "kind": "SubjectAccessReview",
            "spec": {"user": user.name, "groups": list(user.groups),
                     "resourceAttributes": {
                         "verb": rbac_verb, "resource": resource,
                         "namespace": namespace}}}).encode()
        req = urllib.request.Request(
            self.url, data=body, method="POST",
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout) as resp:
                answer = json.loads(resp.read() or b"{}")
            allowed = bool((answer.get("status") or {}).get("allowed"))
        except Exception:  # noqa: BLE001 — webhook down: deny
            return False
        with self._lock:
            self._cache[key] = (now, allowed)
            if len(self._cache) > 4096:
                self._cache.pop(next(iter(self._cache)))
        return allowed


class UnionAuthenticator:
    """union.AuthenticatorRequest: first authenticator to accept wins;
    401 only when every one refuses."""

    def __init__(self, *authenticators):
        self._authenticators = [a for a in authenticators if a is not None]

    def authenticate(self, authorization: str) -> UserInfo:
        last: Exception = AuthenticationError("no authenticators")
        for a in self._authenticators:
            try:
                return a.authenticate(authorization)
            except AuthenticationError as err:
                last = err
        raise last


@dataclass
class ABACAuthorizer:
    """abac.PolicyList.Authorize: any matching policy allows."""

    policies: list[dict] = field(default_factory=list)

    @classmethod
    def from_file(cls, path: str) -> "ABACAuthorizer":
        policies = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                policies.append(json.loads(line))
        return cls(policies)

    def authorize(self, user: UserInfo, verb: str, resource: str) -> bool:
        readonly_verb = verb in ("GET", "HEAD")
        for p in self.policies:
            pu = p.get("user", "")
            pg = p.get("group", "")
            if pu and pu != "*" and pu != user.name:
                continue
            if pg and pg != "*" and pg not in user.groups:
                continue
            pr = p.get("resource", "")
            if pr and pr != "*" and pr != resource:
                continue
            if p.get("readonly", False) and not readonly_verb:
                continue
            return True
        return False


def user_from_cert(cert: dict) -> UserInfo:
    """x509 request authenticator (plugin/pkg/auth/authenticator/request/
    x509 CommonNameUserConversion): a VERIFIED client certificate's
    subject CN is the user name; O entries become groups."""
    cn = ""
    orgs: list[str] = []
    for rdn in cert.get("subject", ()):
        for key, value in rdn:
            if key == "commonName":
                cn = value
            elif key == "organizationName":
                orgs.append(value)
    return UserInfo(name=cn or "system:anonymous", groups=tuple(orgs))


# HTTP method -> RBAC verb (pkg/apiserver request attribute mapping).
_METHOD_VERBS = {"GET": "get", "POST": "create", "PUT": "update",
                 "DELETE": "delete", "HEAD": "get"}

# The reference's superuser convention: system:masters bypasses RBAC
# (pkg/registry + the --authorization-rbac-super-user bootstrap) — without
# it an RBAC-only apiserver could never receive its first RoleBinding.
SUPER_GROUP = "system:masters"


class RBACAuthorizer:
    """Alpha RBAC (pkg/apis/rbac; plugin/pkg/auth/authorizer/rbac):
    Roles/ClusterRoles hold rules {verbs, resources}; RoleBindings/
    ClusterRoleBindings grant them to User/Group subjects.  Reads the
    live objects from the store on every check — a kubectl-created
    binding takes effect immediately, like the reference's informers."""

    def __init__(self, store):
        self._store = store

    @staticmethod
    def _rule_covers(rule: dict, verb: str, resource: str) -> bool:
        verbs = rule.get("verbs") or []
        resources = rule.get("resources") or []
        return ("*" in verbs or verb in verbs) and \
            ("*" in resources or resource in resources)

    @staticmethod
    def _subject_matches(subj: dict, user: UserInfo) -> bool:
        kind = subj.get("kind", "User")
        name = subj.get("name", "")
        if kind == "User":
            return name == "*" or name == user.name
        if kind == "Group":
            return name in user.groups
        if kind == "ServiceAccount":
            # pkg/apis/rbac validation REQUIRES namespace on SA
            # subjects; silently defaulting it would make a forgetful
            # ClusterRoleBinding grant to default/<name> — a different
            # principal than intended.  No namespace, no match.
            ns = subj.get("namespace")
            if not ns:
                return False
            return user.name == serviceaccount_username(ns, name)
        return False

    def _role_rules(self, ref: dict, namespace: str) -> list[dict]:
        kind = ref.get("kind", "Role")
        name = ref.get("name", "")
        if kind == "ClusterRole":
            obj = self._store.get("clusterroles", name)
        else:
            obj = self._store.get("roles", f"{namespace}/{name}")
        return (obj or {}).get("rules") or []

    def authorize(self, user: UserInfo, verb: str, resource: str,
                  namespace: str = "") -> bool:
        if SUPER_GROUP in user.groups:
            return True
        rbac_verb = _METHOD_VERBS.get(verb, verb.lower())
        try:
            crbs, _ = self._store.list("clusterrolebindings")
            # A RoleBinding authorizes ONLY inside its own namespace: a
            # namespace-less request (cluster-scoped resource or flat
            # cluster-wide list) is judged by ClusterRoleBindings alone —
            # otherwise one team-a grant would leak cluster-wide reads.
            if namespace:
                rbs, _ = self._store.list(
                    "rolebindings",
                    lambda o: (o.get("metadata") or {})
                    .get("namespace", "default") == namespace)
            else:
                rbs = []
        except Exception:  # noqa: BLE001 — store unreadable: deny
            return False
        for binding, cluster_scoped in \
                [(b, True) for b in crbs] + [(b, False) for b in rbs]:
            subjects = binding.get("subjects") or []
            if not any(self._subject_matches(s, user) for s in subjects):
                continue
            ref = binding.get("roleRef") or {}
            # A ClusterRoleBinding may only reference a ClusterRole
            # (pkg/apis/rbac/validation): resolving a namespaced Role
            # against the binding's own namespace would grant
            # cluster-wide authority from a namespace-scoped object
            # (ADVICE r4).
            if cluster_scoped and ref.get("kind", "Role") != "ClusterRole":
                continue
            bns = (binding.get("metadata") or {}).get(
                "namespace", "default")
            for rule in self._role_rules(ref, bns):
                if self._rule_covers(rule, rbac_verb, resource):
                    return True
        return False


@dataclass
class AuthConfig:
    """The chain the server consults; either part may be absent."""

    authenticator: Optional[TokenAuthenticator] = None
    authorizer: Optional[object] = None   # ABACAuthorizer | RBACAuthorizer
    # --anonymous-auth analogue: with it on, a request carrying NO
    # credentials proceeds as system:anonymous for the authorizer to
    # judge (the x509-only secure port's behavior); off, a configured
    # authenticator 401s credential-less requests (the tokenfile
    # server's behavior).
    anonymous: bool = False

    def check(self, authorization: str, verb: str, resource: str,
              namespace: str = "",
              peer_user: Optional[UserInfo] = None
              ) -> Optional[tuple[int, str]]:
        """None = allowed; else (status, message).  ``peer_user`` is a
        verified-client-cert identity (x509 authenticator): it outranks
        the token layer, as the reference's request-auth union does."""
        user = peer_user
        if user is None and self.authenticator is not None and \
                (authorization or not self.anonymous):
            # Credentials present must authenticate; with anonymous auth
            # off, absent credentials fail the same way (401).
            try:
                user = self.authenticator.authenticate(authorization)
            except AuthenticationError as err:
                return 401, str(err)
        if self.authorizer is not None:
            if user is None:
                user = UserInfo(name="system:anonymous")
            if isinstance(self.authorizer,
                          (RBACAuthorizer, WebhookAuthorizer)):
                allowed = self.authorizer.authorize(user, verb, resource,
                                                    namespace)
            else:
                allowed = self.authorizer.authorize(user, verb, resource)
            if not allowed:
                return 403, (f"user {user.name!r} is not allowed to "
                             f"{verb} {resource}")
        return None
