"""Locate/build the native apiserver binary (native/apiserver.cpp).

The C++ core implements the same storage/watch/bind contract as the
Python apiserver (see the header comment in native/apiserver.cpp); the
perf rig prefers it because the measured wire ceiling of the Python
server is its GIL.  ``native_binary()`` returns the binary path, building
it with make on first use, or None when no toolchain is available (the
caller falls back to ``python -m kubernetes_tpu.apiserver``).
"""

from __future__ import annotations

import os
import subprocess
from typing import Optional

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_BINARY = os.path.join(_NATIVE_DIR, "kube-apiserver-native")


# Probe results keyed by (path, mtime): one spawn per distinct build.
_probed: dict[tuple[str, float], bool] = {}


def _machine_tag() -> str:
    """The axis exec-compatibility actually varies on: the loader/libc."""
    try:
        return os.confstr("CS_GNU_LIBC_VERSION") or "unknown"
    except (OSError, ValueError):
        return "unknown"


def _binary_runs(path: str) -> bool:
    """True when the binary actually executes on THIS machine.  A binary
    built elsewhere can be newer than every source and still die at exec
    (dynamic loader: GLIBC version mismatch) — mtime comparison cannot
    see that.  Probe: a healthy server keeps running on an ephemeral
    port; a broken one exits immediately.  Positive results persist in a
    sidecar marker (keyed by mtime + libc version) so only the first
    process after a rebuild — or after moving to a different libc — pays
    the probe spawn."""
    try:
        key = (path, os.path.getmtime(path))
    except OSError:
        return False
    cached = _probed.get(key)
    if cached is not None:
        return cached
    marker, stamp = path + ".probe_ok", f"{key[1]} {_machine_tag()}"
    try:
        with open(marker) as f:
            if f.read().strip() == stamp:
                _probed[key] = True
                return True
    except OSError:
        pass
    try:
        proc = subprocess.Popen([path, "--port", "0"],
                                stdout=subprocess.DEVNULL,
                                stderr=subprocess.DEVNULL)
    except OSError:
        _probed[key] = False
        return False
    try:
        # Loader failures exit within milliseconds; a healthy binary
        # pays this wait once per process (result cached by mtime).
        proc.wait(timeout=0.15)
        ok = False  # exited at once: loader/startup failure
    except subprocess.TimeoutExpired:
        ok = True   # it serves; that's the probe
        proc.terminate()
        try:
            proc.wait(timeout=5)
        except subprocess.TimeoutExpired:  # pragma: no cover
            proc.kill()
            proc.wait()
    _probed[key] = ok
    if ok:
        try:
            with open(marker, "w") as f:
                f.write(stamp)
        except OSError:  # read-only checkout: per-process cache only
            pass
    return ok


def native_binary(build: bool = True) -> Optional[str]:
    src = os.path.join(_NATIVE_DIR, "apiserver.cpp")
    # The kind table is generated from types.py (one manifest for both
    # servers), so a types.py edit must also trigger a rebuild.
    types_py = os.path.join(_NATIVE_DIR, "..", "kubernetes_tpu", "api",
                            "types.py")
    fresh = os.path.exists(_BINARY) and os.path.exists(src) and \
        os.path.getmtime(_BINARY) >= os.path.getmtime(src) and \
        (not os.path.exists(types_py) or
         os.path.getmtime(_BINARY) >= os.path.getmtime(types_py))
    if fresh and _binary_runs(_BINARY):
        return _BINARY
    if not build or not os.path.exists(src):
        return None
    # fresh-but-dead: a binary committed from a different libc — make
    # would call it up to date, so force the rebuild (-B).  Never delete
    # the tracked binary first: with no local toolchain the committed
    # artifact (valid on other machines) must survive the attempt.
    cmd = ["make", "-B", "-C", _NATIVE_DIR] if fresh else \
        ["make", "-C", _NATIVE_DIR]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except Exception:  # noqa: BLE001 — no toolchain: Python fallback
        return None
    if os.path.exists(_BINARY) and _binary_runs(_BINARY):
        return _BINARY
    return None
