"""Locate/build the native apiserver binary (native/apiserver.cpp).

The C++ core implements the same storage/watch/bind contract as the
Python apiserver (see the header comment in native/apiserver.cpp); the
perf rig prefers it because the measured wire ceiling of the Python
server is its GIL.  ``native_binary()`` returns the binary path, building
it with make on first use, or None when no toolchain is available (the
caller falls back to ``python -m kubernetes_tpu.apiserver``).
"""

from __future__ import annotations

import os
import subprocess
from typing import Optional

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_BINARY = os.path.join(_NATIVE_DIR, "kube-apiserver-native")


def native_binary(build: bool = True) -> Optional[str]:
    src = os.path.join(_NATIVE_DIR, "apiserver.cpp")
    # The kind table is generated from types.py (one manifest for both
    # servers), so a types.py edit must also trigger a rebuild.
    types_py = os.path.join(_NATIVE_DIR, "..", "kubernetes_tpu", "api",
                            "types.py")
    if os.path.exists(_BINARY) and os.path.exists(src) and \
            os.path.getmtime(_BINARY) >= os.path.getmtime(src) and \
            (not os.path.exists(types_py) or
             os.path.getmtime(_BINARY) >= os.path.getmtime(types_py)):
        return _BINARY
    if not build or not os.path.exists(src):
        return None
    try:
        subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                       capture_output=True, timeout=120)
    except Exception:  # noqa: BLE001 — no toolchain: Python fallback
        return None
    return _BINARY if os.path.exists(_BINARY) else None
