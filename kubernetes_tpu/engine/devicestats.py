"""Device accounting: HBM occupancy, per-cause transfer bytes, and the
post-prewarm recompile watchdog.

PRs 2-3 made the HOST side observable (spans, stage histograms, a
flight recorder); this module is the DEVICE side — the layer where the
regressions ROADMAP items 1 and 3 name would otherwise be invisible:

* **Transfer accounting.**  ``record_transfer(cause, nbytes)`` feeds
  ``scheduler_device_transfer_bytes_total{cause=}`` (and an ops
  counter).  The drain path records three causes: ``scatter`` (dirty
  rows into the resident cluster mirror — the steady-state path),
  ``full_upload`` (whole-cluster re-snapshot — legitimate only on
  relist/capacity growth; dominating steady-state drains means the
  residency protocol silently broke), and ``readback`` (device→host
  result fetches).  ``transfer_snapshot()`` returns the per-cause byte
  totals so benches can diff a window and stamp bytes-per-pod columns
  into their artifacts.

* **HBM accounting.**  ``hbm_live_bytes()`` asks the backend
  (``device.memory_stats()``; TPU/GPU report ``bytes_in_use``) and
  falls back to summing ``jax.live_arrays()`` where the backend keeps
  no books (CPU).  ``sample_hbm()`` refreshes a process-lifetime peak;
  the ``scheduler_device_hbm_{live,peak}_bytes`` gauges read through
  live at expose, and the telemetry ring's self-scrape cadence is the
  peak-tracking cadence — deliberately NOT the drain path, where the
  fallback's live-array walk would tax every sync.

* **Recompile watchdog.**  ``arm()`` (called when ``prewarm()``
  finishes) registers a JAX monitoring listener for backend-compile
  events; every compile AFTER arming is a stall the bucket-ladder
  prewarm should have traced, so it increments
  ``scheduler_post_prewarm_compiles_total{path=}`` (the live path the
  drain declared via ``live_path()``) and records a ``slow_trace``-style
  ``post_prewarm_compile`` span carrying the offending signature (the
  innermost non-library frame of the compiling call stack).  The bench
  ratchet (tools/check_bench.py) fails tier-1 on any such compile in
  the density run.  ``watchdog_window()`` scopes arming for benches and
  tests.

Everything here is observability: every hook is wrapped so a failure
can never take the drain path down with it.
"""

from __future__ import annotations

import contextlib
import threading
import traceback
from typing import Callable, Iterator

from kubernetes_tpu.utils import metrics
from kubernetes_tpu.utils.logging import get_logger

log = get_logger("devicestats")

CAUSES = ("scatter", "full_upload", "readback")

_lock = threading.Lock()
_peak_fallback = 0          # high-water mark of sampled live bytes
_armed = False
_listener_installed = False
_tls = threading.local()    # .path — the live path compiling right now


# -- transfer accounting -----------------------------------------------------

def nbytes(tree: object) -> int:
    """Total array bytes of a pytree-ish value (NamedTuple / list /
    tuple / dict of numpy or jax arrays)."""
    if tree is None:
        return 0
    if hasattr(tree, "nbytes"):
        return int(tree.nbytes)
    if isinstance(tree, dict):
        return sum(nbytes(v) for v in tree.values())
    if isinstance(tree, (list, tuple)):  # NamedTuples included
        return sum(nbytes(v) for v in tree)
    return 0


def record_transfer(cause: str, n: int) -> None:
    """Count ``n`` bytes moved for ``cause`` (scatter/full_upload/
    readback)."""
    if n <= 0:
        return
    metrics.DEVICE_TRANSFER_BYTES.labels(cause=cause).inc(int(n))
    metrics.DEVICE_TRANSFERS.labels(cause=cause).inc()


def transfer_snapshot() -> dict[str, int]:
    """Per-cause byte totals so far — diff two snapshots to account a
    window (the bench's bytes-per-pod columns)."""
    children = metrics.DEVICE_TRANSFER_BYTES.children()
    out = {cause: 0 for cause in CAUSES}
    for key, child in children.items():
        out[key[0]] = int(child.value)
    return out


# -- HBM accounting ----------------------------------------------------------

def _backend_memory_stats() -> dict | None:
    try:
        import jax
        stats = jax.devices()[0].memory_stats()
        return stats if stats else None
    except Exception:  # noqa: BLE001 — accounting must never raise
        return None


def hbm_live_bytes() -> int:
    """Device bytes currently held by live arrays: the backend's
    ``bytes_in_use`` when it keeps books, else the sum over
    ``jax.live_arrays()``."""
    stats = _backend_memory_stats()
    if stats and "bytes_in_use" in stats:
        return int(stats["bytes_in_use"])
    try:
        import jax
        return int(sum(a.nbytes for a in jax.live_arrays()))
    except Exception:  # noqa: BLE001
        return 0


def hbm_peak_bytes() -> int:
    """Peak device occupancy: the backend's ``peak_bytes_in_use`` when
    reported, else the high-water mark of sampled live bytes."""
    stats = _backend_memory_stats()
    if stats and "peak_bytes_in_use" in stats:
        return int(stats["peak_bytes_in_use"])
    return max(_peak_fallback, 0)


def sample_hbm() -> int:
    """Refresh the fallback peak from the current live bytes (called per
    resident sync and per telemetry scrape); returns the live bytes."""
    global _peak_fallback
    live = hbm_live_bytes()
    if live > _peak_fallback:
        with _lock:
            if live > _peak_fallback:
                _peak_fallback = live
    return live


metrics.DEVICE_HBM_LIVE_BYTES.set_fn(hbm_live_bytes)
metrics.DEVICE_HBM_PEAK_BYTES.set_fn(hbm_peak_bytes)


# -- recompile watchdog ------------------------------------------------------

def _offending_signature() -> str:
    """The innermost caller frame OUTSIDE jax/library code — the call
    site whose shape minted the compile.  Paid only when the watchdog
    actually fires (compiles post-prewarm are the rare bug, not the
    steady state)."""
    try:
        stack = traceback.extract_stack()
        # Innermost frame of OUR code (the drain call site whose shape
        # minted the compile), else the innermost non-jax/non-stdlib one.
        for frame in reversed(stack):
            fn = frame.filename
            if "kubernetes_tpu" in fn and not fn.endswith(
                    "devicestats.py"):
                return (f"{fn.rsplit('/', 1)[-1]}:{frame.lineno} "
                        f"{frame.name}")
    except Exception:  # noqa: BLE001
        pass
    return "unknown"


def _fire(secs: float) -> None:
    path = getattr(_tls, "path", None) or "unknown"
    sig = _offending_signature()
    metrics.POST_PREWARM_COMPILES.labels(path=path).inc()
    try:
        from kubernetes_tpu.utils import trace
        trace.begin_span("post_prewarm_compile", path=path,
                         signature=sig,
                         compile_s=round(secs, 3)).end()
    except Exception:  # noqa: BLE001
        pass
    log.warning("post-prewarm XLA compile on live path %r (%.2fs) at %s "
                "— a shape the prewarm ladder never traced",
                path, secs, sig)


def _on_compile_duration(event: str, secs: float, **kw) -> None:
    # backend_compile_duration wraps compile_or_get_cached, so it fires
    # exactly once per NEW executable — full XLA compiles and
    # persistent-cache deserializes alike (a cache hit is cheaper, but
    # still a live-path program the prewarm ladder missed).  Verified
    # against jax 0.4.37: the hit path fires this event too, so
    # listening for cache_hits as well would double-count.
    if _armed and event.endswith("backend_compile_duration"):
        _fire(secs)


def _install_listener() -> None:
    global _listener_installed
    if _listener_installed:
        return
    try:
        from jax import monitoring
        monitoring.register_event_duration_secs_listener(
            _on_compile_duration)
        _listener_installed = True
    except Exception:  # noqa: BLE001 — observability only
        log.debug("jax monitoring unavailable; recompile watchdog off")


def arm() -> None:
    """Arm the watchdog: every XLA compile from now on counts as a
    post-prewarm compile.  Called by ``Scheduler.prewarm()`` once the
    ladder is traced."""
    global _armed
    with _lock:
        _install_listener()
        _armed = True


def disarm() -> None:
    global _armed
    with _lock:
        _armed = False


def armed() -> bool:
    return _armed


def post_prewarm_compiles() -> int:
    return int(metrics.POST_PREWARM_COMPILES.value)


@contextlib.contextmanager
def watchdog_window() -> Iterator[Callable[[], int]]:
    """Arm for the duration of a measured window (benches, tests) and
    yield a callable returning the compiles observed inside it."""
    before = post_prewarm_compiles()
    was = _armed
    arm()
    try:
        yield lambda: post_prewarm_compiles() - before
    finally:
        if not was:
            disarm()


@contextlib.contextmanager
def live_path(name: str) -> Iterator[None]:
    """Declare the live path (stream/oneshot/joint/single_pod/...) for
    compiles fired from this thread — the watchdog's ``path`` label."""
    prev = getattr(_tls, "path", None)
    _tls.path = name
    try:
        yield
    finally:
        _tls.path = prev
