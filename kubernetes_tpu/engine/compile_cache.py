"""Persistent XLA compilation cache configuration.

Every scheduler start used to pay the full XLA compile tax (19.6 s cold,
3.8-10.9 s "warm" per BENCH_r05) because jit executables lived only in
process memory.  This module points JAX's persistent compilation cache at
a per-machine directory so the cost is paid once per (machine, jaxlib,
program) and every later start deserializes the executables instead of
re-running XLA:

* default location: ``~/.cache/kubernetes_tpu/xla``
* ``KT_COMPILE_CACHE=<dir>`` overrides the directory
* ``KT_COMPILE_CACHE=0`` (or ``off``/``none``/``disabled``) disables it

The cache thresholds are dropped to zero so *every* executable persists —
the drain path's small shapes (the stream bucket ladder, the explain-pass
batch) individually compile in under JAX's default 1 s floor but add up
to the multi-second warm-start stall the ladder pre-warm then re-pays.

``configure()`` is idempotent and must run before the first jit trace to
cover it; ``GenericScheduler.__init__`` calls it, which puts it ahead of
every Solver executable in every rig (daemon, bench, tests).
"""

from __future__ import annotations

import os
import threading
from typing import Optional

DEFAULT_CACHE_DIR = os.path.join(
    os.path.expanduser("~"), ".cache", "kubernetes_tpu", "xla")

_DISABLED_VALUES = ("0", "off", "none", "disabled", "false")

_lock = threading.Lock()
_configured = False
_dir: Optional[str] = None


def configure() -> Optional[str]:
    """Point JAX's persistent compilation cache at the per-machine
    directory (created on demand).  Returns the directory, or None when
    disabled via ``KT_COMPILE_CACHE=0`` or when the runtime lacks the
    cache knobs.  Safe to call from any thread, any number of times; the
    environment is read ONCE — like the stream bucket floor, a mid-run
    change must not silently split state between two directories."""
    global _configured, _dir
    with _lock:
        if _configured:
            return _dir
        _configured = True
        from kubernetes_tpu.utils import knobs
        raw = knobs.get("KT_COMPILE_CACHE")
        if raw.lower() in _DISABLED_VALUES:
            return None
        path = raw or DEFAULT_CACHE_DIR
        try:
            os.makedirs(path, exist_ok=True)
            import jax
            jax.config.update("jax_compilation_cache_dir", path)
        except Exception:  # noqa: BLE001 — cache is an optimization only
            return None
        # Persist everything: the bucket-ladder scans and explain-pass
        # shapes each compile below the default 1 s floor but together
        # are the warm-start stall this cache exists to kill.
        for knob, value in (
                ("jax_persistent_cache_min_compile_time_secs", 0.0),
                ("jax_persistent_cache_min_entry_size_bytes", 0)):
            try:
                jax.config.update(knob, value)
            except Exception:  # noqa: BLE001 — older jaxlib: best effort
                pass
        _register_hit_miss_listener()
        _dir = path
        return _dir


_listener_registered = False


def _register_hit_miss_listener() -> None:
    """Feed ``compile_cache_{hits,misses}_total`` from JAX's monitoring
    events: a hit is a jit executable deserialized from the persistent
    cache, a miss one that re-paid the full XLA compile.  Without them
    the multi-second \"warm\" start is undiagnosable — the counters say
    exactly which restarts still compile (ROADMAP item 3)."""
    global _listener_registered
    if _listener_registered:
        return
    try:
        from jax import monitoring

        from kubernetes_tpu.utils.metrics import (COMPILE_CACHE_HITS,
                                                  COMPILE_CACHE_MISSES)

        def _on_event(event: str, **kw) -> None:
            if event == "/jax/compilation_cache/cache_hits":
                COMPILE_CACHE_HITS.inc()
            elif event == "/jax/compilation_cache/cache_misses":
                COMPILE_CACHE_MISSES.inc()

        monitoring.register_event_listener(_on_event)
        _listener_registered = True
    except Exception:  # noqa: BLE001 — observability only, never fatal
        pass


def cache_dir() -> Optional[str]:
    """The active cache directory (None = disabled or not configured)."""
    with _lock:
        return _dir


def _reset_for_tests() -> None:
    """Drop the idempotence latch (tests exercising the env contract)."""
    global _configured, _dir
    with _lock:
        _configured = False
        _dir = None
