"""The host fallback engine: sequential-greedy scheduling in pure NumPy.

When the device guard's circuit breaker opens (``engine/guard.py``), the
control plane must KEEP SCHEDULING — the whole premise of trusting a TPU
with scheduling decisions is that losing the TPU degrades throughput,
not availability.  ``HostSolver`` is that degraded mode: a sequential-
greedy solver grown from ``oracle.py``'s reference semantics, but
consuming the SAME host tensor trees the device solver does
(``solver.host_batch`` + ``solver._host_cluster``), behind the same
masks/evaluate/solve surface — so the daemon's commit path, gang
reduction, and flight recorder run unchanged on its output.

Semantics relative to the device scan:

* **Exact** for the families the greedy loop tracks in-batch: resources
  (requested/nonzero/pod-count), host ports, volume conflicts, node
  selector/affinity-required, taints, memory/disk pressure, host
  pinning, node-label policy predicates, and the LeastRequested /
  MostRequested / BalancedResourceAllocation dynamic priorities —
  byte-for-byte ports of the formulas in ``ops/predicates.py`` and
  ``ops/priorities.py`` (incl. the reference's int-truncation
  arithmetic), pinned by the oracle-parity tests in
  tests/test_device_faults.py.
* **Batch-start** for the remaining planes (inter-pod affinity, PD
  volume counts, selector spread, service anti-affinity, and the
  topology-spread hard/soft planes, which the engine feeds in through
  ``topology.spread_planes_host`` exactly as the device one-shot path
  feeds ``spread_planes``): their masks and scores are computed once
  against the pre-batch cluster state and held fixed through the
  batch, like the device scan does for batches whose flags show no
  such content.  This can cost placement QUALITY mid-batch, never
  drop a hard constraint that held at batch start — and there is no
  resource overcommit, no port conflict, no out-of-range index; every
  host placement passes the post-solve sanity gate.

The solver is O(P·N·vocab) NumPy per drain — orders of magnitude slower
than the device scan at density scale, and always available.
"""

from __future__ import annotations

import numpy as np
from typing import TYPE_CHECKING, Optional

if TYPE_CHECKING:
    from kubernetes_tpu.engine import solver as sv

from kubernetes_tpu.features.compiler import RES_CPU, RES_MEM, RES_PODS

_MIN_IMG_KIB = 23 * 1024
_MAX_IMG_KIB = 1000 * 1024


def _trunc(x: np.ndarray) -> np.ndarray:
    """Go's int(float) truncation with the same epsilon guard the device
    kernels use (ops/priorities._trunc)."""
    return np.trunc(np.asarray(x, np.float64) + 1e-5)


def _overlap(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """[P,C] x [N,C] bool -> [P,N] any-shared-member."""
    if a.size == 0 or b.size == 0:
        return np.zeros((a.shape[0], b.shape[0]), bool)
    return (a.astype(np.float32) @ b.astype(np.float32).T) > 0.0


def _unused_score(requested, capacity):
    safe = np.maximum(capacity, 1)
    score = ((capacity - requested) * 10) // safe
    return np.where((capacity == 0) | (requested > capacity), 0, score)


def _used_score(requested, capacity):
    safe = np.maximum(capacity, 1)
    score = (requested * 10) // safe
    return np.where((capacity == 0) | (requested > capacity), 0, score)


class HostSolver:
    """NumPy mirror of ``solver.Solver`` for the policy's predicate and
    priority lists (shared with the device Solver instance so the two
    engines can never schedule by different policies)."""

    # Predicates whose masks the greedy loop recomputes per placement.
    TRACKED_PREDICATES = ("PodFitsResources", "PodFitsHostPorts",
                          "PodFitsPorts", "NoDiskConflict")
    TRACKED_PRIORITIES = ("LeastRequestedPriority", "MostRequestedPriority",
                          "BalancedResourceAllocation")

    def __init__(self, solver: "sv.Solver"):
        self.solver = solver  # the compiled-policy Solver (names/weights)

    # -- predicate masks (batch-start state) -------------------------------

    def _predicate_mask(self, name: str, b, c, n: int) -> np.ndarray:
        p = b.request.shape[0]
        vs, a = b.volsvc, b.aff
        if name in ("PodFitsHost", "HostName"):
            ids = np.arange(n, dtype=np.int64)[None, :]
            hi = np.asarray(b.host_idx)[:, None]
            return (hi == -1) | (hi == ids)
        if name == "MatchNodeSelector":
            return np.asarray(b.sel_required)[np.asarray(b.sel_group)]
        if name == "PodToleratesNodeTaints":
            matched = ~_overlap(~np.asarray(b.tol_nosched),
                                np.asarray(c.taints_nosched))
            ok = np.asarray(b.has_tolerations)[:, None] & matched
            return ~np.asarray(c.has_taints)[None, :] | ok
        if name == "CheckNodeMemoryPressure":
            return ~(np.asarray(b.best_effort)[:, None] &
                     np.asarray(c.mem_pressure)[None, :])
        if name == "CheckNodeDiskPressure":
            return np.broadcast_to(~np.asarray(c.disk_pressure)[None, :],
                                   (p, n))
        if name == "NewNodeLabelPredicate":
            return np.broadcast_to(np.asarray(vs.nl_pred_row)[None, :],
                                   (p, n))
        if name == "NoVolumeZoneConflict":
            return np.asarray(vs.vz_mask)[np.asarray(vs.vz_group)]
        if name == "ServiceAffinity":
            return np.asarray(vs.sa_mask)[np.asarray(vs.sa_group)]
        if name == "PodFitsResources":
            return self._fits_resources(
                np.asarray(b.request), np.asarray(b.zero_request),
                np.asarray(c.alloc), np.asarray(c.requested))
        if name in ("PodFitsHostPorts", "PodFitsPorts"):
            return ~_overlap(np.asarray(b.ports), np.asarray(c.ports_used))
        if name == "NoDiskConflict":
            return ~(_overlap(np.asarray(b.vol_rw), np.asarray(c.vol_any)) |
                     _overlap(np.asarray(b.vol_ro), np.asarray(c.vol_rw)))
        if name in ("MaxEBSVolumeCount", "MaxGCEPDVolumeCount"):
            fam = "ebs" if name == "MaxEBSVolumeCount" else "gce"
            return self._max_pd(
                np.asarray(getattr(vs, f"pd_pod_{fam}")),
                np.asarray(getattr(vs, f"pd_extra_{fam}")),
                np.asarray(getattr(vs, f"pd_node_{fam}")),
                np.asarray(getattr(vs, f"pd_node_extra_{fam}")),
                np.asarray(getattr(vs, f"pd_node_err_{fam}")),
                self.solver.extra[f"max_{fam}"])
        if name == "MatchInterPodAffinity":
            reach = np.asarray(a.match_cnt) > 0.0            # [Sm,N]
            live = np.asarray(a.aff_need) & ~(
                np.asarray(a.aff_self) &
                (np.asarray(a.match_total) == 0.0)[None, :])
            f32 = np.float32
            violate = (live.astype(f32) @ (~reach).astype(f32) +
                       np.asarray(a.anti_need).astype(f32) @
                       reach.astype(f32) +
                       np.asarray(a.decl_match).astype(f32) @
                       np.asarray(a.decl_reach).astype(f32)) > 0
            return ~violate
        return np.ones((p, n), bool)  # unknown/passthrough: never block

    @staticmethod
    def _fits_resources(request, zero_request, alloc, requested):
        fits_pods = (requested[:, RES_PODS] + 1) <= alloc[:, RES_PODS]
        free = alloc[None, :, :3] - requested[None, :, :3]
        fits_res = np.all(request[:, None, :3] <= free, axis=-1)
        return fits_pods[None, :] & (zero_request[:, None] | fits_res)

    @staticmethod
    def _max_pd(pod_pd, pod_extra, node_pd, node_extra, node_err,
                max_volumes):
        f32 = np.float32
        if pod_pd.shape[1] == 0:
            overlap = np.zeros((pod_pd.shape[0], node_pd.shape[0]), f32)
        else:
            overlap = pod_pd.astype(f32) @ node_pd.astype(f32).T
        existing = node_pd.astype(f32).sum(1) + node_extra.astype(f32)
        new = pod_pd.astype(f32).sum(1) + pod_extra.astype(f32)
        total = existing[None, :] + new[:, None] - overlap
        ok = (total <= f32(max_volumes)) & ~node_err[None, :]
        return (new[:, None] == 0) | ok

    def masks(self, b: "sv.DeviceBatch", c: "sv.DeviceCluster"
              ) -> dict[str, np.ndarray]:
        """Per-predicate [P,N] masks against batch-start state (the
        FitError / failure-detail surface, mirroring Solver.masks)."""
        n = int(np.asarray(c.alloc).shape[0])
        return {name: self._predicate_mask(name, b, c, n)
                for name in self.solver.predicate_names}

    # -- priority planes ----------------------------------------------------

    def _priority_plane(self, name: str, b, c, n: int, aux: int,
                        requested=None, nonzero=None) -> np.ndarray:
        """One [P,N] score plane.  ``requested``/``nonzero`` override the
        cluster aggregates for the tracked dynamic priorities."""
        p = b.request.shape[0]
        vs, a = b.volsvc, b.aff
        alloc = np.asarray(c.alloc)
        sched = np.asarray(c.schedulable)
        nz = nonzero if nonzero is not None else np.asarray(c.nonzero)
        if name in ("LeastRequestedPriority", "MostRequestedPriority"):
            total = np.asarray(b.nonzero)[:, None, :] + nz[None, :, :]
            fn = _unused_score if name == "LeastRequestedPriority" \
                else _used_score
            cpu = fn(total[..., 0], alloc[None, :, RES_CPU])
            mem = fn(total[..., 1], alloc[None, :, RES_MEM])
            return ((cpu + mem) // 2).astype(np.float64)
        if name == "BalancedResourceAllocation":
            total = (np.asarray(b.nonzero)[:, None, :] +
                     nz[None, :, :]).astype(np.float64)
            cap_c = alloc[None, :, RES_CPU].astype(np.float64)
            cap_m = alloc[None, :, RES_MEM].astype(np.float64)
            cf = np.where(cap_c == 0, 1.0,
                          total[..., 0] / np.maximum(cap_c, 1))
            mf = np.where(cap_m == 0, 1.0,
                          total[..., 1] / np.maximum(cap_m, 1))
            score = _trunc(10.0 - np.abs(cf - mf) * 10.0)
            return np.where((cf >= 1.0) | (mf >= 1.0), 0.0, score)
        if name == "NodeAffinityPriority":
            counts = np.asarray(b.sel_pref_counts)[
                np.asarray(b.sel_group)].astype(np.float64)
            mx = np.max(np.where(sched[None, :], counts, 0.0), axis=1,
                        keepdims=True)
            score = _trunc(10.0 * counts / np.maximum(mx, 1e-9))
            return np.where(mx > 0, score, 0.0)
        if name == "TaintTolerationPriority":
            counts = (~np.asarray(b.tol_prefer)).astype(np.float32) @ \
                np.asarray(c.taints_prefer).astype(np.float32).T
            mx = np.max(np.where(sched[None, :], counts, 0.0), axis=1,
                        keepdims=True)
            score = _trunc((1.0 - counts / np.maximum(mx, 1e-9)) * 10.0)
            return np.where(mx > 0, score, 10.0)
        if name == "ImageLocalityPriority":
            sums = (np.asarray(b.images).astype(np.float32) @
                    np.asarray(c.image_kib).astype(np.float32).T
                    ).astype(np.int64)
            clamped = np.minimum(sums, _MAX_IMG_KIB)
            mid = (10 * (clamped - _MIN_IMG_KIB)) // \
                (_MAX_IMG_KIB - _MIN_IMG_KIB) + 1
            return np.where(sums < _MIN_IMG_KIB, 0,
                            np.where(sums >= _MAX_IMG_KIB, 10, mid)
                            ).astype(np.float64)
        if name == "NodePreferAvoidPodsPriority":
            return np.where(np.asarray(b.avoid_rows)[
                np.asarray(b.avoid_group)], 0.0, 10.0)
        if name in ("SelectorSpreadPriority", "ServiceSpreadingPriority"):
            counts = np.asarray(b.spread_node_counts)[
                np.asarray(b.spread_group)].astype(np.float64)
            mx = np.max(np.where(sched[None, :], counts, 0.0), axis=1,
                        keepdims=True)
            f = np.where(mx > 0,
                         10.0 * (mx - counts) / np.maximum(mx, 1e-9),
                         10.0)
            zc = np.asarray(b.spread_zone_counts)[
                np.asarray(b.spread_group)].astype(np.float64)
            has_zones = np.asarray(b.spread_has_zones)[
                np.asarray(b.spread_group)][:, None]
            zid = np.asarray(b.node_zone_id)
            node_has_zone = zid >= 0
            zcounts = np.take_along_axis(
                zc, np.clip(zid, 0, None)[None, :].repeat(zc.shape[0], 0),
                axis=1)
            zcounts = np.where(node_has_zone[None, :], zcounts, 0.0)
            mz = np.max(zc, axis=1, keepdims=True)
            zscore = 10.0 * (mz - zcounts) / np.maximum(mz, 1e-9)
            blended = f / 3.0 + (2.0 / 3.0) * zscore
            f = np.where(has_zones & node_has_zone[None, :] & (mz > 0),
                         blended, f)
            return _trunc(f)
        if name == "InterPodAffinityPriority":
            f32 = np.float32
            own = np.asarray(a.pref_w).astype(f32) @ \
                np.asarray(a.match_cnt).astype(f32)
            sym = (np.asarray(a.sym_match).astype(f32) *
                   np.asarray(a.sym_w).astype(f32)[None, :]) @ \
                np.asarray(a.sym_cnt).astype(f32)
            counts = (own + sym).astype(np.float64)
            neg, pos = -np.inf, np.inf
            mx = np.maximum(np.max(np.where(sched[None, :], counts, neg),
                                   axis=1), 0.0)
            mn = np.minimum(np.min(np.where(sched[None, :], counts, pos),
                                   axis=1), 0.0)
            denom = (mx - mn)[:, None]
            score = _trunc(10.0 * (counts - mn[:, None]) /
                           np.maximum(denom, 1e-9))
            return np.where(denom > 0, score, 0.0)
        if name == "NodeLabelPriority":
            row = np.asarray(vs.nl_prio_rows)[aux]
            return np.broadcast_to(np.where(row, 10.0, 0.0)[None, :],
                                   (p, n)).copy()
        if name == "ServiceAntiAffinityPriority":
            cnt = np.asarray(vs.saa_cnt)[aux][
                np.asarray(vs.saa_group)].astype(np.float64)     # [P,D]
            num = np.asarray(vs.saa_num)[
                np.asarray(vs.saa_group)].astype(np.float64)[:, None]
            dom = np.asarray(vs.saa_dom)[aux]                    # [N]
            labeled = np.asarray(vs.saa_labeled)[aux]            # [N]
            per = np.take(cnt, np.clip(dom, 0, None), axis=1)
            score = np.where(num > 0.0,
                             _trunc(10.0 * (num - per) /
                                    np.maximum(num, 1.0)),
                             10.0)
            return np.where(labeled[None, :], score, 0.0)
        if name == "EqualPriority":
            return np.ones((p, n), np.float64)
        return np.zeros((p, n), np.float64)  # unknown: contribute nothing

    # -- the evaluate / solve surface ---------------------------------------

    def evaluate(self, b: "sv.DeviceBatch", c: "sv.DeviceCluster"
                 ) -> tuple[np.ndarray, np.ndarray]:
        """(feasible [P,N], scores [P,N]) against batch-start state —
        the host mirror of Solver.evaluate."""
        n = int(np.asarray(c.alloc).shape[0])
        p = b.request.shape[0]
        feasible = np.broadcast_to(np.asarray(c.schedulable)[None, :],
                                   (p, n)).copy()
        for name in self.solver.predicate_names:
            feasible &= self._predicate_mask(name, b, c, n)
        scores = np.zeros((p, n), np.float64)
        for name, weight, aux in self.solver.priority_specs:
            scores += float(weight) * self._priority_plane(name, b, c, n,
                                                           aux)
        return feasible, scores

    @staticmethod
    def _tracked_score(name: str, pod_nz: np.ndarray, nonzero: np.ndarray,
                       alloc: np.ndarray) -> np.ndarray:
        """One pod's [N] row of a tracked dynamic priority against the
        CURRENT (in-batch) aggregates — the per-step recompute the
        device scan does inside lax.scan."""
        total = pod_nz[None, :] + nonzero                     # [N,2]
        if name in ("LeastRequestedPriority", "MostRequestedPriority"):
            fn = _unused_score if name == "LeastRequestedPriority" \
                else _used_score
            cpu = fn(total[:, 0], alloc[:, RES_CPU])
            mem = fn(total[:, 1], alloc[:, RES_MEM])
            return ((cpu + mem) // 2).astype(np.float64)
        # BalancedResourceAllocation
        totalf = total.astype(np.float64)
        cap_c = alloc[:, RES_CPU].astype(np.float64)
        cap_m = alloc[:, RES_MEM].astype(np.float64)
        cf = np.where(cap_c == 0, 1.0, totalf[:, 0] / np.maximum(cap_c, 1))
        mf = np.where(cap_m == 0, 1.0, totalf[:, 1] / np.maximum(cap_m, 1))
        score = _trunc(10.0 - np.abs(cf - mf) * 10.0)
        return np.where((cf >= 1.0) | (mf >= 1.0), 0.0, score)

    def solve_greedy(self, b: "sv.DeviceBatch", c: "sv.DeviceCluster",
                     last_node_index: int,
                     live: Optional[np.ndarray] = None,
                     extra_mask: Optional[np.ndarray] = None,
                     score_bias: Optional[np.ndarray] = None
                     ) -> tuple[np.ndarray, int]:
        """Sequential greedy placement with in-batch visibility for the
        tracked families — the host mirror of ``Solver._solve_scan``'s
        contract: (choices [P] int32 or -1, advanced tie counter)."""
        n = int(np.asarray(c.alloc).shape[0])
        p = b.request.shape[0]
        request = np.asarray(b.request)
        zero_request = np.asarray(b.zero_request)
        b_nonzero = np.asarray(b.nonzero)
        ports = np.asarray(b.ports)
        vol_ro, vol_rw = np.asarray(b.vol_ro), np.asarray(b.vol_rw)
        alloc = np.asarray(c.alloc)
        # Tracked dynamic state (copied: the caller's arrays are the
        # cache's snapshot views).
        requested = np.asarray(c.requested).copy()
        nonzero = np.asarray(c.nonzero).copy()
        ports_used = np.asarray(c.ports_used).copy()
        vol_any = np.asarray(c.vol_any).copy()
        c_vol_rw = np.asarray(c.vol_rw).copy()
        # Static plane: every predicate EXCEPT the tracked ones, plus
        # the batch-start score of every untracked priority.
        static_mask = np.broadcast_to(np.asarray(c.schedulable)[None, :],
                                      (p, n)).copy()
        for name in self.solver.predicate_names:
            if name not in self.TRACKED_PREDICATES:
                static_mask &= self._predicate_mask(name, b, c, n)
        if live is not None:
            static_mask &= np.asarray(live, bool)[:, None]
        if extra_mask is not None:
            static_mask &= np.asarray(extra_mask, bool)
        static_score = np.zeros((p, n), np.float64)
        if score_bias is not None:
            static_score += np.asarray(score_bias, np.float64)
        dynamic_prios = []
        for name, weight, aux in self.solver.priority_specs:
            if name in self.TRACKED_PRIORITIES:
                dynamic_prios.append((name, weight, aux))
            else:
                static_score += float(weight) * self._priority_plane(
                    name, b, c, n, aux)
        use_resources = "PodFitsResources" in self.solver.predicate_names
        use_ports = any(nm in self.solver.predicate_names for nm in
                        ("PodFitsHostPorts", "PodFitsPorts")) and \
            bool(ports.size)
        use_volumes = "NoDiskConflict" in self.solver.predicate_names \
            and bool(vol_ro.size or vol_rw.size)
        choices = np.full(p, -1, np.int32)
        counter = int(last_node_index) & 0xFFFFFFFF
        for i in range(p):
            feasible = static_mask[i].copy()
            if use_resources:
                fits_pods = (requested[:, RES_PODS] + 1) <= \
                    alloc[:, RES_PODS]
                fits = np.all(request[i, :3][None, :] <=
                              (alloc[:, :3] - requested[:, :3]), axis=1)
                feasible &= fits_pods & (bool(zero_request[i]) | fits)
            if use_ports and ports[i].any():
                feasible &= ~(ports_used[:, ports[i]].any(axis=1))
            if use_volumes and (vol_rw[i].any() or vol_ro[i].any()):
                conflict = vol_any[:, vol_rw[i]].any(axis=1) | \
                    c_vol_rw[:, vol_ro[i]].any(axis=1)
                feasible &= ~conflict
            if not feasible.any():
                continue
            score = static_score[i].copy()
            for name, weight, _aux in dynamic_prios:
                score += float(weight) * self._tracked_score(
                    name, b_nonzero[i], nonzero, alloc)
            # selectHost: round-robin among max-score feasible nodes;
            # the counter bumps only on success (combine.select_hosts).
            masked = np.where(feasible, score, -np.inf)
            ties = feasible & (masked == masked.max())
            n_ties = int(ties.sum())
            ix = counter % n_ties
            choice = int(np.nonzero(ties)[0][ix])
            choices[i] = choice
            counter = (counter + 1) & 0xFFFFFFFF
            # Commit: the batched AssumePod.
            requested[choice] += request[i]
            nonzero[choice] += b_nonzero[i]
            if use_ports:
                ports_used[choice] |= ports[i]
            if use_volumes:
                vol_any[choice] |= vol_rw[i] | vol_ro[i]
                c_vol_rw[choice] |= vol_rw[i]
        return choices, counter
