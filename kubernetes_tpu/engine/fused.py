"""Fused selectHost kernels for the solve scan's inner step.

The per-step mask -> score -> tie-break -> select chain is the floor of
the sequential solve's cost once the score planes are template-factored
(engine/solver.py ``_solve_scan``): four reduction passes over the node
axis per pod.  This module provides that chain as ONE fused unit with
two interchangeable implementations sharing exact semantics:

* ``select_xla`` — jnp ops arranged for XLA's fuser (three reductions:
  max, one cumsum that also yields the tie count, argmax).  This is the
  CPU/GPU path and the fallback everywhere.
* ``select_pallas`` — a Pallas kernel computing the whole chain over a
  VMEM-resident row (PAPER.md's "native layer"); used on TPU, and in
  interpret mode by the CPU parity tests so tier-1 exercises the same
  code path.

Selection happens once at import/engine init through :func:`impl`
(KT_PALLAS knob: auto / interpret / off) — never per drain (ktlint D04).

Semantics (generic_scheduler.go:124-141 selectHost): among the feasible
max-score nodes, pick the ``counter % n_ties``-th in node-index order;
``-1`` when nothing is feasible.  ``masked`` already encodes
infeasibility as ``-inf`` (the caller folds the static mask and the
dynamic predicate results into the score plane), so a single row is the
whole per-pod decision input.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp

from kubernetes_tpu.utils import knobs

SelectFn = Callable[[jnp.ndarray, jnp.ndarray],
                    Tuple[jnp.ndarray, jnp.ndarray]]


def select_xla(masked: jnp.ndarray, counter: jnp.ndarray
               ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(choice int32 [-1 = infeasible], any_feasible bool) for one pod.

    ``masked`` [N] f32 with -inf at infeasible nodes; ``counter`` uint32
    round-robin state.  Three node-axis passes: max, cumsum (whose last
    element is the tie count — no separate sum pass), argmax."""
    mx = jnp.max(masked)
    ties = (masked == mx) & jnp.isfinite(mx)
    rank = jnp.cumsum(ties.astype(jnp.int32))  # 1-based among ties
    n_raw = rank[-1]
    any_feasible = n_raw > 0
    ix = (counter % jnp.maximum(n_raw, 1).astype(jnp.uint32)) \
        .astype(jnp.int32)
    choice = jnp.argmax(ties & (rank == ix + 1)).astype(jnp.int32)
    return jnp.where(any_feasible, choice, -1), any_feasible


def _pallas_kernel(counter_ref, masked_ref, out_ref) -> None:
    """The same chain over a [1, N] VMEM row; scalar I/O in SMEM.  The
    round-robin modulo runs in uint32 like select_xla/the legacy body:
    an int32 cast would go negative past 2^31 cumulative placements and
    the negative remainder would mark every pod unschedulable."""
    m = masked_ref[...]                          # [1, N]
    mx = jnp.max(m)
    ties = (m == mx) & jnp.isfinite(mx)
    rank = jnp.cumsum(ties.astype(jnp.int32), axis=1)
    n_raw = rank[0, -1]
    ix = (counter_ref[0] %
          jnp.maximum(n_raw, 1).astype(jnp.uint32)).astype(jnp.int32)
    pick = ties & (rank == ix + 1)
    col = jax.lax.broadcasted_iota(jnp.int32, m.shape, 1)
    choice = jnp.max(jnp.where(pick, col, -1))
    out_ref[0] = jnp.where(n_raw > 0, choice, -1)
    out_ref[1] = n_raw


def select_pallas(masked: jnp.ndarray, counter: jnp.ndarray,
                  interpret: bool = False
                  ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Pallas form of :func:`select_xla` — one kernel launch per step,
    the whole row resident in VMEM.  ``interpret=True`` runs the same
    kernel body on CPU (the parity-test path)."""
    from jax.experimental import pallas as pl
    n = masked.shape[-1]
    out = pl.pallas_call(
        _pallas_kernel,
        out_shape=jax.ShapeDtypeStruct((2,), jnp.int32),
        interpret=interpret,
    )(counter.astype(jnp.uint32)[None], masked.reshape(1, n))
    return out[0], out[1] > 0


def impl() -> SelectFn:
    """The select implementation for THIS process's backend, resolved
    once (KT_PALLAS: '' = auto, 'interpret' = Pallas interpret mode,
    '0' = force the XLA path)."""
    mode = knobs.get_str("KT_PALLAS")
    if mode == "0":
        return select_xla
    if mode == "interpret":
        return lambda m, c: select_pallas(m, c, interpret=True)
    if mode == "" and jax.default_backend() != "tpu":
        return select_xla
    return lambda m, c: select_pallas(m, c)
