"""Topology-aware spread: ``topologySpreadConstraints`` as vmapped
mask/score planes over the cluster topology tensor.

Every node row of ``DeviceCluster.topo_dom`` holds the compact domain id
of each interned topology label key (-1 = the node lacks the label) —
the compressed encoding of the (nodes x topology_domains) one-hot, which
the kernel expands per constraint term by gather (materializing the full
one-hot would be O(N x D) with hostname-keyed domains making D ~ N).

A batch's constraints compile to per-TERM tables (one term per distinct
(namespace, selector, topologyKey, maxSkew, whenUnsatisfiable)
signature, shared by every pod carrying it — the template-dedup
discipline of features/batch.py):

    key_col [T]    column of topo_dom the term reads
    max_skew [T]   admissible count spread above the least-loaded domain
    hard [T]       DoNotSchedule (mask plane) vs ScheduleAnyway (score)
    counts [T, D]  matching-pod count per domain at batch start
    valid [T, D]   domain exists among schedulable nodes (min runs here)
    src [P, T]     pod p carries term t

``spread_planes`` contracts these against ``topo_dom`` into a [P, N]
hard mask (placing must not push the domain more than max_skew above the
global minimum; nodes lacking the key fail hard terms, the reference's
DoNotSchedule semantics) and a [P, N] soft score (negative skew delta).

Counts are snapshotted at batch START (the ServiceAntiAffinity pre-r4
discipline): in-batch placements of the same spread group do not move
them mid-scan.  The parity/property tests drive multi-drain sequences
where this matters; ARCHITECTURE.md documents the drift bound.
"""

from __future__ import annotations

import functools
from typing import Callable, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.api import types as api


class SpreadTerms(NamedTuple):
    """Host-side term tables (device_put by ``spread_planes``)."""

    key_col: np.ndarray    # [T] int32
    max_skew: np.ndarray   # [T] float32
    hard: np.ndarray       # [T] bool
    counts: np.ndarray     # [T, D] float32
    valid: np.ndarray      # [T, D] bool
    src: np.ndarray        # [P, T] bool
    any_hard: bool
    any_soft: bool


def _pow2(n: int, floor: int = 1) -> int:
    return max(1 << max(n - 1, 0).bit_length(), floor)


def batch_has_spread(pods: Sequence) -> bool:
    return any(api.TOPOLOGY_SPREAD_ANNOTATION_KEY in pod.annotations
               for pod in pods)


def spread_topology_keys(pods: Sequence) -> set[str]:
    """Topology keys named by any constraint in the batch — the engine
    interns these (cache.ensure_topo_key) BEFORE the snapshot so the
    topo_dom columns exist."""
    keys: set[str] = set()
    for pod in pods:
        if api.TOPOLOGY_SPREAD_ANNOTATION_KEY in pod.annotations:
            for tsc in pod.topology_spread_constraints():
                if tsc.topology_key:
                    keys.add(tsc.topology_key)
    return keys


def compile_terms(pods: Sequence, nt: object, space: object,
                  domain_counts_bulk: Callable[[list],
                                               list[dict[int, int]]]
                  ) -> Optional[SpreadTerms]:
    """Build the per-term tables for a batch (None when no pod carries a
    constraint).  ``domain_counts_bulk([(namespace, selector,
    key_col)])`` is the cache's domain bookkeeping
    (SchedulerCache.topo_domain_counts_bulk): matching tracked-pod count
    per domain id for every term in ONE pod walk, assumed pods included.

    T and D are padded to powers of two (padcap's discipline) so the
    plane kernel compiles O(log) shapes as workloads churn."""
    p = len(pods)
    term_of: dict[tuple, int] = {}
    rows: list[tuple] = []   # (key_col, max_skew, hard, ns, selector)
    src_pairs: list[tuple[int, int]] = []
    for i, pod in enumerate(pods):
        if api.TOPOLOGY_SPREAD_ANNOTATION_KEY not in pod.annotations:
            continue
        for tsc in pod.topology_spread_constraints():
            col = space.topo_keys.get(tsc.topology_key)
            if col < 0:
                continue  # key never interned: no node can carry it yet
            sel = tsc.label_selector
            sig = (pod.namespace, col, tsc.max_skew, tsc.hard,
                   sel if sel is not None else ("__self__",
                                                tuple(sorted(
                                                    pod.labels.items()))))
            ti = term_of.get(sig)
            if ti is None:
                ti = len(rows)
                term_of[sig] = ti
                # A nil selector spreads the pod's own label set (the
                # common "spread my replicas" shorthand).
                eff_sel = sel if sel is not None else api.LabelSelector(
                    match_labels=tuple(sorted(pod.labels.items())))
                rows.append((col, tsc.max_skew, tsc.hard, pod.namespace,
                             eff_sel))
            src_pairs.append((i, ti))
    if not rows:
        return None
    t_cap = _pow2(len(rows))
    d_cap = _pow2(max(len(space.topo_vals), 1), floor=8)
    key_col = np.zeros(t_cap, np.int32)
    max_skew = np.full(t_cap, np.float32(1e9))  # pad terms constrain nothing
    hard = np.zeros(t_cap, bool)
    counts = np.zeros((t_cap, d_cap), np.float32)
    valid = np.zeros((t_cap, d_cap), bool)
    src = np.zeros((p, t_cap), bool)
    sched = np.asarray(nt.schedulable, bool)
    all_counts = domain_counts_bulk(
        [(ns, sel, col) for col, _, _, ns, sel in rows])
    for ti, (col, skew, is_hard, ns, sel) in enumerate(rows):
        key_col[ti] = col
        max_skew[ti] = skew
        hard[ti] = is_hard
        doms = nt.topo_val[sched, col]
        for d in np.unique(doms[doms >= 0]):
            valid[ti, int(d)] = True
        for dom, cnt in all_counts[ti].items():
            if 0 <= dom < d_cap:
                counts[ti, dom] = cnt
    for i, ti in enumerate(src_pairs):
        src[ti[0], ti[1]] = True
    return SpreadTerms(key_col, max_skew, hard, counts, valid, src,
                       any_hard=bool(hard.any()),
                       any_soft=bool((~hard[: len(rows)]).any()))


# kt-xray: no-donate(topo_dom is a column of the shared resident
# cluster; term tables are host numpy re-used across solve paths)
@functools.partial(jax.jit)
def _planes_kernel(key_col: jnp.ndarray, max_skew: jnp.ndarray,
                   hard: jnp.ndarray, counts: jnp.ndarray,
                   valid: jnp.ndarray, src: jnp.ndarray,
                   topo_dom: jnp.ndarray
                   ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """[P,N] (mask, score) from the term tables and the cluster topology
    tensor.  The per-term one-hot expansion is the take_along_axis gather
    (counts[t, dom[n, key_col[t]]]) — sparse, never O(N x D)."""
    f32 = jnp.float32
    dom_tn = topo_dom[:, key_col].T                        # [T, N]
    cnt_tn = jnp.take_along_axis(counts, jnp.clip(dom_tn, 0), axis=1)
    big = f32(1e9)
    min_t = jnp.min(jnp.where(valid, counts, big), axis=1)
    min_t = jnp.where(min_t >= big, 0.0, min_t)            # no valid domain
    has = dom_tn >= 0
    ok = (cnt_tn + 1.0 - min_t[:, None]) <= max_skew[:, None]
    viol_tn = ((~has) | ~ok).astype(f32)                   # [T, N]
    hard_viol = viol_tn * hard.astype(f32)[:, None]
    srcf = src.astype(f32)                                 # [P, T]
    mask = (srcf @ hard_viol) < 0.5
    soft_tn = jnp.where((~hard)[:, None] & has,
                        -(cnt_tn - min_t[:, None]), 0.0)
    score = srcf @ soft_tn
    return mask, score


def spread_planes(terms: SpreadTerms, topo_dom: jnp.ndarray
                  ) -> tuple[Optional[jnp.ndarray], Optional[jnp.ndarray]]:
    """(extra_mask, score_bias) planes for the solver — None for a plane
    no term populates (the scan then compiles it away entirely)."""
    mask, score = _planes_kernel(
        jnp.asarray(terms.key_col), jnp.asarray(terms.max_skew),
        jnp.asarray(terms.hard), jnp.asarray(terms.counts),
        jnp.asarray(terms.valid), jnp.asarray(terms.src), topo_dom)
    return (mask if terms.any_hard else None,
            score if terms.any_soft else None)


def spread_planes_host(terms: SpreadTerms, topo_dom: "np.ndarray"
                       ) -> tuple[Optional["np.ndarray"],
                                  Optional["np.ndarray"]]:
    """``spread_planes`` in pure NumPy — the host fallback engine
    (engine/hostsolver.py) must honor hard DoNotSchedule terms with the
    device gone, so this mirrors ``_planes_kernel`` line for line on
    host arrays."""
    if terms is None:
        return None, None
    f32 = np.float32
    topo_dom = np.asarray(topo_dom)
    dom_tn = topo_dom[:, terms.key_col].T                   # [T, N]
    cnt_tn = np.take_along_axis(terms.counts,
                                np.clip(dom_tn, 0, None), axis=1)
    big = f32(1e9)
    min_t = np.min(np.where(terms.valid, terms.counts, big), axis=1)
    min_t = np.where(min_t >= big, 0.0, min_t)
    has = dom_tn >= 0
    ok = (cnt_tn + 1.0 - min_t[:, None]) <= terms.max_skew[:, None]
    viol_tn = ((~has) | ~ok).astype(f32)
    hard_viol = viol_tn * terms.hard.astype(f32)[:, None]
    srcf = terms.src.astype(f32)                            # [P, T]
    mask = (srcf @ hard_viol) < 0.5
    soft_tn = np.where((~terms.hard)[:, None] & has,
                       -(cnt_tn - min_t[:, None]), 0.0)
    score = srcf @ soft_tn
    return (mask if terms.any_hard else None,
            score if terms.any_soft else None)
