"""Priority preemption: the second batched solve over the victim set.

When a priority-carrying pod's feasibility row is all-false, the
scheduler asks a different question: which node COULD host it after
evicting some strictly-lower-priority pods, and what is the cheapest such
eviction?  The reference answers per node with pod-by-pod simulation;
here it is one vmapped reduction over the whole cluster:

* the cache reconstructs the VICTIM TABLE from its tracked (assumed +
  confirmed) pods: per node, victims sorted ascending by (priority, key)
  and padded to a power-of-two V (SchedulerCache.victim_table);
* ``victim_solve`` computes, for EVERY node at once, the minimal victim
  count k whose eviction lets the pod fit — the "cluster minus victims"
  row update is the prefix-sum ``requested - cumsum(victim_requests)``,
  so prefix k is exactly the k cheapest (lowest-priority) victims;
* the host picks the node minimizing (victim count, summed victim
  priority, node index) — fewest evictions first, then least important
  victims, deterministic tie-break (the parity oracle replays the same
  order, kubernetes_tpu/oracle.py).

Victims are strictly lower priority by construction (the eligibility
mask), and non-resource predicates (selectors, taints, pressure) are
required to pass WITH the victims still present — conservative: a node
that only becomes selector-feasible after eviction is never nominated.

The daemon executes a decision as evict -> assume -> bind
(scheduler/scheduler.py._execute_preemption) with the nominated node
recorded in the flight recorder and surfaced by ``kubectl explain``.
"""

from __future__ import annotations

import functools
import os
from dataclasses import dataclass, field
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.features.compiler import RES_PODS
from kubernetes_tpu.utils import knobs

# Victim-table width: victims per node considered, padded pow2.  Bounds
# both the kernel shape and the blast radius of one decision.
MAX_VICTIMS = knobs.get_int("KT_PREEMPT_MAX_VICTIMS")


class VictimTable(NamedTuple):
    """Per-node victim candidates (host side; see SchedulerCache
    .victim_table).  Rows sorted ascending by (priority, pod key)."""

    req: np.ndarray       # [N, V, 4] int32 (cpu, mem_mib, gpu, 1)
    prio: np.ndarray      # [N, V] int32
    valid: np.ndarray     # [N, V] bool
    keys: list            # [N] lists of pod keys, aligned with rows


@dataclass
class PreemptionDecision:
    pod_key: str
    node: str
    node_idx: int
    victims: list[str] = field(default_factory=list)
    prio_cost: int = 0


# kt-xray: no-donate(alloc/requested/victim tables are host-built per
# decision and re-read by the next decision's overlay)
@functools.partial(jax.jit)
def victim_solve(alloc: jnp.ndarray, requested: jnp.ndarray,
                 base_ok: jnp.ndarray, vic_req: jnp.ndarray,
                 vic_prio: jnp.ndarray, vic_valid: jnp.ndarray,
                 pod_req: jnp.ndarray, pod_zero: jnp.ndarray,
                 pod_prio: jnp.ndarray
                 ) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Minimal victim prefix per node: (k_min [N], prio_cost [N],
    feasible [N]).  ``base_ok`` is the pod's non-resource predicate row
    (victims present); ``pod_zero`` the zero-request escape hatch
    (predicates.go:463 — a zero-request pod only needs a pod slot)."""
    eligible = vic_valid & (vic_prio < pod_prio)             # [N, V]
    k_elig = jnp.sum(eligible, axis=1)                       # [N]
    vreq = vic_req * eligible[..., None].astype(vic_req.dtype)
    cum = jnp.cumsum(vreq, axis=1)                           # [N, V, 4]
    cumz = jnp.concatenate([jnp.zeros_like(cum[:, :1]), cum], axis=1)
    free = alloc[:, None, :] - requested[:, None, :] + cumz  # [N, V+1, 4]
    fits_res = jnp.all(pod_req[None, None, :3] <= free[..., :3],
                       axis=-1) | pod_zero
    fits_pods = free[..., RES_PODS] >= 1
    ks = jnp.arange(cumz.shape[1], dtype=jnp.int32)          # [V+1]
    feasible_k = fits_res & fits_pods & \
        (ks[None, :] <= k_elig[:, None]) & base_ok[:, None]
    k_min = jnp.argmax(feasible_k, axis=1).astype(jnp.int32)
    any_k = jnp.any(feasible_k, axis=1)
    prio_cum = jnp.concatenate(
        [jnp.zeros_like(vic_prio[:, :1]),
         jnp.cumsum(vic_prio * eligible, axis=1)], axis=1)
    prio_cost = jnp.take_along_axis(prio_cum, k_min[:, None],
                                    axis=1)[:, 0].astype(jnp.int32)
    return k_min, prio_cost, any_k


def pick_node(k_min: np.ndarray, prio_cost: np.ndarray,
              feasible: np.ndarray) -> Optional[int]:
    """argmin over (victim count, summed victim priority, node index) —
    the deterministic cost order both the engine and the parity oracle
    use.  None when no node is feasible even after evictions."""
    idx = np.flatnonzero(np.asarray(feasible, bool))
    if idx.size == 0:
        return None
    k = np.asarray(k_min)[idx]
    c = np.asarray(prio_cost)[idx]
    order = np.lexsort((idx, c, k))
    return int(idx[order[0]])


def prewarm_shapes(n_nodes: int, v: int = 0) -> None:
    """Trace ``victim_solve`` at the cluster's (N, V) shape so the first
    live preemption never pays its XLA compile (Scheduler.prewarm's
    bucket-ladder discipline extended to the workloads subsystem).  V is
    pow2-padded exactly like SchedulerCache.victim_table pads its rows —
    a non-pow2 KT_PREEMPT_MAX_VICTIMS must warm the shape the live
    solve actually runs at."""
    v = v or MAX_VICTIMS
    v = 1 << max(v - 1, 0).bit_length()
    n = max(n_nodes, 1)
    victim_solve(
        jnp.zeros((n, 4), jnp.int32), jnp.zeros((n, 4), jnp.int32),
        jnp.zeros(n, bool), jnp.zeros((n, v, 4), jnp.int32),
        jnp.zeros((n, v), jnp.int32), jnp.zeros((n, v), bool),
        jnp.zeros(4, jnp.int32), jnp.asarray(False),
        jnp.asarray(0, jnp.int32))[0].block_until_ready()
