"""Gang scheduling: all-or-nothing admission for co-scheduled pod groups.

A gang is the set of pending pods sharing a ``scheduling.kt.io/gang``
annotation value (api/types.py).  TPU multi-slice jobs are the motivating
shape: a 4-slice training job that gets 3 of its 4 workers bound makes no
progress while holding capacity hostage — the reference points for the
semantics are Borg's job-level admission (Verma et al., EuroSys 2015 §2.2)
and the kube coscheduling plugin's minMember contract.

The guarantee is enforced at ADMISSION: after the batched solve produces
the assignment vector, ``reduce_all_or_nothing`` nulls the placements of
every gang whose placed member count is below its required size, so the
daemon never assumes or binds a partial gang.  Rejected members requeue
with backoff; the queue's gang hold (scheduler/queue.py) re-releases them
only as a complete unit, so the next drain solves the whole gang again.

Bind-time faults (chaos 409/reset) are repaired per member: the already-
bound members keep their nodes, the failed member requeues and — because
its siblings' capacity is already committed — rebinding converges to the
full gang.  The all-or-nothing invariant is therefore an admission-time
guarantee plus convergence under faults, pinned by the chaos e2e suite
(tests/test_chaos_control_plane.py) and the property tests
(tests/test_workload_constraints.py).
"""

from __future__ import annotations

from typing import Optional, Sequence


def gang_groups(pods: Sequence) -> dict[str, list[int]]:
    """Batch indices of each gang present in ``pods`` (annotation-keyed)."""
    groups: dict[str, list[int]] = {}
    for i, pod in enumerate(pods):
        name = pod.gang
        if name:
            groups.setdefault(name, []).append(i)
    return groups


def required_size(pods: Sequence, members: list[int]) -> int:
    """The gang's all-or-nothing floor: the largest declared
    ``gang-size`` among members, never below the member count present
    (an undeclared size means "whoever drained together")."""
    declared = max((pods[i].gang_size for i in members), default=0)
    return max(declared, len(members))


def reduce_all_or_nothing(pods: Sequence, placements: list
                          ) -> tuple[list, dict[str, dict]]:
    """The post-solve gang-feasibility reduction over the assignment
    vector: a gang is admitted only if EVERY member placed AND at least
    its declared size of members are present in this batch; otherwise
    every member's placement is nulled (the capacity its members consumed
    during the scan is released when the daemon skips their assume).

    Returns (reduced placements, rejections) where rejections maps gang
    name -> {"required", "present", "placed", "members": [batch idx]}.
    """
    groups = gang_groups(pods)
    if not groups:
        return placements, {}
    out = list(placements)
    rejected: dict[str, dict] = {}
    for name, members in groups.items():
        need = required_size(pods, members)
        placed = [i for i in members if out[i] is not None]
        if len(members) >= need and len(placed) == len(members):
            continue
        for i in members:
            out[i] = None
        rejected[name] = {"required": need, "present": len(members),
                          "placed": len(placed), "members": members}
    return out, rejected


def partial_gangs(bound_by_gang: dict[str, tuple[int, int]]
                  ) -> list[str]:
    """Names of gangs with SOME but not all members bound — the invariant
    probe the chaos suite asserts empty at settle.  Input maps gang name
    -> (bound members, gang size)."""
    return [name for name, (bound, size) in bound_by_gang.items()
            if 0 < bound < size]


def gang_failure_message(name: str, info: dict) -> str:
    if info["present"] < info["required"]:
        return (f"gang {name!r}: only {info['present']}/{info['required']} "
                f"members present in the batch; rejecting atomically")
    return (f"gang {name!r}: only {info['placed']}/{info['required']} "
            f"members fit; rejecting atomically (all-or-nothing)")


def batch_has_gangs(pods: Sequence) -> bool:
    return any(pod.gang for pod in pods)
