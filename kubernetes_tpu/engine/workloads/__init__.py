"""Workload-constraints subsystem: gang scheduling, priority preemption,
and topology-aware spread as batched tensor operations.

The dense mask/score pipeline (engine/solver.py) covers per-pod
fit-and-score; this package lowers three *workload-level* constraint
classes into that same pipeline — the scenarios Borg makes first-class
(job-level admission, priority preemption; Verma et al., EuroSys 2015)
and Firmament gains quality from by solving whole problems at once
(Gog et al., OSDI 2016):

``gang``
    All-or-nothing admission for pods sharing a
    ``scheduling.kt.io/gang`` annotation (TPU multi-slice jobs): a
    post-solve feasibility reduction over the assignment vector rejects
    incomplete gangs atomically; members requeue with backoff and drain
    again as a unit.

``preemption``
    When a priority-carrying pod fits nowhere, a second batched solve
    over the victim set (every tracked pod of strictly lower priority,
    reconstructed per node from the resident cluster) picks the
    minimal-cost victim set via a vmapped cluster-minus-victims prefix
    reduction, and the daemon executes evict -> assume -> bind with
    nominated-node plumbing through the flight recorder.

``topology``
    ``topologySpreadConstraints`` (and the affinity planes already in
    the solver) as mask/score planes contracted against the
    ``DeviceCluster.topo_dom`` (nodes x topology-keys) domain-id tensor —
    the compressed encoding of the (nodes x topology_domains) one-hot,
    expanded on device per constraint term by gather.
"""

from kubernetes_tpu.engine.workloads import gang, preemption, topology

__all__ = ["gang", "preemption", "topology"]
