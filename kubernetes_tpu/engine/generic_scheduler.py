"""Host orchestration: the batched counterpart of genericScheduler.Schedule
(generic_scheduler.go:78-122).

``GenericScheduler`` owns a Solver (compiled policy), the tensor cache, and
the cluster-object listers (services/RCs/RSs for spreading, per
selector_spreading.go:70-86).  ``schedule()`` places one pod (decision
parity path); ``schedule_batch()`` places a whole pending queue in one
device solve (the TPU win).
"""

from __future__ import annotations

import functools
import os
import sys
import time
from dataclasses import dataclass, field
from typing import Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.policy import (Policy, default_provider,
                                       node_label_args, node_label_prio_args,
                                       service_affinity_labels,
                                       service_anti_affinity_labels)
from kubernetes_tpu.cache.scheduler_cache import SchedulerCache
from kubernetes_tpu.engine import devicestats
from kubernetes_tpu.engine import guard as guard_mod
from kubernetes_tpu.engine import solver as sv
from kubernetes_tpu.engine.hostsolver import HostSolver
from kubernetes_tpu.engine.extender_client import (ExtenderError,
                                                   ExtenderUnavailable,
                                                   HTTPExtender)
from kubernetes_tpu.utils import metrics
from kubernetes_tpu.features import batch as fb
from kubernetes_tpu.features import compiler as fc
from kubernetes_tpu.features import padcap
from kubernetes_tpu.features.volumes import compile_volsvc
from kubernetes_tpu.utils.logging import get_logger
from kubernetes_tpu.utils.trace import Trace, stage

log = get_logger("engine")


class FitError(Exception):
    """No node fits (generic_scheduler.go:39-61). failed_predicates maps
    node name -> list of failing predicate names."""

    def __init__(self, pod: api.Pod, failed_predicates: dict[str, list[str]]):
        self.pod = pod
        self.failed_predicates = failed_predicates
        super().__init__(f"pod ({pod.name}) failed to fit in any node")


@dataclass
class Listers:
    """In-memory cluster-object stores standing in for the reference's
    reflector-backed caches (factory.go:387-416)."""

    services: list[api.Service] = field(default_factory=list)
    controllers: list[api.ReplicationController] = field(default_factory=list)
    replica_sets: list[api.ReplicaSet] = field(default_factory=list)
    pvs: list[api.PersistentVolume] = field(default_factory=list)
    pvcs: list[api.PersistentVolumeClaim] = field(default_factory=list)

    def get_pv(self, name: str) -> api.PersistentVolume | None:
        for pv in self.pvs:
            if pv.name == name:
                return pv
        return None

    def get_pvc(self, namespace: str, name: str) -> api.PersistentVolumeClaim | None:
        for pvc in self.pvcs:
            if pvc.namespace == namespace and pvc.name == name:
                return pvc
        return None

    def first_service(self, pod: api.Pod) -> api.Service | None:
        """GetPodServices[0] (the reference's ServiceAffinity/ServiceAnti
        Affinity use only the first matching service,
        predicates.go:676-678)."""
        for s in self.services:
            if s.namespace == pod.namespace and s.selector and \
                    all(pod.labels.get(k) == v for k, v in s.selector.items()):
                return s
        return None

    def spread_selectors(self, pod: api.Pod) -> list:
        """GetPodServices/GetPodControllers/GetPodReplicaSets
        (pkg/client/cache/listers.go): same namespace, empty selectors match
        nothing, unlabeled pods match no RC/RS."""
        out: list = []
        for s in self.services:
            if s.namespace == pod.namespace and s.selector and \
                    all(pod.labels.get(k) == v for k, v in s.selector.items()):
                out.append(s.selector)
        if pod.labels:
            for rc in self.controllers:
                if rc.namespace == pod.namespace and rc.selector and \
                        all(pod.labels.get(k) == v for k, v in rc.selector.items()):
                    out.append(rc.selector)
            for rs in self.replica_sets:
                if rs.namespace == pod.namespace and rs.selector is not None:
                    if (rs.selector.match_labels or rs.selector.match_expressions) \
                            and rs.selector.matches(pod.labels):
                        out.append(rs.selector)
        return out

    def controller_refs(self, pod: api.Pod) -> list:
        """Controller signatures for NodePreferAvoidPods (priorities.go:340-342).
        UIDs are modeled as 'namespace/name'."""
        out = []
        if pod.labels:
            for rc in self.controllers:
                if rc.namespace == pod.namespace and rc.selector and \
                        all(pod.labels.get(k) == v for k, v in rc.selector.items()):
                    out.append(("ReplicationController", f"{rc.namespace}/{rc.name}"))
            for rs in self.replica_sets:
                if rs.namespace == pod.namespace and rs.selector is not None:
                    if (rs.selector.match_labels or rs.selector.match_expressions) \
                            and rs.selector.matches(pod.labels):
                        out.append(("ReplicaSet", f"{rs.namespace}/{rs.name}"))
        return out


class GenericScheduler:
    def __init__(self, policy: Policy | None = None,
                 cache: SchedulerCache | None = None,
                 listers: Listers | None = None):
        self.policy = policy or default_provider()
        self.cache = cache or SchedulerCache()
        self.listers = listers or Listers()
        # Persistent XLA compilation cache, configured before the first
        # trace: warm starts deserialize executables instead of paying
        # the multi-second compile tax again (engine/compile_cache.py).
        from kubernetes_tpu.engine import compile_cache
        compile_cache.configure()
        # Shared per policy signature: a fresh Solver per engine would
        # re-trace and re-compile every executable (see Solver.for_policy).
        self.solver = sv.Solver.for_policy(self.policy)
        # Device-resident cluster mirror: per drain only the cache's
        # dirty rows are scattered into the resident (nodes x features)
        # arrays; a full re-upload happens only on relist or capacity
        # growth (sv.ResidentCluster).
        self.resident = sv.ResidentCluster()
        self.extenders = [HTTPExtender(cfg) for cfg in self.policy.extenders]
        # Guarded device execution (engine/guard.py): every solve site
        # runs inside the guard so accelerator faults classify, count,
        # and recover (OOM -> evict + bisect, repeated/terminal -> the
        # host fallback engine below) instead of stalling the drain.
        self.guard = guard_mod.DeviceGuard(evict_fn=self.resident.invalidate)
        # The NumPy fallback engine behind the same masks/evaluate/solve
        # surface — slower than the device scan, always available.
        self.host_solver = HostSolver(self.solver)
        self.last_node_index = np.uint32(0)
        # Monotonic compile state (features.padcap): table-axis capacities
        # and the OR of all content flags seen, so a long-running daemon
        # converges on ONE compiled scan per (chunk, cluster) shape
        # instead of re-specializing whenever batch content wobbles.
        self._axis_caps: dict[str, int] = {}
        self._flags_seen: sv.BatchFlags | None = None
        # Spread-constraint term tables for the batch _compile last saw
        # (None = no pod carried topologySpreadConstraints).
        self._topo_terms = None
        # Stream-path debug prints, read ONCE at engine init: the old
        # per-drain env read ran twice per streamed drain (a ktlint D04
        # hot-path finding — the KT_STREAM_MIN_BUCKET bug class).
        from kubernetes_tpu.utils import knobs
        self._stream_debug = knobs.get_bool("KT_STREAM_DEBUG")

    def _pinned_flags(self, batch) -> sv.BatchFlags:
        """Content flags OR-ed monotonically (padcap's discipline for the
        scan's boolean specialization): once a family has appeared, later
        batches keep paying its (numerically no-op when empty) state
        rather than minting a new compiled scan when it vanishes."""
        flags = sv.batch_flags(batch)
        if self._flags_seen is not None:
            flags = sv.BatchFlags(*(a or b for a, b in
                                    zip(flags, self._flags_seen)))
        self._flags_seen = flags
        return flags

    # -- compilation helpers --------------------------------------------

    def _compile(self, pods: list[api.Pod], device: bool = True,
                 host_only: bool = False
                 ) -> tuple[fb.PodBatch, sv.DeviceBatch,
                            sv.DeviceCluster, list[str]]:
        """``host_only=True`` is the fallback engine's compile: the same
        snapshot + feature compile, but NO device participation — the
        cluster comes back as host numpy (``_host_cluster``) and the
        dirty-row set is NOT consumed (it belongs to the device mirror,
        which must replay every mutation when the breaker closes)."""
        from kubernetes_tpu.engine.workloads import topology
        # Topology keys named by spread constraints must be interned
        # BEFORE the snapshot so topo_dom columns exist for them (a NEW
        # key marks the node tensors dirty — once per workload type).
        has_spread = topology.batch_has_spread(pods)
        if has_spread:
            for key in topology.spread_topology_keys(pods):
                self.cache.ensure_topo_key(key)
        # The whole compile runs under the cache lock: cache mutators
        # (reflector handlers, async-bind forget_pod) update the aggregate
        # and existing-pod arrays IN PLACE, so every read — snapshot,
        # volume/affinity pod lists, feature compilation, and the device
        # transfer itself — must see one consistent generation.
        with self.cache.lock:
            with stage("snapshot", pods=len(pods)):
                nt, agg, ep, nodes = self.cache.snapshot()
                # Tag for the device-aggregate handoff: the snapshot the
                # solve starts from (assume_pods validates nothing changed
                # since).
                self._snapshot_generation = self.cache.generation
            with stage("compile", pods=len(pods)):
                volsvc = compile_volsvc(
                    pods, nodes, nt.schedulable,
                    volume_pods=self.cache.volume_pods(),
                    listers=self.listers,
                    service_affinity_labels=service_affinity_labels(
                        self.policy),
                    service_anti_affinity_labels=(
                        service_anti_affinity_labels(self.policy)),
                    node_label_args=node_label_args(self.policy),
                    node_label_prio_args=node_label_prio_args(self.policy),
                    service_peers=self.cache.service_peer_nodes,
                    first_peer=self.cache.first_peer_node)
                batch = fb.compile_batch(
                    pods, nt, self.cache.space, ep=ep, nodes=nodes,
                    spread_selectors=self.listers.spread_selectors,
                    controller_refs=self.listers.controller_refs,
                    affinity_pods=self.cache.affinity_pods(),
                    hard_pod_affinity_weight=(
                        self.policy.hard_pod_affinity_symmetric_weight),
                    volsvc=volsvc)
                batch = padcap.apply_caps(batch, self._axis_caps)
                # Spread-constraint term tables (counts snapshotted under
                # the same lock as everything else this solve reads).
                self._topo_terms = topology.compile_terms(
                    pods, nt, self.cache.space,
                    self.cache.topo_domain_counts_bulk) \
                    if has_spread else None
            if host_only:
                return (batch, sv.host_batch(batch),
                        sv._host_cluster(nt, agg, self.cache.space), nt)
            with stage("transfer", device=device):
                # device=False keeps the batch pytree on host (the chunked
                # drain slices it in numpy and transfers fixed-shape
                # chunks).
                db = sv.device_batch(batch) if device \
                    else sv.host_batch(batch)
                # Cluster state syncs through the device-resident mirror:
                # dirty rows scatter into the resident arrays; the full
                # snapshot transfer happens only on relist or capacity
                # growth.  Same locked section as the snapshot, so the
                # dirty set and the row contents are one generation.
                dc = self.resident.sync(nt, agg, self.cache.space,
                                        self.cache.take_dirty_rows(),
                                        self.cache.tensor_epoch)
        return batch, db, dc, nt

    # -- single-pod path (Schedule, generic_scheduler.go:78) -------------

    def schedule(self, pod: api.Pod) -> str:
        """One decision through the guarded device path; a classified
        device fault (or an open breaker) decides the pod on the host
        fallback engine instead — FitError semantics are identical on
        both engines."""
        if self.guard.enabled and self.guard.mode == "host":
            return self._schedule_host(pod)
        try:
            return self._schedule_device(pod)
        except guard_mod.DeviceFault as fault:
            self.guard.recover(fault, can_bisect=False)
            return self._schedule_host(pod)

    def _schedule_device(self, pod: api.Pod) -> str:
        trace = Trace(f"Scheduling {pod.namespace}/{pod.name}")
        if not self.cache.nodes():
            raise FitError(pod, {})
        with devicestats.live_path("single_pod"), \
                self.guard.watch("single_pod"):
            batch, db, dc, nt = self._compile([pod])
            trace.step("Computing predicates & priorities")
            feasible, scores = self.solver.evaluate(
                db, dc, self._pinned_flags(batch))
            topo_mask_np = None
            if self._topo_terms is not None:
                from kubernetes_tpu.engine.workloads import topology
                tmask, tscore = topology.spread_planes(self._topo_terms,
                                                       dc.topo_dom)
                if tmask is not None:
                    feasible = feasible & tmask
                    topo_mask_np = np.asarray(tmask[0])
                if tscore is not None:
                    scores = scores + tscore
            trace.step("Selecting host")
            feasible_np, _ = self.guard.checked_scores(
                "single_pod", np.asarray(feasible[0]),
                np.asarray(scores[0]))
        if not feasible_np.any():
            # The masks pass is device work too: a fault here must take
            # the same classify -> host-fallback road as the evaluate.
            with self.guard.watch("single_pod", inject=False):
                masks = {k: np.asarray(v[0]) for k, v in
                         self.solver.masks(db, dc).items()}
            if topo_mask_np is not None:
                masks["TopologySpread"] = topo_mask_np
            failed: dict[str, list[str]] = {}
            for i, name in enumerate(nt.names):
                if nt.schedulable[i]:
                    failed[name] = [p for p, m in masks.items() if not m[i]]
            trace.log_if_long()
            raise FitError(pod, failed)
        if self.extenders:
            host = self._schedule_with_extenders(
                pod, nt, feasible_np, np.asarray(scores[0]))
            trace.log_if_long()
            return host
        with self.guard.watch("single_pod", inject=False):
            choice, new_last = sv.combine.select_hosts(
                scores, feasible, jnp.uint32(self.last_node_index))
            picked = int(choice[0])
        self.last_node_index = np.uint32(new_last)
        trace.log_if_long()
        return nt.names[picked]

    def _schedule_with_extenders(self, pod: api.Pod, nt,
                                 feasible_np: np.ndarray,
                                 scores_np: np.ndarray) -> str:
        """Extender filter after built-in predicates
        (generic_scheduler.go:189-207) and prioritize summed at weight
        (:287-305), then selectHost (:124-141) host-side."""
        nodes = self.cache.nodes()
        candidates = [nodes[i] for i in range(len(nodes)) if feasible_np[i]]
        failed_ext: dict[str, list[str]] = {}
        degraded = False
        for ext in self.extenders:
            try:
                candidates, failed = ext.filter(pod, candidates)
            except ExtenderUnavailable:
                # Breaker open: the endpoint is known-dead.  Graceful
                # degradation — schedule on built-in predicates alone
                # rather than failing every pod until it recovers.  (A
                # closed-breaker timeout still raises ExtenderError and
                # fails THIS pod, the reference's filter-timeout
                # semantics, api/types.go:128-130.)
                if not degraded:
                    degraded = True
                    metrics.EXTENDER_DEGRADED_DECISIONS.labels(
                        extender=ext.config.url_prefix).inc()
                    # debug, not warning: thousands of pods degrade per
                    # 15 s open window — the breaker transition itself is
                    # logged once (extender_client) and counted above.
                    log.debug("extender %s unavailable (breaker open); "
                              "scheduling %s with built-in predicates "
                              "only", ext.config.url_prefix, pod.key)
                continue
            for name, msg in failed.items():
                failed_ext.setdefault(name, []).append(msg or "extender")
            if not candidates:
                raise FitError(pod, failed_ext)
        name_to_idx = nt.name_to_idx
        combined = {n.name: float(scores_np[name_to_idx[n.name]])
                    for n in candidates}
        for ext in self.extenders:
            for host, score in ext.prioritize(pod, candidates).items():
                if host in combined:
                    combined[host] += score
        best = max(combined.values())
        ties = [n.name for n in candidates if combined[n.name] == best]
        choice = ties[int(self.last_node_index) % len(ties)]
        self.last_node_index = np.uint32(int(self.last_node_index) + 1)
        return choice

    # -- host fallback engine paths (engine/hostsolver.py) ----------------

    def _compile_host(self, pods: list[api.Pod]):
        """The fallback engine's compile: ``_compile`` with
        ``host_only=True`` — ONE implementation of the snapshot/feature
        sequence, so predicate and workload-constraint additions reach
        both engines automatically (incl. ``self._topo_terms``, which
        the host paths consume through ``topology.spread_planes_host``)."""
        return self._compile(pods, host_only=True)

    def _host_topo_planes(self, hc):
        """(extra_mask, score_bias) numpy planes for the host solve —
        the fallback must honor hard DoNotSchedule spread terms too
        (quality may degrade on host, constraints may not)."""
        if self._topo_terms is None:
            return None, None
        from kubernetes_tpu.engine.workloads import topology
        return topology.spread_planes_host(self._topo_terms,
                                           np.asarray(hc.topo_dom))

    def _schedule_host(self, pod: api.Pod) -> str:
        """The single-pod decision on the host fallback engine — same
        FitError / extender / round-robin contract as the device path."""
        trace = Trace(f"Scheduling {pod.namespace}/{pod.name} "
                      f"(host engine)")
        if not self.cache.nodes():
            raise FitError(pod, {})
        metrics.SOLVE_FALLBACKS.labels(mode="host").inc()
        batch, hb, hc, nt = self._compile_host([pod])
        trace.step("Computing predicates & priorities (host)")
        feasible, scores = self.host_solver.evaluate(hb, hc)
        extra_mask, score_bias = self._host_topo_planes(hc)
        topo_mask_np = None
        if extra_mask is not None:
            feasible = feasible & extra_mask
            topo_mask_np = extra_mask[0]
        if score_bias is not None:
            scores = scores + score_bias
        feasible_np, scores_np = feasible[0], scores[0]
        trace.step("Selecting host")
        if not feasible_np.any():
            masks = {k: m[0] for k, m in
                     self.host_solver.masks(hb, hc).items()}
            if topo_mask_np is not None:
                masks["TopologySpread"] = topo_mask_np
            failed: dict[str, list[str]] = {}
            for i, name in enumerate(nt.names):
                if nt.schedulable[i]:
                    failed[name] = [p for p, m in masks.items()
                                    if not m[i]]
            trace.log_if_long()
            raise FitError(pod, failed)
        if self.extenders:
            host = self._schedule_with_extenders(
                pod, nt, feasible_np, scores_np.astype(np.float32))
            trace.log_if_long()
            return host
        # selectHost round-robin (combine.select_hosts, host-side).
        masked = np.where(feasible_np, scores_np, -np.inf)
        ties = feasible_np & (masked == masked.max())
        ix = int(self.last_node_index) % int(ties.sum())
        choice = int(np.nonzero(ties)[0][ix])
        self.last_node_index = np.uint32(int(self.last_node_index) + 1)
        trace.log_if_long()
        return nt.names[choice]

    def schedule_batch_host(self, pods: list[api.Pod]) -> list[str | None]:
        """The host fallback drain: ``schedule_batch``'s contract (node
        names, None where unschedulable) on the NumPy sequential-greedy
        engine.  No padding, no buckets, no device — and its output
        still runs through the sanity gate, so both engines bind under
        the same guarantees."""
        if not pods:
            return []
        if not self.cache.nodes():
            return [None] * len(pods)
        if self.extenders:
            return self._schedule_batch_via_extenders(pods)
        metrics.SOLVE_FALLBACKS.labels(mode="host").inc()
        self._agg_handoff = None
        batch, hb, hc, nt = self._compile_host(pods)
        extra_mask, score_bias = self._host_topo_planes(hc)
        with stage("solve", pods=len(pods), mode="host"):
            choices, counter = self.host_solver.solve_greedy(
                hb, hc, int(self.last_node_index),
                extra_mask=extra_mask, score_bias=score_bias)
        choices = self.guard.checked_readback(
            "host", choices, len(nt.names),
            alloc=nt.alloc, requests=np.asarray(batch.request),
            keys_fn=lambda: [p.key for p in pods])
        self.last_node_index = np.uint32(counter)
        names = nt.names
        return [names[int(c)] if c >= 0 else None for c in choices]

    # -- batched path ----------------------------------------------------

    def schedule_batch(self, pods: list[api.Pod],
                       joint: bool = False,
                       pad_to: int = 0) -> list[str | None]:
        """Place a pending queue in one device solve.  Returns node names,
        None where unschedulable.

        Default mode is sequential-greedy in queue order with full in-batch
        visibility (decision parity with the reference's one-at-a-time
        loop).  ``joint=True`` runs the LP-relaxed global assignment
        (price iteration + regret-ordered repair) — better aggregate
        placement quality, no per-pod order parity.

        ``pad_to``: pad the batch to this length with live-masked inert
        rows so the solve hits a fixed compiled shape (the workload-
        constrained drain's bucket-ladder discipline — gang and joint
        drains can't stream-chunk, so this is how their shapes stay
        pre-warmable)."""
        if not pods:
            return []
        if not self.cache.nodes():
            # Empty cluster: findNodesThatFit over zero nodes fails every
            # pod (no device solve; zero-size tensors don't reduce).
            return [None] * len(pods)
        if self.extenders:
            # Extenders are a per-pod HTTP protocol; run the exact one-pod
            # path with temporary assumes for in-batch visibility, then
            # restore (callers re-assume through the daemon).
            return self._schedule_batch_via_extenders(pods)
        real_p = len(pods)
        live = live_np = None
        if pad_to > real_p:
            pods = list(pods) + [
                api.Pod(name=f"__pad-{i}", namespace="__pad__")
                for i in range(pad_to - real_p)]
        with self.guard.watch("oneshot" if not joint else "joint",
                              inject=False):
            batch, db, dc, nt = self._compile(pods)
        flags = self._pinned_flags(batch)
        if pad_to > real_p:
            live_np = np.zeros(len(pods), bool)
            live_np[:real_p] = True
            live = jnp.asarray(live_np)
        extra_mask = score_bias = None
        if self._topo_terms is not None:
            from kubernetes_tpu.engine.workloads import topology
            extra_mask, score_bias = topology.spread_planes(
                self._topo_terms, dc.topo_dom)
        if log.isEnabledFor(10):
            log.debug("schedule_batch: %d pods (%d templates) x %d nodes, "
                      "joint=%s flags=%s", len(pods),
                      len({getattr(p, "_tpl_key", None) for p in pods}),
                      sv.cluster_nodes(dc), joint, flags)
        self._agg_handoff = None
        from kubernetes_tpu.utils.profiling import device_trace
        if joint:
            with devicestats.live_path("joint"), \
                    device_trace("solve_joint"), \
                    self.guard.watch("joint"), \
                    stage("solve", pods=len(pods), mode="joint"):
                choices, new_last, _ = self.solver.solve_joint(
                    db, dc, jnp.uint32(self.last_node_index), flags=flags,
                    extra_mask=extra_mask, score_bias=score_bias,
                    live=live)
                choices.block_until_ready()
            with stage("readback", pods=len(pods)):
                with self.guard.watch("joint", inject=False):
                    choices_np = np.asarray(choices)
                devicestats.record_transfer("readback", choices_np.nbytes)
                choices_np = self.guard.checked_readback(
                    "joint", choices_np, sv.cluster_nodes(dc), live=live_np,
                    alloc=nt.alloc, requests=np.asarray(batch.request),
                    keys_fn=lambda: [pd.key for pd in pods[:real_p]])
                rows = choices_np[:real_p].tolist()
            self.last_node_index = np.uint32(new_last)
        else:
            # One packed device->host fetch for the whole drain (each fetch
            # is a full RTT on a tunneled chip): choices + tie counter +
            # final aggregates.
            p, n = len(pods), sv.cluster_nodes(dc)
            with devicestats.live_path("oneshot"), \
                    device_trace("solve_sequential"), \
                    self.guard.watch("oneshot"), \
                    stage("solve", pods=p, mode="sequential"):
                host_dev = self.solver.solve_sequential_packed(
                    db, dc, jnp.uint32(self.last_node_index), flags,
                    extra_mask=extra_mask, score_bias=score_bias,
                    live=live)
                # Block here so the solve stage measures device compute
                # and readback measures only the D2H copy.
                host_dev.block_until_ready()
            with stage("readback", pods=p):
                with self.guard.watch("oneshot", inject=False):
                    host = np.asarray(host_dev)
                devicestats.record_transfer("readback", host.nbytes)
            choices_np = self.guard.checked_readback(
                "oneshot", host[:p], n, live=live_np, alloc=nt.alloc,
                requests=np.asarray(batch.request),
                keys_fn=lambda: [pd.key for pd in pods[:real_p]])
            rows = choices_np[:real_p].tolist()
            self.last_node_index = np.uint32(host[p])
            # Device-aggregate handoff: the scan's final requested/nonzero
            # equal the snapshot plus every in-batch placement, so
            # assume_pods can ingest them instead of re-aggregating — valid
            # only when the batch carries no port/volume state (host-only
            # counters), the cache hasn't moved since the snapshot, and the
            # assumed set is EXACTLY this solve's placements (stamped with
            # their signature so a caller can't pair the aggregates with a
            # different assignment set at an unchanged generation).
            if not (flags.any_ports or flags.any_volumes or flags.any_ebs
                    or flags.any_gce):
                placed_sig = hash(frozenset(
                    (pod.key, rows[i])
                    for i, pod in enumerate(pods[:real_p])
                    if rows[i] >= 0))
                self._agg_handoff = (
                    self._snapshot_generation, placed_sig, nt,
                    host[p + 1:p + 1 + 4 * n].reshape(n, 4),
                    host[p + 1 + 4 * n:].reshape(n, 2))
        names = nt.names
        return [names[c] if c >= 0 else None for c in rows]

    def take_agg_handoff(self) -> Optional[tuple]:
        """One-shot: the (generation, requested, nonzero) handoff from the
        last schedule_batch, if any (see assume_pods)."""
        h = getattr(self, "_agg_handoff", None)
        self._agg_handoff = None
        return h

    # Cap on pods explained per call: one small compile + two device
    # evaluations cover the whole explained set, but the host-side mask
    # walk is O(pods x nodes x predicates).
    EXPLAIN_CAP = 64

    def explain_failures(self, pods: list[api.Pod]) -> dict:
        """Per-predicate failure counts (and top-scoring nodes) for pods
        that failed to place — the flight recorder's detail pass.  Runs
        against the CURRENT cache snapshot, so a pod that only failed
        because of in-batch contention may show zero failing predicates;
        the counts answer "why does this pod not fit the cluster", the
        reference ``FitError.failed_predicates`` aggregation.

        Cost is one ``_compile`` + ``masks`` + ``evaluate`` over at most
        ``EXPLAIN_CAP`` pods, paid only when a drain actually failed pods
        (a fully-placed drain never calls this).  The batch is padded to
        EXPLAIN_CAP with inert pods so every call hits ONE compiled
        shape — unpadded, each distinct failed-pod count would mint its
        own multi-second XLA compile in the drain path."""
        pods = pods[:self.EXPLAIN_CAP]
        if not pods:
            return {}
        nodes = self.cache.nodes()
        if not nodes:
            return {pod.key: {"message": "no nodes in cluster",
                              "failed_predicates": {}}
                    for pod in pods}
        padded = list(pods) + [
            api.Pod(name=f"__explain-pad-{i}", namespace="__pad__")
            for i in range(self.EXPLAIN_CAP - len(pods))]
        batch, db, dc, nt = self._compile(padded)
        masks = {name: np.asarray(m) for name, m in
                 self.solver.masks(db, dc).items()}
        _, scores = self.solver.evaluate(db, dc, sv.batch_flags(batch))
        scores = np.asarray(scores)
        sched = np.asarray(nt.schedulable, dtype=bool)
        n_sched = int(sched.sum())
        out: dict = {}
        for i, pod in enumerate(pods):
            counts = {}
            for name, m in masks.items():
                failing = int(np.count_nonzero(sched & ~m[i]))
                if failing:
                    counts[name] = failing
            top_idx = np.argsort(-scores[i])[:5]
            out[pod.key] = {
                "message": f"pod ({pod.name}) failed to fit in any node"
                if counts else
                f"pod ({pod.name}) fit no node in this batch (in-batch "
                f"contention; predicates pass against the current "
                f"snapshot)",
                "nodes_considered": n_sched,
                "failed_predicates": counts,
                "top_scores": [{"node": nt.names[int(j)],
                                "score": float(scores[i][int(j)])}
                               for j in top_idx]}
        return out

    # Preemption decisions computed per drain: the masks pass pads to
    # this many pods (one compiled shape, the EXPLAIN_CAP discipline) and
    # the per-decision eviction blast radius is bounded separately
    # (workloads.preemption.MAX_VICTIMS).
    PREEMPT_CAP = 16

    def find_preemptions(self, pods: list[api.Pod],
                         protected: frozenset = frozenset()) -> list:
        """Minimal-cost victim sets for unschedulable priority pods — the
        second batched solve (engine/workloads/preemption.py).

        Per pod, in priority order: one vmapped ``victim_solve`` over the
        (nodes x victims) table picks the cheapest feasible eviction
        prefix per node; the host takes the (victim count, victim
        priority sum, node index) argmin.  Decisions within one call see
        each other through host-side overlays (victims already claimed
        are consumed, the preemptor's own request charged), so two pods
        never nominate the same victim.  ``protected`` keys are never
        victims (the daemon shields the current drain's own placements).
        The caller executes the decisions (evict -> assume -> bind,
        scheduler/scheduler.py); it must have ASSUMED the batch's
        placements first so the aggregates this solve reads include
        them."""
        from kubernetes_tpu.engine.workloads import preemption as pre
        pods = [p for p in pods if p.effective_priority > 0]
        pods.sort(key=lambda p: (-p.effective_priority, p.key))
        pods = pods[:self.PREEMPT_CAP]
        if not pods or not self.cache.nodes():
            return []
        padded = list(pods) + [
            api.Pod(name=f"__preempt-pad-{i}", namespace="__pad__")
            for i in range(self.PREEMPT_CAP - len(pods))]
        batch, db, dc, nt = self._compile(padded)
        # Non-resource predicate rows: victims free resources, nothing
        # else — a node that only becomes selector/taint-feasible after
        # eviction is never nominated (conservative).
        masks = {name: np.asarray(m) for name, m in
                 self.solver.masks(db, dc).items()}
        base = np.broadcast_to(np.asarray(nt.schedulable, bool),
                               (len(padded), nt.alloc.shape[0])).copy()
        for name, m in masks.items():
            if name not in ("PodFitsResources",):
                base &= m
        if self._topo_terms is not None:
            from kubernetes_tpu.engine.workloads import topology
            tmask, _ = topology.spread_planes(self._topo_terms,
                                              dc.topo_dom)
            if tmask is not None:
                base &= np.asarray(tmask)
        with self.cache.lock:
            _, agg, _, _ = self.cache.snapshot()
            vt = self.cache.victim_table(pre.MAX_VICTIMS,
                                         exclude=protected)
            requested = agg.requested.copy()
        alloc = nt.alloc
        vic_req, vic_prio, vic_valid = (vt.req.copy(), vt.prio.copy(),
                                        vt.valid.copy())
        vic_keys = [list(k) for k in vt.keys]
        decisions = []
        with devicestats.live_path("victim"), self.guard.watch("victim"):
            self._find_preemptions_inner(
                pods, alloc, requested, base, vic_req, vic_prio,
                vic_valid, vic_keys, nt, decisions)
        return decisions

    def _find_preemptions_inner(self, pods, alloc, requested, base,
                                vic_req, vic_prio, vic_valid, vic_keys,
                                nt, decisions) -> None:
        from kubernetes_tpu.engine.workloads import preemption as pre
        for i, pod in enumerate(pods):
            pod_req = fc.pod_resource_row(pod)
            k_min, cost, feas = pre.victim_solve(
                jnp.asarray(alloc), jnp.asarray(requested),
                jnp.asarray(base[i]), jnp.asarray(vic_req),
                jnp.asarray(vic_prio), jnp.asarray(vic_valid),
                jnp.asarray(pod_req),
                jnp.asarray(bool(pod_req[0] == pod_req[1]
                                 == pod_req[2] == 0)),
                jnp.asarray(pod.effective_priority, jnp.int32))
            n_idx = pre.pick_node(np.asarray(k_min), np.asarray(cost),
                                  np.asarray(feas))
            if n_idx is None:
                continue
            k = int(np.asarray(k_min)[n_idx])
            victims = vic_keys[n_idx][:k]
            decisions.append(pre.PreemptionDecision(
                pod_key=pod.key, node=nt.names[n_idx], node_idx=n_idx,
                victims=victims,
                prio_cost=int(np.asarray(cost)[n_idx])))
            # Overlay for later pods in this call: free the claimed
            # victims' rows, charge the preemptor, shift the table.
            freed = vic_req[n_idx, :k].sum(axis=0)
            requested[n_idx] = requested[n_idx] - freed + pod_req
            if k:
                vic_req[n_idx] = np.concatenate(
                    [vic_req[n_idx, k:], np.zeros((k, 4), np.int32)])
                vic_prio[n_idx] = np.concatenate(
                    [vic_prio[n_idx, k:], np.zeros(k, np.int32)])
                vic_valid[n_idx] = np.concatenate(
                    [vic_valid[n_idx, k:], np.zeros(k, bool)])
                vic_keys[n_idx] = vic_keys[n_idx][k:]

    def schedule_batch_stream(self, pods: list[api.Pod],
                              chunk_size: int = 2048,
                              defer_readback: bool = False) -> Iterator:
        """Pipelined batched drain: one host compile, then the scan runs in
        equal-shaped chunks with device-carried state (identical choices to
        ``schedule_batch`` — each chunk continues the previous chunk's
        aggregates).  Yields ``(chunk_pods, chunk_placements)`` as each
        chunk's results land, while the device is already scanning the next
        chunk — the double-buffered decide/commit pipeline the reference
        gets from its async-bind goroutine (scheduler.go:122-153), stretched
        over the whole queue.

        With ``defer_readback=True`` each yield is ``(chunk_pods,
        resolve)`` instead, where ``resolve()`` performs the blocking
        device->host readback and returns the placements — the daemon's
        overlapped pipeline calls it on the binder pool so the drain
        thread never blocks on the device and batch N's scan runs while
        batch N-1 commits (scheduler.pipeline.DrainPipeline._solve_stream).

        The last chunk is padded with inert pods (live=False rows are
        infeasible everywhere and bump no tie counter) so every chunk hits
        the same compiled executable.  (A pow2 tail-bucket ladder was
        measured and REJECTED: on a tunneled chip each extra chunk launch
        costs a full RTT, which dwarfs the dead padded rows it saves.)"""
        p = len(pods)
        if p == 0:
            return
        if not self.cache.nodes():
            for start in range(0, p, chunk_size):
                chunk = pods[start:start + chunk_size]
                empty = [None] * len(chunk)
                yield (chunk, (lambda c=chunk, e=empty: (c, e))) \
                    if defer_readback else (chunk, empty)
            return
        n_chunks = (p + chunk_size - 1) // chunk_size
        padded = n_chunks * chunk_size
        all_pods = list(pods)
        if padded > p:
            all_pods += [api.Pod(name=f"__pad-{i}", namespace="__pad__")
                         for i in range(padded - p)]
        t_c0 = time.perf_counter()
        with self.guard.watch("stream", inject=False):
            batch, hb, dc, nt = self._compile(all_pods, device=False)
        flags = self._pinned_flags(batch)
        # Spread-constraint planes, host-resident like the batch: each
        # chunk device_puts its fixed-shape row slice (pad rows carry no
        # constraints, so their mask rows are all-pass).
        topo_mask_np = topo_score_np = None
        if self._topo_terms is not None:
            from kubernetes_tpu.engine.workloads import topology
            tmask, tscore = topology.spread_planes(self._topo_terms,
                                                   dc.topo_dom)
            topo_mask_np = None if tmask is None else np.asarray(tmask)
            topo_score_np = None if tscore is None else np.asarray(tscore)
        if self._stream_debug:
            shapes = {f: tuple(getattr(hb, f).shape)
                      for f in ("sel_required", "spread_node_counts",
                                "avoid_rows")}
            shapes.update({f: tuple(getattr(hb.aff, f).shape)
                           for f in ("match_cnt", "decl_reach", "sym_cnt",
                                     "node_dom")})
            shapes.update({f: tuple(getattr(hb.volsvc, f).shape)
                           for f in ("pd_pod_ebs", "pd_pod_gce", "vz_mask",
                                     "sa_mask", "saa_cnt",
                                     "nl_prio_rows")})
            print(f"stream-debug compile({len(all_pods)} pods): "
                  f"{time.perf_counter() - t_c0:.3f}s flags={tuple(flags)} "
                  f"shapes={shapes}", file=sys.stderr)
        n = sv.cluster_nodes(dc)
        counter = jnp.uint32(self.last_node_index)
        carry = None
        live_np = np.zeros(padded, bool)
        live_np[:p] = True
        pending: list[tuple[int, jnp.ndarray]] = []

        def emit(start: int, choices) -> tuple[list, list]:
            with stage("readback", chunk_at=start):
                with self.guard.watch("stream", inject=False):
                    rows = np.asarray(choices)  # blocks on this chunk
                devicestats.record_transfer("readback", rows.nbytes)
            stop = min(start + chunk_size, p)
            chunk_pods = pods[start:stop]
            # Post-solve sanity gate: a corrupt chunk readback requeues
            # the chunk (DeviceFault through the commit worker) instead
            # of binding garbage.
            rows = self.guard.checked_readback(
                "stream", rows, n,
                live=live_np[start:start + chunk_size],
                alloc=nt.alloc,
                requests=np.asarray(hb.request)[start:start + chunk_size],
                keys_fn=lambda: [pd.key for pd in chunk_pods])
            placements = [nt.names[int(c)] if c >= 0 else None
                          for c in rows[: stop - start]]
            return chunk_pods, placements

        from kubernetes_tpu.utils.profiling import device_trace
        debug_t = self._stream_debug
        for start in range(0, padded, chunk_size):
            t0 = time.perf_counter() if debug_t else 0.0
            # Host-slice (free numpy views), then one batched device_put of
            # the fixed [chunk_size, ...] shapes: slicing ON DEVICE minted
            # a dynamic_slice program per distinct drain length.
            with stage("transfer", chunk_at=start):
                db_k = jax.device_put(
                    sv.slice_pod_axis(hb, start, start + chunk_size))
                live = jnp.asarray(live_np[start:start + chunk_size])
                em_k = None if topo_mask_np is None else jax.device_put(
                    topo_mask_np[start:start + chunk_size])
                sb_k = None if topo_score_np is None else jax.device_put(
                    topo_score_np[start:start + chunk_size])
            # The launch is async: device time surfaces in the next
            # chunk's readback, which is what keeps the pipeline
            # overlapped — this stage measures dispatch only.
            with devicestats.live_path("stream"), \
                    device_trace("solve_stream_chunk"), \
                    self.guard.watch("stream"), \
                    stage("solve", chunk_at=start, mode="stream"):
                choices_k, counter, carry = self.solver._solve_scan(
                    db_k, dc, counter, sb_k, flags, carry, live, em_k)
            if debug_t:
                t1 = time.perf_counter()
            pending.append((start, choices_k))
            if len(pending) > 1:
                s_k, c_k = pending.pop(0)
                if defer_readback:
                    yield (pods[s_k:min(s_k + chunk_size, p)],
                           functools.partial(emit, s_k, c_k))
                else:
                    yield emit(s_k, c_k)
            if debug_t:
                print(f"stream-debug chunk@{start}: put+launch "
                      f"{t1 - t0:.3f}s emit {time.perf_counter() - t1:.3f}s",
                      file=sys.stderr)
        for start, choices_k in pending:
            if defer_readback:
                yield (pods[start:min(start + chunk_size, p)],
                       functools.partial(emit, start, choices_k))
            else:
                yield emit(start, choices_k)
        self.last_node_index = np.uint32(counter)

    def _schedule_batch_via_extenders(self, pods: list[api.Pod]
                                      ) -> list[str | None]:
        out: list[str | None] = []
        assumed: list[api.Pod] = []
        try:
            for pod in pods:
                try:
                    dest = self.schedule(pod)
                except FitError:
                    out.append(None)
                    continue
                self.cache.assume_pod(pod, dest)
                assumed.append(pod)
                out.append(dest)
        finally:
            for pod in assumed:
                self.cache.forget_pod(pod)
                pod.node_name = ""
        return out
