"""Guarded device execution: fault taxonomy, recovery policy, and the
post-solve sanity gate.

Every solve site (one-shot, stream chunk, joint, single-pod, preemption
victim kernel) runs inside this layer so an accelerator fault is a
POLICY DECISION instead of a stalled drain loop:

* **Classification.**  ``classify()`` buckets a device exception into
  the four-fault taxonomy — ``oom`` (HBM ``RESOURCE_EXHAUSTED``),
  ``compile`` (XLA compilation failure), ``lost`` (device in an error
  state / runtime gone), or None (not a device fault: re-raised
  untouched so real bugs keep crashing loudly).  Classified faults
  count in ``scheduler_device_faults_total{kind=}`` and re-raise as
  ``DeviceFault`` for the drain pipeline's recovery ladder.

* **Recovery ladder** (``recover()``): OOM evicts the resident cluster
  arrays and bisects the batch onto the NEXT SMALLER pre-warmed bucket
  (never an unwarmed shape — the cap walks ``effective_ladder()``
  downward); repeated faults of any kind, or a single ``lost``, trip a
  circuit breaker into the HOST fallback engine
  (``engine/hostsolver.py``), with periodic probe solves re-promoting
  back to the device once it answers again.  A ladder that exhausts its
  rounds requeues the batch through the pipeline's crash handler —
  never drops pods, never binds garbage.

* **Sanity gate** (``checked_readback``): every assignment vector read
  back from the device is validated before anything binds — integral
  dtype, no NaN/inf, indices in ``[-1, n_nodes)``, live-mask respected
  (padded rows place nothing), and a host spot-check that sampled
  placed pods' requests fit their chosen node's total allocatable.  A
  failed gate classifies as ``corrupt`` and requeues the batch; the
  pod keys of a rejected batch are remembered so the commit path can
  refuse them outright (``scheduler_sanity_rejected_binds_total`` — a
  defense-in-depth counter that must stay 0).

* **HBM watermark** (``KT_HBM_WATERMARK`` bytes): a PROACTIVE cap —
  when the live-HBM gauge crosses it, bucket growth is capped at the
  ladder floor (and the resident arrays evicted once) BEFORE the
  allocator ever throws, counted in
  ``scheduler_hbm_watermark_trips_total``.

Fault injection for all of this is ``chaos/device.py``; the guard is
the ONLY consumer, so un-guarded paths (the explain pass, benches) are
never chaos'd.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Callable, Iterator, Optional

import numpy as np

from kubernetes_tpu.chaos import device as chaos_device
from kubernetes_tpu.utils import knobs, locktrace, metrics
from kubernetes_tpu.utils.logging import get_logger

log = get_logger("guard")

KIND_OOM = "oom"
KIND_COMPILE = "compile"
KIND_LOST = "lost"
KIND_CORRUPT = "corrupt"

# Substring → kind, checked in order: device-lost shapes first because a
# dying runtime often wraps its status in INTERNAL like compile failures.
_PATTERNS = (
    ("RESOURCE_EXHAUSTED", KIND_OOM),
    ("Out of memory", KIND_OOM),
    ("OOM ", KIND_OOM),
    ("DEVICE_LOST", KIND_LOST),
    ("device is in an error state", KIND_LOST),
    ("unrecoverable error state", KIND_LOST),
    ("Unable to initialize backend", KIND_LOST),
    ("FAILED_PRECONDITION", KIND_LOST),
    ("compilation failed", KIND_COMPILE),
    ("XLA compilation", KIND_COMPILE),
    ("during compilation", KIND_COMPILE),
    ("Mosaic", KIND_COMPILE),
)


def _is_device_error(exc: BaseException) -> bool:
    """Only runtime errors raised by the device stack (jaxlib's
    XlaRuntimeError or the chaos simulation) classify; arbitrary
    Python bugs must keep crashing as themselves."""
    if isinstance(exc, chaos_device.SimulatedDeviceError):
        return True
    name = type(exc).__name__
    if name in ("XlaRuntimeError", "JaxRuntimeError"):
        return True
    mod = type(exc).__module__ or ""
    return isinstance(exc, RuntimeError) and (
        "jaxlib" in mod or "jax" in mod)


def classify(exc: BaseException) -> str | None:
    """The fault taxonomy: oom / compile / lost, or None when the
    exception is not a device fault."""
    if isinstance(exc, DeviceFault):
        return exc.kind
    if not _is_device_error(exc):
        return None
    msg = str(exc)
    for token, kind in _PATTERNS:
        if token in msg:
            return kind
    # A device-stack runtime error with an unknown status: treat as
    # lost — the conservative end of the ladder (host keeps scheduling).
    return KIND_LOST


class DeviceFault(Exception):
    """A classified accelerator fault, carrying the recovery ladder's
    inputs: the fault kind and the solve path it struck."""

    def __init__(self, kind: str, path: str, orig: BaseException | None = None):
        self.kind = kind
        self.path = path
        self.orig = orig
        super().__init__(f"device fault [{kind}] on {path} path: {orig}")


# Recovery actions recover() hands the pipeline.  (There is no
# "requeue" action: ladder exhaustion is the PIPELINE's round bound —
# max_rounds spent -> the last fault re-raises into drain()'s crash
# handler, which requeues.)
ACT_RETRY = "retry"      # re-dispatch the remaining pods unchanged
ACT_BISECT = "bisect"    # re-dispatch chunked at the shrunken bucket cap
ACT_HOST = "host"        # breaker open: re-dispatch on the host engine


class DeviceGuard:
    """Per-engine fault-policy state machine (mode, breaker, bucket cap,
    rejected-batch memory).  Thread-safe: the drain thread, the commit
    worker, and the single-pod path all cross it."""

    def __init__(self, evict_fn: Optional[Callable[[], None]] = None,
                 ladder_fn: Optional[Callable[[], list[int]]] = None):
        self.enabled = knobs.get_bool("KT_GUARD")
        # Consecutive same-kind faults before the breaker trips to host.
        self.breaker_threshold = knobs.get_int("KT_GUARD_BREAKER")
        # Seconds between device probe solves while the breaker is open.
        self.probe_period_s = knobs.get_float("KT_GUARD_PROBE_S")
        # Bound on recovery rounds per drain (each round re-solves only
        # the still-uncommitted pods, so progress is monotone anyway).
        self.max_rounds = knobs.get_int("KT_GUARD_ROUNDS")
        # Device-healthy drains before a bisected bucket cap resets.
        self.cap_reset_streak = knobs.get_int("KT_GUARD_CAP_RESET")
        # Proactive HBM ceiling in bytes (0 = off).
        self.hbm_watermark = knobs.get_int("KT_HBM_WATERMARK")
        self.evict_fn = evict_fn
        self.ladder_fn = ladder_fn or (lambda: [])
        self._lock = locktrace.make_lock("engine.DeviceGuard")
        self._mode = "device"
        self._consecutive: dict[str, int] = {}
        self._bucket_cap: int | None = None
        self._success_streak = 0
        self._opened_at = 0.0
        self._host_mode_s = 0.0   # accumulated seconds spent in host mode
        self._last_probe = 0.0
        self._probing = False
        self._wm_active = False
        self._suppress = False
        self._last_fault: dict | None = None
        self._rejected_keys: set[str] = set()
        self.gate_rejects = 0
        if self.enabled:
            metrics.ENGINE_MODE.set(0.0)

    # -- mode / breaker ---------------------------------------------------

    @property
    def mode(self) -> str:
        return self._mode

    def solve_mode(self) -> str:
        """Routing decision for the next drain: ``device``, ``host``,
        or ``probe`` (breaker open but a probe is due — attempt the
        device; failure falls back to host without re-counting)."""
        with self._lock:
            if self._mode == "device":
                return "device"
            now = time.monotonic()
            if now - self._last_probe >= self.probe_period_s:
                self._last_probe = now
                self._probing = True
                return "probe"
            return "host"

    def note_success(self, probe: bool = False) -> None:
        """A device solve completed and passed the gate: close the
        breaker if this was a probe, and walk the bucket cap back up
        after a healthy streak."""
        with self._lock:
            self._consecutive.clear()
            if probe and self._mode == "host":
                self._host_mode_s += time.monotonic() - self._opened_at
                self._mode = "device"
                self._probing = False
                metrics.ENGINE_MODE.set(0.0)
                log.info("device probe succeeded; breaker closed, "
                         "engine re-promoted to device mode")
            self._success_streak += 1
            if self._bucket_cap is not None and \
                    self._success_streak >= self.cap_reset_streak:
                log.info("device healthy for %d drains; lifting bisect "
                         "cap %d", self._success_streak, self._bucket_cap)
                self._bucket_cap = None

    def _trip(self, kind: str) -> None:
        # Called under self._lock.
        if self._mode != "host":
            self._mode = "host"
            self._opened_at = time.monotonic()
            self._last_probe = self._opened_at
            metrics.ENGINE_MODE.set(1.0)
            log.warning("device breaker OPEN after %s fault(s); engine "
                        "falling back to the host solver (probe every "
                        "%.1fs)", kind, self.probe_period_s)

    def recover(self, fault: DeviceFault, can_bisect: bool = True) -> str:
        """The bounded policy ladder: map a classified fault to the
        pipeline's next action.  OOM walks the pre-warmed bucket ladder
        downward (after evicting the resident arrays); repeated faults
        of one kind, or any ``lost``, trip the breaker to host."""
        with self._lock:
            self._success_streak = 0
            n = self._consecutive.get(fault.kind, 0) + 1
            self._consecutive[fault.kind] = n
            if self._probing:
                # A failed probe never re-escalates: stay on host,
                # reset the probe clock.
                self._probing = False
                self._last_probe = time.monotonic()
                self._trip(fault.kind)
                return ACT_HOST
            if fault.kind == KIND_LOST or n >= self.breaker_threshold:
                # SOLVE_FALLBACKS{mode=host} counts at the execution
                # sites (schedule_batch_host / _schedule_host), not here.
                self._trip(fault.kind)
                return ACT_HOST
            if fault.kind == KIND_OOM:
                self._evict_locked()
                if can_bisect and self._shrink_cap_locked():
                    metrics.SOLVE_FALLBACKS.labels(mode="bisect").inc()
                    return ACT_BISECT
                return ACT_RETRY  # at the ladder floor: evicted, retry
            # compile / corrupt under the threshold: plain retry (the
            # every-Nth chaos shapes and transient XLA hiccups clear).
            return ACT_RETRY

    def _evict_locked(self) -> None:
        if self.evict_fn is not None:
            try:
                self.evict_fn()
            except Exception:  # noqa: BLE001 — eviction is best-effort
                log.exception("resident-array eviction failed")

    def _shrink_cap_locked(self) -> bool:
        """Walk the bucket cap one rung down the PRE-WARMED ladder;
        False when already at (or below) the floor.  The cap can only
        ever hold a ladder value — bisection never mints a shape the
        prewarm didn't trace."""
        ladder = sorted(self.ladder_fn() or [])
        if not ladder:
            return False
        current = self._bucket_cap if self._bucket_cap is not None \
            else ladder[-1]
        smaller = [b for b in ladder if b < current]
        if not smaller:
            return False
        self._bucket_cap = smaller[-1]
        log.warning("OOM: resident arrays evicted, batch bisected onto "
                    "the %d-pod pre-warmed bucket", self._bucket_cap)
        return True

    def bucket_cap(self) -> int | None:
        """The ladder bucket device drains are currently capped at:
        the bisect cap, tightened to the ladder FLOOR while the HBM
        watermark is tripped."""
        with self._lock:
            cap = self._bucket_cap
        wm = self._watermark_cap()
        if wm is not None:
            cap = wm if cap is None else min(cap, wm)
        return cap

    def _watermark_cap(self) -> int | None:
        if not self.hbm_watermark:
            return None
        from kubernetes_tpu.engine import devicestats
        live = devicestats.hbm_live_bytes()
        with self._lock:
            if live <= self.hbm_watermark:
                self._wm_active = False
                return None
            if not self._wm_active:
                self._wm_active = True
                metrics.HBM_WATERMARK_TRIPS.inc()
                self._evict_locked()
                log.warning("HBM watermark tripped (%d > %d bytes): "
                            "bucket growth capped at the ladder floor",
                            live, self.hbm_watermark)
        ladder = sorted(self.ladder_fn() or [])
        return ladder[0] if ladder else None

    # -- the solve-site wrapper -------------------------------------------

    @contextlib.contextmanager
    def suppressed(self) -> Iterator[None]:
        """Turn chaos injection off for a scope.  The prewarm ladder
        runs the SAME solve sites as live drains but has no recovery
        ladder above it — a KT_CHAOS_DEVICE cadence firing mid-warmup
        would fail startup instead of exercising recovery, so
        ``Scheduler.prewarm()`` traces under this.  Real device faults
        still propagate (as their original exceptions)."""
        prev = self._suppress
        self._suppress = True
        try:
            yield
        finally:
            self._suppress = prev

    @contextlib.contextmanager
    def watch(self, path: str, inject: bool = True) -> Iterator[None]:
        """Wrap one device interaction: chaos injection on entry (only
        at the solve LAUNCH sites — ``inject=False`` marks
        compile/readback wrappers that classify real faults but don't
        consume the injector's every-Nth cadence), fault classification
        on the way out.  Classified faults count and re-raise as
        ``DeviceFault``; everything else passes through untouched."""
        if not self.enabled or self._suppress:
            yield
            return
        chaos = chaos_device.active()
        if chaos is not None and inject:
            try:
                chaos.maybe_fail(path)
            except chaos_device.SimulatedDeviceError as exc:
                kind = classify(exc) or KIND_LOST
                self._record_fault(kind, path)
                raise DeviceFault(kind, path, exc) from exc
        try:
            yield
        except DeviceFault:
            raise
        except Exception as exc:  # noqa: BLE001 — classify, then decide
            kind = classify(exc)
            if kind is None:
                raise
            self._record_fault(kind, path)
            raise DeviceFault(kind, path, exc) from exc

    def _record_fault(self, kind: str, path: str) -> None:
        metrics.DEVICE_FAULTS.labels(kind=kind).inc()
        with self._lock:
            self._last_fault = {"kind": kind, "path": path,
                                "at": time.time()}
        log.warning("device fault [%s] on %s path", kind, path)

    # -- the post-solve sanity gate ---------------------------------------

    def checked_readback(self, path: str, rows: np.ndarray, n_nodes: int,
                         live: Optional[np.ndarray] = None,
                         alloc: Optional[np.ndarray] = None,
                         requests: Optional[np.ndarray] = None,
                         keys_fn: Optional[Callable[[], list[str]]] = None,
                         spot_k: int = 16) -> np.ndarray:
        """Validate an assignment readback before anything commits.

        ``rows`` is the choices vector (or the packed vector's choices
        slice); ``live`` the real-row mask when the batch was padded;
        ``alloc``/``requests`` the host-side [N,4]/[P,4] arrays for the
        capacity spot-check; ``keys_fn`` lazily names the batch's pod
        keys so a rejected batch is remembered (and a later clean solve
        of the same pods forgets it).  Returns the int32 choices;
        raises ``DeviceFault('corrupt')`` on any violation."""
        if not self.enabled:
            return np.asarray(rows)
        chaos = chaos_device.active()
        if chaos is not None and path != "host" and not self._suppress:
            rows = chaos.maybe_corrupt(path, rows)
        arr = np.asarray(rows)
        problem = None
        if arr.dtype.kind == "f":
            if not np.isfinite(arr).all():
                problem = "NaN/inf in readback"
            elif arr.size and not (arr == np.trunc(arr)).all():
                problem = "non-integral assignment indices"
        if problem is None:
            choices = arr.astype(np.int64, copy=False)
            if choices.size and (int(choices.min(initial=0)) < -1 or
                                 int(choices.max(initial=-1)) >= n_nodes):
                problem = (f"assignment index out of range "
                           f"[-1, {n_nodes})")
            elif live is not None:
                dead = ~np.asarray(live, bool)
                if choices.size and (choices[dead[:len(choices)]]
                                     != -1).any():
                    problem = "padded (dead) row received a placement"
        if problem is None and alloc is not None and requests is not None:
            # Host spot-check on sampled rows: a placed pod's request can
            # never exceed its node's TOTAL allocatable — a necessary
            # condition that is cheap against batch-start host arrays
            # (in-batch occupancy is the scan's job, not the gate's).
            placed = np.nonzero(choices >= 0)[0]
            if placed.size:
                step = max(placed.size // spot_k, 1)
                sample = placed[::step][:spot_k]
                req = np.asarray(requests)[sample, :3]
                cap = np.asarray(alloc)[choices[sample], :3]
                if (req > cap).any():
                    problem = ("sampled placement exceeds the node's "
                               "total allocatable")
        if problem is not None:
            self.gate_rejects += 1
            metrics.GATE_REJECTS.inc()
            if keys_fn is not None:
                try:
                    with self._lock:
                        self._rejected_keys.update(keys_fn())
                except Exception:  # noqa: BLE001 — bookkeeping only
                    pass
            self._record_fault(KIND_CORRUPT, path)
            raise DeviceFault(KIND_CORRUPT, path,
                              RuntimeError(f"sanity gate: {problem}"))
        if keys_fn is not None and self._rejected_keys:
            with self._lock:
                if self._rejected_keys:
                    self._rejected_keys.difference_update(keys_fn())
        return choices.astype(np.int32, copy=False)

    def checked_scores(self, path: str, feasible: object,
                       scores: object) -> tuple:
        """The single-pod gate: evaluation planes must be finite (a NaN
        score would argmax into garbage)."""
        if not self.enabled:
            return feasible, scores
        chaos = chaos_device.active()
        if chaos is not None and path != "host" and not self._suppress:
            scores = chaos.maybe_corrupt(path, scores)
        arr = np.asarray(scores)
        if not np.isfinite(arr).all():
            self.gate_rejects += 1
            metrics.GATE_REJECTS.inc()
            self._record_fault(KIND_CORRUPT, path)
            raise DeviceFault(KIND_CORRUPT, path,
                              RuntimeError("sanity gate: NaN/inf score "
                                           "plane"))
        return np.asarray(feasible), arr

    # -- rejected-batch memory (defense in depth at the bind path) --------

    def has_rejections(self) -> bool:
        return bool(self._rejected_keys)

    def filter_rejected(self, placed: list) -> tuple[list, list]:
        """Split (pod, dest) pairs into (clean, rejected): a pod whose
        last solve failed the gate and was never cleanly re-solved must
        NOT bind.  Structurally unreachable (the gate raises before
        placements exist) — this is the ratcheted backstop, and every
        hit counts in ``scheduler_sanity_rejected_binds_total``."""
        if not self._rejected_keys:
            return placed, []
        with self._lock:
            rejected = [(pod, dest) for pod, dest in placed
                        if pod.key in self._rejected_keys]
        if rejected:
            metrics.GATE_REJECTED_BINDS.inc(len(rejected))
            log.error("refused to bind %d pod(s) from a sanity-gate-"
                      "rejected batch", len(rejected))
            drop = {id(p) for p, _ in rejected}
            placed = [pd for pd in placed if id(pd[0]) not in drop]
        return placed, rejected

    # -- reporting ---------------------------------------------------------

    def host_mode_seconds(self) -> float:
        with self._lock:
            extra = time.monotonic() - self._opened_at \
                if self._mode == "host" else 0.0
            return self._host_mode_s + extra

    def report(self) -> dict:
        """The /debug/vars + soak-artifact payload."""
        with self._lock:
            return {
                "enabled": self.enabled,
                "mode": self._mode,
                "bucketCap": self._bucket_cap,
                "lastFault": self._last_fault,
                "gateRejects": self.gate_rejects,
                "hbmWatermark": self.hbm_watermark,
                "hostModeSeconds": round(
                    self._host_mode_s +
                    (time.monotonic() - self._opened_at
                     if self._mode == "host" else 0.0), 2),
            }
