"""The device solver: policy -> jitted mask/score/assign computation.

Two entry points:

``evaluate``
    One-shot batched evaluation of every (pod, node) pair against the
    *current* cluster state — the tensor equivalent of running the
    reference's findNodesThatFit + PrioritizeNodes once per pod
    (generic_scheduler.go:145-314), for the whole batch at once.  Used by the
    extender Filter/Prioritize verbs and as the building block of the solvers.

``solve_sequential``
    Greedy sequential assignment under ``lax.scan``: pods are placed in queue
    order and every placement updates device-resident aggregates (requested
    resources, host ports, volume mounts, spreading counts) before the next
    pod is scored — bit-for-bit the visibility the reference's scheduler gets
    through its assumed-pod cache (scheduler.go:116-120, cache.go:107).  The
    expensive O(P*N*V) contractions are hoisted out of the scan (they are
    placement-invariant); only O(N) resource math recomputes per step.

Both are pure jit-compatible functions of arrays; the node axis may be
sharded across a mesh (see kubernetes_tpu.parallel).
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.api.policy import Policy, expand_predicates
from kubernetes_tpu.features.batch import PodBatch
from kubernetes_tpu.features.compiler import (FeatureSpace, NodeAggregates,
                                              NodeTensors, RES_CPU, RES_MEM,
                                              RES_PODS)
from kubernetes_tpu.ops import combine, predicates as pr, priorities as prio

# Predicates whose masks do not depend on in-batch placements.
STATIC_PREDICATES = ("PodFitsHost", "MatchNodeSelector", "HostName",
                     "PodToleratesNodeTaints", "CheckNodeMemoryPressure",
                     "CheckNodeDiskPressure", "NewNodeLabelPredicate")
# Implemented dynamic predicates.
DYNAMIC_PREDICATES = ("PodFitsResources", "PodFitsHostPorts", "PodFitsPorts",
                      "NoDiskConflict")
# Recognized but not yet tensorized: evaluated as pass-through (tracked so
# callers can surface the gap).  NoVolumeZoneConflict / MaxPD need PV/PVC
# listers; MatchInterPodAffinity lands with the affinity kernels.
PASSTHROUGH_PREDICATES = ("NoVolumeZoneConflict", "MaxEBSVolumeCount",
                          "MaxGCEPDVolumeCount", "MatchInterPodAffinity",
                          "ServiceAffinity")

STATIC_PRIORITIES = ("NodeAffinityPriority", "TaintTolerationPriority",
                     "ImageLocalityPriority", "NodePreferAvoidPodsPriority",
                     "EqualPriority", "NodeLabelPriority")
DYNAMIC_PRIORITIES = ("LeastRequestedPriority", "MostRequestedPriority",
                      "BalancedResourceAllocation", "SelectorSpreadPriority",
                      "ServiceSpreadingPriority")
PASSTHROUGH_PRIORITIES = ("InterPodAffinityPriority", "ServiceAntiAffinityPriority")


class DeviceBatch(NamedTuple):
    """PodBatch as device arrays (order mirrors features.batch.PodBatch)."""

    request: jnp.ndarray
    zero_request: jnp.ndarray
    nonzero: jnp.ndarray
    best_effort: jnp.ndarray
    host_idx: jnp.ndarray
    ports: jnp.ndarray
    vol_ro: jnp.ndarray
    vol_rw: jnp.ndarray
    tol_nosched: jnp.ndarray
    tol_prefer: jnp.ndarray
    has_tolerations: jnp.ndarray
    images: jnp.ndarray
    sel_group: jnp.ndarray
    sel_required: jnp.ndarray
    sel_pref_counts: jnp.ndarray
    spread_group: jnp.ndarray
    spread_node_counts: jnp.ndarray
    spread_zone_counts: jnp.ndarray
    spread_has_zones: jnp.ndarray
    spread_incr: jnp.ndarray
    node_zone_id: jnp.ndarray
    avoid_mask: jnp.ndarray


class DeviceCluster(NamedTuple):
    schedulable: jnp.ndarray    # [N] bool — getNodeConditionPredicate
    alloc: jnp.ndarray          # [N,4] int32
    requested: jnp.ndarray      # [N,4] int32
    nonzero: jnp.ndarray        # [N,2] int32
    ports_used: jnp.ndarray     # [N,C] bool
    vol_any: jnp.ndarray        # [N,W] bool
    vol_rw: jnp.ndarray         # [N,W] bool
    taints_nosched: jnp.ndarray  # [N,T] bool
    taints_prefer: jnp.ndarray  # [N,T] bool
    has_taints: jnp.ndarray     # [N] bool — any taint incl. PreferNoSchedule
    mem_pressure: jnp.ndarray   # [N] bool
    disk_pressure: jnp.ndarray  # [N] bool
    image_kib: jnp.ndarray      # [N,I] int32


def _pad_cols(a: np.ndarray, width: int) -> np.ndarray:
    if a.shape[1] == width:
        return a
    out = np.zeros((a.shape[0], width), a.dtype)
    out[:, : a.shape[1]] = a
    return out


def device_batch(b: PodBatch) -> DeviceBatch:
    return DeviceBatch(*[jnp.asarray(getattr(b, f)) for f in DeviceBatch._fields])


def device_cluster(nt: NodeTensors, agg: NodeAggregates,
                   space: FeatureSpace) -> DeviceCluster:
    """Assemble device cluster state, padding aggregate columns to current
    vocabulary capacities (pods may have interned new ports/volumes)."""
    return DeviceCluster(
        schedulable=jnp.asarray(nt.schedulable),
        alloc=jnp.asarray(nt.alloc),
        requested=jnp.asarray(agg.requested),
        nonzero=jnp.asarray(agg.nonzero),
        ports_used=jnp.asarray(_pad_cols(agg.ports_used, space.ports.capacity)),
        vol_any=jnp.asarray(_pad_cols(agg.vol_any, space.volumes.capacity)),
        vol_rw=jnp.asarray(_pad_cols(agg.vol_rw, space.volumes.capacity)),
        taints_nosched=jnp.asarray(nt.taints_nosched),
        taints_prefer=jnp.asarray(nt.taints_prefer),
        has_taints=jnp.asarray(nt.taints_nosched.any(1) | nt.taints_prefer.any(1)),
        mem_pressure=jnp.asarray(nt.mem_pressure),
        disk_pressure=jnp.asarray(nt.disk_pressure),
        image_kib=jnp.asarray(_pad_cols(nt.image_kib, space.images.capacity)))


def _predicate_mask(name: str, b: DeviceBatch, c: DeviceCluster,
                    n_nodes: int, extra: dict) -> jnp.ndarray:
    p = b.request.shape[0]
    if name in ("PodFitsHost", "HostName"):
        return pr.pod_fits_host(b.host_idx, n_nodes)
    if name == "MatchNodeSelector":
        return pr.pod_selector_matches(b.sel_group, b.sel_required)
    if name == "PodToleratesNodeTaints":
        return pr.pod_tolerates_node_taints(b.tol_nosched, b.has_tolerations,
                                            c.taints_nosched, c.has_taints)
    if name == "CheckNodeMemoryPressure":
        return pr.check_node_memory_pressure(b.best_effort, c.mem_pressure)
    if name == "CheckNodeDiskPressure":
        return pr.check_node_disk_pressure(p, c.disk_pressure)
    if name == "NewNodeLabelPredicate":
        return pr.node_label_presence(p, extra["node_label_row"])
    if name == "PodFitsResources":
        return pr.pod_fits_resources(b.request, b.zero_request, c.alloc,
                                     c.requested)
    if name in ("PodFitsHostPorts", "PodFitsPorts"):
        return pr.pod_fits_host_ports(b.ports, c.ports_used)
    if name == "NoDiskConflict":
        return pr.no_disk_conflict(b.vol_rw, b.vol_ro, c.vol_any, c.vol_rw)
    if name in PASSTHROUGH_PREDICATES:
        return jnp.ones((p, n_nodes), bool)
    raise KeyError(f"unknown predicate {name!r}")


def _priority_plane(name: str, b: DeviceBatch, c: DeviceCluster,
                    n_nodes: int, extra: dict) -> jnp.ndarray:
    p = b.request.shape[0]
    if name == "LeastRequestedPriority":
        return prio.least_requested(b.nonzero, c.nonzero, c.alloc)
    if name == "MostRequestedPriority":
        return prio.most_requested(b.nonzero, c.nonzero, c.alloc)
    if name == "BalancedResourceAllocation":
        return prio.balanced_resource_allocation(b.nonzero, c.nonzero, c.alloc)
    if name == "NodeAffinityPriority":
        return prio.node_affinity(b.sel_group, b.sel_pref_counts)
    if name == "TaintTolerationPriority":
        return prio.taint_toleration(b.tol_prefer, c.taints_prefer)
    if name == "ImageLocalityPriority":
        return prio.image_locality(b.images, c.image_kib)
    if name == "NodePreferAvoidPodsPriority":
        return prio.node_prefer_avoid(b.avoid_mask)
    if name in ("SelectorSpreadPriority", "ServiceSpreadingPriority"):
        return prio.selector_spread(b.spread_group, b.spread_node_counts,
                                    b.spread_zone_counts, b.spread_has_zones,
                                    b.node_zone_id)
    if name == "NodeLabelPriority":
        return prio.node_label(p, extra["node_label_prio_row"])
    if name == "EqualPriority":
        return prio.equal_priority(p, n_nodes)
    if name in PASSTHROUGH_PRIORITIES:
        return jnp.zeros((p, n_nodes), jnp.float32)
    raise KeyError(f"unknown priority {name!r}")


class Solver:
    """Compiles a Policy into jitted evaluate / sequential-solve callables."""

    def __init__(self, policy: Policy):
        self.policy = policy
        self.predicate_names = tuple(p.name for p in expand_predicates(policy))
        self.priority_specs = tuple((s.name, s.weight) for s in policy.priorities
                                    if s.weight != 0)
        self.passthrough = tuple(n for n in self.predicate_names
                                 if n in PASSTHROUGH_PREDICATES)

    # -- one-shot batched evaluation ------------------------------------

    @functools.partial(jax.jit, static_argnums=(0,))
    def masks(self, b: DeviceBatch, c: DeviceCluster) -> dict[str, jnp.ndarray]:
        """Per-predicate [P,N] masks (for Filter verbs / failure reporting)."""
        n = c.alloc.shape[0]
        return {name: _predicate_mask(name, b, c, n, {})
                for name in self.predicate_names}

    @functools.partial(jax.jit, static_argnums=(0,))
    def evaluate(self, b: DeviceBatch, c: DeviceCluster
                 ) -> tuple[jnp.ndarray, jnp.ndarray]:
        """(feasible [P,N] bool, scores [P,N] f32) against current state."""
        n = c.alloc.shape[0]
        # Unready nodes are filtered before scheduling (factory.go:436-462).
        feasible = jnp.broadcast_to(c.schedulable[None, :],
                                    (b.request.shape[0], n))
        for name in self.predicate_names:
            feasible &= _predicate_mask(name, b, c, n, {})
        scores = jnp.zeros((b.request.shape[0], n), jnp.float32)
        for name, weight in self.priority_specs:
            scores += jnp.float32(weight) * _priority_plane(name, b, c, n, {})
        return feasible, scores

    # -- sequential greedy solve ----------------------------------------

    @functools.partial(jax.jit, static_argnums=(0,))
    def solve_sequential(self, b: DeviceBatch, c: DeviceCluster,
                         last_node_index: jnp.ndarray
                         ) -> tuple[jnp.ndarray, jnp.ndarray, DeviceCluster]:
        """Greedy in-order placement with on-device state updates.

        Returns (choices [P] int32 node index or -1, new last_node_index,
        updated cluster aggregates).
        """
        n = c.alloc.shape[0]
        p = b.request.shape[0]

        # Hoist placement-invariant work: static predicate masks and static
        # priority planes are the big vocab contractions.
        static_mask = jnp.broadcast_to(c.schedulable[None, :], (p, n))
        for name in self.predicate_names:
            if name not in DYNAMIC_PREDICATES:
                static_mask &= _predicate_mask(name, b, c, n, {})
        # Dynamic predicates run inside the scan, but only those the policy
        # actually configures (evaluate() and the reference honor the policy).
        use_resources = "PodFitsResources" in self.predicate_names
        use_ports = any(nm in self.predicate_names
                        for nm in ("PodFitsHostPorts", "PodFitsPorts"))
        use_volumes = "NoDiskConflict" in self.predicate_names
        static_score = jnp.zeros((p, n), jnp.float32)
        dynamic_prios = []
        for name, weight in self.priority_specs:
            if name in DYNAMIC_PRIORITIES:
                dynamic_prios.append((name, weight))
            else:
                static_score += jnp.float32(weight) * \
                    _priority_plane(name, b, c, n, {})
        dynamic_prios = tuple(dynamic_prios)

        fits_pods_alloc = c.alloc[:, RES_PODS]
        zone_ids = b.node_zone_id  # [N]

        def step(state, xs):
            (requested, nonzero, ports_used, vol_any, vol_rw,
             sp_node, sp_zone, counter) = state
            (req_i, zero_i, nz_i, ports_i, vro_i, vrw_i, smask_i, sscore_i,
             sgroup_i, incr_i) = xs

            # Dynamic predicates on current aggregates (predicates.go:444-485,
            # :721-741, :100-153) — O(N) per step.
            feasible = smask_i
            if use_resources:
                fits_pods = (requested[:, RES_PODS] + 1) <= fits_pods_alloc
                free = c.alloc[:, :3] - requested[:, :3]
                fits_res = jnp.all(req_i[None, :3] <= free, axis=-1)
                feasible &= fits_pods & (zero_i | fits_res)
            if use_ports:
                port_conflict = jnp.einsum(
                    "c,nc->n", ports_i.astype(jnp.float32),
                    ports_used.astype(jnp.float32)) > 0
                feasible &= ~port_conflict
            if use_volumes:
                vol_conflict = (
                    jnp.einsum("w,nw->n", vrw_i.astype(jnp.float32),
                               vol_any.astype(jnp.float32)) +
                    jnp.einsum("w,nw->n", vro_i.astype(jnp.float32),
                               vol_rw.astype(jnp.float32))) > 0
                feasible &= ~vol_conflict

            # Dynamic priorities against current aggregates.
            score = sscore_i
            for name, weight in dynamic_prios:
                w = jnp.float32(weight)
                if name == "LeastRequestedPriority":
                    score = score + w * prio.least_requested(
                        nz_i[None], nonzero, c.alloc)[0]
                elif name == "MostRequestedPriority":
                    score = score + w * prio.most_requested(
                        nz_i[None], nonzero, c.alloc)[0]
                elif name == "BalancedResourceAllocation":
                    score = score + w * prio.balanced_resource_allocation(
                        nz_i[None], nonzero, c.alloc)[0]
                elif name in ("SelectorSpreadPriority", "ServiceSpreadingPriority"):
                    score = score + w * prio.selector_spread(
                        sgroup_i[None], sp_node, sp_zone,
                        jnp.asarray(b.spread_has_zones), zone_ids)[0]

            # selectHost (generic_scheduler.go:124-141): round-robin among
            # max-score feasible nodes; counter bumps only on success.
            neg = jnp.float32(-jnp.inf)
            masked = jnp.where(feasible, score, neg)
            max_score = jnp.max(masked)
            any_feasible = jnp.any(feasible)
            ties = feasible & (masked == max_score)
            n_ties = jnp.maximum(jnp.sum(ties), 1)
            ix = (counter % n_ties.astype(jnp.uint32)).astype(jnp.int32)
            rank = jnp.cumsum(ties.astype(jnp.int32)) - 1
            choice = jnp.argmax(ties & (rank == ix)).astype(jnp.int32)
            choice = jnp.where(any_feasible, choice, -1)

            # Commit: the batched AssumePod (cache.go:107).
            placed = choice >= 0
            onehot = (jnp.arange(n, dtype=jnp.int32) == choice) & placed
            oh_i = onehot.astype(jnp.int32)
            oh_f = onehot.astype(jnp.float32)
            requested = requested + oh_i[:, None] * req_i[None, :]
            nonzero = nonzero + oh_i[:, None] * nz_i[None, :]
            ports_used = ports_used | (onehot[:, None] & ports_i[None, :])
            vol_any = vol_any | (onehot[:, None] & (vrw_i | vro_i)[None, :])
            vol_rw = vol_rw | (onehot[:, None] & vrw_i[None, :])
            sp_node = sp_node + incr_i.astype(jnp.float32)[:, None] * oh_f[None, :]
            zid = jnp.where(placed, zone_ids[jnp.clip(choice, 0)], -1)
            zoh = (jnp.arange(sp_zone.shape[1], dtype=jnp.int32) == zid)
            sp_zone = sp_zone + incr_i.astype(jnp.float32)[:, None] * \
                zoh.astype(jnp.float32)[None, :]
            counter = counter + jnp.where(any_feasible, jnp.uint32(1),
                                          jnp.uint32(0))
            return (requested, nonzero, ports_used, vol_any, vol_rw,
                    sp_node, sp_zone, counter), choice

        init = (c.requested, c.nonzero, c.ports_used, c.vol_any, c.vol_rw,
                jnp.asarray(b.spread_node_counts),
                jnp.asarray(b.spread_zone_counts), last_node_index)
        xs = (b.request, b.zero_request, b.nonzero, b.ports, b.vol_ro,
              b.vol_rw, static_mask, static_score, b.spread_group,
              b.spread_incr)
        (requested, nonzero, ports_used, vol_any, vol_rw, _, _, counter), \
            choices = jax.lax.scan(step, init, xs)
        new_c = c._replace(requested=requested, nonzero=nonzero,
                           ports_used=ports_used, vol_any=vol_any,
                           vol_rw=vol_rw)
        return choices, counter, new_c
