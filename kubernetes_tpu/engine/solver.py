"""The device solver: policy -> jitted mask/score/assign computation.

Two entry points:

``evaluate``
    One-shot batched evaluation of every (pod, node) pair against the
    *current* cluster state — the tensor equivalent of running the
    reference's findNodesThatFit + PrioritizeNodes once per pod
    (generic_scheduler.go:145-314), for the whole batch at once.  Used by the
    extender Filter/Prioritize verbs and as the building block of the solvers.

``solve_sequential``
    Greedy sequential assignment under ``lax.scan``: pods are placed in queue
    order and every placement updates device-resident aggregates (requested
    resources, host ports, volume mounts, spreading counts) before the next
    pod is scored — bit-for-bit the visibility the reference's scheduler gets
    through its assumed-pod cache (scheduler.go:116-120, cache.go:107).  The
    expensive O(P*N*V) contractions are hoisted out of the scan (they are
    placement-invariant); only O(N) resource math recomputes per step.

Both are pure jit-compatible functions of arrays; the node axis may be
sharded across a mesh (see kubernetes_tpu.parallel).
"""

from __future__ import annotations

import functools
import os
import threading
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.utils import knobs
from kubernetes_tpu.engine import fused as fused_mod
from kubernetes_tpu.api.policy import (DEFAULT_MAX_EBS_VOLUMES,
                                       DEFAULT_MAX_GCE_PD_VOLUMES, Policy,
                                       canonical_predicate_name,
                                       canonical_priority_name,
                                       expand_predicates)
from kubernetes_tpu.features.affinity import AffinityTensors
from kubernetes_tpu.features.batch import PodBatch
from kubernetes_tpu.features.compiler import (FeatureSpace, NodeAggregates,
                                              NodeTensors, RES_CPU, RES_MEM,
                                              RES_PODS)
from kubernetes_tpu.ops import (combine, interpod, predicates as pr,
                                priorities as prio)

# Predicates whose masks do not depend on in-batch placements.
STATIC_PREDICATES = ("PodFitsHost", "MatchNodeSelector", "HostName",
                     "PodToleratesNodeTaints", "CheckNodeMemoryPressure",
                     "CheckNodeDiskPressure", "NewNodeLabelPredicate",
                     "NoVolumeZoneConflict", "ServiceAffinity")
# Implemented dynamic predicates (masks read in-batch placement state).
DYNAMIC_PREDICATES = ("PodFitsResources", "PodFitsHostPorts", "PodFitsPorts",
                      "NoDiskConflict", "MatchInterPodAffinity",
                      "MaxEBSVolumeCount", "MaxGCEPDVolumeCount")
PASSTHROUGH_PREDICATES = ()

STATIC_PRIORITIES = ("NodeAffinityPriority", "TaintTolerationPriority",
                     "ImageLocalityPriority", "NodePreferAvoidPodsPriority",
                     "EqualPriority", "NodeLabelPriority")
DYNAMIC_PRIORITIES = ("LeastRequestedPriority", "MostRequestedPriority",
                      "BalancedResourceAllocation", "SelectorSpreadPriority",
                      "ServiceSpreadingPriority", "InterPodAffinityPriority",
                      "ServiceAntiAffinityPriority")
PASSTHROUGH_PRIORITIES = ()

# lax.scan unroll for the sequential solve: measured on v5e at 30k x 5k,
# unroll=4 runs the scan ~1.2x faster than unroll=1 (705 -> 605 ms) by
# amortizing loop control and xs slicing.  Compile time scales with the
# factor; 4 is the knee.
SCAN_UNROLL = knobs.get_int("KT_SCAN_UNROLL")
# Fused scan-step default (KT_FUSED; per-Solver override for tests).
FUSED_DEFAULT = knobs.get_bool("KT_FUSED")
# Resident-plane dtype policy: "narrow" = range-gated int16 wire/HBM
# planes (mem columns stay int32), "wide" = the pre-r15 all-int32 form.
FEATURE_DTYPE = knobs.get_str("KT_FEATURE_DTYPE")
# Cap on distinct nonzero-request templates factored out of the scan.
DYN_TEMPLATE_CAP = knobs.get_int("KT_DYN_TEMPLATES")


class DeviceAffinity(NamedTuple):
    """AffinityTensors' array fields as device arrays (features/affinity.py
    documents each; host-only fields n_default/has_any are dropped)."""

    node_dom: jnp.ndarray
    match_key: jnp.ndarray
    match_cnt: jnp.ndarray
    match_total: jnp.ndarray
    match_src: jnp.ndarray
    aff_need: jnp.ndarray
    aff_self: jnp.ndarray
    anti_need: jnp.ndarray
    pref_w: jnp.ndarray
    decl_key: jnp.ndarray
    decl_reach: jnp.ndarray
    decl_match: jnp.ndarray
    decl_src: jnp.ndarray
    sym_key: jnp.ndarray
    sym_w: jnp.ndarray
    sym_cnt: jnp.ndarray
    sym_match: jnp.ndarray
    sym_src: jnp.ndarray


class DeviceVolSvc(NamedTuple):
    """VolSvcTensors as device arrays (features/volumes.py documents each)."""

    pd_pod_ebs: jnp.ndarray
    pd_node_ebs: jnp.ndarray
    pd_extra_ebs: jnp.ndarray
    pd_node_extra_ebs: jnp.ndarray
    pd_node_err_ebs: jnp.ndarray
    pd_pod_gce: jnp.ndarray
    pd_node_gce: jnp.ndarray
    pd_extra_gce: jnp.ndarray
    pd_node_extra_gce: jnp.ndarray
    pd_node_err_gce: jnp.ndarray
    vz_group: jnp.ndarray
    vz_mask: jnp.ndarray
    sa_group: jnp.ndarray
    sa_mask: jnp.ndarray
    saa_group: jnp.ndarray
    saa_src: jnp.ndarray
    saa_dom: jnp.ndarray
    saa_labeled: jnp.ndarray
    saa_cnt: jnp.ndarray
    saa_num: jnp.ndarray
    nl_pred_row: jnp.ndarray
    nl_prio_rows: jnp.ndarray


class DeviceBatch(NamedTuple):
    """PodBatch as device arrays (order mirrors features.batch.PodBatch)."""

    request: jnp.ndarray
    zero_request: jnp.ndarray
    nonzero: jnp.ndarray
    best_effort: jnp.ndarray
    host_idx: jnp.ndarray
    ports: jnp.ndarray
    vol_ro: jnp.ndarray
    vol_rw: jnp.ndarray
    tol_nosched: jnp.ndarray
    tol_prefer: jnp.ndarray
    has_tolerations: jnp.ndarray
    images: jnp.ndarray
    sel_group: jnp.ndarray
    sel_required: jnp.ndarray
    sel_pref_counts: jnp.ndarray
    spread_group: jnp.ndarray
    spread_node_counts: jnp.ndarray
    spread_zone_counts: jnp.ndarray
    spread_has_zones: jnp.ndarray
    spread_incr: jnp.ndarray
    node_zone_id: jnp.ndarray
    avoid_group: jnp.ndarray
    avoid_rows: jnp.ndarray
    nz_tmpl_idx: jnp.ndarray
    nz_templates: jnp.ndarray
    aff: DeviceAffinity
    volsvc: DeviceVolSvc


class BatchFlags(NamedTuple):
    """Content-derived specialization for the sequential scan (hashable, a
    static jit argument).  The reference pays only for predicates whose
    inputs exist (e.g. a pod with no ports never walks the port loop,
    predicates.go:727-741); the tensor scan gets the same effect by
    compiling away whole dynamic-state families the batch provably cannot
    touch — a no-port batch keeps ``ports_used`` constant and conflict-free,
    so neither the check nor the state update belongs in the loop body."""

    any_ports: bool
    any_volumes: bool
    any_ebs: bool
    any_gce: bool
    any_affinity_pred: bool   # aff_need/anti_need/decl_match content
    any_affinity_prio: bool   # pref_w/sym content
    any_spread: bool          # spread_incr content (placements move counts)
    any_spread_zones: bool    # some spread group blends zone counts
    any_saa: bool             # saa_src content (placements move peer counts)


def batch_flags(b: "PodBatch | DeviceBatch") -> BatchFlags:
    """Derive BatchFlags from a PodBatch (host numpy — call before
    device transfer; also works on a DeviceBatch at the cost of syncs)."""
    a, vs = b.aff, b.volsvc
    return BatchFlags(
        any_ports=bool(np.asarray(b.ports).any()),
        any_volumes=bool(np.asarray(b.vol_ro).any()
                         or np.asarray(b.vol_rw).any()),
        any_ebs=bool(np.asarray(vs.pd_pod_ebs).any()
                     or np.asarray(vs.pd_extra_ebs).any()),
        any_gce=bool(np.asarray(vs.pd_pod_gce).any()
                     or np.asarray(vs.pd_extra_gce).any()),
        any_affinity_pred=bool(np.asarray(a.aff_need).any()
                               or np.asarray(a.anti_need).any()
                               or np.asarray(a.decl_match).any()),
        any_affinity_prio=bool(np.asarray(a.pref_w).any()
                               or (np.asarray(a.sym_match).any()
                                   and np.asarray(a.sym_w).any())),
        # any_spread is force-on: measured on v5e, a scan whose carried state
        # shrinks to just [N,4]+[N,2] falls out of XLA's fast loop regime
        # (~3.4s vs ~0.75s for 30k steps); keeping the [S,N] spread counts
        # carried (numerically a no-op when spread_incr is all-false) keeps
        # the fast schedule and costs ~5% per step.
        any_spread=True,
        any_spread_zones=bool(np.asarray(b.spread_has_zones).any()
                              or np.asarray(b.spread_zone_counts).any()),
        any_saa=bool(np.asarray(vs.saa_src).any()))


ALL_ON_FLAGS = BatchFlags(*([True] * 9))


class DeviceCluster(NamedTuple):
    schedulable: jnp.ndarray    # [N] bool — getNodeConditionPredicate
    alloc: jnp.ndarray          # [N,4] int32
    requested: jnp.ndarray      # [N,4] int32
    nonzero: jnp.ndarray        # [N,2] int32
    ports_used: jnp.ndarray     # [N,C] bool
    vol_any: jnp.ndarray        # [N,W] bool
    vol_rw: jnp.ndarray         # [N,W] bool
    taints_nosched: jnp.ndarray  # [N,T] bool
    taints_prefer: jnp.ndarray  # [N,T] bool
    has_taints: jnp.ndarray     # [N] bool — any taint incl. PreferNoSchedule
    mem_pressure: jnp.ndarray   # [N] bool
    disk_pressure: jnp.ndarray  # [N] bool
    image_kib: jnp.ndarray      # [N,I] int32
    # Topology tensor (engine/workloads/topology.py): per node, the
    # compact domain id of each interned topology label key (-1 = node
    # lacks the label).  The (nodes x topology_domains) one-hot planes the
    # spread kernels consume expand from these ids on device; the ids ride
    # the same dirty-row scatter protocol as every other cluster column.
    topo_dom: jnp.ndarray       # [N,K] int32


class NarrowCluster(NamedTuple):
    """The wire/residency form of DeviceCluster under the narrow dtype
    policy (KT_FEATURE_DTYPE=narrow): the int32 resource planes are
    re-laid as a range-gated int16 matrix plus an always-int32 memory
    matrix (node memory in MiB routinely exceeds int16 — 32 GiB is
    already 32768), the three pressure/taint bits pack into one uint8
    plane, and the id planes (topology domains, image KiB) narrow to
    int16 when their value ranges allow.  ``widen_cluster`` reconstructs
    the exact DeviceCluster at the top of every jitted entrypoint, so
    all solve arithmetic stays int32 — the narrowing changes transfer
    bytes and HBM residency, never a decision."""

    schedulable: jnp.ndarray    # [N] bool
    res16: jnp.ndarray          # [N,7] i16 (range-gated; else i32):
    #                             alloc cpu/gpu/pods, requested
    #                             cpu/gpu/pods, nonzero cpu
    mem32: jnp.ndarray          # [N,3] i32: alloc/requested/nonzero MiB
    ports_used: jnp.ndarray     # [N,C] bool
    vol_any: jnp.ndarray        # [N,W] bool
    vol_rw: jnp.ndarray         # [N,W] bool
    taints_nosched: jnp.ndarray  # [N,T] bool
    taints_prefer: jnp.ndarray   # [N,T] bool
    flags8: jnp.ndarray         # [N] u8: bit0 has_taints, bit1
    #                             mem_pressure, bit2 disk_pressure
    image_kib: jnp.ndarray      # [N,I] i16 (range-gated; else i32)
    topo_dom: jnp.ndarray       # [N,K] i16 (range-gated; else i32)


class DtypePolicy(NamedTuple):
    """Per-signature storage dtypes for the narrow cluster planes —
    chosen from actual value ranges so int16 can never wrap (the
    overflow-guard tests pin the fallback at the limits)."""

    res: str    # "int16" | "int32"
    img: str
    topo: str


# Gate threshold: int16 max minus the largest single-step aggregate
# delta the scan can commit (one pod's nonzero default); values proven
# below this can accumulate one more placement without wrapping.
_I16_GATE = 32000


def narrow_policy(nt: "NodeTensors", agg: "NodeAggregates",
                  space: "FeatureSpace",
                  mode: Optional[str] = None) -> Optional[DtypePolicy]:
    """The dtype policy for THIS host state, or None when the wide
    policy is configured.  Range checks read the live arrays (cheap
    numpy maxima), so adversarial states — overcommitted aggregates
    ingested from a relist, a 64-core node — fall back to int32 for
    that signature instead of wrapping.  ``mode`` overrides the
    KT_FEATURE_DTYPE default (kt-xray's canonical build must not read
    the environment)."""
    if (mode or FEATURE_DTYPE) != "narrow":
        return None
    cols = [nt.alloc[:, (0, 2, 3)], agg.requested[:, (0, 2, 3)],
            agg.nonzero[:, :1]]
    res_max = max(int(a.max()) if a.size else 0 for a in cols)
    res_min = min(int(a.min()) if a.size else 0 for a in cols)
    res = "int16" if 0 <= res_min and res_max < _I16_GATE else "int32"
    img_max = int(nt.image_kib.max()) if nt.image_kib.size else 0
    img = "int16" if img_max < _I16_GATE else "int32"
    topo = "int16" if len(space.topo_vals) < _I16_GATE else "int32"
    return DtypePolicy(res=res, img=img, topo=topo)


def narrow_cluster(c: "DeviceCluster", policy: DtypePolicy
                   ) -> NarrowCluster:
    """Re-lay a (host numpy) DeviceCluster into the narrow wire form.
    Shared by the full upload and the dirty-row gather, so the two
    paths cannot encode differently."""
    res16 = np.concatenate(
        [np.asarray(c.alloc)[:, (0, 2, 3)],
         np.asarray(c.requested)[:, (0, 2, 3)],
         np.asarray(c.nonzero)[:, :1]], axis=1).astype(policy.res)
    mem32 = np.stack(
        [np.asarray(c.alloc)[:, 1], np.asarray(c.requested)[:, 1],
         np.asarray(c.nonzero)[:, 1]], axis=1).astype(np.int32)
    flags8 = (np.asarray(c.has_taints).astype(np.uint8)
              | (np.asarray(c.mem_pressure).astype(np.uint8) << 1)
              | (np.asarray(c.disk_pressure).astype(np.uint8) << 2))
    return NarrowCluster(
        schedulable=c.schedulable, res16=res16, mem32=mem32,
        ports_used=c.ports_used, vol_any=c.vol_any, vol_rw=c.vol_rw,
        taints_nosched=c.taints_nosched, taints_prefer=c.taints_prefer,
        flags8=flags8, image_kib=np.asarray(c.image_kib)
        .astype(policy.img), topo_dom=np.asarray(c.topo_dom)
        .astype(policy.topo))


def widen_cluster(c: "DeviceCluster | NarrowCluster") -> "DeviceCluster":
    """The exact int32 DeviceCluster back from the narrow wire form —
    idempotent (a wide cluster passes through), traced at the top of
    every jitted entrypoint so the widening fuses into the solve."""
    if isinstance(c, DeviceCluster):
        return c
    r = c.res16.astype(jnp.int32)
    m = c.mem32
    return DeviceCluster(
        schedulable=c.schedulable,
        alloc=jnp.stack([r[:, 0], m[:, 0], r[:, 1], r[:, 2]], axis=1),
        requested=jnp.stack([r[:, 3], m[:, 1], r[:, 4], r[:, 5]],
                            axis=1),
        nonzero=jnp.stack([r[:, 6], m[:, 2]], axis=1),
        ports_used=c.ports_used, vol_any=c.vol_any, vol_rw=c.vol_rw,
        taints_nosched=c.taints_nosched, taints_prefer=c.taints_prefer,
        has_taints=(c.flags8 & 1) > 0,
        mem_pressure=(c.flags8 & 2) > 0,
        disk_pressure=(c.flags8 & 4) > 0,
        image_kib=c.image_kib.astype(jnp.int32),
        topo_dom=c.topo_dom.astype(jnp.int32))


def cluster_nodes(c: "DeviceCluster | NarrowCluster") -> int:
    """Node count of either cluster form (the host-side dispatch sites
    must not widen just to read a shape)."""
    return int(c.schedulable.shape[0])


def _pad_cols(a: np.ndarray, width: int, fill=0) -> np.ndarray:
    if a.shape[1] == width:
        return a
    out = np.full((a.shape[0], width), fill, a.dtype)
    out[:, : a.shape[1]] = a
    return out


def host_batch(b: PodBatch) -> DeviceBatch:
    """The DeviceBatch pytree still holding host numpy arrays — the
    chunked drain slices THIS (free numpy views with no dynamic_slice
    programs; device slicing compiled one program per distinct drain
    length) and device_puts each fixed-shape chunk."""
    parts = [getattr(b, f) for f in DeviceBatch._fields
             if f not in ("aff", "volsvc")]
    aff = DeviceAffinity(*[getattr(b.aff, f)
                           for f in DeviceAffinity._fields])
    volsvc = DeviceVolSvc(*[getattr(b.volsvc, f)
                            for f in DeviceVolSvc._fields])
    return DeviceBatch(*parts, aff=aff, volsvc=volsvc)


def device_batch(b: PodBatch) -> DeviceBatch:
    # One batched device_put for the whole pytree (~70 arrays): per-array
    # transfer calls dominate small-batch compiles otherwise.
    return jax.device_put(host_batch(b))


def _host_cluster(nt: NodeTensors, agg: NodeAggregates,
                  space: FeatureSpace) -> DeviceCluster:
    """The DeviceCluster pytree as host numpy, aggregate columns padded to
    current vocabulary capacities (pods may have interned new ports or
    volumes).  Row slicing for the incremental mirror and the full upload
    share this one assembly so they cannot diverge."""
    return DeviceCluster(
        schedulable=nt.schedulable,
        alloc=nt.alloc,
        requested=agg.requested,
        nonzero=agg.nonzero,
        ports_used=_pad_cols(agg.ports_used, space.ports.capacity),
        vol_any=_pad_cols(agg.vol_any, space.volumes.capacity),
        vol_rw=_pad_cols(agg.vol_rw, space.volumes.capacity),
        taints_nosched=nt.taints_nosched,
        taints_prefer=nt.taints_prefer,
        has_taints=nt.taints_nosched.any(1) | nt.taints_prefer.any(1),
        mem_pressure=nt.mem_pressure,
        disk_pressure=nt.disk_pressure,
        image_kib=_pad_cols(nt.image_kib, space.images.capacity),
        topo_dom=_pad_cols(nt.topo_val, space.topo_keys.capacity, fill=-1))


def device_cluster(nt: NodeTensors, agg: NodeAggregates,
                   space: FeatureSpace) -> DeviceCluster:
    """Assemble device cluster state, padding aggregate columns to current
    vocabulary capacities (pods may have interned new ports/volumes)."""
    return jax.device_put(_host_cluster(nt, agg, space))


class ResidentCluster:
    """Device-resident mirror of the cache's node tensors.

    The drain loop used to re-assemble and ``device_put`` the full
    ``(nodes x features)`` cluster state on EVERY drain — ~25 MB of
    transfer per batch at 5k nodes on a tunneled chip, for state that a
    typical drain changes in a handful of rows.  This holder keeps one
    DeviceCluster resident across drains and applies the cache's dirty
    rows (assume/bind aggregate deltas, heartbeat Ready flips) through a
    jitted scatter kernel: per drain, only the changed rows cross the
    wire.

    Invariants (the "device-residency protocol", see ARCHITECTURE.md):

    * a FULL re-upload happens when row identity moved (cache
      ``tensor_epoch`` bump: relist rebuild, node append/remove) or any
      column capacity grew (vocab interning widened a table — the shape
      signature changed and the resident arrays cannot hold the rows);
    * otherwise the mirror equals ``device_cluster`` of the current host
      arrays after scattering the dirty rows — pinned by
      tests/test_device_resident.py against the full assembly;
    * ``sync`` must run under the cache lock (the engine's ``_compile``
      does), so the gathered rows and the dirty set are one generation;
    * dirty-row counts are padded to a pow2 bucket (duplicate rows — a
      duplicate scatter of identical values is a no-op) so the scatter
      compiles O(log N) shapes, and a drain dirtying more than 1/4 of
      the cluster falls back to the full upload (the gather would move
      most of the bytes anyway).
    """

    FULL_FRACTION = 4  # dirty rows > N/4 -> full upload wins

    def __init__(self):
        self.dc: DeviceCluster | NarrowCluster | None = None
        self._sig = None
        self._epoch = None
        self._scatter = None
        self.stats = {"full_syncs": 0, "row_syncs": 0, "rows_scattered": 0}

    def invalidate(self) -> None:
        self.dc = None

    @staticmethod
    def signature(nt: "NodeTensors", space: "FeatureSpace",
                  policy: Optional[DtypePolicy] = None) -> tuple:
        """The shape signature a resident copy was uploaded at; any
        component moving — including the narrow dtype policy (a value
        crossing the int16 gate widens the plane) — means the arrays
        cannot be patched in place."""
        return (nt.alloc.shape[0], space.ports.capacity,
                space.volumes.capacity, nt.taints_nosched.shape[1],
                space.images.capacity, space.topo_keys.capacity,
                policy)

    def in_sync(self, nt: "NodeTensors", space: "FeatureSpace",
                epoch: int) -> bool:
        """True when the resident copy mirrors THIS host state's row
        identity (same epoch, same shape signature) — the precondition
        for the invariant checker's row readback to be meaningful (a
        mirror awaiting a full re-upload legitimately differs).  The
        dtype-policy component is excluded: it needs the aggregates to
        recompute, and a pending policy flip re-uploads on the next
        ``sync`` anyway."""
        return self.dc is not None and self._epoch == epoch and \
            self._sig is not None and \
            self._sig[:-1] == self.signature(nt, space)[:-1]

    def readback_rows(self, idx: "np.ndarray | list[int]") -> dict:
        """Device→host readback of the verifier's sampled rows: the four
        resource-truth fields the dirty-row protocol must keep equal to
        the host arrays.  One gather per field, k rows each — cheap at
        verifier cadence."""
        from kubernetes_tpu.engine import devicestats
        i = jnp.asarray(np.asarray(idx, np.int32))
        # Gather the k sampled rows of every plane, then decode through
        # widen_cluster — the ONE authoritative narrow->wide layout
        # (hand-stacking columns here would be a third copy of the
        # res16/mem32 packing that could silently drift from the
        # encode/decode pair).  Identity for a wide mirror.
        rows = widen_cluster(type(self.dc)(*[arr[i] for arr in self.dc]))
        out = {"schedulable": np.asarray(rows.schedulable),
               "alloc": np.asarray(rows.alloc),
               "requested": np.asarray(rows.requested),
               "nonzero": np.asarray(rows.nonzero)}
        devicestats.record_transfer("readback", devicestats.nbytes(out))
        return out

    def _scatter_fn(self):
        if self._scatter is None:
            # NO buffer donation, deliberately: the previous sync's
            # DeviceCluster may still be aliased by an in-flight drain
            # (the streamed generator holds its dc across chunks, and a
            # mid-drain explain_failures pass re-enters _compile/sync
            # with fresh dirty rows) — donating would invalidate buffers
            # a queued _solve_scan still reads.  The cost is one
            # device-side copy of the cluster arrays per scatter,
            # HBM-to-HBM, micro-seconds at 5k nodes — still nothing like
            # the host->device transfer this mirror exists to avoid.
            def scatter(c: "DeviceCluster | NarrowCluster",
                        idx: jnp.ndarray,
                        rows: "DeviceCluster | NarrowCluster"
                        ) -> "DeviceCluster | NarrowCluster":
                return type(c)(*[arr.at[idx].set(new)
                                 for arr, new in zip(c, rows)])

            # kt-xray: no-donate(prior DeviceCluster may be aliased by an
            # in-flight drain; see the comment above)
            self._scatter = jax.jit(scatter)
        return self._scatter

    @staticmethod
    def scatter_buckets(n: int, max_rows: int | None = None) -> list[int]:
        """The pow2 dirty-row buckets the scatter kernel can compile at
        for an ``n``-row cluster — reachability is bounded by ``sync``'s
        own rule (dirty * FULL_FRACTION >= n takes the full upload), so
        this is the exact shape set ``prewarm_scatter`` traces AND the
        set the kt-xray manifest must cover (one definition, two
        consumers — they cannot drift)."""
        limit = (max(n - 1, 1)) // ResidentCluster.FULL_FRACTION
        if limit < 1:
            return []
        limit = 1 << (limit - 1).bit_length() if limit > 1 else 1
        if max_rows is not None:
            limit = min(limit, max_rows)
        out, k = [], 1
        while k <= limit:
            out.append(k)
            k <<= 1
        return out

    def prewarm_scatter(self, max_rows: int | None = None) -> int:
        """Trace the dirty-row scatter kernel at EVERY reachable pow2
        row-count bucket, so no drain after an assume ever compiles the
        scatter mid-drain — measured as a fresh XLA compile on the clock
        of the first post-warm-up stream drain (the warm-start audit,
        ISSUE 8).  The reachable set is bounded by ``sync``'s own rule
        (dirty * FULL_FRACTION >= N takes the full upload instead), so
        this is log2(N/4) shapes — ~12 at 5k nodes, ~15 at 100k; an
        explicit ``max_rows`` caps it for tests.  Requires a resident
        copy (``sync`` must have run, which any ladder prewarm
        guarantees); the traces scatter row 0's own values onto row 0 —
        a no-op on the data.  Returns the number of shapes traced."""
        if self.dc is None:
            return 0
        n = int(self.dc.schedulable.shape[0])
        # sync() only scatters when dirty * FULL_FRACTION < N; larger
        # dirty sets take the full upload, so their shapes are
        # unreachable (ResidentCluster.scatter_buckets is that rule).
        scatter = self._scatter_fn()
        traced = 0
        for k in self.scatter_buckets(n, max_rows):
            idx = np.zeros(k, np.int32)
            rows = type(self.dc)(*[
                np.repeat(np.asarray(arr[:1]), k, axis=0)
                for arr in self.dc])
            idx_d, rows_d = jax.device_put((idx, rows))
            scatter(self.dc, idx_d,
                    rows_d).schedulable.block_until_ready()
            traced += 1
        return traced

    def sync(self, nt: NodeTensors, agg: NodeAggregates,
             space: FeatureSpace, dirty: set[int],
             epoch: int) -> "DeviceCluster | NarrowCluster":
        """The current cluster state on device: scatter ``dirty`` rows
        into the resident arrays, or re-upload everything when the
        resident copy cannot be patched (see class docstring).  Under
        the narrow dtype policy both the upload and the scattered rows
        travel in the NarrowCluster wire form; the jitted entrypoints
        widen on device."""
        from kubernetes_tpu.engine import devicestats
        n = nt.alloc.shape[0]
        policy = narrow_policy(nt, agg, space)
        sig = self.signature(nt, space, policy)
        if self.dc is None or self._sig != sig or self._epoch != epoch \
                or len(dirty) * self.FULL_FRACTION >= max(n, 1):
            host = _host_cluster(nt, agg, space)
            self.dc = jax.device_put(
                host if policy is None else narrow_cluster(host, policy))
            self._sig = sig
            self._epoch = epoch
            self.stats["full_syncs"] += 1
            # Device accounting: the whole-cluster re-snapshot is the
            # EXPENSIVE transfer the residency protocol exists to avoid
            # — full_upload bytes dominating steady-state drains is the
            # regression signature (a silent re-upload where a dirty-row
            # scatter should have run).  (HBM peak sampling deliberately
            # NOT here: on backends without memory_stats the fallback
            # walks every live array — the telemetry scrape cadence
            # covers it off the drain path.)
            devicestats.record_transfer("full_upload",
                                        devicestats.nbytes(self.dc))
            return self.dc
        if not dirty:
            return self.dc
        idx = np.fromiter(dirty, np.int32, len(dirty))
        # Gather the dirty rows directly (fancy indexing copies), padding
        # and deriving only the k gathered rows — assembling the full
        # padded host cluster here would re-pay the O(N x features) host
        # work the mirror exists to avoid.  Same field encoding as
        # _host_cluster by construction; equivalence is pinned by
        # tests/test_device_resident.py.
        tn, tp = nt.taints_nosched[idx], nt.taints_prefer[idx]
        rows = DeviceCluster(
            schedulable=nt.schedulable[idx],
            alloc=nt.alloc[idx],
            requested=agg.requested[idx],
            nonzero=agg.nonzero[idx],
            ports_used=_pad_cols(agg.ports_used[idx],
                                 space.ports.capacity),
            vol_any=_pad_cols(agg.vol_any[idx], space.volumes.capacity),
            vol_rw=_pad_cols(agg.vol_rw[idx], space.volumes.capacity),
            taints_nosched=tn,
            taints_prefer=tp,
            has_taints=tn.any(1) | tp.any(1),
            mem_pressure=nt.mem_pressure[idx],
            disk_pressure=nt.disk_pressure[idx],
            image_kib=_pad_cols(nt.image_kib[idx], space.images.capacity),
            topo_dom=_pad_cols(nt.topo_val[idx],
                               space.topo_keys.capacity, fill=-1))
        if policy is not None:
            rows = narrow_cluster(rows, policy)
        pad = 1 << (len(dirty) - 1).bit_length()
        if pad > len(dirty):
            extra = pad - len(dirty)
            idx = np.concatenate([idx, np.repeat(idx[:1], extra)])
            rows = type(rows)(*[
                np.concatenate([arr, np.repeat(arr[:1], extra, axis=0)])
                for arr in rows])
        idx_d, rows_d = jax.device_put((idx, rows))
        self.dc = self._scatter_fn()(self.dc, idx_d, rows_d)
        self.stats["row_syncs"] += 1
        self.stats["rows_scattered"] += len(dirty)
        # Only the gathered rows crossed the wire (idx + padded rows).
        devicestats.record_transfer(
            "scatter", idx.nbytes + devicestats.nbytes(rows))
        return self.dc


def _predicate_mask(name: str, b: DeviceBatch, c: DeviceCluster,
                    n_nodes: int, extra: dict) -> jnp.ndarray:
    p = b.request.shape[0]
    if name in ("PodFitsHost", "HostName"):
        return pr.pod_fits_host(b.host_idx, n_nodes)
    if name == "MatchNodeSelector":
        return pr.pod_selector_matches(b.sel_group, b.sel_required)
    if name == "PodToleratesNodeTaints":
        return pr.pod_tolerates_node_taints(b.tol_nosched, b.has_tolerations,
                                            c.taints_nosched, c.has_taints)
    if name == "CheckNodeMemoryPressure":
        return pr.check_node_memory_pressure(b.best_effort, c.mem_pressure)
    if name == "CheckNodeDiskPressure":
        return pr.check_node_disk_pressure(p, c.disk_pressure)
    if name == "NewNodeLabelPredicate":
        return pr.node_label_presence(p, b.volsvc.nl_pred_row)
    if name == "NoVolumeZoneConflict":
        return b.volsvc.vz_mask[b.volsvc.vz_group]
    if name == "ServiceAffinity":
        return b.volsvc.sa_mask[b.volsvc.sa_group]
    if name == "MaxEBSVolumeCount":
        return pr.max_pd_volume_count(b.volsvc.pd_pod_ebs,
                                      b.volsvc.pd_extra_ebs,
                                      b.volsvc.pd_node_ebs,
                                      b.volsvc.pd_node_extra_ebs,
                                      b.volsvc.pd_node_err_ebs,
                                      extra["max_ebs"])
    if name == "MaxGCEPDVolumeCount":
        return pr.max_pd_volume_count(b.volsvc.pd_pod_gce,
                                      b.volsvc.pd_extra_gce,
                                      b.volsvc.pd_node_gce,
                                      b.volsvc.pd_node_extra_gce,
                                      b.volsvc.pd_node_err_gce,
                                      extra["max_gce"])
    if name == "PodFitsResources":
        return pr.pod_fits_resources(b.request, b.zero_request, c.alloc,
                                     c.requested)
    if name in ("PodFitsHostPorts", "PodFitsPorts"):
        return pr.pod_fits_host_ports(b.ports, c.ports_used)
    if name == "NoDiskConflict":
        return pr.no_disk_conflict(b.vol_rw, b.vol_ro, c.vol_any, c.vol_rw)
    if name == "MatchInterPodAffinity":
        a = b.aff
        return interpod.predicate_mask(a.aff_need, a.aff_self, a.anti_need,
                                       a.decl_match, a.match_cnt,
                                       a.match_total, a.decl_reach)
    if name in PASSTHROUGH_PREDICATES:
        return jnp.ones((p, n_nodes), bool)
    raise KeyError(f"unknown predicate {name!r}")


def saa_plane(cnt: jnp.ndarray, num: jnp.ndarray, dom: jnp.ndarray,
              labeled: jnp.ndarray) -> jnp.ndarray:
    """CalculateAntiAffinityPriority score (selector_spreading.go:236-250):
    int(10*(num-count)/num) on ready nodes carrying the label, 10 when the
    service has no pods, 0 on unlabeled nodes.  ``cnt`` [P,D] per-domain
    peer counts of each pod's service group, ``num`` [P,1] peer totals,
    ``dom`` [N] node domain ids, ``labeled`` [N]."""
    per = jnp.take(cnt, dom, axis=1)          # [P, N]
    # prio._trunc, not raw floor: XLA's reciprocal-approximated f32 divide
    # can land an exact quotient (440/110 == 4.0) an ulp low, and the
    # truncation would eat a whole point.
    score = jnp.where(num > 0.0,
                      prio._trunc(10.0 * (num - per) / jnp.maximum(num, 1.0)),
                      10.0)
    return jnp.where(labeled[None, :], score, 0.0)


def _priority_plane(name: str, b: DeviceBatch, c: DeviceCluster,
                    n_nodes: int, extra: dict) -> jnp.ndarray:
    p = b.request.shape[0]
    if name == "LeastRequestedPriority":
        return prio.least_requested(b.nonzero, c.nonzero, c.alloc)
    if name == "MostRequestedPriority":
        return prio.most_requested(b.nonzero, c.nonzero, c.alloc)
    if name == "BalancedResourceAllocation":
        return prio.balanced_resource_allocation(b.nonzero, c.nonzero, c.alloc)
    if name == "NodeAffinityPriority":
        return prio.node_affinity(b.sel_group, b.sel_pref_counts,
                                  c.schedulable)
    if name == "TaintTolerationPriority":
        return prio.taint_toleration(b.tol_prefer, c.taints_prefer,
                                     c.schedulable)
    if name == "ImageLocalityPriority":
        return prio.image_locality(b.images, c.image_kib)
    if name == "NodePreferAvoidPodsPriority":
        return prio.node_prefer_avoid(b.avoid_group, b.avoid_rows)
    if name in ("SelectorSpreadPriority", "ServiceSpreadingPriority"):
        return prio.selector_spread(b.spread_group, b.spread_node_counts,
                                    b.spread_zone_counts, b.spread_has_zones,
                                    b.node_zone_id, c.schedulable)
    if name == "InterPodAffinityPriority":
        a = b.aff
        counts = interpod.priority_counts(a.pref_w, a.match_cnt, a.sym_match,
                                          a.sym_w, a.sym_cnt)
        return interpod.priority_score(counts, c.schedulable, prio._trunc)
    if name == "NodeLabelPriority":
        return prio.node_label(p, b.volsvc.nl_prio_rows[extra.get("aux", 0)])
    if name == "ServiceAntiAffinityPriority":
        vs = b.volsvc
        return saa_plane(vs.saa_cnt[extra.get("aux", 0)][vs.saa_group],
                         vs.saa_num[vs.saa_group][:, None],
                         vs.saa_dom[extra.get("aux", 0)],
                         vs.saa_labeled[extra.get("aux", 0)])
    if name == "EqualPriority":
        return prio.equal_priority(p, n_nodes)
    raise KeyError(f"unknown priority {name!r}")


class Solver:
    """Compiles a Policy into jitted evaluate / sequential-solve callables.

    Solvers are stateless (the policy-derived constants plus XLA
    executables keyed on them), so ``Solver.for_policy`` shares one
    instance per distinct derived signature process-wide: jit caches are
    keyed on the Solver object (static argnum 0), and a fresh Solver per
    daemon/engine instance silently re-traced and re-compiled every
    executable (~15-40 s per rig at the 30k/5k shape)."""

    _registry: dict = {}
    _registry_lock = threading.Lock()

    @classmethod
    def for_policy(cls, policy: Policy) -> "Solver":
        candidate = cls(policy)
        key = (candidate.predicate_names, candidate.priority_specs,
               tuple(sorted(candidate.extra.items())), candidate._fused)
        with cls._registry_lock:
            existing = cls._registry.get(key)
            if existing is not None:
                return existing
            cls._registry[key] = candidate
            return candidate

    def __init__(self, policy: Policy,
                 fused: Optional[bool] = None):
        self.policy = policy
        # Fused scan-step selection, resolved once per Solver (KT_FUSED
        # default; tests pass fused=False to pin the legacy body).  The
        # select kernel implementation (Pallas on TPU, XLA elsewhere)
        # resolves with it — never per drain.
        self._fused = FUSED_DEFAULT if fused is None else fused
        self._select = fused_mod.impl()
        # Half-width encoded-score dtype (resolved once with the
        # backend): bf16 on TPU, f16 — wider mantissa, so a larger
        # exact-integer range — elsewhere.
        self._half_dtype = jnp.bfloat16 \
            if jax.default_backend() == "tpu" else jnp.float16
        # Canonical names: argument-carrying entries resolve to their
        # builtin regardless of the user-chosen policy name (plugins.go).
        self.predicate_names = tuple(canonical_predicate_name(p)
                                     for p in expand_predicates(policy))
        # (name, weight, aux) — aux indexes per-instance policy-arg tables
        # (ServiceAntiAffinityPriority / NodeLabelPriority rows).
        specs = []
        saa_i = nl_i = 0
        for s in policy.priorities:
            if s.weight == 0:
                continue
            name = canonical_priority_name(s)
            if name == "ServiceAntiAffinityPriority":
                specs.append((name, s.weight, saa_i))
                saa_i += 1
            elif name == "NodeLabelPriority":
                specs.append((name, s.weight, nl_i))
                nl_i += 1
            else:
                specs.append((name, s.weight, 0))
        self.priority_specs = tuple(specs)
        self.passthrough = tuple(n for n in self.predicate_names
                                 if n in PASSTHROUGH_PREDICATES)
        # MaxPD caps: policy value, else KUBE_MAX_PD_VOLS env, else provider
        # default (defaults.go:42-54).
        env_max = os.environ.get("KUBE_MAX_PD_VOLS", "")
        env_val = int(env_max) if env_max.isdigit() else 0
        self.extra = {"max_ebs": env_val or DEFAULT_MAX_EBS_VOLUMES,
                      "max_gce": env_val or DEFAULT_MAX_GCE_PD_VOLUMES}
        for spec in expand_predicates(policy):
            if spec.name == "MaxEBSVolumeCount" and spec.max_volumes:
                self.extra["max_ebs"] = spec.max_volumes
            elif spec.name == "MaxGCEPDVolumeCount" and spec.max_volumes:
                self.extra["max_gce"] = spec.max_volumes

    # -- one-shot batched evaluation ------------------------------------

    # kt-xray: no-donate(inputs are the resident cluster + a batch the
    # caller re-reads for evaluate in the same decision)
    @functools.partial(jax.jit, static_argnums=(0,))
    def masks(self, b: DeviceBatch, c: DeviceCluster) -> dict[str, jnp.ndarray]:
        """Per-predicate [P,N] masks (for Filter verbs / failure reporting)."""
        c = widen_cluster(c)
        n = c.alloc.shape[0]
        return {name: _predicate_mask(name, b, c, n, self.extra)
                for name in self.predicate_names}

    # kt-xray: no-donate(c is the shared resident cluster; b is re-used
    # by the failure-detail masks pass)
    @functools.partial(jax.jit, static_argnums=(0, 3))
    def evaluate(self, b: DeviceBatch, c: DeviceCluster,
                 flags: BatchFlags = ALL_ON_FLAGS
                 ) -> tuple[jnp.ndarray, jnp.ndarray]:
        """(feasible [P,N] bool, scores [P,N] f32) against current state.

        ``flags`` (content-derived, see batch_flags) skips planes the batch
        provably cannot trigger — an all-pass mask or all-zero plane — which
        matters because per-kernel dispatch overhead, not FLOPs, dominates
        small-batch evaluation."""
        c = widen_cluster(c)
        n = c.alloc.shape[0]
        skip_preds = set()
        if not flags.any_ports:
            skip_preds |= {"PodFitsHostPorts", "PodFitsPorts"}
        if not flags.any_volumes:
            skip_preds.add("NoDiskConflict")
        if not flags.any_ebs:
            skip_preds.add("MaxEBSVolumeCount")
        if not flags.any_gce:
            skip_preds.add("MaxGCEPDVolumeCount")
        if not flags.any_affinity_pred:
            skip_preds.add("MatchInterPodAffinity")
        # Unready nodes are filtered before scheduling (factory.go:436-462).
        feasible = jnp.broadcast_to(c.schedulable[None, :],
                                    (b.request.shape[0], n))
        for name in self.predicate_names:
            if name not in skip_preds:
                feasible &= _predicate_mask(name, b, c, n, self.extra)
        scores = jnp.zeros((b.request.shape[0], n), jnp.float32)
        for name, weight, aux in self.priority_specs:
            if name == "InterPodAffinityPriority" and \
                    not flags.any_affinity_prio:
                continue  # all counts provably zero -> score plane is zero
            scores += jnp.float32(weight) * \
                _priority_plane(name, b, c, n, {"aux": aux})
        return feasible, scores

    # -- sequential greedy solve ----------------------------------------

    def solve_sequential(self, b: DeviceBatch, c: DeviceCluster,
                         last_node_index: jnp.ndarray,
                         flags: BatchFlags | None = None,
                         extra_mask: jnp.ndarray | None = None,
                         score_bias: jnp.ndarray | None = None
                         ) -> tuple[jnp.ndarray, jnp.ndarray, DeviceCluster]:
        """Greedy in-order placement with on-device state updates.

        ``extra_mask``/``score_bias``: optional [P,N] workload-constraint
        planes (topology spread, engine/workloads/topology.py) ANDed into
        feasibility / added to the static score.

        Returns (choices [P] int32 node index or -1, new last_node_index,
        updated cluster aggregates)."""
        if flags is None:
            flags = batch_flags(b)
        choices, counter, final = self._solve_scan(
            b, c, last_node_index, score_bias, flags, None, None,
            extra_mask)
        return choices, counter, self._carry_cluster(c, final)

    def solve_sequential_packed(self, b: DeviceBatch, c: DeviceCluster,
                                last_node_index: jnp.ndarray,
                                flags: BatchFlags,
                                extra_mask: jnp.ndarray | None = None,
                                score_bias: jnp.ndarray | None = None,
                                live: jnp.ndarray | None = None
                                ) -> jnp.ndarray:
        """solve_sequential, with every host-bound result packed into ONE
        int32 vector: [choices (P), counter (1), requested (4N), nonzero
        (2N)].  On a tunneled device each device->host fetch pays a full
        RTT (~250 ms measured), so the daemon fetches exactly one array per
        drain and unpacks host-side."""
        choices, counter, final = self._solve_scan(
            b, c, last_node_index, score_bias, flags, None, live,
            extra_mask)
        requested, nonzero = self._final_aggregates(final)
        return jnp.concatenate([
            choices, counter.astype(jnp.int32)[None],
            requested.ravel(), nonzero.ravel()])

    @staticmethod
    def _final_aggregates(final: dict) -> tuple[jnp.ndarray, jnp.ndarray]:
        """(requested [N,4], nonzero [N,2]) from a scan's final state —
        the fused body carries them as one packed [N,6] matrix (a single
        scatter-add per step), the legacy body as two planes."""
        if "packed" in final:
            return final["packed"][:, :4], final["packed"][:, 4:6]
        return final["requested"], final["nonzero"]

    @staticmethod
    def _carry_cluster(c: "DeviceCluster | NarrowCluster",
                       final: dict) -> DeviceCluster:
        """Fold a scan's final dynamic state back into a DeviceCluster."""
        requested, nonzero = Solver._final_aggregates(final)
        return widen_cluster(c)._replace(
            requested=requested, nonzero=nonzero,
            ports_used=final.get("ports_used", c.ports_used),
            vol_any=final.get("vol_any", c.vol_any),
            vol_rw=final.get("vol_rw", c.vol_rw))

    # kt-xray: donate(donate_argnums=(6,) — the carry: each chunk's
    # final state is consumed exactly once, by the next chunk's launch;
    # nothing else aliases it (choices ride separate buffers), so the
    # scan updates the carried aggregates in place instead of minting a
    # fresh copy of every state plane per chunk.  c and b stay
    # non-donated: they alias the resident mirror / the sliced batch.)
    @functools.partial(jax.jit, static_argnums=(0, 5), donate_argnums=(6,))
    def _solve_scan(self, b: DeviceBatch, c: DeviceCluster,
                    last_node_index: jnp.ndarray, score_bias: jnp.ndarray,
                    flags: BatchFlags = ALL_ON_FLAGS,
                    carry: dict | None = None,
                    live: jnp.ndarray | None = None,
                    extra_mask: jnp.ndarray | None = None
                    ) -> tuple[jnp.ndarray, jnp.ndarray, dict]:
        """The sequential scan, with an additive per-(pod,node) score bias
        (zero for parity greedy; price-shaped for the joint solver).

        ``flags`` compiles away dynamic-state families the batch cannot
        touch; ``carry`` continues a previous chunk's final state (chunked
        drain) — flags MUST come from the full batch, not the chunk, so
        every chunk carries the same state shape.  ``extra_mask`` [P,N] is
        an additional hard feasibility plane (workload constraints —
        topology spread's DoNotSchedule terms); None compiles it away.
        Returns (choices [P], counter, final state dict)."""
        c = widen_cluster(c)
        n = c.alloc.shape[0]
        p = b.request.shape[0]
        a = b.aff

        # Hoist placement-invariant work: static predicate masks and static
        # priority planes are the big vocab contractions.  A policy-dynamic
        # predicate whose inputs are absent from this batch (flags) is
        # hoisted too — its mask and state provably never change mid-scan.
        use_resources = "PodFitsResources" in self.predicate_names
        use_ports = flags.any_ports and any(
            nm in self.predicate_names
            for nm in ("PodFitsHostPorts", "PodFitsPorts"))
        use_volumes = flags.any_volumes and \
            "NoDiskConflict" in self.predicate_names
        use_interpod = flags.any_affinity_pred and \
            "MatchInterPodAffinity" in self.predicate_names
        use_max_ebs = flags.any_ebs and \
            "MaxEBSVolumeCount" in self.predicate_names
        use_max_gce = flags.any_gce and \
            "MaxGCEPDVolumeCount" in self.predicate_names
        in_scan_preds = {"PodFitsResources"} if use_resources else set()
        if use_ports:
            in_scan_preds |= {"PodFitsHostPorts", "PodFitsPorts"}
        if use_volumes:
            in_scan_preds.add("NoDiskConflict")
        if use_interpod:
            in_scan_preds.add("MatchInterPodAffinity")
        if use_max_ebs:
            in_scan_preds.add("MaxEBSVolumeCount")
        if use_max_gce:
            in_scan_preds.add("MaxGCEPDVolumeCount")
        static_mask = jnp.broadcast_to(c.schedulable[None, :], (p, n))
        for name in self.predicate_names:
            if name not in in_scan_preds:
                static_mask &= _predicate_mask(name, b, c, n, self.extra)
        if live is not None:
            # Chunk padding: dead rows are infeasible everywhere, place
            # nothing, and bump no counter (hoisted — zero per-step cost).
            static_mask &= live[:, None]
        if extra_mask is not None:
            # Workload-constraint hard plane (batch-start topology spread):
            # hoisted like every other static predicate.
            static_mask &= extra_mask
        # None bias (the greedy path) becomes a zeros plane inside the jit,
        # which XLA elides — callers avoid materializing a [P,N] zeros arg.
        static_score = score_bias if score_bias is not None \
            else jnp.zeros((p, n), jnp.float32)
        dynamic_prios = []
        for name, weight, aux in self.priority_specs:
            in_scan = name in DYNAMIC_PRIORITIES
            if name in ("SelectorSpreadPriority", "ServiceSpreadingPriority"):
                in_scan = flags.any_spread
            elif name == "InterPodAffinityPriority":
                in_scan = flags.any_affinity_prio
            elif name == "ServiceAntiAffinityPriority":
                # No batch pod joins any scored service group: counts are
                # provably constant, the batch-start plane is exact.
                in_scan = flags.any_saa
            if in_scan:
                dynamic_prios.append((name, weight, aux))
            else:
                static_score += jnp.float32(weight) * \
                    _priority_plane(name, b, c, n, {"aux": aux})
        dynamic_prios = tuple(dynamic_prios)
        use_interpod_prio = any(nm == "InterPodAffinityPriority"
                                for nm, _, _ in dynamic_prios)
        track_affinity = use_interpod or use_interpod_prio
        track_spread = any(nm in ("SelectorSpreadPriority",
                                  "ServiceSpreadingPriority")
                           for nm, _, _ in dynamic_prios)
        track_spread_zones = track_spread and flags.any_spread_zones
        track_saa = any(nm == "ServiceAntiAffinityPriority"
                        for nm, _, _ in dynamic_prios)

        fits_pods_alloc = c.alloc[:, RES_PODS]
        zone_ids = b.node_zone_id  # [N]
        f32 = jnp.float32

        if self._fused:
            return self._fused_scan(
                b, c, last_node_index, static_mask, static_score, carry,
                live, score_bias is not None, dict(
                    use_resources=use_resources, use_ports=use_ports,
                    use_volumes=use_volumes, use_interpod=use_interpod,
                    use_max_ebs=use_max_ebs, use_max_gce=use_max_gce,
                    track_affinity=track_affinity,
                    track_spread=track_spread,
                    track_spread_zones=track_spread_zones,
                    track_saa=track_saa),
                dynamic_prios)

        def step(state, xs):
            counter = state["counter"]

            # Dynamic predicates on current aggregates (predicates.go:444-485,
            # :721-741, :100-153) — O(N) per step.
            feasible = xs["smask"]
            if use_resources:
                requested = state["requested"]
                fits_pods = (requested[:, RES_PODS] + 1) <= fits_pods_alloc
                free = c.alloc[:, :3] - requested[:, :3]
                fits_res = jnp.all(xs["req"][None, :3] <= free, axis=-1)
                feasible &= fits_pods & (xs["zero"] | fits_res)
            if use_ports:
                port_conflict = jnp.einsum(
                    "c,nc->n", xs["ports"].astype(f32),
                    state["ports_used"].astype(f32)) > 0
                feasible &= ~port_conflict
            if use_volumes:
                vol_conflict = (
                    jnp.einsum("w,nw->n", xs["vrw"].astype(f32),
                               state["vol_any"].astype(f32)) +
                    jnp.einsum("w,nw->n", xs["vro"].astype(f32),
                               state["vol_rw"].astype(f32))) > 0
                feasible &= ~vol_conflict
            for fam in ("ebs", "gce") if (use_max_ebs or use_max_gce) else ():
                if (fam == "ebs" and not use_max_ebs) or \
                        (fam == "gce" and not use_max_gce):
                    continue
                pd_node = state[f"pd_{fam}"]
                pod_row = xs[f"pd_pod_{fam}"].astype(f32)
                overlap = jnp.einsum("w,nw->n", pod_row, pd_node.astype(f32))
                new = jnp.sum(pod_row) + xs[f"pd_extra_{fam}"].astype(f32)
                node_extra = getattr(b.volsvc, f"pd_node_extra_{fam}")
                node_err = getattr(b.volsvc, f"pd_node_err_{fam}")
                total = jnp.sum(pd_node.astype(f32), axis=1) + \
                    node_extra.astype(f32) + new - overlap
                ok = (total <= f32(self.extra[f"max_{fam}"])) & ~node_err
                feasible &= (new == 0) | ok
            if track_affinity:
                reach = state["match_cnt"] > 0.0  # [Sm, N]
            if use_interpod:
                # MatchInterPodAffinity for one pod against current state
                # (predicates.go:825-853 with the self-match escape hatch).
                live = xs["aff_need"] & ~(xs["aff_self"] &
                                          (state["match_total"] == 0.0))
                viol = (jnp.einsum("s,sn->n", live.astype(f32),
                                   (~reach).astype(f32)) +
                        jnp.einsum("s,sn->n", xs["anti_need"].astype(f32),
                                   reach.astype(f32)) +
                        jnp.einsum("s,sn->n", xs["decl_match"].astype(f32),
                                   state["decl_reach"].astype(f32))) > 0
                feasible &= ~viol

            # Dynamic priorities against current aggregates.
            score = xs["sscore"]
            for name, weight, aux in dynamic_prios:
                w = f32(weight)
                if name == "LeastRequestedPriority":
                    score = score + w * prio.least_requested(
                        xs["nz"][None], state["nonzero"], c.alloc)[0]
                elif name == "MostRequestedPriority":
                    score = score + w * prio.most_requested(
                        xs["nz"][None], state["nonzero"], c.alloc)[0]
                elif name == "BalancedResourceAllocation":
                    score = score + w * prio.balanced_resource_allocation(
                        xs["nz"][None], state["nonzero"], c.alloc)[0]
                elif name in ("SelectorSpreadPriority",
                              "ServiceSpreadingPriority"):
                    if track_spread_zones:
                        score = score + w * prio.selector_spread(
                            xs["sgroup"][None], state["sp_node"],
                            state["sp_zone"], b.spread_has_zones,
                            zone_ids, c.schedulable)[0]
                    else:
                        # No zone-aware spread group in the batch: the
                        # blended arm is provably never taken.
                        score = score + w * prio.selector_spread_node_only(
                            xs["sgroup"][None], state["sp_node"],
                            c.schedulable)[0]
                elif name == "InterPodAffinityPriority":
                    counts = interpod.priority_counts(
                        xs["pref_w"][None], state["match_cnt"],
                        xs["sym_match"][None], a.sym_w, state["sym_cnt"])
                    score = score + w * interpod.priority_score(
                        counts, c.schedulable, prio._trunc)[0]
                elif name == "ServiceAntiAffinityPriority":
                    # Live per-domain peer counts (selector_spreading.go
                    # would re-list the service's pods on every decision;
                    # the scan carries the counts instead).
                    score = score + w * saa_plane(
                        state["saa_cnt"][aux][xs["saa_g"]][None],
                        state["saa_num"][xs["saa_g"]][None, None],
                        b.volsvc.saa_dom[aux],
                        b.volsvc.saa_labeled[aux])[0]

            # selectHost (generic_scheduler.go:124-141): round-robin among
            # max-score feasible nodes; counter bumps only on success.
            neg = f32(-jnp.inf)
            masked = jnp.where(feasible, score, neg)
            max_score = jnp.max(masked)
            any_feasible = jnp.any(feasible)
            ties = feasible & (masked == max_score)
            n_ties = jnp.maximum(jnp.sum(ties), 1)
            ix = (counter % n_ties.astype(jnp.uint32)).astype(jnp.int32)
            rank = jnp.cumsum(ties.astype(jnp.int32)) - 1
            choice = jnp.argmax(ties & (rank == ix)).astype(jnp.int32)
            choice = jnp.where(any_feasible, choice, -1)

            # Commit: the batched AssumePod (cache.go:107).
            placed = choice >= 0
            onehot = (jnp.arange(n, dtype=jnp.int32) == choice) & placed
            oh_i = onehot.astype(jnp.int32)
            oh_f = onehot.astype(f32)
            new_state = dict(state)
            new_state["requested"] = state["requested"] + \
                oh_i[:, None] * xs["req"][None, :]
            new_state["nonzero"] = state["nonzero"] + \
                oh_i[:, None] * xs["nz"][None, :]
            if use_ports:
                new_state["ports_used"] = state["ports_used"] | \
                    (onehot[:, None] & xs["ports"][None, :])
            if use_volumes:
                new_state["vol_any"] = state["vol_any"] | \
                    (onehot[:, None] & (xs["vrw"] | xs["vro"])[None, :])
                new_state["vol_rw"] = state["vol_rw"] | \
                    (onehot[:, None] & xs["vrw"][None, :])
            if track_spread:
                new_state["sp_node"] = state["sp_node"] + \
                    xs["incr"].astype(f32)[:, None] * oh_f[None, :]
                if track_spread_zones:
                    zid = jnp.where(placed, zone_ids[jnp.clip(choice, 0)], -1)
                    zoh = (jnp.arange(state["sp_zone"].shape[1],
                                      dtype=jnp.int32) == zid)
                    new_state["sp_zone"] = state["sp_zone"] + \
                        xs["incr"].astype(f32)[:, None] * \
                        zoh.astype(f32)[None, :]
            if use_max_ebs:
                new_state["pd_ebs"] = state["pd_ebs"] | \
                    (onehot[:, None] & xs["pd_pod_ebs"][None, :])
            if use_max_gce:
                new_state["pd_gce"] = state["pd_gce"] | \
                    (onehot[:, None] & xs["pd_pod_gce"][None, :])
            if track_saa:
                # The placed pod joins every matching service's peer set:
                # totals bump for each joined group, the domain count only
                # when the chosen node carries the label.
                src = xs["saa_src"].astype(f32) * placed.astype(f32)  # [Gy]
                new_state["saa_num"] = state["saa_num"] + src
                j = jnp.clip(choice, 0)
                dom_j = b.volsvc.saa_dom[:, j]                  # [L]
                lab_j = b.volsvc.saa_labeled[:, j] & placed     # [L]
                n_dom = state["saa_cnt"].shape[2]
                domoh = ((jnp.arange(n_dom, dtype=jnp.int32)[None, :]
                          == dom_j[:, None]) & lab_j[:, None]).astype(f32)
                new_state["saa_cnt"] = state["saa_cnt"] + \
                    domoh[:, None, :] * src[None, :, None]
            if track_affinity:
                (new_state["match_cnt"], new_state["match_total"],
                 new_state["decl_reach"], new_state["sym_cnt"]) = \
                    interpod.place_update(
                        a.node_dom, a.match_key, state["match_cnt"],
                        state["match_total"], xs["match_src"],
                        a.decl_key, state["decl_reach"], xs["decl_src"],
                        a.sym_key, state["sym_cnt"], xs["sym_src"],
                        choice, placed)
            new_state["counter"] = counter + \
                jnp.where(any_feasible, jnp.uint32(1), jnp.uint32(0))
            return new_state, choice

        init = {
            "requested": c.requested, "nonzero": c.nonzero,
            "counter": last_node_index,
        }
        xs = {
            "req": b.request, "zero": b.zero_request, "nz": b.nonzero,
            "smask": static_mask, "sscore": static_score,
        }
        if use_ports:
            init["ports_used"] = c.ports_used
            xs["ports"] = b.ports
        if use_volumes:
            init["vol_any"] = c.vol_any
            init["vol_rw"] = c.vol_rw
            xs["vro"] = b.vol_ro
            xs["vrw"] = b.vol_rw
        if track_spread:
            init["sp_node"] = b.spread_node_counts
            init["sp_zone"] = b.spread_zone_counts
            xs["sgroup"] = b.spread_group
            xs["incr"] = b.spread_incr
        if track_affinity:
            init.update(match_cnt=a.match_cnt, match_total=a.match_total,
                        decl_reach=a.decl_reach, sym_cnt=a.sym_cnt)
            xs.update(aff_need=a.aff_need, aff_self=a.aff_self,
                      anti_need=a.anti_need, decl_match=a.decl_match,
                      match_src=a.match_src, decl_src=a.decl_src,
                      pref_w=a.pref_w, sym_match=a.sym_match,
                      sym_src=a.sym_src)
        if track_saa:
            init["saa_cnt"] = b.volsvc.saa_cnt
            init["saa_num"] = b.volsvc.saa_num
            xs["saa_g"] = b.volsvc.saa_group
            xs["saa_src"] = b.volsvc.saa_src
        if use_max_ebs:
            init["pd_ebs"] = b.volsvc.pd_node_ebs
            xs["pd_pod_ebs"] = b.volsvc.pd_pod_ebs
            xs["pd_extra_ebs"] = b.volsvc.pd_extra_ebs
        if use_max_gce:
            init["pd_gce"] = b.volsvc.pd_node_gce
            xs["pd_pod_gce"] = b.volsvc.pd_pod_gce
            xs["pd_extra_gce"] = b.volsvc.pd_extra_gce
        if carry is not None:
            # Continue a previous chunk: carried keys override batch-derived
            # initial state (same key set — flags come from the full batch).
            init.update({k: v for k, v in carry.items() if k in init})
        final, choices = jax.lax.scan(step, init, xs, unroll=SCAN_UNROLL)
        return choices, final["counter"], final

    # Dynamic priorities whose pod-dependence is ONLY the nonzero-request
    # row: their per-step [N] score plane is a pure function of
    # (template, carried aggregates), so the scan can carry one
    # [templates, N] plane and update a single column per placement
    # instead of recomputing the whole chain every step.
    _TEMPLATE_PRIOS = ("LeastRequestedPriority", "MostRequestedPriority",
                       "BalancedResourceAllocation")

    def _template_col(self, tmpl_prios: tuple, templates: jnp.ndarray,
                      nz_j: jnp.ndarray, alloc_j: jnp.ndarray
                      ) -> jnp.ndarray:
        """[T] — the template-factored score column for one node, from
        its (new) nonzero aggregates.  EXACTLY the per-step formulas of
        the legacy scan body, evaluated at a single node."""
        col = jnp.zeros(templates.shape[0], jnp.float32)
        for name, weight, _aux in tmpl_prios:
            w = jnp.float32(weight)
            if name == "LeastRequestedPriority":
                col += w * prio.least_requested(
                    templates, nz_j[None], alloc_j[None])[:, 0]
            elif name == "MostRequestedPriority":
                col += w * prio.most_requested(
                    templates, nz_j[None], alloc_j[None])[:, 0]
            elif name == "BalancedResourceAllocation":
                col += w * prio.balanced_resource_allocation(
                    templates, nz_j[None], alloc_j[None])[:, 0]
        return col

    def _fused_scan(self, b: DeviceBatch, c: DeviceCluster,
                    last_node_index: jnp.ndarray,
                    static_mask: jnp.ndarray, static_score: jnp.ndarray,
                    carry: dict | None, live: jnp.ndarray | None,
                    has_bias: bool, fams: dict, dynamic_prios: tuple
                    ) -> tuple[jnp.ndarray, jnp.ndarray, dict]:
        """The fused scan body (KT_FUSED, the default) — decision-parity
        identical to the legacy ``step`` (pinned by
        tests/test_fused_solver.py against legacy, oracle and the host
        engine), restructured for per-step cost:

        * the hoisted mask/score planes merge into ONE encoded plane
          (``-inf`` = statically infeasible), so each step slices one
          row and folds dynamic feasibility with a single ``where``;
        * ``requested``+``nonzero`` carry as one packed [N,6] matrix
          committed by a single one-row scatter-add (the legacy body
          re-materialized every plane every step);
        * spread/zone counts commit by one-column scatter-adds;
          port/volume/PD planes by one-row updates;
        * the nz-only dynamic priorities (least/most-requested,
          balanced) are template-factored: a carried [T,N] plane is
          row-gathered per pod and recomputed for ONE column per
          placement (``_template_col``);
        * mask -> score -> tie-break -> select runs through the fused
          select kernel (engine/fused.py; Pallas on TPU, XLA fused
          elsewhere) — three node-axis reductions per step.

        ``live`` and ``extra_mask`` are already folded into
        ``static_mask`` by the caller."""
        del live  # folded into static_mask by _solve_scan
        n = c.alloc.shape[0]
        a = b.aff
        f32 = jnp.float32
        neg = f32(-jnp.inf)
        zone_ids = b.node_zone_id
        fits_pods_alloc = c.alloc[:, RES_PODS]
        alloc3 = c.alloc[:, :3]
        select = self._select
        use_resources = fams["use_resources"]
        use_ports = fams["use_ports"]
        use_volumes = fams["use_volumes"]
        use_interpod = fams["use_interpod"]
        use_max_ebs = fams["use_max_ebs"]
        use_max_gce = fams["use_max_gce"]
        track_affinity = fams["track_affinity"]
        track_spread = fams["track_spread"]
        track_spread_zones = fams["track_spread_zones"]
        track_saa = fams["track_saa"]

        tmpl_prios = tuple(sp for sp in dynamic_prios
                           if sp[0] in self._TEMPLATE_PRIOS)
        other_prios = tuple(sp for sp in dynamic_prios
                            if sp[0] not in self._TEMPLATE_PRIOS)
        use_templates = bool(tmpl_prios) and b.nz_templates.shape[0] > 0
        if not use_templates:
            other_prios = dynamic_prios
            tmpl_prios = ()

        # The encoded static plane: score where statically feasible,
        # -inf elsewhere — one xs row per step instead of mask + score.
        # Narrow score accumulation: when the greedy score is provably
        # small-integral (no joint price bias; the policy's summed
        # weight x MAX_PRIORITY bound fits the half-width mantissa
        # exactly), the plane stores at half width — halving the
        # dominant hoisted-plane bytes and the per-step row read — and
        # every step widens its one row back to f32 before the reduce
        # (the "bf16 accumulate, f32 final reduce" policy: bf16 on TPU,
        # f16 — wider mantissa — elsewhere; -inf encodes exactly in
        # both).  Values are integers well inside the exact range, so
        # tie sets cannot move (parity-pinned).
        enc = jnp.where(static_mask, static_score, neg)
        weight_bound = sum(abs(w) for _n, w, _a in self.priority_specs) \
            * prio.MAX_PRIORITY
        if not has_bias:
            exact = 256 if self._half_dtype is jnp.bfloat16 else 2048
            if weight_bound < exact:
                enc = enc.astype(self._half_dtype)

        def step(state, xs):
            counter = state["counter"]
            packed = state["packed"]
            masked = xs["enc"].astype(f32)

            # Dynamic score families (identical formulas to the legacy
            # body; template-factored ones come from the carried plane).
            if use_templates:
                masked = masked + state["D"][xs["tmpl"]]
            for name, weight, aux in other_prios:
                w = f32(weight)
                if name == "LeastRequestedPriority":
                    masked = masked + w * prio.least_requested(
                        xs["nz"][None], packed[:, 4:6], c.alloc)[0]
                elif name == "MostRequestedPriority":
                    masked = masked + w * prio.most_requested(
                        xs["nz"][None], packed[:, 4:6], c.alloc)[0]
                elif name == "BalancedResourceAllocation":
                    masked = masked + w * prio.balanced_resource_allocation(
                        xs["nz"][None], packed[:, 4:6], c.alloc)[0]
                elif name in ("SelectorSpreadPriority",
                              "ServiceSpreadingPriority"):
                    # Reduction-free selector spread: the per-step max
                    # reductions of prio.selector_spread are replaced by
                    # CARRIED per-group maxima (sp_maxn over schedulable
                    # nodes, sp_maxz over zones) — counts only grow, and
                    # only at the placed column, so the maxima update in
                    # O(S) at commit.  Term-for-term the same float
                    # expressions as selector_spreading.go via
                    # prio.selector_spread (parity-pinned).
                    g = xs["sgroup"]
                    counts_g = state["sp_node"][g]          # [N]
                    maxn_g = state["sp_maxn"][g]
                    fsc = jnp.where(
                        maxn_g > 0,
                        10.0 * ((maxn_g - counts_g)
                                / jnp.maximum(maxn_g, 1e-9)), 10.0)
                    if track_spread_zones:
                        zc_g = state["sp_zone"][g]          # [Z]
                        maxz_g = state["sp_maxz"][g]
                        zs_z = 10.0 * ((maxz_g - zc_g)
                                       / jnp.maximum(maxz_g, 1e-9))
                        node_has_zone = zone_ids >= 0
                        zs_n = jnp.where(
                            node_has_zone,
                            zs_z[jnp.clip(zone_ids, 0)],
                            10.0 * (maxz_g
                                    / jnp.maximum(maxz_g, 1e-9)))
                        blended = fsc * (1.0 - 2.0 / 3.0) + \
                            (2.0 / 3.0) * zs_n
                        fsc = jnp.where(
                            b.spread_has_zones[g] & node_has_zone
                            & (maxz_g > 0), blended, fsc)
                    masked = masked + w * prio._trunc(fsc)
                elif name == "InterPodAffinityPriority":
                    counts = interpod.priority_counts(
                        xs["pref_w"][None], state["match_cnt"],
                        xs["sym_match"][None], a.sym_w, state["sym_cnt"])
                    masked = masked + w * interpod.priority_score(
                        counts, c.schedulable, prio._trunc)[0]
                elif name == "ServiceAntiAffinityPriority":
                    masked = masked + w * saa_plane(
                        state["saa_cnt"][aux][xs["saa_g"]][None],
                        state["saa_num"][xs["saa_g"]][None, None],
                        b.volsvc.saa_dom[aux],
                        b.volsvc.saa_labeled[aux])[0]

            # Dynamic predicates folded into the encoded plane by one
            # where (legacy: per-family boolean ANDs into `feasible`).
            dyn_ok = None

            def also(cond):
                return cond if dyn_ok is None else (dyn_ok & cond)

            if use_resources:
                fits_pods = (packed[:, RES_PODS] + 1) <= fits_pods_alloc
                free = alloc3 - packed[:, :3]
                fits_res = jnp.all(xs["req"][None, :3] <= free, axis=-1)
                dyn_ok = also(fits_pods & (xs["zero"] | fits_res))
            if use_ports:
                port_conflict = jnp.einsum(
                    "c,nc->n", xs["ports"].astype(f32),
                    state["ports_used"].astype(f32)) > 0
                dyn_ok = also(~port_conflict)
            if use_volumes:
                vol_conflict = (
                    jnp.einsum("w,nw->n", xs["vrw"].astype(f32),
                               state["vol_any"].astype(f32)) +
                    jnp.einsum("w,nw->n", xs["vro"].astype(f32),
                               state["vol_rw"].astype(f32))) > 0
                dyn_ok = also(~vol_conflict)
            for fam in ("ebs", "gce") if (use_max_ebs or use_max_gce) \
                    else ():
                if (fam == "ebs" and not use_max_ebs) or \
                        (fam == "gce" and not use_max_gce):
                    continue
                pd_node = state[f"pd_{fam}"]
                pod_row = xs[f"pd_pod_{fam}"].astype(f32)
                overlap = jnp.einsum("w,nw->n", pod_row,
                                     pd_node.astype(f32))
                new = jnp.sum(pod_row) + xs[f"pd_extra_{fam}"].astype(f32)
                node_extra = getattr(b.volsvc, f"pd_node_extra_{fam}")
                node_err = getattr(b.volsvc, f"pd_node_err_{fam}")
                total = jnp.sum(pd_node.astype(f32), axis=1) + \
                    node_extra.astype(f32) + new - overlap
                ok = (total <= f32(self.extra[f"max_{fam}"])) & ~node_err
                dyn_ok = also((new == 0) | ok)
            if track_affinity:
                reach = state["match_cnt"] > 0.0  # [Sm, N]
            if use_interpod:
                live_need = xs["aff_need"] & ~(
                    xs["aff_self"] & (state["match_total"] == 0.0))
                viol = (jnp.einsum("s,sn->n", live_need.astype(f32),
                                   (~reach).astype(f32)) +
                        jnp.einsum("s,sn->n", xs["anti_need"].astype(f32),
                                   reach.astype(f32)) +
                        jnp.einsum("s,sn->n", xs["decl_match"].astype(f32),
                                   state["decl_reach"].astype(f32))) > 0
                dyn_ok = also(~viol)
            if dyn_ok is not None:
                masked = jnp.where(dyn_ok, masked, neg)

            # Fused selectHost (generic_scheduler.go:124-141).
            choice, any_feasible = select(masked, counter)

            # Commit (the batched AssumePod, cache.go:107) — one-row /
            # one-column scatters instead of full-plane rewrites.
            placed = choice >= 0
            j = jnp.clip(choice, 0)
            pi = placed.astype(jnp.int32)
            pf = placed.astype(f32)
            new_state = dict(state)
            req6 = jnp.concatenate([xs["req"], xs["nz"]])
            new_packed = packed.at[j].add(req6 * pi)
            new_state["packed"] = new_packed
            if use_templates:
                new_state["D"] = state["D"].at[:, j].set(
                    self._template_col(tmpl_prios, b.nz_templates,
                                       new_packed[j, 4:6], c.alloc[j]))
            if use_ports:
                new_state["ports_used"] = state["ports_used"].at[j].set(
                    state["ports_used"][j] | (xs["ports"] & placed))
            if use_volumes:
                new_state["vol_any"] = state["vol_any"].at[j].set(
                    state["vol_any"][j] |
                    ((xs["vrw"] | xs["vro"]) & placed))
                new_state["vol_rw"] = state["vol_rw"].at[j].set(
                    state["vol_rw"][j] | (xs["vrw"] & placed))
            if track_spread:
                incr_f = xs["incr"].astype(f32) * pf          # [S]
                new_col = state["sp_node"][:, j] + incr_f
                new_state["sp_node"] = state["sp_node"].at[:, j].set(
                    new_col)
                # The placed node is feasible hence schedulable, so the
                # max-over-schedulable can only move through its column;
                # unplaced steps must NOT fold column 0 (clip target) of
                # a possibly-unschedulable node into the maximum.
                new_state["sp_maxn"] = jnp.where(
                    placed, jnp.maximum(state["sp_maxn"], new_col),
                    state["sp_maxn"])
                if track_spread_zones:
                    zid = zone_ids[j]
                    zc = jnp.clip(zid, 0)
                    zval = incr_f * (zid >= 0).astype(f32)
                    new_zcol = state["sp_zone"][:, zc] + zval
                    new_state["sp_zone"] = state["sp_zone"] \
                        .at[:, zc].set(new_zcol)
                    new_state["sp_maxz"] = jnp.where(
                        placed & (zid >= 0),
                        jnp.maximum(state["sp_maxz"], new_zcol),
                        state["sp_maxz"])
            if use_max_ebs:
                new_state["pd_ebs"] = state["pd_ebs"].at[j].set(
                    state["pd_ebs"][j] | (xs["pd_pod_ebs"] & placed))
            if use_max_gce:
                new_state["pd_gce"] = state["pd_gce"].at[j].set(
                    state["pd_gce"][j] | (xs["pd_pod_gce"] & placed))
            if track_saa:
                src = xs["saa_src"].astype(f32) * pf          # [Gy]
                new_state["saa_num"] = state["saa_num"] + src
                dom_j = b.volsvc.saa_dom[:, j]                # [L]
                lab_j = b.volsvc.saa_labeled[:, j] & placed   # [L]
                n_dom = state["saa_cnt"].shape[2]
                domoh = ((jnp.arange(n_dom, dtype=jnp.int32)[None, :]
                          == dom_j[:, None]) & lab_j[:, None]).astype(f32)
                new_state["saa_cnt"] = state["saa_cnt"] + \
                    domoh[:, None, :] * src[None, :, None]
            if track_affinity:
                (new_state["match_cnt"], new_state["match_total"],
                 new_state["decl_reach"], new_state["sym_cnt"]) = \
                    interpod.place_update(
                        a.node_dom, a.match_key, state["match_cnt"],
                        state["match_total"], xs["match_src"],
                        a.decl_key, state["decl_reach"], xs["decl_src"],
                        a.sym_key, state["sym_cnt"], xs["sym_src"],
                        choice, placed)
            new_state["counter"] = counter + \
                jnp.where(any_feasible, jnp.uint32(1), jnp.uint32(0))
            return new_state, choice

        init = {
            "packed": jnp.concatenate([c.requested, c.nonzero], axis=1),
            "counter": last_node_index,
        }
        xs = {
            "req": b.request, "zero": b.zero_request, "nz": b.nonzero,
            "enc": enc,
        }
        if use_templates:
            D0 = jnp.zeros((b.nz_templates.shape[0], n), f32)
            for name, weight, _aux in tmpl_prios:
                w = f32(weight)
                if name == "LeastRequestedPriority":
                    D0 = D0 + w * prio.least_requested(
                        b.nz_templates, c.nonzero, c.alloc)
                elif name == "MostRequestedPriority":
                    D0 = D0 + w * prio.most_requested(
                        b.nz_templates, c.nonzero, c.alloc)
                elif name == "BalancedResourceAllocation":
                    D0 = D0 + w * prio.balanced_resource_allocation(
                        b.nz_templates, c.nonzero, c.alloc)
            init["D"] = D0
            xs["tmpl"] = b.nz_tmpl_idx
        if use_ports:
            init["ports_used"] = c.ports_used
            xs["ports"] = b.ports
        if use_volumes:
            init["vol_any"] = c.vol_any
            init["vol_rw"] = c.vol_rw
            xs["vro"] = b.vol_ro
            xs["vrw"] = b.vol_rw
        if track_spread:
            init["sp_node"] = b.spread_node_counts
            init["sp_zone"] = b.spread_zone_counts
            # Carried maxima, seeded exactly like the per-step
            # reductions they replace (selector_spreading.go's
            # countsByNodeName max spans the ready node list; the zone
            # max spans all zones).
            init["sp_maxn"] = jnp.max(
                jnp.where(c.schedulable[None, :],
                          b.spread_node_counts, 0.0), axis=1)
            init["sp_maxz"] = jnp.max(b.spread_zone_counts, axis=1)
            xs["sgroup"] = b.spread_group
            xs["incr"] = b.spread_incr
        if track_affinity:
            init.update(match_cnt=a.match_cnt, match_total=a.match_total,
                        decl_reach=a.decl_reach, sym_cnt=a.sym_cnt)
            xs.update(aff_need=a.aff_need, aff_self=a.aff_self,
                      anti_need=a.anti_need, decl_match=a.decl_match,
                      match_src=a.match_src, decl_src=a.decl_src,
                      pref_w=a.pref_w, sym_match=a.sym_match,
                      sym_src=a.sym_src)
        if track_saa:
            init["saa_cnt"] = b.volsvc.saa_cnt
            init["saa_num"] = b.volsvc.saa_num
            xs["saa_g"] = b.volsvc.saa_group
            xs["saa_src"] = b.volsvc.saa_src
        if use_max_ebs:
            init["pd_ebs"] = b.volsvc.pd_node_ebs
            xs["pd_pod_ebs"] = b.volsvc.pd_pod_ebs
            xs["pd_extra_ebs"] = b.volsvc.pd_extra_ebs
        if use_max_gce:
            init["pd_gce"] = b.volsvc.pd_node_gce
            xs["pd_pod_gce"] = b.volsvc.pd_pod_gce
            xs["pd_extra_gce"] = b.volsvc.pd_extra_gce
        if carry is not None:
            init.update({k: v for k, v in carry.items() if k in init})
        final, choices = jax.lax.scan(step, init, xs, unroll=SCAN_UNROLL)
        return choices, final["counter"], final

    # -- joint batched assignment (the LP-relaxed global solve) ----------

    # kt-xray: no-donate(b/c flow on into the repair scan of the same
    # joint solve)
    @functools.partial(jax.jit, static_argnums=(0, 3))
    def _price_iterate(self, b: DeviceBatch, c: DeviceCluster,
                       n_iters: int,
                       extra_mask: jnp.ndarray | None = None
                       ) -> tuple[jnp.ndarray, jnp.ndarray]:
        """Dual-price iteration for the joint assignment objective.

        The batched placement is a generalized assignment problem: maximize
        the summed combined score subject to per-node multi-resource
        capacity.  Its LP relaxation decomposes by pricing each node
        resource (dual variables lam [N, R]): pods bid for their
        utility-argmax node, prices rise on oversubscribed resources
        (projected subgradient on the dual), and the final prices shape a
        regret-ordered greedy repair pass that restores full feasibility
        (including ports/volumes/affinity) in ``solve_joint``.

        Returns (score_bias [P, N] = -price cost, repair-order key [P]).
        """
        c = widen_cluster(c)
        feasible, scores = self.evaluate(b, c)
        if extra_mask is not None:
            feasible &= extra_mask
        f32 = jnp.float32
        free = jnp.maximum((c.alloc[:, :3] - c.requested[:, :3]).astype(f32),
                           1.0)                          # [N, 3]
        demand = b.request[:, :3].astype(f32)            # [P, 3]
        # Normalize so prices are in score units per fraction-of-node.
        dnorm = demand[:, None, :] / free[None, :, :]    # [P, N, 3]
        neg = f32(-jnp.inf)
        score_span = jnp.maximum(jnp.max(jnp.where(feasible, scores, 0.0)),
                                 1.0)
        lr = score_span  # one full-node oversubscription ~ top score

        def it(lam, _):
            cost = jnp.einsum("pnr,nr->pn", dnorm, lam)
            util = jnp.where(feasible, scores - cost, neg)
            choice = jnp.argmax(util, axis=1)            # [P]
            placed = jnp.any(feasible, axis=1)
            onehot = (jax.nn.one_hot(choice, util.shape[1], dtype=f32)
                      * placed[:, None].astype(f32))     # [P, N]
            load = jnp.einsum("pn,pr->nr", onehot, demand)  # [N, 3]
            over = jnp.maximum(load - free, 0.0) / free
            lam = jnp.maximum(lam + lr * over - 0.02 * lr * (over == 0), 0.0)
            return lam, None

        lam0 = jnp.zeros((c.alloc.shape[0], 3), f32)
        lam, _ = jax.lax.scan(it, lam0, None, length=n_iters)
        cost = jnp.einsum("pnr,nr->pn", dnorm, lam)
        util = jnp.where(feasible, scores - cost, neg)
        top2 = jax.lax.top_k(util, 2)[0] if util.shape[1] > 1 else \
            jnp.pad(util, ((0, 0), (0, 1)), constant_values=neg)
        regret = jnp.where(jnp.isfinite(top2[:, 0]),
                           top2[:, 0] - jnp.where(jnp.isfinite(top2[:, 1]),
                                                  top2[:, 1], top2[:, 0] - 1e3),
                           neg)
        # Repair-order key: smallest dominant-resource fraction first (for a
        # sum-of-scores objective with commensurate per-pod scores this
        # maximizes admitted count), regret-tiebroken within a size bucket.
        dfrac = jnp.max(demand[:, None, :] / free[None, :, :], axis=(1, 2))
        key = -jnp.floor(jnp.minimum(dfrac, 1.0) * 16.0) * \
            (20.0 * score_span) + jnp.where(jnp.isfinite(regret), regret, 0.0)
        return -cost, key

    # kt-xray: no-donate(c is the shared resident cluster; donation
    # would invalidate it for the next drain's scatter)
    @functools.partial(jax.jit, static_argnums=(0, 7, 8))
    def _solve_joint_jit(self, b: DeviceBatch, c: DeviceCluster,
                         last_node_index: jnp.ndarray,
                         extra_mask: jnp.ndarray | None,
                         score_bias: jnp.ndarray | None,
                         live: jnp.ndarray | None,
                         n_iters: int, flags: BatchFlags
                         ) -> tuple[jnp.ndarray, jnp.ndarray, dict]:
        """The WHOLE joint pipeline (price iteration -> regret ordering ->
        pod-axis permutation -> repair scan -> inverse permutation) as ONE
        jitted executable.  The pre-r6 host-side glue dispatched ~75
        individual device ops per solve (argsort + one jnp.take per
        DeviceBatch field), each minting its own shape-keyed executable
        OUTSIDE the jit cache — none of which the persistent compilation
        cache could amortize as a unit.  One trace means one XLA program,
        persisted once, deserialized on every later start
        (tests/test_joint_solver.py pins the cold-vs-warm gap)."""
        c = widen_cluster(c)
        bias, key = self._price_iterate(b, c, n_iters, extra_mask)
        if score_bias is not None:
            bias = bias + score_bias
        order = jnp.argsort(-key)   # biggest, then highest-regret, first
        pb = permute_pod_axis(b, order)
        pbias = jnp.take(bias, order, axis=0)
        pmask = None if extra_mask is None else \
            jnp.take(extra_mask, order, axis=0)
        plive = None if live is None else jnp.take(live, order)
        choices_p, counter, final = self._solve_scan(
            pb, c, last_node_index, pbias, flags, None, plive, pmask)
        inv = jnp.argsort(order)
        return jnp.take(choices_p, inv), counter, final

    def solve_joint(self, b: DeviceBatch, c: DeviceCluster,
                    last_node_index: jnp.ndarray, n_iters: int = 24,
                    flags: BatchFlags | None = None,
                    extra_mask: jnp.ndarray | None = None,
                    score_bias: jnp.ndarray | None = None,
                    live: jnp.ndarray | None = None
                    ) -> tuple[jnp.ndarray, jnp.ndarray, DeviceCluster]:
        """Joint batched assignment: price iteration + regret-ordered greedy
        repair.  Same return contract as solve_sequential; placements honor
        EVERY predicate (the repair pass is the exact sequential scan, just
        price-shaped and reordered) plus the workload-constraint
        ``extra_mask``/``score_bias`` planes.  ``live`` marks real rows
        when the caller padded the batch to a warm bucket.  Quality
        (summed score, placement count) is benchmarked against the greedy
        baseline — BASELINE.json's last config."""
        if flags is None:
            flags = batch_flags(b)
        choices, counter, final = self._solve_joint_jit(
            b, c, last_node_index, extra_mask, score_bias, live,
            n_iters, flags)
        return choices, counter, self._carry_cluster(c, final)


# Pod-axis fields of DeviceBatch (dim 0 = P) for permutation/sharding.
_POD_AXIS_FIELDS = ("request", "zero_request", "nonzero", "best_effort",
                    "host_idx", "ports", "vol_ro", "vol_rw", "tol_nosched",
                    "tol_prefer", "has_tolerations", "images", "sel_group",
                    "spread_group", "spread_incr", "avoid_group",
                    "nz_tmpl_idx")
_AFF_POD_AXIS_FIELDS = ("match_src", "aff_need", "aff_self", "anti_need",
                        "pref_w", "decl_match", "decl_src", "sym_match",
                        "sym_src")
_VS_POD_AXIS_FIELDS = ("pd_pod_ebs", "pd_extra_ebs", "pd_pod_gce",
                       "pd_extra_gce", "vz_group", "sa_group", "saa_group",
                       "saa_src")


def slice_pod_axis(b: DeviceBatch, start: int, stop: int) -> DeviceBatch:
    """A [start:stop) view of every pod-axis tensor (chunked drain)."""
    updates = {f: getattr(b, f)[start:stop] for f in _POD_AXIS_FIELDS}
    aff = b.aff._replace(**{f: getattr(b.aff, f)[start:stop]
                            for f in _AFF_POD_AXIS_FIELDS})
    volsvc = b.volsvc._replace(**{f: getattr(b.volsvc, f)[start:stop]
                                  for f in _VS_POD_AXIS_FIELDS})
    return b._replace(aff=aff, volsvc=volsvc, **updates)


def permute_pod_axis(b: DeviceBatch, order: jnp.ndarray) -> DeviceBatch:
    """Reorder every pod-axis tensor of a DeviceBatch by ``order``."""
    updates = {f: jnp.take(getattr(b, f), order, axis=0)
               for f in _POD_AXIS_FIELDS}
    aff = b.aff._replace(**{f: jnp.take(getattr(b.aff, f), order, axis=0)
                            for f in _AFF_POD_AXIS_FIELDS})
    volsvc = b.volsvc._replace(
        **{f: jnp.take(getattr(b.volsvc, f), order, axis=0)
           for f in _VS_POD_AXIS_FIELDS})
    return b._replace(aff=aff, volsvc=volsvc, **updates)
