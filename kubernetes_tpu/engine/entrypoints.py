"""The jitted-entrypoint registry: every live-path XLA program, named.

PAPER.md calls the JAX-generated XLA/Pallas kernels this system's
"native layer"; this module is that layer's table of contents.  Each
entry names one compiled program family the runtime can dispatch — the
jit entrypoint(s) it compiles through, the runtime dispatch site that
launches it, and the live-path label the PR 9 recompile watchdog files
its compiles under.

Consumers:

* ``kubernetes_tpu/analysis/xray.py`` abstractly traces every entry via
  ``jax.eval_shape`` / ``jax.make_jaxpr`` (no device, no compile) into
  the committed ``tools/shape_manifest.json`` and proves the X-rules
  over the jaxprs;
* rule X04 cross-checks this registry three ways: every AST-discovered
  jit site under ``engine/`` must be claimed by some entry (an
  unregistered jit entrypoint is an unmanifested compile surface),
  every entry's dispatch site must exist, and the manifest's warmed
  programs must equal ``scheduler.prewarm_plan``'s canonical plan.

Adding a jitted function to the engine without registering it here
fails tier-1 — by design: a new compile surface must be manifested
(and prewarmed) before it can ship.
"""

from __future__ import annotations

from typing import NamedTuple


class EntrySpec(NamedTuple):
    """One live-path program family.

    ``name``: program-family name; manifest program keys are either the
    bare name or ``name@<pod bucket>``.
    ``live_path``: the ``devicestats.live_path`` label its dispatch
    site runs under ("" = launched outside a watchdog-labelled region,
    e.g. the single-pod failure-detail masks pass).
    ``jit_entrypoints``: ``"<repo-relative path>:<function>"`` of each
    jit/pjit site this family compiles through.
    ``dispatch_site``: ``"<repo-relative path>:<function>"`` of the
    runtime function that launches it.
    ``warmed``: traced by ``Scheduler.prewarm()`` (X04 pins the warmed
    set against ``scheduler.prewarm_plan``).
    """

    name: str
    live_path: str
    jit_entrypoints: tuple[str, ...]
    dispatch_site: str
    warmed: bool
    doc: str


_SOLVER = "kubernetes_tpu/engine/solver.py"
_GS = "kubernetes_tpu/engine/generic_scheduler.py"
_PRE = "kubernetes_tpu/engine/workloads/preemption.py"
_TOPO = "kubernetes_tpu/engine/workloads/topology.py"

ENTRYPOINTS: tuple[EntrySpec, ...] = (
    EntrySpec(
        "scan_first", "stream", (f"{_SOLVER}:_solve_scan",),
        f"{_GS}:schedule_batch_stream", True,
        "First stream chunk / one-shot sequential solve: the scan with "
        "no carried state, live-mask padded to a ladder bucket (the "
        "fused body under KT_FUSED — packed aggregates, template "
        "score planes, fused select; the canonical manifest records "
        "the fused jaxpr)."),
    EntrySpec(
        "scan_carry", "stream", (f"{_SOLVER}:_solve_scan",),
        f"{_GS}:schedule_batch_stream", True,
        "Later stream chunks: the same scan continuing the previous "
        "chunk's carried (donated) state."),
    EntrySpec(
        "oneshot_topo", "oneshot", (f"{_SOLVER}:_solve_scan",),
        f"{_GS}:schedule_batch", True,
        "The workload-constrained one-shot solve: extra_mask + "
        "score_bias planes (topology spread) enter the scan at the "
        "floor bucket (gang drains pad onto the same signatures)."),
    EntrySpec(
        "joint", "joint",
        (f"{_SOLVER}:_solve_joint_jit", f"{_SOLVER}:_price_iterate"),
        f"{_GS}:schedule_batch", True,
        "The LP-relaxed joint assignment: price iteration + regret "
        "ordering + repair scan as one executable."),
    EntrySpec(
        "single_evaluate", "single_pod", (f"{_SOLVER}:evaluate",),
        f"{_GS}:_schedule_device", True,
        "The single-pod decision path's feasibility/score evaluation "
        "(schedule_one, recovery parity probes)."),
    EntrySpec(
        "single_masks", "", (f"{_SOLVER}:masks",),
        f"{_GS}:_schedule_device", False,
        "Per-predicate masks for FitError detail — the single-pod "
        "failure branch plus explain_failures/preemption masks passes; "
        "launched outside the live-path clock, so prewarm does not "
        "trace it (X04 tracks it as a manifested, unwarmed surface)."),
    EntrySpec(
        "select_hosts", "single_pod", (),
        f"{_GS}:_schedule_device", True,
        "Vectorized selectHost (ops/combine.py) — eager jnp ops, not a "
        "jit site, but still a compiled live-path program; prewarm's "
        "single-pod trace covers it."),
    EntrySpec(
        "scatter", "stream", (f"{_SOLVER}:_scatter_fn",),
        f"{_SOLVER}:sync", True,
        "The dirty-row scatter kernel of the device-resident mirror, "
        "compiled per pow2 dirty-row bucket "
        "(ResidentCluster.scatter_buckets)."),
    EntrySpec(
        "victim_solve", "victim", (f"{_PRE}:victim_solve",),
        f"{_GS}:_find_preemptions_inner", True,
        "The vmapped minimal-victim-prefix kernel of priority "
        "preemption."),
    EntrySpec(
        "topo_planes", "oneshot", (f"{_TOPO}:_planes_kernel",),
        f"{_TOPO}:spread_planes", True,
        "Topology-spread hard-mask/soft-score planes contracted "
        "against the cluster topology tensor."),
)


def by_name() -> dict[str, EntrySpec]:
    return {e.name: e for e in ENTRYPOINTS}


def claimed_jit_entrypoints() -> set[str]:
    """Every ``path:function`` some registered family compiles
    through — X04's 'no unmanifested jit entrypoints' universe."""
    out: set[str] = set()
    for e in ENTRYPOINTS:
        out.update(e.jit_entrypoints)
    return out
