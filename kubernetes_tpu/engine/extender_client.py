"""HTTP scheduler-extender client (the reference's HTTPExtender,
extender.go:39-187): this engine can also *call out* to extenders configured
in the policy, exactly as the stock scheduler does — filter after built-in
predicates, prioritize added at the configured weight.

Timeout semantics (api/types.go:128-130): a filter timeout fails the pod's
scheduling; a prioritize timeout is ignored (zero scores).

Hardening beyond the reference: filter/prioritize exchanges are read-only
queries, so transport faults get one bounded retry; consecutive failures
trip a circuit breaker (``utils.circuitbreaker``).  While the breaker is
open, ``filter`` raises ``ExtenderUnavailable`` — the engine treats that as
"skip this extender" (built-in-predicates-only degradation) instead of the
per-pod scheduling failure a closed-breaker timeout still causes.  A dead
extender therefore fails at most ``BREAKER_THRESHOLD`` pods per breaker
window instead of every pod forever."""

from __future__ import annotations

import http.client
import json
import random
import socket
import time
import urllib.error
import urllib.request
import weakref

from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.policy import ExtenderConfig
from kubernetes_tpu.utils import metrics
from kubernetes_tpu.utils import trace
from kubernetes_tpu.utils.circuitbreaker import OPEN, CircuitBreaker
from kubernetes_tpu.utils.logging import get_logger

log = get_logger("extender")

# Faults where the exchange did not complete (connection refused, timeout,
# garbled/truncated response): retriable, counted on the breaker.  Note
# http.client.HTTPException (BadStatusLine, IncompleteRead) is NOT an
# OSError — omitting it would let a half-open trial escape without
# recording, wedging the breaker in half-open forever.
TRANSPORT_ERRORS = (urllib.error.URLError, http.client.HTTPException,
                    socket.timeout, OSError)

# Bounded retry of one extender exchange: the calls are idempotent reads,
# but the pod's scheduling latency is on the line — one quick retry, no
# more (the breaker handles persistent death).
EXTENDER_MAX_RETRIES = 1
EXTENDER_RETRY_SLEEP = 0.05

# Breaker: N consecutive transport failures open it for T seconds.
BREAKER_THRESHOLD = 3
BREAKER_RESET_S = 15.0


class ExtenderError(Exception):
    pass


class ExtenderUnavailable(ExtenderError):
    """The extender's circuit breaker is open: the endpoint is known-dead
    and was not called.  The engine degrades to built-in predicates for
    this extender rather than failing the pod."""


# The open-breaker gauge reads live object state, not paired inc/dec: an
# HTTPExtender discarded while its breaker is open (scheduler rebuilt
# with a new policy) silently leaves the set when it is collected, so the
# gauge can never stick at >=1 with zero breakers actually open.
_OPEN_BREAKERS: "weakref.WeakSet[CircuitBreaker]" = weakref.WeakSet()
metrics.EXTENDER_BREAKER_OPEN.set_fn(lambda: len(_OPEN_BREAKERS))


class HTTPExtender:
    def __init__(self, config: ExtenderConfig,
                 breaker: CircuitBreaker | None = None):
        self.config = config
        self.breaker = breaker or CircuitBreaker(
            failure_threshold=BREAKER_THRESHOLD,
            reset_timeout=BREAKER_RESET_S,
            on_transition=self._on_breaker_transition)

    def _on_breaker_transition(self, old: str, new: str) -> None:
        metrics.EXTENDER_BREAKER_TRANSITIONS.labels(state=new).inc()
        # One line per state change (not per pod: the scheduler degrades
        # thousands of pods per open window — see generic_scheduler.py).
        log.warning("extender %s breaker %s -> %s",
                    self.config.url_prefix, old, new)
        if new == OPEN:
            _OPEN_BREAKERS.add(self.breaker)
        elif old == OPEN:
            _OPEN_BREAKERS.discard(self.breaker)

    def _send(self, verb: str, args: dict):
        url = (f"{self.config.url_prefix.rstrip('/')}/"
               f"{self.config.api_version}/{verb}")
        headers = {"Content-Type": "application/json"}
        tp = trace.traceparent()
        if tp:
            headers["traceparent"] = tp
        req = urllib.request.Request(
            url, data=json.dumps(args).encode(),
            headers=headers, method="POST")
        with urllib.request.urlopen(
                req, timeout=self.config.http_timeout_s) as resp:
            return json.loads(resp.read())

    def _send_with_retry(self, verb: str, args: dict):
        """One bounded retry on transport faults; records the outcome on
        the breaker.  Wire-contract errors (the server answered) count as
        successes for the breaker — the endpoint is alive."""
        attempt = 0
        while True:
            try:
                result = self._send(verb, args)
            except (urllib.error.HTTPError, ValueError):
                # The server ANSWERED (an HTTP error status, or a 200
                # with malformed JSON): the endpoint is alive, so the
                # breaker records a success, and a retry would only
                # repeat the same answer.  The caller still applies the
                # per-call semantics (filter error fails this pod).
                self.breaker.record_success()
                raise
            except TRANSPORT_ERRORS:
                if attempt < EXTENDER_MAX_RETRIES:
                    metrics.EXTENDER_RETRIES.labels(verb=verb).inc()
                    attempt += 1
                    time.sleep(EXTENDER_RETRY_SLEEP *
                               (0.5 + random.random()))
                    continue
                self.breaker.record_failure()
                raise
            self.breaker.record_success()
            return result

    def _args(self, pod: api.Pod, nodes: list[api.Node]) -> dict:
        return {"pod": api.pod_to_json(pod),
                "nodes": {"items": [api.node_to_json(n) for n in nodes]}}

    def filter(self, pod: api.Pod, nodes: list[api.Node]
               ) -> tuple[list[api.Node], dict[str, str]]:
        """Subset + FailedNodesMap; raises ExtenderError on error/timeout
        (extender.go:97-125), ExtenderUnavailable while the breaker is
        open (the caller degrades instead of failing the pod)."""
        if not self.config.filter_verb:
            return nodes, {}
        if not self.breaker.allow():
            raise ExtenderUnavailable(
                f"extender {self.config.url_prefix} circuit open")
        try:
            result = self._send_with_retry(self.config.filter_verb,
                                           self._args(pod, nodes))
        except TRANSPORT_ERRORS + (ValueError,) as err:
            raise ExtenderError(f"extender filter failed: {err}") from err
        if result.get("error"):
            raise ExtenderError(result["error"])
        keep_names = {(n.get("metadata") or {}).get("name", "")
                      for n in (result.get("nodes") or {}).get("items") or []}
        kept = [n for n in nodes if n.name in keep_names]
        return kept, dict(result.get("failedNodes") or {})

    def prioritize(self, pod: api.Pod, nodes: list[api.Node]
                   ) -> dict[str, float]:
        """Weighted score per host; errors/timeouts yield zeros
        (generic_scheduler.go:287-305 ignores prioritize failures), as
        does an open breaker (no call is made)."""
        if not self.config.prioritize_verb:
            return {}
        if not self.breaker.allow():
            return {}
        try:
            result = self._send_with_retry(self.config.prioritize_verb,
                                           self._args(pod, nodes))
        except TRANSPORT_ERRORS + (ValueError,):
            return {}
        out: dict[str, float] = {}
        for entry in result or []:
            host = entry.get("host", "")
            if host:
                out[host] = float(entry.get("score", 0)) * self.config.weight
        return out
