"""HTTP scheduler-extender client (the reference's HTTPExtender,
extender.go:39-187): this engine can also *call out* to extenders configured
in the policy, exactly as the stock scheduler does — filter after built-in
predicates, prioritize added at the configured weight.

Timeout semantics (api/types.go:128-130): a filter timeout fails the pod's
scheduling; a prioritize timeout is ignored (zero scores)."""

from __future__ import annotations

import json
import socket
import urllib.error
import urllib.request

from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.policy import ExtenderConfig


class ExtenderError(Exception):
    pass


class HTTPExtender:
    def __init__(self, config: ExtenderConfig):
        self.config = config

    def _send(self, verb: str, args: dict):
        url = (f"{self.config.url_prefix.rstrip('/')}/"
               f"{self.config.api_version}/{verb}")
        req = urllib.request.Request(
            url, data=json.dumps(args).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(
                req, timeout=self.config.http_timeout_s) as resp:
            return json.loads(resp.read())

    def _args(self, pod: api.Pod, nodes: list[api.Node]) -> dict:
        return {"pod": api.pod_to_json(pod),
                "nodes": {"items": [api.node_to_json(n) for n in nodes]}}

    def filter(self, pod: api.Pod, nodes: list[api.Node]
               ) -> tuple[list[api.Node], dict[str, str]]:
        """Subset + FailedNodesMap; raises ExtenderError on error/timeout
        (extender.go:97-125)."""
        if not self.config.filter_verb:
            return nodes, {}
        try:
            result = self._send(self.config.filter_verb,
                                self._args(pod, nodes))
        except (urllib.error.URLError, socket.timeout, OSError,
                ValueError) as err:
            raise ExtenderError(f"extender filter failed: {err}") from err
        if result.get("error"):
            raise ExtenderError(result["error"])
        keep_names = {(n.get("metadata") or {}).get("name", "")
                      for n in (result.get("nodes") or {}).get("items") or []}
        kept = [n for n in nodes if n.name in keep_names]
        return kept, dict(result.get("failedNodes") or {})

    def prioritize(self, pod: api.Pod, nodes: list[api.Node]
                   ) -> dict[str, float]:
        """Weighted score per host; errors/timeouts yield zeros
        (generic_scheduler.go:287-305 ignores prioritize failures)."""
        if not self.config.prioritize_verb:
            return {}
        try:
            result = self._send(self.config.prioritize_verb,
                                self._args(pod, nodes))
        except (urllib.error.URLError, socket.timeout, OSError, ValueError):
            return {}
        out: dict[str, float] = {}
        for entry in result or []:
            host = entry.get("host", "")
            if host:
                out[host] = float(entry.get("score", 0)) * self.config.weight
        return out
