"""Device-mesh sharding for the scheduling tensors.

The reference parallelizes one decision across 16 goroutines over the node
list (workqueue.Parallelize, generic_scheduler.go:182) and across priority
functions (goroutine per priority, :255-285).  The TPU-native scaling axis is
the same one — nodes — but expressed as a sharded mesh dimension: every
``[*, N]`` tensor (node features, aggregates, masks, score planes, group
tables) is sharded over the ``nodes`` mesh axis, the ``[P, *]`` pod tensors
are sharded over the ``batch`` axis (data-parallel over pods), and XLA
inserts the ICI collectives (all-reduce for per-pod max normalizations,
all-gather for argmax host selection) that the goroutine fan-in/fan-out
performed on CPU.

A cluster of 5k nodes x few hundred feature columns fits easily in one
chip's HBM; the mesh pays off on the [P,N,*] intermediates (30k x 5k masks
and score planes), which shard cleanly over both axes.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubernetes_tpu.engine.solver import (DeviceAffinity, DeviceBatch,
                                          DeviceCluster, DeviceVolSvc)

BATCH_AXIS = "batch"
NODE_AXIS = "nodes"


def make_mesh(n_devices: Optional[int] = None, batch: int = 1) -> Mesh:
    """1D node-sharded mesh by default; pass batch>1 for a 2D (batch, nodes)
    mesh for the one-shot evaluate path."""
    devs = jax.devices()[: (n_devices or len(jax.devices()))]
    n = len(devs)
    assert n % batch == 0, f"{n} devices not divisible by batch={batch}"
    arr = np.array(devs).reshape(batch, n // batch)
    return Mesh(arr, (BATCH_AXIS, NODE_AXIS))


# Which DeviceCluster fields carry the node axis as dim 0 (all of them).
_CLUSTER_NODE_FIELDS = set(DeviceCluster._fields)
# DeviceBatch fields whose dim 0 is the pod axis — the solver's
# slice/permute registry IS the authority (a field added there, like
# nz_tmpl_idx, must shard here too; a hand-copied set silently
# diverged once).
from kubernetes_tpu.engine.solver import _POD_AXIS_FIELDS as \
    _BATCH_POD_FIELDS_TUPLE  # noqa: E402 — registry import, not a cycle
_BATCH_POD_FIELDS = set(_BATCH_POD_FIELDS_TUPLE)
# Group tables etc. whose last/only meaningful axis is nodes.
_BATCH_NODE_LAST_FIELDS = {"sel_required", "sel_pref_counts",
                           "spread_node_counts", "avoid_rows"}
_BATCH_REPLICATED_FIELDS = {"spread_zone_counts", "spread_has_zones",
                            "nz_templates"}
_BATCH_NODE_VEC_FIELDS = {"node_zone_id"}


def shard_cluster(c: DeviceCluster, mesh: Mesh) -> DeviceCluster:
    """Place every cluster tensor with its node axis sharded over the
    mesh.  Form-generic: the narrow wire form (solver.NarrowCluster)
    also carries the node axis as dim 0 of every plane, so both
    resident layouts shard under the same rule."""
    out = {}
    for name, arr in zip(type(c)._fields, c):
        spec = P(NODE_AXIS) if arr.ndim == 1 else P(NODE_AXIS, None)
        out[name] = jax.device_put(arr, NamedSharding(mesh, spec))
    return type(c)(**out)


# DeviceAffinity: [S, N] row tables shard over nodes, [P, S] incidence over
# the batch axis, small [S]/[K] vectors replicate.
_AFF_NODE_ROW_FIELDS = {"node_dom", "match_cnt", "decl_reach", "sym_cnt"}
_AFF_POD_FIELDS = {"match_src", "aff_need", "aff_self", "anti_need",
                   "pref_w", "decl_match", "decl_src", "sym_match", "sym_src"}


def _shard_affinity(a: DeviceAffinity, mesh: Mesh,
                    shard_pods: bool) -> DeviceAffinity:
    out = {}
    for name, arr in zip(DeviceAffinity._fields, a):
        if name in _AFF_NODE_ROW_FIELDS:
            spec = P(None, NODE_AXIS)
        elif name in _AFF_POD_FIELDS and shard_pods:
            spec = P(BATCH_AXIS, None)
        else:
            spec = P(*([None] * arr.ndim))
        out[name] = jax.device_put(arr, NamedSharding(mesh, spec))
    return DeviceAffinity(**out)


# DeviceVolSvc: node-axis tables shard over nodes; per-pod rows over batch.
_VS_NODE_FIELDS = {"pd_node_ebs", "pd_node_gce", "nl_pred_row",
                   "pd_node_extra_ebs", "pd_node_err_ebs",
                   "pd_node_extra_gce", "pd_node_err_gce"}
_VS_NODE_LAST_FIELDS = {"vz_mask", "sa_mask", "nl_prio_rows"}
_VS_POD_FIELDS = {"pd_pod_ebs", "pd_pod_gce", "pd_extra_ebs", "pd_extra_gce",
                  "vz_group", "sa_group", "saa_group", "saa_src"}


def _shard_volsvc(v: DeviceVolSvc, mesh: Mesh,
                  shard_pods: bool) -> DeviceVolSvc:
    out = {}
    for name, arr in zip(DeviceVolSvc._fields, v):
        if name in _VS_NODE_FIELDS:
            spec = P(NODE_AXIS) if arr.ndim == 1 else P(NODE_AXIS, None)
        elif name in _VS_NODE_LAST_FIELDS:
            spec = P(None, NODE_AXIS)
        elif name in ("saa_dom", "saa_labeled"):
            spec = P(None, NODE_AXIS)
        elif name in _VS_POD_FIELDS and shard_pods:
            spec = P(BATCH_AXIS) if arr.ndim == 1 else P(BATCH_AXIS, None)
        else:
            spec = P(*([None] * arr.ndim))
        out[name] = jax.device_put(arr, NamedSharding(mesh, spec))
    return DeviceVolSvc(**out)


def shard_batch(b: DeviceBatch, mesh: Mesh,
                shard_pods: bool = False) -> DeviceBatch:
    """Shard group tables over nodes; optionally shard pod-axis tensors over
    the batch axis (for the one-shot evaluate; the sequential scan needs
    per-step dynamic slices of the pod axis, which stay replicated)."""
    out = {}
    for name, arr in zip(DeviceBatch._fields, b):
        if name == "pods":
            out[name] = arr
            continue
        if name == "aff":
            out[name] = _shard_affinity(arr, mesh, shard_pods)
            continue
        if name == "volsvc":
            out[name] = _shard_volsvc(arr, mesh, shard_pods)
            continue
        if name in _BATCH_NODE_LAST_FIELDS:
            spec = P(None, NODE_AXIS)
        elif name in _BATCH_NODE_VEC_FIELDS:
            spec = P(NODE_AXIS)
        elif name in _BATCH_REPLICATED_FIELDS:
            spec = P(*([None] * arr.ndim))
        elif name in _BATCH_POD_FIELDS and shard_pods:
            spec = P(BATCH_AXIS) if arr.ndim == 1 else P(BATCH_AXIS, None)
        else:
            spec = P(*([None] * arr.ndim))
        out[name] = jax.device_put(arr, NamedSharding(mesh, spec))
    return DeviceBatch(**out)
