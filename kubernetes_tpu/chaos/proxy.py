"""In-process fault-injecting HTTP proxy (toxiproxy-style).

``ChaosProxy`` listens on its own port and forwards every request to an
upstream apiserver, applying matching fault rules first.  It speaks the
same HTTP/1.1 subset as the apiserver front end (Content-Length framed
requests, Content-Length or chunked responses) so every client in this
repo — ``APIClient``, ``HTTPWatcher``, ``HTTPBinder``, kubectl — can point
at the proxy instead of the apiserver and exercise its failure paths.

Faults (``Rule.fault``):

* ``error``      — answer ``status`` (500/503/409/410/...) without
  forwarding; optional ``retry_after`` sets a Retry-After header.
* ``reset``      — close the client connection without a response, BEFORE
  forwarding (the request never reaches the upstream, so a client-side
  resend cannot double-apply a write).
* ``latency``    — sleep ``delay_s`` before forwarding (stacks with other
  rules: a latency rule plus an error rule delays the error).
* ``cut-stream`` — on a streamed (watch) response, forward
  ``after_events`` event lines then cut the stream MID-EVENT: half of the
  next event's bytes are written and the connection dropped, so the
  client's JSON parse fails exactly as a half-delivered chunk would.

Rules match on ``method`` (empty = any) and ``path`` (regex, searched in
the full request target including the query string), fire with
``probability`` — or deterministically on every ``every_nth`` matching
request (the Nth, 2Nth, ... match fires; 0 = off) — and at most
``count`` times (-1 = unlimited).

Admin endpoints (served by the proxy itself, never faulted):

    GET    /chaos/rules        list rules
    POST   /chaos/rules        add a rule (JSON body = Rule fields)
    DELETE /chaos/rules        clear all rules
    DELETE /chaos/rules/{id}   remove one rule
    GET    /chaos/stats        request/injection counters
"""

from __future__ import annotations

import http.client
import json
import random
import re
import socket
import socketserver
import struct
import threading
import time
import urllib.parse
from dataclasses import asdict, dataclass

FAULT_ERROR = "error"
FAULT_RESET = "reset"
FAULT_LATENCY = "latency"
FAULT_CUT_STREAM = "cut-stream"

_FAULTS = (FAULT_ERROR, FAULT_RESET, FAULT_LATENCY, FAULT_CUT_STREAM)

# Upstream read deadline while relaying a watch: the apiserver heartbeats
# every ~10 s, so a genuinely dead upstream is detected within this.
_UPSTREAM_WATCH_DEADLINE = 75.0

_REASONS = {200: "OK", 201: "Created", 409: "Conflict", 410: "Gone",
            429: "Too Many Requests", 500: "Internal Server Error",
            502: "Bad Gateway", 503: "Service Unavailable"}


@dataclass
class Rule:
    fault: str = FAULT_ERROR
    method: str = ""          # "" = any verb
    path: str = ""            # regex searched in the full request target
    probability: float = 1.0
    count: int = -1           # max fires; -1 = unlimited
    every_nth: int = 0        # fire on every Nth matching request (0 = off)
    status: int = 500         # for fault="error"
    body: str = ""            # error body ("" = a default message)
    retry_after: float | None = None   # Retry-After header seconds
    delay_s: float = 0.0      # for fault="latency"
    after_events: int = 0     # for fault="cut-stream": events to pass first
    id: int = 0
    fired: int = 0
    seen: int = 0             # matching requests observed (every_nth cadence)

    def __post_init__(self):
        if self.fault not in _FAULTS:
            raise ValueError(f"unknown fault {self.fault!r}")
        self._pattern = re.compile(self.path) if self.path else None

    def matches(self, method: str, target: str) -> bool:
        if self.method and self.method.upper() != method.upper():
            return False
        if self._pattern is not None and \
                not self._pattern.search(target):
            return False
        return True

    def to_json(self) -> dict:
        d = asdict(self)
        d.pop("_pattern", None)
        return d

    @classmethod
    def from_json(cls, d: dict) -> "Rule":
        known = {k: d[k] for k in (
            "fault", "method", "path", "probability", "count", "every_nth",
            "status", "body", "retry_after", "delay_s", "after_events")
            if k in d}
        return cls(**known)


# -- composable node-lifecycle rule helpers ----------------------------------
#
# Soak scenarios are DECLARED out of these, not hand-rolled per test:
# each helper returns a list of Rules (compose by concatenation, install
# with ChaosProxy.add_rules) built on the deterministic every_nth cadence
# so a scenario replays identically run over run.


def heartbeat_drop(every_nth: int = 3, count: int = -1,
                   name: str = "", status: int = 503) -> list[Rule]:
    """Drop every Nth node-status heartbeat PUT (one node's when ``name``
    is given, the fleet's otherwise) — the flapping-kubelet shape: the
    apiserver's view of Ready goes stale in deterministic waves while
    lists and pod traffic flow normally."""
    path = rf"^/api/v1/nodes/{re.escape(name)}" if name \
        else r"^/api/v1/nodes/"
    return [Rule(fault=FAULT_ERROR, method="PUT", path=path,
                 status=status, every_nth=every_nth, count=count)]


def node_flap(kind: str = "reset", period: int = 2, name: str = "",
              count: int = -1, delay_s: float = 0.2) -> list[Rule]:
    """A node's control-plane path flaps on a deterministic cadence:
    every ``period``-th request touching the node's object fails by
    ``kind`` — ``reset`` (connection torn down, the half-dead-node
    shape), ``drop`` (5xx answered, the sick-apiserver-shard shape), or
    ``latency`` (the congested-link shape).  All three leave the
    intervening requests untouched, so the node looks alive-then-dead-
    then-alive to whoever heartbeats or updates it."""
    path = rf"/api/v1/nodes/{re.escape(name)}" if name \
        else r"/api/v1/nodes/"
    if kind == "reset":
        return [Rule(fault=FAULT_RESET, path=path, every_nth=period,
                     count=count)]
    if kind == "drop":
        return [Rule(fault=FAULT_ERROR, path=path, status=503,
                     every_nth=period, count=count)]
    if kind == "latency":
        return [Rule(fault=FAULT_LATENCY, path=path, delay_s=delay_s,
                     every_nth=period, count=count)]
    raise ValueError(f"unknown node_flap kind {kind!r} "
                     f"(reset/drop/latency)")


def watch_cut_on_relist(kind: str = "pods", every_nth: int = 2,
                        after_events: int = 0, count: int = -1
                        ) -> list[Rule]:
    """Cut every Nth watch stream of ``kind`` mid-event, right after the
    relist's replay window (``after_events`` events pass first) — the
    storm shape that makes a reflector relist repeatedly and exercises
    the resume-after-410/fresh-resourceVersion path without ever letting
    a stale event replay look healthy."""
    return [Rule(fault=FAULT_CUT_STREAM, method="GET",
                 path=rf"/{re.escape(kind)}\?watch=1",
                 after_events=after_events, every_nth=every_nth,
                 count=count)]


def overload(kind: int = 429, retry_after_s: float | None = 0.5,
             path: str = "", method: str = "", every_nth: int = 1,
             count: int = -1) -> list[Rule]:
    """A shedding control plane: answer ``kind`` (429 by default, or 503
    for the generic brown-out shape) with an optional Retry-After on
    every ``every_nth``-th matching request — the sustained-overload
    shape the client's retry budget and AIMD window must absorb without
    amplification.  ``path``/``method`` scope the storm (e.g. only
    creates, only binds); the default sheds everything forwarded."""
    return [Rule(fault=FAULT_ERROR, method=method, path=path, status=kind,
                 retry_after=retry_after_s, every_nth=every_nth,
                 count=count)]


def bind_conflict_storm(every_nth: int = 3, count: int = -1) -> list[Rule]:
    """409 every Nth binding POST — the competing-writer shape: the
    daemon must forget+requeue exactly the victims while the rest of the
    batch lands (pinned by the PR 4 chaos e2e; the soak keeps it on for
    the whole run)."""
    return [Rule(fault=FAULT_ERROR, method="POST", path=r"/bindings",
                 status=409, every_nth=every_nth, count=count)]


class _ProxyServer(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True
    request_queue_size = 128

    def handle_error(self, request, client_address):
        import sys
        exc = sys.exc_info()[1]
        if isinstance(exc, (ConnectionError, TimeoutError, OSError)):
            return  # connection churn IS this proxy's business; stay quiet
        super().handle_error(request, client_address)


class ChaosProxy:
    """Programmatic handle + HTTP admin surface over the fault rules."""

    def __init__(self, upstream: str, host: str = "127.0.0.1",
                 port: int = 0):
        parsed = urllib.parse.urlparse(upstream)
        if parsed.scheme not in ("", "http"):
            raise ValueError("ChaosProxy fronts plain-HTTP upstreams only")
        self._up_host = parsed.hostname or "127.0.0.1"
        self._up_port = parsed.port or 80
        self._lock = threading.Lock()
        self._rules: list[Rule] = []
        self._next_id = 1
        self.requests_total = 0
        self.injected_total = 0
        self._server = _ProxyServer((host, port), self._make_handler())
        self._thread: threading.Thread | None = None

    # -- lifecycle -------------------------------------------------------

    @property
    def base_url(self) -> str:
        host, port = self._server.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "ChaosProxy":
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            kwargs={"poll_interval": 0.05},  # tests stop proxies often
            daemon=True, name="chaos-proxy")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    # -- rule management (programmatic; the admin endpoint calls these) --

    def add_rule(self, rule: Rule | None = None, **kw) -> int:
        rule = rule or Rule(**kw)
        with self._lock:
            rule.id = self._next_id
            self._next_id += 1
            self._rules.append(rule)
            return rule.id

    def add_rules(self, rules: list[Rule]) -> list[int]:
        """Install a composed rule set (the node-lifecycle helpers below
        return lists so scenarios compose by concatenation)."""
        return [self.add_rule(rule) for rule in rules]

    def remove_rule(self, rule_id: int) -> bool:
        with self._lock:
            before = len(self._rules)
            self._rules = [r for r in self._rules if r.id != rule_id]
            return len(self._rules) < before

    def clear(self) -> int:
        with self._lock:
            n = len(self._rules)
            self._rules = []
            return n

    def rules(self) -> list[Rule]:
        with self._lock:
            return list(self._rules)

    def stats(self) -> dict:
        with self._lock:
            return {"requests": self.requests_total,
                    "injected": self.injected_total,
                    "rules": [r.to_json() for r in self._rules]}

    def _fire(self, method: str, target: str) -> list[Rule]:
        """Decide which rules fire for this request (count decremented,
        probability rolled, all under one lock so concurrent requests
        can't overspend a count-limited rule)."""
        fired: list[Rule] = []
        with self._lock:
            self.requests_total += 1
            for rule in self._rules:
                if rule.count == 0 or not rule.matches(method, target):
                    continue
                if rule.every_nth:
                    # Deterministic cadence: the Nth, 2Nth, ... matching
                    # request fires (e.g. "409 every 3rd bind").
                    rule.seen += 1
                    if rule.seen % rule.every_nth:
                        continue
                if rule.probability < 1.0 and \
                        random.random() >= rule.probability:
                    continue
                if rule.count > 0:
                    rule.count -= 1
                rule.fired += 1
                self.injected_total += 1
                fired.append(rule)
        return fired

    # -- the wire --------------------------------------------------------

    def _make_handler(proxy):  # noqa: N805 — closure style, like server.py

        class Handler(socketserver.StreamRequestHandler):
            disable_nagle_algorithm = True

            def setup(self):
                super().setup()
                self.connection.setsockopt(socket.IPPROTO_TCP,
                                           socket.TCP_NODELAY, 1)
                self.connection.settimeout(120.0)
                self._upstream: http.client.HTTPConnection | None = None

            def finish(self):
                if self._upstream is not None:
                    self._upstream.close()
                super().finish()

            def handle(self):
                try:
                    while self._handle_one():
                        pass
                except (TimeoutError, OSError):
                    return

            # -- request parsing (Content-Length framing, the subset every
            # client in this repo speaks) -------------------------------

            def _handle_one(self) -> bool:
                line = self.rfile.readline(65536)
                if not line or line in (b"\r\n", b"\n"):
                    return False
                try:
                    method_b, target_b, _ = line.split(b" ", 2)
                except ValueError:
                    return False
                method = method_b.decode()
                target = target_b.decode()
                headers: list[tuple[str, str]] = []
                clen = 0
                while True:
                    h = self.rfile.readline(65536)
                    if h in (b"\r\n", b"\n", b""):
                        break
                    name, _, value = h.decode(errors="replace").partition(":")
                    name = name.strip()
                    value = value.strip()
                    if name.lower() == "content-length":
                        try:
                            clen = int(value)
                        except ValueError:
                            return False
                    headers.append((name, value))
                if not 0 <= clen <= 64 * 1024 * 1024:
                    return False
                body = self.rfile.read(clen) if clen else b""
                if len(body) < clen:
                    return False
                if target.startswith("/chaos/") or target == "/chaos":
                    return self._admin(method, target, body)
                return self._proxy(method, target, headers, body)

            # -- admin surface ------------------------------------------

            def _send_json(self, code: int, obj,
                           retry_after: float | None = None) -> bool:
                body = json.dumps(obj).encode()
                reason = _REASONS.get(code, "")
                extra = b""
                if retry_after is not None:
                    extra = (f"Retry-After: {retry_after:g}\r\n").encode()
                self.wfile.write(
                    f"HTTP/1.1 {code} {reason}\r\n".encode() + extra +
                    b"Content-Type: application/json\r\nContent-Length: " +
                    str(len(body)).encode() + b"\r\n\r\n" + body)
                self.wfile.flush()
                return True

            def _admin(self, method: str, target: str, body: bytes) -> bool:
                path = target.split("?", 1)[0]
                if path == "/chaos/rules":
                    if method == "GET":
                        return self._send_json(200, {
                            "rules": [r.to_json() for r in proxy.rules()]})
                    if method == "POST":
                        try:
                            rule = Rule.from_json(json.loads(body or b"{}"))
                        except (ValueError, TypeError) as err:
                            return self._send_json(400, {"error": str(err)})
                        return self._send_json(201,
                                               {"id": proxy.add_rule(rule)})
                    if method == "DELETE":
                        return self._send_json(200,
                                               {"removed": proxy.clear()})
                m = re.fullmatch(r"/chaos/rules/(\d+)", path)
                if m and method == "DELETE":
                    ok = proxy.remove_rule(int(m.group(1)))
                    return self._send_json(200, {"removed": int(ok)})
                if path == "/chaos/stats" and method == "GET":
                    return self._send_json(200, proxy.stats())
                return self._send_json(404, {"error": "unknown chaos path"})

            # -- fault application + relay ------------------------------

            def _proxy(self, method: str, target: str,
                       headers: list[tuple[str, str]], body: bytes) -> bool:
                fired = proxy._fire(method, target)
                cut_rule = None
                terminal = None
                for rule in fired:
                    if rule.fault == FAULT_LATENCY:
                        time.sleep(rule.delay_s)
                    elif rule.fault == FAULT_CUT_STREAM:
                        cut_rule = cut_rule or rule
                    elif terminal is None:
                        terminal = rule
                if terminal is not None:
                    if terminal.fault == FAULT_RESET:
                        # Abortive close (RST where the stack allows): the
                        # request never reached the upstream.
                        try:
                            self.connection.setsockopt(
                                socket.SOL_SOCKET, socket.SO_LINGER,
                                struct.pack("ii", 1, 0))
                        except OSError:
                            pass
                        self.connection.close()
                        return False
                    msg = terminal.body or \
                        f"chaos: injected {terminal.status}"
                    self._send_json(terminal.status, {"error": msg},
                                    retry_after=terminal.retry_after)
                    return True
                return self._forward(method, target, headers, body,
                                     cut_rule)

            def _up_conn(self) -> http.client.HTTPConnection:
                if self._upstream is None:
                    self._upstream = http.client.HTTPConnection(
                        proxy._up_host, proxy._up_port, timeout=30.0)
                return self._upstream

            def _forward(self, method: str, target: str,
                         headers: list[tuple[str, str]], body: bytes,
                         cut_rule: Rule | None) -> bool:
                hop = {"connection", "keep-alive", "transfer-encoding",
                       "content-length", "host"}
                fwd = {n: v for n, v in headers if n.lower() not in hop}
                for attempt in (0, 1):
                    c = self._up_conn()
                    try:
                        c.request(method, target, body or None, fwd)
                    except (http.client.HTTPException, OSError):
                        # Stale upstream keep-alive: the request was not
                        # delivered; one reconnect + resend is safe for
                        # any verb.
                        c.close()
                        self._upstream = None
                        if attempt:
                            return self._send_json(
                                502, {"error": "chaos proxy: upstream "
                                               "unreachable"})
                        continue
                    try:
                        resp = c.getresponse()
                        break
                    except (http.client.HTTPException, OSError):
                        # Response lost: the upstream may have processed
                        # the request — resending a write would double-
                        # apply it.  Relay the fault to the client (502)
                        # and let ITS retry policy decide; reads get one
                        # transparent resend.
                        c.close()
                        self._upstream = None
                        if attempt or method not in ("GET", "HEAD"):
                            return self._send_json(
                                502, {"error": "chaos proxy: upstream "
                                               "dropped the response"})
                if resp.getheader("Transfer-Encoding", ""
                                  ).lower() == "chunked":
                    if c.sock is not None:
                        c.sock.settimeout(_UPSTREAM_WATCH_DEADLINE)
                    self._relay_stream(resp, cut_rule)
                    return False  # stream consumed the connection
                payload = resp.read()
                reason = resp.reason or _REASONS.get(resp.status, "")
                ctype = resp.getheader("Content-Type", "application/json")
                hdr = (f"HTTP/1.1 {resp.status} {reason}\r\n"
                       f"Content-Type: {ctype}\r\n"
                       f"Content-Length: {len(payload)}\r\n")
                ra = resp.getheader("Retry-After")
                if ra:
                    hdr += f"Retry-After: {ra}\r\n"
                self.wfile.write(hdr.encode() + b"\r\n" + payload)
                self.wfile.flush()
                return True

            def _relay_stream(self, resp, cut_rule: Rule | None) -> None:
                """Relay a chunked (watch) response line-by-line.  Each
                event is one NDJSON line; heartbeats are blank lines.
                With a cut rule: pass ``after_events`` event lines, then
                write HALF of the next event and drop the connection."""
                self.wfile.write(
                    b"HTTP/1.1 200 OK\r\nContent-Type: application/json\r\n"
                    b"Transfer-Encoding: chunked\r\n\r\n")
                self.wfile.flush()
                passed = 0
                try:
                    while True:
                        line = resp.readline()
                        if not line:
                            break  # upstream closed the stream
                        is_event = bool(line.strip())
                        if cut_rule is not None and is_event and \
                                passed >= cut_rule.after_events:
                            half = line[:max(1, len(line) // 2)]
                            self.wfile.write(
                                f"{len(half):x}\r\n".encode() + half +
                                b"\r\n")
                            self.wfile.flush()
                            break  # mid-event cut: close abruptly
                        if is_event:
                            passed += 1
                        self.wfile.write(f"{len(line):x}\r\n".encode() +
                                         line + b"\r\n")
                        self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError,
                        socket.timeout, OSError):
                    pass
                finally:
                    resp.close()
                    if self._upstream is not None:
                        self._upstream.close()
                        self._upstream = None

        return Handler
