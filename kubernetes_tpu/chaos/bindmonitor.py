"""Double-bind referee: a store-watch monitor classifying nodeName
transitions.

Born as the soak harness's post-run reconciliation detector and promoted
to a reusable helper: every chaos/e2e rig that races binds (409 storms,
mid-drain kills, multiple active-active incarnations) wants the same
referee.  A BIND is ``"" -> node``; a DOUBLE BIND — the invariant a kill
between solve and bind, or two incarnations racing one shard, must never
break — is ``node -> different node`` on the same live pod object.
Delivery is synchronous under the store lock into an unbounded queue, so
no event is ever missed; a DELETED pod's slate is wiped (rolling updates
recreate names, which is a fresh bind, not a double one).
"""

from __future__ import annotations

import threading


class BindMonitor:
    """Watch ``store``'s pod stream in-process and count binds and
    double-binds.  ``store`` is a MemStore (the watch rides the store
    lock, so the count is exact, not sampled)."""

    def __init__(self, store):
        self.binds = 0
        self.double_binds = 0
        # pod key -> node of the offending transition, for assertion
        # messages that name the actual victim.
        self.double_bind_keys: list[tuple[str, str, str]] = []
        self._nodes: dict[str, str] = {}
        self._stopped = threading.Event()
        # Watch from the CURRENT rv: fleet registration that ran before
        # this monitor can exceed the server's replay window, and no pod
        # events predate it anyway.
        self._watcher = store.watch(["pods"],
                                    from_rv=store.list("pods")[1])
        self._thread = threading.Thread(target=self._pump, daemon=True,
                                        name="bind-monitor")
        self._thread.start()

    def _pump(self) -> None:
        while not self._stopped.is_set():
            ev = self._watcher.next(timeout=0.5)
            if ev is None:
                continue  # timeout (or the stop sentinel; flag decides)
            if ev.type == "DELETED":
                self._nodes.pop(ev.key, None)
                continue
            node = (ev.object.get("spec") or {}).get("nodeName") or ""
            prev = self._nodes.get(ev.key, "")
            if node and not prev:
                self.binds += 1
            elif node and prev and node != prev:
                self.double_binds += 1
                self.double_bind_keys.append((ev.key, prev, node))
            self._nodes[ev.key] = node

    def stop(self) -> None:
        self._stopped.set()
        self._watcher.stop()

    def assert_clean(self) -> None:
        """Raise with the offending transitions if any double bind was
        seen — the one-line acceptance check for e2e scenarios."""
        assert self.double_binds == 0, \
            f"double binds detected: {self.double_bind_keys}"
