"""Double-bind referee: a store-watch monitor classifying nodeName
transitions.

Born as the soak harness's post-run reconciliation detector and promoted
to a reusable helper: every chaos/e2e rig that races binds (409 storms,
mid-drain kills, multiple active-active incarnations) wants the same
referee.  A BIND is ``"" -> node``; a DOUBLE BIND — the invariant a kill
between solve and bind, or two incarnations racing one shard, must never
break — is ``node -> different node`` on the same live pod object.
Delivery is synchronous under the store lock into an unbounded queue, so
no event is ever missed; a DELETED pod's slate is wiped (rolling updates
recreate names, which is a fresh bind, not a double one).

Defrag migrations (scheduler/defrag.py) add a second invariant: an
evicted-but-not-yet-rebound migrant must read as PENDING, never as
capacity on two nodes at once.  The monitor tracks the migration window
— a ``node -> ""`` unbind on a pod carrying the migration-intent
annotation opens it, the re-bind closes it — and counts any
``node -> different node`` transition that skipped the pending hop on a
migrating pod as DOUBLE CAPACITY (``assert_clean`` fails on either
counter).
"""

from __future__ import annotations

import threading

from kubernetes_tpu.api.types import DEFRAG_MIGRATION_ANNOTATION_KEY


class BindMonitor:
    """Watch ``store``'s pod stream in-process and count binds,
    double-binds, and migration-window violations.  ``store`` is a
    MemStore (the watch rides the store lock, so the count is exact,
    not sampled)."""

    def __init__(self, store):
        self.binds = 0
        self.unbinds = 0
        self.double_binds = 0
        # pod key -> node of the offending transition, for assertion
        # messages that name the actual victim.
        self.double_bind_keys: list[tuple[str, str, str]] = []
        # Migration accounting: windows opened (evict-to-pending with
        # the intent annotation), closed (the migrant rebound), and the
        # double-capacity violations (a migrating pod seen on two nodes
        # without passing through pending).
        self.migrations_started = 0
        self.migrations_completed = 0
        self.double_capacity = 0
        self.double_capacity_keys: list[tuple[str, str, str]] = []
        self._migrating: set[str] = set()
        self._nodes: dict[str, str] = {}
        self._stopped = threading.Event()
        # Watch from the CURRENT rv: fleet registration that ran before
        # this monitor can exceed the server's replay window, and no pod
        # events predate it anyway.
        self._watcher = store.watch(["pods"],
                                    from_rv=store.list("pods")[1])
        self._thread = threading.Thread(target=self._pump, daemon=True,
                                        name="bind-monitor")
        self._thread.start()

    def _pump(self) -> None:
        while not self._stopped.is_set():
            ev = self._watcher.next(timeout=0.5)
            if ev is None:
                continue  # timeout (or the stop sentinel; flag decides)
            if ev.type == "DELETED":
                self._nodes.pop(ev.key, None)
                self._migrating.discard(ev.key)
                continue
            node = (ev.object.get("spec") or {}).get("nodeName") or ""
            prev = self._nodes.get(ev.key, "")
            migrating = DEFRAG_MIGRATION_ANNOTATION_KEY in \
                ((ev.object.get("metadata") or {}).get("annotations")
                 or {})
            if node and not prev:
                self.binds += 1
                if ev.key in self._migrating:
                    self.migrations_completed += 1
                    self._migrating.discard(ev.key)
            elif prev and not node:
                self.unbinds += 1
                if migrating:
                    self.migrations_started += 1
                    self._migrating.add(ev.key)
            elif node and prev and node != prev:
                self.double_binds += 1
                self.double_bind_keys.append((ev.key, prev, node))
                if migrating or ev.key in self._migrating:
                    # A migrating pod observed bound on two nodes with
                    # no pending hop in between: it was counted as
                    # capacity twice.
                    self.double_capacity += 1
                    self.double_capacity_keys.append(
                        (ev.key, prev, node))
            self._nodes[ev.key] = node

    def stop(self) -> None:
        self._stopped.set()
        self._watcher.stop()

    def assert_clean(self) -> None:
        """Raise with the offending transitions if any double bind — or
        any migration-window double capacity — was seen: the one-line
        acceptance check for e2e scenarios."""
        assert self.double_binds == 0, \
            f"double binds detected: {self.double_bind_keys}"
        assert self.double_capacity == 0, \
            f"migration double-capacity detected: " \
            f"{self.double_capacity_keys}"
